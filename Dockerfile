# Single-environment image. The reference needed TWO conda environments in
# one container because PWC-Net's CuPy CUDA kernel pinned torch 1.2 + CUDA 10
# while everything else ran torch 1.7 + CUDA 11 (reference Dockerfile,
# conda_env_pwc.yml, conda_env_torch_zoo.yml). The PWC cost volume here is a
# Pallas/XLA kernel, so one environment serves every model family.
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        ffmpeg libgl1 libglib2.0-0 \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/video_features_tpu
COPY pyproject.toml README.md ./
COPY video_features_tpu ./video_features_tpu
COPY main.py bench.py ./
COPY scripts ./scripts

# CPU jax by default; swap for the TPU wheel on TPU VMs:
#   pip install -e ".[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
RUN pip install --no-cache-dir -e ".[convert]"

# converted weights cache (mount a volume here; see scripts/convert_weights.py)
ENV VFT_WEIGHTS_DIR=/weights
VOLUME /weights

ENTRYPOINT ["python", "main.py"]
