"""Sequence/context parallelism: ring attention and all-to-all (Ulysses).

The reference has no long-range attention at all — its "sequence" dimension
is video time, scaled by windowing (SURVEY §5: fixed clip stacks, streaming
decode). This module makes long-sequence attention a first-class primitive of
the TPU framework so temporal transformers over thousands of frames (or very
high frame-token counts) shard across a mesh instead of hitting the
single-chip memory wall:

  - :func:`ring_attention` — blockwise attention with the K/V shards rotated
    around the ``seq`` mesh axis by ``jax.lax.ppermute`` (ICI
    neighbor-to-neighbor traffic only) and a streaming log-sum-exp softmax,
    so no device ever materializes the full (T, T) score matrix or the full
    K/V. Memory per device: O(T/n * T/n) scores, O(T/n) K/V.
  - :func:`ulysses_attention` — all-to-all context parallelism: heads are
    exchanged for sequence shards (``jax.lax.all_to_all``), each device runs
    dense attention for H/n heads over the FULL sequence, then the layout is
    swapped back. One collective pair per attention call; best when
    n_devices <= n_heads and T*T/n scores fit.
  - :func:`blockwise_attention` — the INTRA-device path: the same streaming
    log-sum-exp recurrence over K/V blocks on one device (FlashAttention at
    the XLA level), O(T * block_size) score memory. Compose with
    ring/Ulysses when a single shard's sequence is itself too long to score
    densely.

The sharded pair are written as shard_map bodies (take ``axis_name``) plus
convenience wrappers that build the shard_map over a 1-D ``seq`` mesh. All
support the causal mask (global positions reconstructed from the device
index, so the mask is exact across shards); all share one streaming-softmax
fold (:func:`_softmax_fold`). Numerics are validated against dense softmax
attention on the 8-device CPU mesh in tests/test_sequence_parallel.py.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # older jax: not yet promoted out of experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def dense_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False,
                    scale: Optional[float] = None) -> jnp.ndarray:
    """Reference single-device attention. (B, T, H, D) -> (B, T, H, D)."""
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), tk - tq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _fold_init(b, h, t, d):
    """Fresh streaming-softmax accumulator (o, m, l), f32."""
    return (jnp.zeros((b, h, t, d), jnp.float32),
            jnp.full((b, h, t, 1), -jnp.inf, jnp.float32),
            jnp.zeros((b, h, t, 1), jnp.float32))


def _fold_finalize(o, l, dtype):
    """Normalize + (B, H, T, D) -> (B, T, H, D) in the caller's dtype."""
    out = o / jnp.maximum(l, 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(dtype)


def _softmax_fold(q, acc, ck, cv, scale, valid):
    """Fold one K/V block into the streaming-softmax accumulator
    ``(o, m, l)`` — unnormalized output, running max, normalizer. ``valid``
    is an optional (tq, tk) bool mask (causal and/or padding); the -inf
    guards keep fully-masked rows finite. Shared by the ring and blockwise
    paths so the delicate numerics live once."""
    o, m, l = acc
    s = jnp.einsum("bqhd,bkhd->bhqk", q, ck,
                   preferred_element_type=jnp.float32) * scale
    if valid is not None:
        s = jnp.where(valid, s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    m_safe = jnp.where(jnp.isinf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe)
    if valid is not None:
        p = jnp.where(jnp.isinf(s), 0.0, p)
    alpha = jnp.where(jnp.isinf(m), 0.0, jnp.exp(m - m_safe))
    o = o * alpha + jnp.einsum("bhqk,bkhd->bhqd", p, cv,
                               preferred_element_type=jnp.float32)
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    return o, m_new, l


def blockwise_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        block_size: int = 512, causal: bool = False,
                        scale: Optional[float] = None) -> jnp.ndarray:
    """Single-device memory-efficient attention (B, T, H, D) -> same.

    The intra-device complement of :func:`ring_attention`: a ``lax.scan``
    over K/V blocks with the same streaming log-sum-exp softmax, so peak
    score memory is O(T * block_size) instead of O(T^2) — the
    FlashAttention recurrence expressed at the XLA level. Use it when one
    device's sequence shard is itself too long to score densely; compose
    with ring/Ulysses for the cross-device axis. T need not divide
    block_size (keys pad with a mask).
    """
    b, t, h, d = q.shape
    scale = (d ** -0.5) if scale is None else scale
    bs = min(block_size, t)
    n_blocks = -(-t // bs)
    pad = n_blocks * bs - t
    kp = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    # (n_blocks, B, bs, H, D) scan sequence
    kb = jnp.moveaxis(kp.reshape(b, n_blocks, bs, h, d), 1, 0)
    vb = jnp.moveaxis(vp.reshape(b, n_blocks, bs, h, d), 1, 0)
    q_pos = jnp.arange(t)

    def step(acc, blk):
        o, m, l, i = acc
        ck, cv = blk
        k_pos = i * bs + jnp.arange(bs)
        valid = k_pos[None, :] < t
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        o, m, l = _softmax_fold(q, (o, m, l), ck, cv, scale, valid)
        return (o, m, l, i + 1), None

    o0, m0, l0 = _fold_init(b, h, t, d)
    (o, _, l, _), _ = jax.lax.scan(step, (o0, m0, l0, 0), (kb, vb))
    return _fold_finalize(o, l, q.dtype)


def ring_attention_sharded(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           axis_name: str, causal: bool = False,
                           scale: Optional[float] = None) -> jnp.ndarray:
    """shard_map body: q/k/v are the LOCAL (B, T/n, H, D) sequence shards.

    lax.scan over n ring steps; each step attends the local queries to the
    currently-held K/V shard (with exact global-position causal masking),
    folds the block into the streaming-softmax accumulator (running max m,
    normalizer l, unnormalized output o), then rotates the K/V shard to the
    next device with ppermute. The ppermute is inside the scanned step, so
    XLA overlaps the ICI transfer of step i+1's shard with step i's compute.
    """
    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    scale = (d ** -0.5) if scale is None else scale
    q_pos = me * t_local + jnp.arange(t_local)  # global query positions

    def fold(acc, ck, cv, src):
        """Fold the K/V shard currently held (originally device ``src``)."""
        valid = None
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            valid = q_pos[:, None] >= k_pos[None, :]
        return _softmax_fold(q, acc, ck, cv, scale, valid)

    o0, m0, l0 = _fold_init(b, h, t_local, d)
    if hasattr(jax.lax, "pcast"):
        # the accumulators become device-varying after one scan step; the
        # replicated initializers must be cast so the carry types are stable
        o0, m0, l0 = (jax.lax.pcast(x, (axis_name,), to="varying")
                      for x in (o0, m0, l0))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, i):
        o, m, l, ck, cv = carry
        o, m, l = fold((o, m, l), ck, cv, src=(me - i) % n)
        ck = jax.lax.ppermute(ck, axis_name, perm)
        cv = jax.lax.ppermute(cv, axis_name, perm)
        return (o, m, l, ck, cv), None

    # n-1 scanned fold+rotate steps, then the last held block is folded
    # outside the scan — the final rotation (whose result nobody reads)
    # would otherwise cost a full extra K+V ICI transfer per call
    (o, m, l, ck, cv), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(n - 1))
    o, _, l = fold((o, m, l), ck, cv, src=(me - (n - 1)) % n)
    return _fold_finalize(o, l, q.dtype)


def ulysses_attention_sharded(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                              axis_name: str, causal: bool = False,
                              scale: Optional[float] = None) -> jnp.ndarray:
    """shard_map body: all-to-all heads<->sequence swap, dense attention on
    H/n heads x full T, swap back. Requires H % n == 0."""
    # (B, T/n, H, D) -> (B, T, H/n, D)
    qg = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    kg = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    vg = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    out = dense_attention(qg, kg, vg, causal=causal, scale=scale)
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def _seq_mesh(mesh: Optional[Mesh], axis: str) -> Mesh:
    if mesh is not None:
        return mesh
    devs = np.array(jax.devices())
    return Mesh(devs, (axis,))


_BODIES = {"ring": ring_attention_sharded, "ulysses": ulysses_attention_sharded}


@functools.lru_cache(maxsize=None)
def _sharded_fn(kind: str, mesh: Mesh, axis: str, causal: bool,
                scale: Optional[float]):
    """Jitted shard_map per (kind, mesh, axis, causal, scale) — cached so
    repeated calls (one per transformer layer per step) hit the jit cache
    instead of retracing a fresh function object every time."""
    body = functools.partial(_BODIES[kind], axis_name=axis, causal=causal,
                             scale=scale)
    spec = P(None, axis, None, None)
    return jax.jit(shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec))


def _sharded_call(kind: str, mesh: Mesh, axis: str, causal: bool,
                  scale: Optional[float], q, k, v):
    sh = NamedSharding(mesh, P(None, axis, None, None))
    fn = _sharded_fn(kind, mesh, axis, causal, scale)
    # device_put is a no-op when the operand already has this sharding
    return fn(jax.device_put(q, sh), jax.device_put(k, sh),
              jax.device_put(v, sh))


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Optional[Mesh] = None, axis: str = "seq",
                   causal: bool = False,
                   scale: Optional[float] = None) -> jnp.ndarray:
    """Global-shape entry point: shards (B, T, H, D) over ``axis`` and runs
    :func:`ring_attention_sharded`. T must divide by the mesh size."""
    return _sharded_call("ring", _seq_mesh(mesh, axis), axis, causal, scale,
                         q, k, v)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Optional[Mesh] = None, axis: str = "seq",
                      causal: bool = False,
                      scale: Optional[float] = None) -> jnp.ndarray:
    """Global-shape entry point for the all-to-all path. T and H must divide
    by the mesh size."""
    return _sharded_call("ulysses", _seq_mesh(mesh, axis), axis, causal,
                         scale, q, k, v)
