"""Device mesh + data-parallel batch execution.

This replaces the reference's scale-out story — "run another copy of main.py
per GPU" (reference README.md:70-84) — with in-process SPMD over a
`jax.sharding.Mesh`:

  - single host: clip/frame batches are sharded over the mesh's ``data`` axis;
    XLA partitions the jitted forward, no collectives needed (embarrassingly
    data-parallel at clip granularity, see SURVEY §2.4).
  - multi host: `jax.distributed` + deterministic video->host assignment
    (:func:`local_shard_of_list`), replacing the reference's shuffle +
    skip-if-exists collision avoidance with collision-free hashing. The
    idempotent output contract (utils/sinks.py) still makes preempted workers
    resumable.

The mesh is 1-D ("data") by default because every model family here is
data-parallel at clip granularity; a second "model" axis is reserved for
tensor-parallel experiments on the largest family (CLIP RN50x16) and for the
dryrun multichip validation path.
"""
from __future__ import annotations

import hashlib
from pathlib import Path
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# roofline=true (telemetry/roofline.py): per-program cost-card capture at
# the dispatch boundary — one module-global read per dispatch when off
from ..telemetry.roofline import observe_dispatch as _roofline_observe


def get_mesh(n_devices: Optional[int] = None,
             axis_names: Tuple[str, ...] = ("data",),
             shape: Optional[Tuple[int, ...]] = None,
             backend: Optional[str] = None) -> Mesh:
    """Build a mesh over the first ``n_devices`` local devices (default: all).

    ``backend`` pins the platform (e.g. ``"cpu"``) — an explicit
    ``device=cpu`` run must never enumerate (and thereby claim) the TPU.

    Uses *addressable* devices on purpose: under ``jax.distributed`` each
    process runs its own data-parallel mesh over its own chips (extraction
    is embarrassingly parallel at clip granularity — the only multi-host
    coordination is the work-list shard, :func:`local_shard_of_list`). A
    global-device mesh here would make every ``device_put`` of host frames
    target other hosts' chips and fail. Single-process runs are unaffected
    (local == global).
    """
    devs = jax.local_devices(backend=backend) if backend \
        else jax.local_devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    mesh_devs = np.array(devs).reshape(shape)
    return Mesh(mesh_devs, axis_names)


def mesh_topology() -> dict:
    """JSON-safe device/mesh topology snapshot for the run manifest
    (telemetry/manifest.py): what hardware this process actually saw,
    recorded so a perf number in ``_run.json`` is interpretable months
    later. Uses the same addressable-device view as :func:`get_mesh`."""
    devs = jax.local_devices()
    kinds = sorted({getattr(d, "device_kind", "?") for d in devs})
    return {
        "platform": devs[0].platform if devs else "none",
        "device_kinds": kinds,
        "n_local_devices": len(devs),
        "n_global_devices": jax.device_count(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "default_mesh_axes": {"data": len(devs)},
    }


def local_shard_of_list(items: Sequence[str], host_id: Optional[int] = None,
                        num_hosts: Optional[int] = None) -> List[str]:
    """Deterministic item->host assignment: ``md5(stem) % num_hosts``.

    The multi-host analog of the reference's shuffled work list
    (reference utils/utils.py:164-165): instead of decorrelating workers
    probabilistically and tolerating duplicate work (README.md:84), each video
    is owned by exactly one host. Stable across restarts, so resume works.
    """
    if host_id is None:
        host_id = jax.process_index()
    if num_hosts is None:
        num_hosts = jax.process_count()
    if num_hosts <= 1:
        return list(items)
    out = []
    for it in items:
        # hash the stem, not the path: hosts may see the shared filesystem
        # under different mount prefixes; stems are unique (sanity_check)
        stem = Path(str(it)).stem
        h = int(hashlib.md5(stem.encode()).hexdigest(), 16)
        if h % num_hosts == host_id:
            out.append(it)
    return out


#: Megatron-style tensor-parallel rules for the transformer blocks used by
#: CLIP (models/clip.py param tree): column-parallel qkv/mlp-in (shard the
#: output feature dim + bias), row-parallel out/mlp-out (shard the input
#: dim, replicate bias — XLA inserts the psum). First match wins; everything
#: unmatched stays replicated. GSPMD propagates the internal activation
#: shardings and collectives from these param annotations alone.
TP_RULES_TRANSFORMER: Tuple[Tuple[str, int], ...] = (
    (r"attn/(q|k|v)_proj/kernel$", 1),
    (r"attn/(q|k|v)_proj/bias$", 0),
    (r"mlp_c_fc/kernel$", 1),
    (r"mlp_c_fc/bias$", 0),
    (r"attn/out_proj/kernel$", 0),
    (r"mlp_c_proj/kernel$", 0),
    # ModifiedResNet's AttentionPool2d head (the RN* checkpoints' largest
    # single weight block); the conv trunk stays replicated
    (r"attnpool/(q|k|v)_proj/kernel$", 1),
    (r"attnpool/(q|k|v)_proj/bias$", 0),
    (r"attnpool/c_proj/kernel$", 0),
)


def param_specs_by_rules(params: Any,
                         rules: Sequence[Tuple[str, int]],
                         model_axis: str = "model") -> Any:
    """PartitionSpec tree from path-regex rules: ``(pattern, dim)`` shards
    that tensor dimension over ``model_axis`` for the first matching rule;
    unmatched leaves are replicated. This is how a plain (metadata-free)
    flax param tree gets tensor-parallel layouts without rewriting modules."""
    import re

    def spec(path, x):
        p = "/".join(str(getattr(k, "key", k)) for k in path)
        for pat, dim in rules:
            if re.search(pat, p):
                s: List[Optional[str]] = [None] * np.ndim(x)
                s[dim] = model_axis
                return P(*s)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def settle(out: Any) -> float:
    """Completion fence via a D2H read: sums every leaf of ``out`` on host.

    ``block_until_ready`` has been observed to ack before execution finishes
    on remotely-tunneled dev chips (yielding physically impossible benchmark
    rates); a host read of the output cannot return early, and the device's
    in-order queue makes it fence every prior dispatch. Used by bench.py and
    scripts/bench_i3d.py.
    """
    return float(sum(np.asarray(x).sum()
                     for x in jax.tree_util.tree_leaves(out)))


def cast_floating(tree: Any, dtype) -> Any:
    """Cast every floating-point leaf of a param tree to ``dtype``.

    This is what makes ``precision=bfloat16`` real on TPU: flax modules with
    ``dtype=None`` promote inputs and params to a common type, so a bf16
    activation against f32 params silently runs the conv/matmul in f32 on the
    MXU. Casting the params (the standard bf16-inference layout) keeps the
    whole network in bf16; norm internals still accumulate in f32
    (models/common.py BNInf rsqrt).
    """
    def cast(x):
        x = jnp.asarray(x)
        return x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x
    return jax.tree_util.tree_map(cast, tree)


class DataParallelApply:
    """Jitted, batch-sharded wrapper around ``apply_fn(params, batch)``.

    The batch's leading axis is sharded over the mesh ``data`` axis; params are
    replicated. Ragged host batches pad to a power-of-two wire bucket capped
    at ``fixed_batch`` (XLA needs static shapes — SURVEY §7 "pad+mask the
    last partial batch" — but padding on the HOST costs H2D bytes, so the
    bucket ladder bounds that waste at 2x; see ``bucket_batch_size``).
    Padded rows are dropped after device execution.
    """

    def __init__(self,
                 apply_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                 params: Any,
                 mesh: Optional[Mesh] = None,
                 data_axis: str = "data",
                 fixed_batch: Optional[int] = None,
                 param_specs: Any = None):
        self.mesh = mesh if mesh is not None else get_mesh()
        self.data_axis = data_axis
        self.fixed_batch = fixed_batch
        batch_sharding = NamedSharding(self.mesh, P(data_axis))
        if param_specs is None:
            param_shardings = NamedSharding(self.mesh, P())  # replicated
        else:
            # tensor parallelism: per-leaf PartitionSpecs (e.g. from
            # param_specs_by_rules) shard the weights over the 'model' axis;
            # GSPMD derives the activation shardings + collectives
            param_shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s), param_specs,
                is_leaf=lambda x: isinstance(x, P))
        self.params = jax.device_put(params, param_shardings)
        self._batch_sharding = batch_sharding
        self._fn = jax.jit(
            apply_fn,
            in_shardings=(param_shardings, batch_sharding),
            out_shardings=batch_sharding,
        )

    @property
    def n_devices(self) -> int:
        return int(np.prod(self.mesh.devices.shape))

    def padded_batch_size(self, batch_size: int) -> int:
        """Smallest multiple of the *data-axis* size >= batch_size (on a 2-D
        (data, model) mesh the batch only splits over 'data'; padding to the
        total device count would over-pad by the model-parallel factor)."""
        n = int(self.mesh.shape[self.data_axis])
        return ((batch_size + n - 1) // n) * n

    def bucket_batch_size(self, n: int) -> int:
        """Wire-efficient static shape for a ragged HOST batch: the smallest
        mesh-divisible power-of-two step >= n, capped at ``fixed_batch``.

        Padding ragged groups all the way to ``fixed_batch`` on the host
        ships up to fixed_batch/n more H2D bytes than the rows need — at
        B=128 the 22-clip sample video paid a 5.8x wire tax per flush, and
        H2D is the pipeline's usual bottleneck (worse still through a
        tunneled dev chip). Bucketing bounds the padding waste at 2x while
        keeping the executable count logarithmic (each bucket size compiles
        once and lands in the persistent cache)."""
        b = self.padded_batch_size(max(n, 1))
        t = self.padded_batch_size(1)
        while t < b:
            t *= 2  # stays mesh-divisible: n_data * 2^k
        if self.fixed_batch is not None:
            full = self.padded_batch_size(self.fixed_batch)
            if t >= full:
                t = full
        # never below the rows actually present (oversized host batches —
        # n > fixed_batch — must pad up like before, not truncate the pad)
        return max(t, b)

    def _pad(self, batch_np: np.ndarray) -> np.ndarray:
        """Pad a host batch to its wire bucket (``bucket_batch_size``), or a
        chained device batch up to ``fixed_batch`` — device padding is free
        and keeping the one fixed shape avoids retracing the consumer.
        Device arrays (e.g. the i3d flow->i3d handoff) pad with jnp —
        async, on device — so a ragged group never forces a D2H round trip
        of the intermediate."""
        is_device = isinstance(batch_np, jax.Array)
        if is_device or self.fixed_batch is None:
            target = max(batch_np.shape[0], self.fixed_batch or 0)
            full = self.padded_batch_size(target)
        else:
            full = self.bucket_batch_size(batch_np.shape[0])
        if full != batch_np.shape[0]:
            pad_width = [(0, full - batch_np.shape[0])] + \
                        [(0, 0)] * (batch_np.ndim - 1)
            xp = jnp if isinstance(batch_np, jax.Array) else np
            batch_np = xp.pad(batch_np, pad_width)
        if isinstance(batch_np, jax.Array):
            # chained-runner inputs carry the *producer's* sharding; the jit
            # below requires the batch sharding exactly, so reshard on device
            # (async; a no-op when shardings already match)
            batch_np = jax.device_put(batch_np, self._batch_sharding)
        return batch_np

    def dispatch(self, batch_np: np.ndarray) -> jnp.ndarray:
        """Pad + enqueue the jitted forward; returns the device array
        WITHOUT synchronizing (JAX dispatch is async — the host thread is
        free as soon as the computation is enqueued). Padded rows are NOT
        dropped; callers track validity (see :class:`FeatureStream`).

        Host batches go through an explicit ``device_put`` under an
        ``h2d`` profiler stage, so the per-stage breakdown (profile=true,
        trace=true, scripts/throughput.py --stages) can attribute wire
        time separately from decode/transform and device compute. The put
        is what the jit's implicit transfer would have done anyway — on
        accelerators the DMA completes asynchronously, so the stage times
        the host-side staging copy + enqueue (a lower bound on wire
        time); on CPU it is the full copy."""
        padded = self._pad(batch_np)
        _roofline_observe(self, padded)
        if not isinstance(padded, jax.Array):
            from ..utils.profiling import profiler
            with profiler.stage("h2d"):
                padded = jax.device_put(padded, self._batch_sharding)
        return self._fn(self.params, padded)

    def __call__(self, batch_np: np.ndarray, n_valid: Optional[int] = None
                 ) -> np.ndarray:
        """Run a (possibly ragged) batch; returns only the valid rows."""
        from ..utils.profiling import profiler
        n = batch_np.shape[0] if n_valid is None else n_valid
        padded = self._pad(batch_np)  # host copy kept out of the timed stage
        _roofline_observe(self, padded)
        # np.asarray blocks on the device->host copy, so this stage is true
        # H2D + forward + D2H wall time
        with profiler.stage("forward"):
            return np.asarray(self._fn(self.params, padded))[:n]

    def stream(self, depth: int = 4,
               callback: Optional[Callable[[np.ndarray, Any], None]] = None
               ) -> "FeatureStream":
        return FeatureStream(self, depth=depth, callback=callback)


class FeatureStream:
    """Ordered async pipeline over a :class:`DataParallelApply`.

    The synchronous ``runner(batch)`` call blocks on the device->host copy of
    every batch, serializing host work with the device (and, on a tunneled
    dev chip, paying a round trip per batch). ``submit`` instead just
    enqueues the jitted forward — decode of batch k+1, device compute of
    batch k, and the D2H of batch k-``depth`` all overlap — and ``finish``
    materializes every result in submit order.

    ``depth`` bounds how many un-materialized outputs may live on the device
    at once — exactly: the oldest output is drained *before* a new batch is
    dispatched when at capacity (matters for flow families, whose per-batch
    output is a full (B, H, W, 2) field). 0 means synchronous: each submit
    materializes its result before returning.

    ``callback(feats, ctx)`` (optional) fires at materialization time, in
    submit order, with the valid rows and the ``ctx`` passed to ``submit`` —
    how show_pred paths get per-batch host values (with depth=0 to keep the
    reference's print-as-you-go behavior) without a second code path in the
    extractors.
    """

    def __init__(self, runner: Optional[DataParallelApply], depth: int = 4,
                 callback: Optional[Callable[[np.ndarray, Any], None]] = None):
        from collections import deque
        self.runner = runner
        self.depth = max(int(depth), 0)
        self.callback = callback
        self._inflight: Any = deque()  # (device_array, n_valid, ctx)
        self._done: List[np.ndarray] = []

    def submit(self, batch_np: np.ndarray, n_valid: Optional[int] = None,
               ctx: Any = None) -> None:
        n = batch_np.shape[0] if n_valid is None else n_valid
        while self._inflight and len(self._inflight) >= self.depth:
            self._pop()  # drain BEFORE dispatching: bound holds during _pop
        self.submit_device(self.runner.dispatch(batch_np), n, ctx)

    def submit_device(self, dev: jnp.ndarray, n_valid: int,
                      ctx: Any = None) -> None:
        """Enqueue an ALREADY-dispatched device array (multi-runner
        pipelines, e.g. i3d's per-stream chains, dispatch themselves); the
        stream still bounds retained results and materializes in order. A
        runner-less stream (``FeatureStream(None, ...)``) supports only this
        entry point."""
        if self.callback is None:
            ctx = None  # don't pin (possibly large) host batches in the queue
        while self._inflight and len(self._inflight) >= max(self.depth, 1):
            self._pop()
        self._inflight.append((dev, n_valid, ctx))
        if self.depth == 0:
            self._pop()

    def _pop(self) -> None:
        from ..utils.profiling import profiler
        out, n, ctx = self._inflight.popleft()
        # the blocking host copy: under the profiler this stage is the
        # pipeline's *stall* time on the device, not raw device time — by
        # design everything else already happened in the background
        with profiler.stage("forward"):
            feats = np.asarray(out)[:n]
        if self.callback is not None:
            self.callback(feats, ctx)
        self._done.append(feats)

    def finish(self) -> List[np.ndarray]:
        """Materialize all pending results; returns them in submit order."""
        while self._inflight:
            self._pop()
        done, self._done = self._done, []
        return done
