from .mesh import DataParallelApply, get_mesh, local_shard_of_list
