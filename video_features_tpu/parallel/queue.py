"""Work-stealing fleet queue: filesystem-coordinated dynamic work claims.

Static hash sharding (:func:`~.mesh.local_shard_of_list`) fixes every
video's owner at launch, so fleet makespan is the *slowest shard* — one
long video, one throttled host or one preempted worker idles every other
chip while it finishes, and membership cannot change mid-run. This module
replaces that with a dynamic queue coordinated purely through the shared
output filesystem (no new daemon, no coordinator): makespan approaches
``total_work / n_hosts`` instead of ``max(shard)``.

Layout, under the run's shared output root::

    {out_root}/_queue/
      pending/{item_id}.json            un-owned work items
      claimed/{host_id}/{item_id}.json  leased items (lease stamp inside)
      done/{item_id}.json               completion records (first writer wins)
      quarantined/{item_id}.json        pathological items (>= max_reclaims)
      .staging/                         reclaim-in-progress scratch
      canary/{host_id}/                 joining-host canary slice + verdict

**Claim discipline**: ``os.rename(pending/x, claimed/{me}/x)`` — atomic
on POSIX, a losing racer just sees ENOENT (the exact discipline the
serve.py request spool proved). After the rename the claimant owns the
file exclusively and stamps a lease ``{host_id, run_id, claim_time,
deadline, reclaims}`` with an atomic replace.

**Leases** are renewed from the existing telemetry heartbeat flusher
thread (:meth:`WorkQueue.heartbeat_section` is installed as a
``recorder.extra_sections`` hook, so every heartbeat tick both publishes
fleet state and pushes the deadlines of this host's active claims
forward). A host that dies — or stalls past its heartbeat — stops
renewing, and its leases expire.

**Stealing**: an idle host (:meth:`reclaim_expired`) scans other hosts'
claim dirs for leases that are past-deadline or whose owner's heartbeat
is stale/final, moves them back to ``pending/`` with ``reclaims`` bumped
(atomically, via a staging rename so two stealers cannot both requeue),
and claims them like any other item. An item reclaimed more than
``max_reclaims`` times is *pathological* — it has now taken down (or
outlived) several workers — and routes through the existing quarantine
journal (utils/faults.py) instead of being re-dispatched forever.

**Membership** is discovered, not configured: any process that seeds the
same list into the same queue root participates; hosts may join or leave
mid-run. Joining hosts can be gated by the **canary**
(:meth:`canary_gate`): re-extract a slice of already-completed work and
pass compare_runs.py digest bands + bench_history.py timing bands before
claiming freely — a bad binary/config on a new host fails its canary
instead of poisoning the run.

**Exactly-once extraction** is the layered contract: a video is always
represented in >= 1 of {pending, claimed, done}; completion writes the
``done/`` marker with ``O_EXCL`` (first writer wins) *before* the claim
is released; claimants discard a claim whose done marker already exists;
and the sinks' idempotent skip-if-exists + atomic writes are the final
backstop — duplicate *dispatch* (possible after a reclaim race) can never
become duplicate or torn *output*.

``fleet=static`` (the default) bypasses all of this and keeps the
hash-sharding behavior byte-identical. See docs/fleet.md.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
import uuid
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from .. import telemetry
from ..telemetry import trace
from ..telemetry.heartbeat import STALL_INTERVALS, heartbeat_filename
from ..telemetry.jsonl import write_json_atomic
from ..utils import inject

QUEUE_DIRNAME = "_queue"
PENDING, CLAIMED, DONE, QUARANTINED = ("pending", "claimed", "done",
                                       "quarantined")
STAGING = ".staging"
ITEM_SCHEMA = "vft.fleet_item/1"
DONE_SCHEMA = "vft.fleet_done/1"

#: orphaned staging files (a stealer died mid-reclaim) older than this
#: many lease periods are recovered back into pending/
STAGING_ORPHAN_LEASES = 4.0

#: canary timing band for a compile-warm joining host: the generous
#: default band exists to absorb cold-compile jitter, so a host whose
#: compile-cache fingerprint fully hit (compile_cache.py) is held to
#: this much tighter bar instead — it has no compile to pay
WARM_CANARY_BAND = 0.25


def _safe(name: str) -> str:
    """Filesystem-safe id (host ids embed hostnames, stems embed user
    filenames) — same sanitation as telemetry/heartbeat.py."""
    return re.sub(r"[^A-Za-z0-9._-]+", "-", str(name))


def item_id(video: str) -> str:
    """Stable, collision-free, filesystem-safe id for one work item:
    readable stem prefix + a hash of the full path (stems are unique
    within a run — sanity_check — but the hash keeps ids safe across
    runs that reuse the queue root with different directories)."""
    stem = _safe(Path(str(video)).stem)[:80]
    h = hashlib.md5(str(video).encode()).hexdigest()[:10]
    return f"{stem}-{h}"


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            rec = json.load(f)
        return rec if isinstance(rec, dict) else None
    except (OSError, ValueError):
        return None


class WorkQueue:
    """One host's handle on the shared fleet queue.

    ``clock`` is injectable so tests exercise lease expiry without
    sleeping; everything else is plain filesystem state, so N instances
    (threads, processes, or hosts on a shared mount) coordinate with no
    other channel.
    """

    def __init__(self, out_root: str, *, host_id: str,
                 run_id: Optional[str] = None,
                 lease_s: float = 60.0, max_reclaims: int = 3,
                 journal=None,
                 staging_retention_s: Optional[float] = None,
                 clock: Callable[[], float] = time.time) -> None:
        if float(lease_s) <= 0:
            raise ValueError(f"fleet_lease_s={lease_s}: need > 0")
        if int(max_reclaims) < 1:
            raise ValueError(f"fleet_max_reclaims={max_reclaims}: need >= 1")
        if staging_retention_s is not None and float(staging_retention_s) <= 0:
            raise ValueError(
                f"gc_staging_retention_s={staging_retention_s}: need > 0")
        self.out_root = str(out_root)
        self.root = os.path.join(self.out_root, QUEUE_DIRNAME)
        self.host_id = str(host_id)
        self.run_id = run_id
        self.lease_s = float(lease_s)
        self.max_reclaims = int(max_reclaims)
        # how long a .staging/ orphan may sit before recovery sweeps it
        # back to pending: the GC retention knob when set (gc.py), else
        # the legacy several-lease heuristic
        self.staging_retention_s = (
            float(staging_retention_s) if staging_retention_s is not None
            else STAGING_ORPHAN_LEASES * self.lease_s)
        self.journal = journal
        self.clock = clock
        self.host_dir = os.path.join(self.root, CLAIMED, _safe(self.host_id))
        for d in (PENDING, DONE, QUARANTINED, STAGING):
            os.makedirs(os.path.join(self.root, d), exist_ok=True)
        os.makedirs(self.host_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._active: Dict[str, dict] = {}  # item_id -> claim record
        self._tallies = {"claimed": 0, "stolen": 0, "reclaimed": 0,
                         "requeued": 0, "done": 0, "quarantined": 0,
                         "lease_lost": 0, "duplicate_discarded": 0}
        self._canary_state = "off"
        #: set by the driver when this host's compile-cache fingerprint
        #: fully hit at attach (compile_cache.py): the canary gate drops
        #: its cold-compile timing allowance, and the heartbeat fleet
        #: section records it for vft-fleet
        self.canary_warm = False
        #: cumulative seconds this host spent idle-waiting on other
        #: hosts' live leases (the drain loop's fleet.idle_wait spans,
        #: summed) — the stall-share input to the capacity planner
        #: (fleet_report.py CapacityPlanner)
        self._idle_wait_s = 0.0

    # -- path helpers -------------------------------------------------------
    def _p(self, *parts: str) -> str:
        return os.path.join(self.root, *parts)

    def _done_path(self, iid: str) -> str:
        return self._p(DONE, f"{iid}.json")

    def _claim_path(self, iid: str) -> str:
        return os.path.join(self.host_dir, f"{iid}.json")

    # -- seeding ------------------------------------------------------------
    def seed(self, videos: List[str]) -> int:
        """Idempotently publish the work list: every video not already
        pending/claimed/done/quarantined gets a ``pending/`` item
        (``O_EXCL``, so concurrent seeders never duplicate). Every host
        seeds the same list at startup — a late joiner recovers items a
        reclaimer lost mid-move, and already-finished work stays
        finished (claimants re-check the done marker, see claim_next)."""
        added = 0
        for video in videos:
            iid = item_id(video)
            if os.path.exists(self._done_path(iid)) or \
                    os.path.exists(self._p(QUARANTINED, f"{iid}.json")) or \
                    self._claimed_anywhere(iid):
                continue
            rec = {"schema": ITEM_SCHEMA, "id": iid, "video": str(video),
                   "reclaims": 0, "seeded_by": self.host_id,
                   "time": round(self.clock(), 3)}
            try:
                # O_EXCL create, not rename-into-place: a rename would
                # clobber a concurrent seeder's (or requeuer's) item
                fd = os.open(self._p(PENDING, f"{iid}.json"),
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                # vft-lint: disable=VFT004 — O_EXCL create IS the atomicity: a rename would clobber a concurrent seeder; a torn record is healed by the idempotent re-seed
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    json.dump(rec, f)
                added += 1
            except FileExistsError:
                pass
        return added

    def _claimed_anywhere(self, iid: str) -> bool:
        claimed_root = self._p(CLAIMED)
        try:
            hosts = os.listdir(claimed_root)
        except OSError:
            return False
        return any(os.path.exists(os.path.join(claimed_root, h,
                                               f"{iid}.json"))
                   for h in hosts)

    # -- claiming -----------------------------------------------------------
    def claim_next(self) -> Optional[dict]:
        """Claim the first pending item (name order, so seed order — the
        operator can front-load known-long videos). Returns the stamped
        claim record, or None when nothing is claimable."""
        try:
            names = sorted(n for n in os.listdir(self._p(PENDING))
                           if n.endswith(".json"))
        except OSError:
            return None
        for name in names:
            src = self._p(PENDING, name)
            dst = os.path.join(self.host_dir, name)
            with trace.span("fleet.claim", item=name[:-len(".json")]):
                try:
                    # chaos hooks (utils/inject.py): `queue.claim=eio`
                    # fails the rename like a lost race; `skew` stamps an
                    # already-expired lease (a claimant whose clock — or
                    # whose renewals — lag the fleet's), making the claim
                    # immediately stealable while this host still works it
                    fault = inject.fire("queue.claim",
                                        item=name[:-len(".json")])
                    os.rename(src, dst)
                except OSError:
                    continue  # another host won this item; try the next
                rec = _read_json(dst) or {"id": name[:-len(".json")],
                                          "video": None, "reclaims": 0}
                iid = str(rec.get("id") or name[:-len(".json")])
                if os.path.exists(self._done_path(iid)):
                    # re-seed race lost to a completed item: the done
                    # marker is ground truth — discard, never re-extract
                    try:
                        os.unlink(dst)
                    except OSError:
                        pass
                    with self._lock:
                        self._tallies["duplicate_discarded"] += 1
                    continue
                stolen = int(rec.get("reclaims", 0)) > 0 and \
                    rec.get("last_owner") not in (None, self.host_id)
                now = self.clock()
                deadline = now + self.lease_s
                if fault is not None and fault.kind == "skew":
                    deadline = now - self.lease_s  # already expired
                rec.update(host_id=self.host_id, run_id=self.run_id,
                           claim_time=round(now, 3),
                           deadline=round(deadline, 3))
                # request correlation (telemetry/context.py): a lease
                # claimed on behalf of a spool request carries its id, so
                # the claim/steal/quarantine trail of a request's videos
                # is retrievable too; absent outside serve mode
                rid = telemetry.current_request_id()
                if rid is not None:
                    rec["request_id"] = rid
                write_json_atomic(dst, rec)
            with self._lock:
                self._active[iid] = rec
                self._tallies["claimed"] += 1
                if stolen:
                    self._tallies["stolen"] += 1
            telemetry.inc("vft_fleet_claimed_total")
            if stolen:
                telemetry.inc("vft_fleet_stolen_total")
                trace.instant("fleet.steal", item=iid,
                              prev_owner=str(rec.get("last_owner")),
                              reclaims=int(rec.get("reclaims", 0)))
            return rec
        return None

    # -- lease maintenance --------------------------------------------------
    def renew_leases(self) -> None:
        """Push this host's active lease deadlines forward. Called from
        the heartbeat flusher thread (via :meth:`heartbeat_section`) —
        a live host's leases therefore never expire, and a dead/stalled
        host's expire within one lease period."""
        with self._lock:
            active = dict(self._active)
        now = self.clock()
        for iid, rec in active.items():
            path = self._claim_path(iid)
            if not os.path.exists(path):
                # stolen from under us (we stalled past the lease and
                # somebody reclaimed): drop it — complete() re-checks too
                with self._lock:
                    if self._active.pop(iid, None) is not None:
                        self._tallies["lease_lost"] += 1
                continue
            rec = dict(rec, deadline=round(now + self.lease_s, 3))
            write_json_atomic(path, rec)
            with self._lock:
                if iid in self._active:
                    self._active[iid] = rec

    def _owner_stale(self, host_dirname: str,
                     hb_cache: Dict[str, Optional[dict]]) -> bool:
        """True when a claim-dir owner's heartbeat says it cannot renew:
        missing (never started telemetry — impossible for a live queue
        participant), marked final, or silent past the stall window."""
        if host_dirname not in hb_cache:
            hb_cache[host_dirname] = _read_json(
                os.path.join(self.out_root, heartbeat_filename(host_dirname)))
        hb = hb_cache[host_dirname]
        if hb is None:
            return True
        if hb.get("final"):
            return True
        interval = float(hb.get("interval_s", 30.0) or 30.0)
        age = self.clock() - float(hb.get("time", 0))
        return age > STALL_INTERVALS * interval

    def reclaim_expired(self) -> int:
        """Steal back expired leases: every claim whose deadline passed,
        or whose owner's heartbeat is stale/final, goes back to
        ``pending/`` with ``reclaims`` bumped — unless it has been
        reclaimed ``max_reclaims`` times already, in which case it is
        quarantined as pathological. Returns the number of items made
        claimable again."""
        requeued = 0
        hb_cache: Dict[str, Optional[dict]] = {}
        claimed_root = self._p(CLAIMED)
        try:
            hosts = [h for h in os.listdir(claimed_root)
                     if h != _safe(self.host_id)]
        except OSError:
            hosts = []
        now = self.clock()
        for host in hosts:
            hdir = os.path.join(claimed_root, host)
            try:
                names = [n for n in os.listdir(hdir) if n.endswith(".json")]
            except OSError:
                continue
            for name in names:
                path = os.path.join(hdir, name)
                rec = _read_json(path)
                if rec is None:
                    continue  # mid-stamp or torn; next scan decides
                deadline = rec.get("deadline")
                expired = deadline is not None and float(deadline) < now
                if not expired and not self._owner_stale(host, hb_cache):
                    continue
                if self._requeue(path, rec, reason="lease expired"
                                 if expired else "owner heartbeat stale"):
                    requeued += 1
        requeued += self._sweep_staging(now)
        return requeued

    def _requeue(self, claimed_path: str, rec: dict, *,
                 reason: str, bump: bool = True) -> bool:
        """Atomically move one claim back to pending (or quarantine).
        The staging rename is the mutual exclusion: exactly one stealer
        wins the source file."""
        iid = str(rec.get("id") or Path(claimed_path).stem)
        staging = self._p(STAGING, f"{uuid.uuid4().hex[:8]}.{iid}.json")
        try:
            os.rename(claimed_path, staging)
        except OSError:
            return False  # another stealer (or the owner's unlink) won
        # chaos hook: the stealer "dies" exactly between the two renames
        # (`drop` abandons the item in .staging/, which ONLY the orphan
        # sweep can recover; `kill` is the real SIGKILL for subprocess
        # chaos runs) — the narrowest window in the steal protocol
        fault = inject.fire("queue.steal_staging", item=iid)
        if fault is not None and fault.kind == "drop":
            return False
        prev_owner = rec.get("host_id")
        reclaims = int(rec.get("reclaims", 0)) + (1 if bump else 0)
        rec = {"schema": ITEM_SCHEMA, "id": iid, "video": rec.get("video"),
               "reclaims": reclaims, "last_owner": prev_owner,
               "seeded_by": rec.get("seeded_by"),
               "time": round(self.clock(), 3)}
        if bump:
            with self._lock:
                self._tallies["reclaimed"] += 1
            telemetry.inc("vft_fleet_reclaimed_total")
            trace.instant("fleet.reclaim", item=iid,
                          prev_owner=str(prev_owner), reason=reason,
                          reclaims=reclaims)
        if bump and reclaims > self.max_reclaims:
            self._quarantine(rec, staging)
            return False  # off the queue, not claimable
        write_json_atomic(self._p(PENDING, f"{iid}.json"), rec)
        with self._lock:
            self._tallies["requeued"] += 1
        telemetry.inc("vft_fleet_requeued_total")
        try:
            os.unlink(staging)
        except OSError:
            pass
        return True

    def _quarantine(self, rec: dict, staging: str) -> None:
        """Route a pathological item (reclaimed past the cap — it has
        repeatedly outlived or taken down its claimants) through the
        existing quarantine machinery: a queue-level marker plus a
        POISON record in the failure journal, so restarted workers and
        ``retry_failed=true`` follow the PR-1 contract unchanged."""
        iid = str(rec.get("id"))
        write_json_atomic(self._p(QUARANTINED, f"{iid}.json"), rec)
        with self._lock:
            self._tallies["quarantined"] += 1
        telemetry.inc("vft_fleet_quarantined_total")
        trace.instant("fleet.quarantine", item=iid,
                      reclaims=int(rec.get("reclaims", 0)))
        if self.journal is not None and rec.get("video"):
            try:
                from ..utils.faults import POISON
                self.journal.record(
                    rec["video"], POISON, attempts=int(rec["reclaims"]),
                    error=f"fleet: lease reclaimed {rec['reclaims']}x "
                          f"(> fleet_max_reclaims={self.max_reclaims}); "
                          "item repeatedly killed or outlived its workers",
                    elapsed_s=0.0)
            except Exception:
                pass  # the quarantine marker alone still blocks re-dispatch
        try:
            os.unlink(staging)
        except OSError:
            pass

    def _sweep_staging(self, now: float) -> int:
        """Recover items a stealer lost mid-reclaim (died between the
        staging rename and the pending write): anything in .staging/
        older than ``staging_retention_s`` (the GC retention knob, or a
        several-lease default) goes back to pending unless its done
        marker exists."""
        recovered = 0
        try:
            names = [n for n in os.listdir(self._p(STAGING))
                     if n.endswith(".json")]
        except OSError:
            return 0
        for name in names:
            path = self._p(STAGING, name)
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                continue
            if age < self.staging_retention_s:
                continue
            rec = _read_json(path)
            if rec is None or not rec.get("id"):
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            if os.path.exists(self._done_path(str(rec["id"]))):
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            if self._requeue(path, rec, reason="staging orphan",
                             bump=False):
                recovered += 1
        return recovered

    # -- completion / release -----------------------------------------------
    def complete(self, rec: dict, status: str, *,
                 elapsed_s: Optional[float] = None,
                 families: Optional[Dict[str, str]] = None) -> bool:
        """Publish one item's completion. First writer wins (``O_EXCL``):
        if another host finished a stolen copy first, this host's result
        is identical anyway (idempotent sinks) and only the marker race
        is lost. Returns True when this host's record became the
        marker."""
        iid = str(rec.get("id"))
        done = {"schema": DONE_SCHEMA, "id": iid,
                "video": rec.get("video"), "status": str(status),
                "by": self.host_id, "run_id": self.run_id,
                "reclaims": int(rec.get("reclaims", 0)),
                "time": round(self.clock(), 3)}
        if elapsed_s is not None:
            done["elapsed_s"] = round(float(elapsed_s), 3)
        if families:
            done["families"] = dict(families)
        won = True
        try:
            fd = os.open(self._done_path(iid),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            # vft-lint: disable=VFT004 — done markers are O_EXCL first-writer-wins (exactly-once contract); vft-audit tolerates a torn marker body, existence is the signal
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(done, f)
        except FileExistsError:
            won = False
        with self._lock:
            self._active.pop(iid, None)
            self._tallies["done" if won else "lease_lost"] += 1
        try:
            os.unlink(self._claim_path(iid))
        except OSError:
            pass
        return won

    def release(self, rec: dict) -> None:
        """Voluntarily hand a claim back (SIGTERM drain, escaped driver
        exception): the item returns to pending WITHOUT a reclaim bump —
        a graceful exit is not a pathology signal."""
        iid = str(rec.get("id"))
        with self._lock:
            self._active.pop(iid, None)
        path = self._claim_path(iid)
        if os.path.exists(path):
            self._requeue(path, rec, reason="released", bump=False)

    def release_all(self) -> int:
        with self._lock:
            active = list(self._active.values())
        for rec in active:
            self.release(rec)
        return len(active)

    # -- state --------------------------------------------------------------
    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for d in (PENDING, DONE, QUARANTINED):
            try:
                out[d] = sum(1 for n in os.listdir(self._p(d))
                             if n.endswith(".json"))
            except OSError:
                out[d] = 0
        claimed = 0
        try:
            for h in os.listdir(self._p(CLAIMED)):
                try:
                    claimed += sum(
                        1 for n in os.listdir(self._p(CLAIMED, h))
                        if n.endswith(".json"))
                except OSError:
                    pass
        except OSError:
            pass
        out[CLAIMED] = claimed
        return out

    def all_done(self) -> bool:
        c = self.counts()
        return c[PENDING] == 0 and c[CLAIMED] == 0

    def live_hosts(self) -> List[str]:
        """Queue membership right now: host_ids with a fresh, non-final
        heartbeat in the output root (joiners appear, leavers age out —
        nothing is fixed at launch)."""
        import glob as _glob
        out = []
        now = self.clock()
        for p in _glob.glob(os.path.join(self.out_root,
                                         "_heartbeat_*.json")):
            hb = _read_json(p)
            if hb is None or hb.get("final"):
                continue
            interval = float(hb.get("interval_s", 30.0) or 30.0)
            if now - float(hb.get("time", 0)) <= STALL_INTERVALS * interval:
                out.append(str(hb.get("host_id")))
        return sorted(out)

    def heartbeat_section(self) -> dict:
        """The ``fleet`` heartbeat section AND the lease-renewal tick:
        installed as a ``recorder.extra_sections`` hook so the existing
        heartbeat flusher thread keeps this host's claims alive and
        publishes fleet state in one atomic heartbeat write."""
        self.renew_leases()
        with self._lock:
            tallies = dict(self._tallies)
            active = dict(self._active)
        now = self.clock()
        oldest = max((now - float(r.get("claim_time", now))
                      for r in active.values()), default=0.0)
        with self._lock:
            idle_s = self._idle_wait_s
        return {"mode": "queue", "lease_s": self.lease_s,
                "host_id": self.host_id,
                "active_claims": len(active),
                "oldest_active_claim_age_s": round(oldest, 3),
                "queue": self.counts(), "canary": self._canary_state,
                "canary_warm": bool(self.canary_warm),
                "idle_wait_s_total": round(idle_s, 3),
                **tallies}

    # -- the drain loop ------------------------------------------------------
    def drain(self, run_fn: Callable[[str], str], *, workers: int = 1,
              stop: Optional[threading.Event] = None, poll_s: float = 0.5,
              on_complete: Optional[Callable[[dict, str], None]] = None
              ) -> None:
        """Claim -> extract -> complete until the queue is drained
        fleet-wide. ``run_fn(video) -> status`` ('done'/'skipped'/
        'error'/'quarantined', or 'dropped' when preempted — dropped
        items are released, not completed). When pending is empty this
        host steals expired leases; when other hosts still hold live
        leases it idle-waits (the per-host idle tail
        ``fleet.idle_wait`` spans make visible in trace_report.py)."""
        stop = stop if stop is not None else threading.Event()
        errors: List[BaseException] = []

        def loop() -> None:
            while not stop.is_set():
                rec = self.claim_next()
                if rec is None:
                    if self.reclaim_expired() > 0:
                        continue
                    if self.all_done():
                        return
                    with trace.span("fleet.idle_wait"):
                        t_idle = time.perf_counter()
                        stop.wait(poll_s)
                        with self._lock:
                            self._idle_wait_s += \
                                time.perf_counter() - t_idle
                    continue
                video = rec.get("video")
                t0 = time.perf_counter()
                try:
                    status = run_fn(str(video))
                except BaseException as e:
                    # an ESCAPED exception is a driver bug, not a video
                    # verdict: hand the item back for another host
                    self.release(rec)
                    errors.append(e)
                    return
                if status == "dropped":
                    self.release(rec)
                    return
                self.complete(rec, status,
                              elapsed_s=time.perf_counter() - t0)
                if on_complete is not None:
                    on_complete(rec, status)

        if workers <= 1:
            loop()
        else:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=workers,
                                    thread_name_prefix="vft-fleet") as pool:
                for f in [pool.submit(loop) for _ in range(workers)]:
                    f.result()
        if errors:
            raise errors[0]

    # -- canary gating -------------------------------------------------------
    def canary_gate(self, extract_fn, *, slice_n: int = 2,
                    band: float = 1.0, atol: float = 1e-2,
                    rtol: float = 0.02) -> Tuple[bool, List[str]]:
        """Gate a joining host before it may claim freely: re-extract up
        to ``slice_n`` videos that OTHER hosts already completed into a
        private canary dir, then hold the results against

          - **compare_runs.py digest bands**: the canary's feature
            digests (health=true) must sit inside the same atol/rtol
            bands compare_runs applies between runs — a new binary or
            config that drifts the numerics fails here, on throwaway
            output, instead of inside the shared run;
          - **bench_history.py timing bands**: the canary's best
            seconds-per-video vs the fleet's recorded times for the same
            videos, through check_regressions' banding (generous default
            ``band=1.0`` = 2x: a joining host pays cold compiles).

        ``extract_fn(video, out_dir) -> (status, elapsed_s)`` is supplied
        by the driver (cli.py builds a cache-disabled extractor pointed
        at the canary dir). A founding member — no completed work by
        other hosts yet — passes trivially: there is nothing to compare
        against, and the run-level health gates still apply."""
        self._canary_state = "running"
        lines: List[str] = []
        if self.canary_warm:
            # warm fast path (compile_cache.py): the generous default
            # timing band exists to absorb a joining host's cold XLA
            # compiles; a fully-hit compile-cache fingerprint means there
            # are none to absorb, so the re-compile allowance is skipped
            # and the gate is held to the tight band instead
            band = min(float(band), WARM_CANARY_BAND)
            lines.append("fleet canary: compile cache warm (fingerprint "
                         "fully hit) — cold-compile allowance removed, "
                         f"timing band tightened to {band:.0%}")
        sample = []
        try:
            names = sorted(n for n in os.listdir(self._p(DONE))
                           if n.endswith(".json"))
        except OSError:
            names = []
        for name in names:
            rec = _read_json(self._p(DONE, name))
            if rec is None or rec.get("by") == self.host_id:
                continue
            if rec.get("status") != "done" or not rec.get("video"):
                continue
            if os.path.exists(str(rec["video"])):
                sample.append(rec)
        if not sample:
            self._canary_state = "founding"
            return True, ["fleet canary: founding member — no completed "
                          "work by other hosts yet, claims open"]
        sample = sample[-int(slice_n):]
        # fresh subdir per attempt: a rerun must re-extract, not ride the
        # sinks' skip-if-exists over a previous attempt's output
        canary_dir = self._p("canary", _safe(self.host_id),
                             uuid.uuid4().hex[:8])
        os.makedirs(canary_dir, exist_ok=True)
        results = []
        for rec in sample:
            with trace.span("fleet.canary", item=str(rec.get("id"))):
                status, elapsed = extract_fn(str(rec["video"]), canary_dir)
            results.append((rec, status, elapsed))
            lines.append(f"fleet canary: {Path(str(rec['video'])).name} -> "
                         f"{status} in {elapsed:.2f}s (fleet did it in "
                         f"{rec.get('elapsed_s', '?')}s)")
        ok = all(status == "done" for _, status, _ in results)
        if not ok:
            lines.append("fleet canary: FAILED — canary extraction did not "
                         "complete cleanly")
        ok = self._canary_digests(canary_dir, atol, rtol, lines) and ok
        ok = self._canary_timing(canary_dir, results, band, lines) and ok
        verdict = {"schema": "vft.fleet_canary/1", "host_id": self.host_id,
                   "run_id": self.run_id, "ok": bool(ok),
                   "canary_warm": bool(self.canary_warm),
                   "videos": [str(r.get("video")) for r, _, _ in results],
                   "time": round(self.clock(), 3), "lines": lines}
        write_json_atomic(self._p("canary", f"{_safe(self.host_id)}.json"),
                          verdict)
        self._canary_state = "passed" if ok else "failed"
        return ok, lines

    def _load_fleet_health(self) -> Dict[Tuple[str, str, str], dict]:
        """The fleet's digests, EXCLUDING everything under the queue dir
        (canary output lives there — comparing it against itself would
        make the gate vacuous)."""
        from ..telemetry.health import HEALTH_FILENAME
        from ..telemetry.jsonl import read_jsonl
        qroot = Path(self.root).resolve()
        out: Dict[Tuple[str, str, str], dict] = {}
        for path in sorted(Path(self.out_root).rglob(HEALTH_FILENAME)):
            if qroot in path.resolve().parents:
                continue
            for rec in read_jsonl(path):
                k = (os.path.basename(str(rec.get("video"))),
                     str(rec.get("feature_type")), str(rec.get("key")))
                out[k] = rec
        return out

    def _canary_digests(self, canary_dir: str, atol: float, rtol: float,
                        lines: List[str]) -> bool:
        cr = _load_script("compare_runs")
        if cr is None:
            lines.append("fleet canary: compare_runs.py unavailable "
                         "(installed package without scripts/) — digest "
                         "gate skipped")
            return True
        da = self._load_fleet_health()
        db: Dict[Tuple[str, str, str], dict] = cr.load_health(canary_dir)
        fails, infos, n = cr.compare_digests(da, db, atol, rtol)
        if n == 0:
            lines.append("fleet canary: no overlapping health digests "
                         "(run with health=true fleet-wide for digest "
                         "gating) — digest gate vacuous")
            return True
        lines += [f"fleet canary: DIGEST DRIFT {x}" for x in fails]
        lines.append(f"fleet canary: {n} digest(s) compared against "
                     f"compare_runs bands (atol={atol}, rtol={rtol}) — "
                     + ("PASS" if not fails else "FAIL"))
        return not fails

    def _canary_timing(self, canary_dir: str, results, band: float,
                       lines: List[str]) -> bool:
        bh = _load_script("bench_history")
        fleet_times = [float(r.get("elapsed_s", 0) or 0)
                       for r, _, _ in results]
        my_times = [float(e) for _, status, e in results
                    if status in ("done", "skipped")]
        fleet_times = [t for t in fleet_times if t > 0]
        if bh is None or not fleet_times or not my_times:
            lines.append("fleet canary: timing gate skipped "
                         "(no comparable timings or bench_history.py "
                         "unavailable)")
            return True
        fleet_med = sorted(fleet_times)[len(fleet_times) // 2]
        # best canary video: the first one carries this host's cold
        # compile/weights tax, which is a join cost, not a speed verdict
        mine = min(my_times)
        hist = os.path.join(canary_dir, "_canary_history.jsonl")
        try:
            os.unlink(hist)
        except OSError:
            pass
        from ..telemetry.jsonl import append_jsonl
        metric = "fleet canary seconds per video"
        for rnd, val, src in ((1, fleet_med, "fleet"),
                              (2, mine, self.host_id)):
            append_jsonl(hist, {
                "schema": bh.SCHEMA_VERSION, "round": rnd, "source": src,
                "recorded_time": round(self.clock(), 3),
                "headline": {"metric": metric, "value": round(val, 3),
                             "unit": "seconds per video",
                             "vs_baseline": None},
                "metrics": []})
        regressions, rep = bh.check_regressions(hist, band)
        lines += [f"fleet canary: {x}" for x in rep[1:]]
        lines.append(f"fleet canary: timing band ({band:.0%}) via "
                     "bench_history check — "
                     + ("PASS" if not regressions else "FAIL"))
        return not regressions


def _load_script(name: str):
    """Import a repo-root scripts/ module (compare_runs, bench_history)
    from a checkout; None when the package is installed without them —
    canary gates degrade loudly, they never crash the run."""
    import importlib.util
    path = Path(__file__).resolve().parents[2] / "scripts" / f"{name}.py"
    if not path.exists():
        return None
    try:
        spec = importlib.util.spec_from_file_location(f"_vft_{name}", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        return None
