"""Shared-decode fan-out: ONE decode pass per video feeding N families.

The reference toolkit (and this CLI until now) runs one model family per
invocation, so extracting the common resnet+clip+s3d+vggish bundle for a
corpus pays the full video decode cost once PER FAMILY — and on real
hosts decode is the wall (docs/performance.md: ~3.2 ms/frame of cv2
decode vs ~0.36 ms of transform; the sustained r21d pipeline is
decode-bound at 19.2 clips/s while the chip sustains ~1,515). This
module amortizes one decode pass across every requested consumer:

  :class:`FrameBus`
      One video's single decoder (utils/io.py ``_FrameStream``, the same
      missing-frame-0 workaround and grab()-skip economy as the serial
      path) walking the UNION of all subscribers' frame-selection plans.
      Each subscriber's plan is computed with the very
      ``plan_frame_selection``/``fps_filter_map`` walk ``VideoSource``
      uses, so a source frame needed by any family is decoded exactly
      once and every family's delivered (frame, timestamp, index) stream
      is bit-identical to what its own private ``VideoSource`` would
      have produced (pinned by tests/test_multi_family.py). Frames decode
      in native BGR; the RGB reorder happens at most once per frame no
      matter how many subscribers want RGB.

  :class:`SharedFrameSource`
      A subscriber's end of the bus, with the ``VideoSource`` observable
      surface (``fps``/``num_frames``/``frames()``/batched ``__iter__``/
      thread-safe ``cancel()``), drawing raw frames from a bounded queue
      (backpressure: the decoder blocks when a family falls behind,
      bounding host memory at ``depth`` frames per family) and applying
      the family's own host transform on the family's thread — so N
      transforms and N families' device programs are all in flight
      concurrently over one decode. A closed/cancelled subscriber is
      skipped by the bus, never wedging the other families (per-family
      fault isolation).

  :class:`SharedDecodeSession`
      The per-(video, run) umbrella the MultiExtractor installs
      thread-locally on each family's thread (:func:`use_session`):
      visual families reach the bus through
      ``BaseExtractor.video_source``; audio families share one wav rip
      (vggish) instead of re-running ffmpeg per family.

Subscription protocol: the bus is constructed with the set of expected
families; each family either ``subscribe()``\\ s (blocking until every
expected family has arrived, then returning a fully-probed source) or is
marked ``done()`` (skipped / failed before subscribing), and decode
starts once all have arrived. A family retrying after a mid-stream
failure gets ``None`` from ``subscribe`` (the one-shot pass has already
flowed) and falls back to a private ``VideoSource`` — isolation over
sharing for the rare retry.
"""
from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .. import telemetry
from ..telemetry import trace
from ..utils.faults import DeadlineExceeded
from ..utils.io import (CHANNEL_ORDERS, _batched, _FrameStream,
                        convert_decoded, count_frames_by_decode,
                        get_video_props, plan_frame_selection)

#: default per-subscriber queue depth (raw decoded frames; a 320x240
#: frame is ~230 KB, so the default bounds each family at ~15 MB)
DEFAULT_DEPTH = 64

_tls = threading.local()


def current_session() -> Optional["SharedDecodeSession"]:
    """The shared-decode session installed on THIS thread, if any."""
    return getattr(_tls, "session", None)


@contextmanager
def use_session(session: Optional["SharedDecodeSession"]) -> Iterator[None]:
    """Install ``session`` thread-locally for a block — how the
    MultiExtractor's per-family threads route ``video_source``/wav-rip
    calls to the shared pass without changing extractor signatures."""
    prev = getattr(_tls, "session", None)
    _tls.session = session
    try:
        yield
    finally:
        _tls.session = prev


class SharedFrameSource:
    """One family's subscription: the consumer half mimics ``VideoSource``.

    Constructed by :meth:`FrameBus.subscribe`; plan fields (``fps``,
    ``index_map``, ``num_frames``, source props) are filled in by the bus
    before ``subscribe`` returns, so extractors can read them exactly as
    they would off a private source.
    """

    def __init__(self, bus: "FrameBus", family: str, *, batch_size: int = 1,
                 fps: Optional[float] = None, total: Optional[int] = None,
                 transform: Optional[Callable] = None, overlap: int = 0,
                 channel_order: str = "rgb", depth: int = DEFAULT_DEPTH):
        import queue as _queue
        assert isinstance(batch_size, int) and batch_size > 0
        assert isinstance(overlap, int) and 0 <= overlap < batch_size
        assert channel_order in CHANNEL_ORDERS, channel_order
        if fps is not None and total is not None:
            raise ValueError("'fps' and 'total' are mutually exclusive")
        self.bus = bus
        self.family = str(family)
        self.path = bus.path
        self.batch_size = batch_size
        self.overlap = overlap
        self.transform = transform
        self.channel_order = channel_order
        self._want_fps = None if fps is None else float(fps)
        self._want_total = None if total is None else int(total)
        self.queue: "_queue.Queue" = _queue.Queue(maxsize=max(int(depth), 2))
        self.closed = False
        self._cancelled = False
        self._cancel_reason = ""
        self._error: Optional[str] = None
        #: ms of shared decode wall time that had run when this family's
        #: stream completed — the telemetry attribution field
        #: (``decode_shared_ms`` on the family's video span)
        self.decode_shared_ms: Optional[float] = None
        #: cumulative backpressure seconds, both directions: the decoder
        #: blocked on THIS family's full queue (put_blocked — this family
        #: is the slow consumer holding everyone back) vs this family
        #: blocked on an empty queue (get_starved — decode is the wall).
        #: Mirrored into vft_fanout_*_ms_total{family=} counters and the
        #: heartbeat "fanout" section; stalls past trace.STALL_MIN_S also
        #: become timeline events.
        self.put_blocked_s = 0.0
        self.get_starved_s = 0.0
        # plan fields, set by the bus at finalize time
        self.fps: float = 0.0
        self.index_map: Optional[np.ndarray] = None
        self.num_frames: int = 0
        self.src_fps: float = 0.0
        self.src_num_frames: int = 0
        self.height = self.width = 0

    # -- bus side -----------------------------------------------------------
    def _set_plan(self, out_fps: float, index_map: Optional[np.ndarray],
                  num_frames: int, src_fps: float, src_num_frames: int,
                  height: int, width: int) -> None:
        self.fps = out_fps
        self.index_map = index_map
        self.num_frames = num_frames
        self.src_fps = src_fps
        self.src_num_frames = src_num_frames
        self.height, self.width = height, width

    def _push(self, item) -> bool:
        """Bounded put that gives up when this subscriber is gone — one
        family abandoning its stream must never wedge the bus (and
        thereby every other family). A put that found the queue full is
        backpressure — the decoder outran this family — and is accounted
        as put-blocked time (counter + trace span + depth gauge)."""
        import queue as _queue
        try:
            # uncontended fast path: a non-full queue costs no timing call
            self.queue.put_nowait(item)
            telemetry.gauge_set("vft_fanout_queue_depth",
                                self.queue.qsize(), family=self.family)
            return True
        except _queue.Full:
            pass
        t0 = time.perf_counter()
        ok = False
        while not self.closed:
            try:
                self.queue.put(item, timeout=0.1)
                ok = True
                break
            except _queue.Full:
                continue
        self._account_put_blocked(t0)
        if ok:
            telemetry.gauge_set("vft_fanout_queue_depth",
                                self.queue.qsize(), family=self.family)
        return ok

    def _account_put_blocked(self, t0: float) -> None:
        dt = time.perf_counter() - t0
        self.put_blocked_s += dt
        telemetry.inc("vft_fanout_put_blocked_ms_total", dt * 1e3,
                      family=self.family)
        tr = trace.active()
        if tr is not None and dt >= trace.STALL_MIN_S:
            tr.complete("fanout.put_blocked", t0, dt, family=self.family)
            tr.counter(f"fanout_queue_depth/{self.family}",
                       self.queue.qsize())

    # -- consumer side ------------------------------------------------------
    def __len__(self) -> int:
        return self.num_frames

    def _raise_if_cancelled(self) -> None:
        if self._cancelled:
            raise DeadlineExceeded(f"{self.path}: {self._cancel_reason}")

    def frames(self) -> Iterator[Tuple[np.ndarray, float, int]]:
        """(frame, timestamp_ms, out_index) with the family's transform
        applied on THIS thread — same contract as VideoSource.frames()."""
        import queue as _queue

        from ..utils.profiling import profiler
        tf = self.transform
        try:
            while True:
                self._raise_if_cancelled()
                t_wait = time.perf_counter()
                while True:
                    try:
                        # 1s poll (not one long get) bounds how stale the
                        # cancellation/liveness checks can be
                        tag, payload = self.queue.get(timeout=1.0)
                        break
                    except _queue.Empty:
                        self._raise_if_cancelled()
                        t = self.bus._thread
                        if t is not None and t.is_alive():
                            continue
                        # the bus may have flushed its tail and exited
                        # between the timeout and the liveness check:
                        # drain first
                        try:
                            tag, payload = self.queue.get_nowait()
                            break
                        except _queue.Empty:
                            err = self._error
                            raise RuntimeError(
                                f"shared decode for {self.path} " +
                                (f"failed: {err}" if err
                                 else "died without a result")) from None
                # time spent inside get() is time THIS family sat idle
                # waiting on the shared decoder (starvation)
                waited = time.perf_counter() - t_wait
                self.get_starved_s += waited
                telemetry.inc("vft_fanout_get_starved_ms_total",
                              waited * 1e3, family=self.family)
                tr = trace.active()
                if tr is not None and waited >= trace.STALL_MIN_S:
                    tr.complete("fanout.get_starved", t_wait, waited,
                                family=self.family)
                if tag == "frame":
                    raw, out_idx = payload
                    with profiler.stage("decode"):
                        x = tf(raw) if tf is not None else raw
                    yield x, out_idx / self.fps * 1000.0, out_idx
                elif tag == "done":
                    return
                else:
                    raise RuntimeError(
                        f"shared decode failed for {self.path}: {payload}")
        finally:
            self.close()

    def __iter__(self):
        return _batched(self.frames(), self.batch_size, self.overlap)

    def cancel(self, reason: str = "cancelled") -> None:
        """Thread-safe kill (deadline watchdog): closes only THIS
        family's subscription; the bus keeps serving the others."""
        self._cancel_reason = reason or "cancelled"
        self._cancelled = True
        self.close()

    def release(self) -> None:
        self.close()

    def close(self) -> None:
        """Mark abandoned and drain, so a bus blocked in a bounded put
        sees ``closed`` within its poll interval."""
        self.closed = True
        try:
            while True:
                self.queue.get_nowait()
        except Exception:
            pass


class FrameBus:
    """One shared decode pass over the union of N families' frame plans."""

    def __init__(self, path, expected_families: Sequence[str],
                 depth: int = DEFAULT_DEPTH):
        self.path = str(path)
        self.expected = frozenset(str(f) for f in expected_families)
        self.depth = int(depth)
        self._cond = threading.Condition()
        self._subs: Dict[str, SharedFrameSource] = {}
        self._done_families: set = set()
        self._finalizing = False
        self._plans_ready = False
        self._started = False
        self._probe_error: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        self._stream: Optional[_FrameStream] = None
        self._cancelled = False
        #: cumulative shared decode seconds (read/skip/cvtColor); written
        #: only by the decode thread, read for per-family attribution
        self._decode_s = 0.0

    # -- family-side API ----------------------------------------------------
    def subscribe(self, family: str, *, batch_size: int = 1,
                  fps: Optional[float] = None, total: Optional[int] = None,
                  transform: Optional[Callable] = None, overlap: int = 0,
                  channel_order: str = "rgb",
                  **unsupported) -> Optional[SharedFrameSource]:
        """Join the shared pass; blocks until every expected family has
        arrived and the plans are probed, then returns the source.

        Returns ``None`` (caller falls back to a private VideoSource)
        when: the family is not expected, it already subscribed once
        (retry attempts), decode already started, or the caller needs a
        knob the shared pass cannot honor (e.g. ``fps_mode=reencode`` —
        per-family lossy temp-file provenance cannot share one decode).
        """
        family = str(family)
        if any(v not in (None, "select", False) for v in
               unsupported.values()):
            return None
        with self._cond:
            if (family not in self.expected or family in self._subs
                    or family in self._done_families or self._started):
                return None
            sub = SharedFrameSource(
                self, family, batch_size=batch_size, fps=fps, total=total,
                transform=transform, overlap=overlap,
                channel_order=channel_order, depth=self.depth)
            self._subs[family] = sub
        # register with the calling attempt's fault context BEFORE the
        # barrier wait below: the per-video deadline watchdog must be able
        # to cancel a family blocked waiting for its siblings to arrive
        from ..utils import faults
        ctx = faults.current_context()
        if ctx is not None:
            ctx.register(sub)
        self._maybe_finalize()
        t_wait = time.perf_counter()
        with self._cond:
            while not self._plans_ready and self._probe_error is None \
                    and not sub._cancelled:
                self._cond.wait(0.1)
            waited = time.perf_counter() - t_wait
            tr = trace.active()
            if tr is not None and waited >= trace.STALL_MIN_S:
                # arrival-barrier stall: this family sat waiting for its
                # siblings to subscribe (or the probe to finish) — the
                # first suspect when a multi-family run's lanes start late
                tr.complete("fanout.subscribe_wait", t_wait, waited,
                            family=family)
            if sub._cancelled:
                sub._raise_if_cancelled()
            if self._probe_error is not None:
                # a fresh exception per waiter (sharing one instance across
                # N raising threads races traceback mutation); the embedded
                # type name keeps utils/faults.classify's marker logic
                # working exactly like the decode-worker protocol
                raise RuntimeError(f"shared decode probe failed for "
                                   f"{self.path}: {self._probe_error}")
        return sub

    def done(self, family: str) -> None:
        """Mark ``family`` as never-going-to-subscribe(-again): skipped,
        quarantined, failed before reaching the decoder, or finished.
        Idempotent; the barrier releases once every expected family has
        subscribed or is done."""
        family = str(family)
        with self._cond:
            if family in self._done_families:
                return
            self._done_families.add(family)
        self._maybe_finalize()

    def shared_ms(self, family: str) -> Optional[float]:
        sub = self._subs.get(str(family))
        return None if sub is None else sub.decode_shared_ms

    def cancel(self, reason: str = "cancelled") -> None:
        """Kill the whole pass (every family fails with
        DeadlineExceeded semantics via its own source cancel)."""
        self._cancelled = True
        with self._cond:
            subs = list(self._subs.values())
            stream = self._stream
            self._cond.notify_all()
        for s in subs:
            s.cancel(reason)
        if stream is not None:
            stream.release()

    # -- barrier + plan probing ---------------------------------------------
    def _all_arrived(self) -> bool:
        return self.expected <= (set(self._subs) | self._done_families)

    def _maybe_finalize(self) -> None:
        with self._cond:
            if self._finalizing or not self._all_arrived():
                return
            self._finalizing = True
            subs = list(self._subs.values())
        try:
            if subs:
                props = get_video_props(self.path)
                src_fps, n = props["fps"], props["num_frames"]
                if n <= 0:
                    # metadata lied; every plan (and truncation warning)
                    # needs a real count — same recount the serial
                    # resampling path performs
                    n = count_frames_by_decode(self.path)
                    if n == 0:
                        raise ValueError(
                            f"No decodable frames in {self.path}")
                for s in subs:
                    out_fps, index_map, num = plan_frame_selection(
                        src_fps, n, fps=s._want_fps, total=s._want_total)
                    s._set_plan(out_fps, index_map, num, src_fps, n,
                                props["height"], props["width"])
        except BaseException as e:
            with self._cond:
                self._probe_error = f"{type(e).__name__}: {e}"
                self._started = True  # no decode will run
                self._cond.notify_all()
            return
        with self._cond:
            self._plans_ready = True
            self._started = True
            self._cond.notify_all()
        if subs:
            self._thread = threading.Thread(
                target=self._decode, name="vft-fanout-decode", daemon=True)
            self._thread.start()

    # -- the single decode pass ---------------------------------------------
    def _finish_sub(self, sub: SharedFrameSource, emitted: int) -> None:
        sub.decode_shared_ms = round(self._decode_s * 1000.0, 3)
        sub._push(("done", emitted))

    def _decode(self) -> None:
        from ..utils.profiling import profiler
        subs = list(self._subs.values())
        ptrs = {s.family: 0 for s in subs}
        emitted = {s.family: 0 for s in subs}
        finished: set = set()
        t_pass = time.perf_counter()
        stream = _FrameStream(self.path, channel_order="bgr")
        with self._cond:
            self._stream = stream
        try:
            src_idx = 0
            while not self._cancelled:
                # union step: which open subscribers need THIS src frame,
                # and does anyone still need a future one?
                wants: List[Tuple[SharedFrameSource, List[int]]] = []
                pending = False
                for s in subs:
                    if s.family in finished or s.closed:
                        continue
                    if s.index_map is None:
                        # native delivery: every frame until EOF
                        wants.append((s, [src_idx]))
                        pending = True
                        continue
                    m = s.index_map
                    p = ptrs[s.family]
                    outs: List[int] = []
                    while p < len(m) and int(m[p]) == src_idx:
                        outs.append(p)  # duplication on upsampling
                        p += 1
                    ptrs[s.family] = p
                    if outs:
                        wants.append((s, outs))
                    if p < len(m):
                        pending = True
                if not wants and not pending:
                    break  # every plan satisfied
                t0 = time.perf_counter()
                with profiler.stage("decode"):
                    if wants:
                        frame = stream.read()
                        ok = frame is not None
                    else:
                        # nobody materializes this frame: grab()-skip it
                        # (decode only, no YUV->BGR conversion/copy)
                        ok = stream.skip()
                        frame = None
                self._decode_s += time.perf_counter() - t0
                if not ok:
                    break  # EOF (possibly before the plans: see below)
                if frame is not None:
                    # each delivery format ('rgb' reorder / 'i420' pack) is
                    # converted AT MOST ONCE per source frame no matter how
                    # many subscribers want it; 'bgr' shares the decoder's
                    # native buffer with zero conversion
                    by_order = {"bgr": frame}
                    for s, outs in wants:
                        if s.closed:
                            continue
                        arr = by_order.get(s.channel_order)
                        if arr is None:
                            t1 = time.perf_counter()
                            with profiler.stage("decode"):
                                arr = convert_decoded(frame, s.channel_order)
                            self._decode_s += time.perf_counter() - t1
                            by_order[s.channel_order] = arr
                        for out_idx in outs:
                            if not s._push(("frame", (arr, out_idx))):
                                break  # subscriber abandoned mid-frame
                            emitted[s.family] += 1
                    for s in subs:
                        if s.family in finished or s.closed \
                                or s.index_map is None:
                            continue
                        if ptrs[s.family] >= len(s.index_map):
                            finished.add(s.family)
                            self._finish_sub(s, emitted[s.family])
                src_idx += 1
            for s in subs:
                if s.family in finished:
                    continue
                if self._cancelled:
                    s.cancel("shared decode cancelled")
                    continue
                if s.index_map is not None \
                        and emitted[s.family] < len(s.index_map) \
                        and not s.closed:
                    # container metadata overstated the frame count; same
                    # truncation warning contract as the serial path
                    print(f"Warning: {self.path} ended after {src_idx} "
                          f"frames (metadata said {s.src_num_frames}); "
                          f"{s.family} emitted {emitted[s.family]}/"
                          f"{len(s.index_map)} resampled frames.")
                self._finish_sub(s, emitted[s.family])
        except BaseException as e:
            # the forwarded string keeps the exception's name AND message
            # (str(OSError) includes the strerror), so the subscribers'
            # classify() sees the same POISON/FATAL markers an inline
            # failure would — an injected ENOSPC inside the bus must not
            # soften into a retried TRANSIENT on the family side
            # (utils/faults.py _FATAL_MARKERS; utils/inject.py)
            msg = f"{type(e).__name__}: {e}"
            telemetry.inc("vft_fanout_decode_errors_total")
            for s in subs:
                if s.family in finished:
                    continue
                s._error = msg
                s._push(("error", msg))
        finally:
            with self._cond:
                self._stream = None
            stream.release()
            # one umbrella span over the whole union pass: on the bus
            # thread's lane it brackets the per-frame decode stage spans,
            # and its gaps ARE the put-blocked stalls
            trace.complete("fanout.decode_pass", t_pass,
                           time.perf_counter() - t_pass, video=self.path,
                           families=len(subs))


class SharedDecodeSession:
    """Per-(video, run) shared resources: the visual-family FrameBus and
    the one-rip-per-video wav cache for audio families."""

    def __init__(self, video_path, visual_families: Sequence[str],
                 depth: int = DEFAULT_DEPTH):
        self.video_path = str(video_path)
        self.bus: Optional[FrameBus] = (
            FrameBus(video_path, visual_families, depth=depth)
            if visual_families else None)
        self._wav_lock = threading.Lock()
        self._wav: Optional[Tuple[str, str]] = None
        self._wav_error: Optional[str] = None

    # -- visual -------------------------------------------------------------
    def subscribe(self, family: str, **kwargs
                  ) -> Optional[SharedFrameSource]:
        if self.bus is None:
            return None
        return self.bus.subscribe(family, **kwargs)

    def family_done(self, family: str) -> None:
        if self.bus is not None:
            self.bus.done(family)

    def shared_ms(self, family: str) -> Optional[float]:
        if self.bus is None:
            return None
        return self.bus.shared_ms(family)

    # -- audio --------------------------------------------------------------
    def shared_wav(self, video_path, tmp_path, ripper: Callable) -> str:
        """Rip the audio track once; every audio family reads the same
        wav. The SESSION owns cleanup (``cleanup()``), so a family must
        not delete what its siblings may still be reading."""
        with self._wav_lock:
            if self._wav_error is not None:
                # embed the original type name so classify()'s marker
                # logic treats the replay like the first failure
                raise RuntimeError(f"shared wav rip failed for "
                                   f"{video_path}: {self._wav_error}")
            if self._wav is None:
                try:
                    with trace.span("wav_rip", video=str(video_path),
                                    shared=True):
                        self._wav = ripper(video_path, tmp_path)
                except BaseException as e:
                    self._wav_error = f"{type(e).__name__}: {e}"
                    raise
            return self._wav[0]

    def cleanup(self, keep_tmp: bool = False) -> None:
        """Drop the shared wav/aac temps (unless ``keep_tmp``); called by
        the MultiExtractor after every family's thread has joined."""
        with self._wav_lock:
            wav, self._wav = self._wav, None
        if wav and not keep_tmp:
            for p in wav:
                try:
                    os.remove(p)
                except OSError:
                    pass
