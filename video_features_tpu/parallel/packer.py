"""Cross-video clip batching: fill fixed-shape device groups from several
videos' clips at once.

Per-video async streams (parallel/mesh.py FeatureStream) dispatch each
video's trailing group ragged — padded rows that burn device FLOPs. At the
bench sweet spot (``clip_batch_size=128`` on v5e) the 18 s reference sample
yields 22 clips, so 83% of a per-video flagship group would be padding and
the measured steady state is unreachable on short-video corpora. The
packer instead keeps ONE buffer shared by the ``video_workers`` decode
threads: a device group dispatches only when FULL (the sole exception is
the final drain, when every still-open video is already waiting to close),
so sustained throughput approaches the fixed-shape bench steady state
regardless of per-video clip counts.

Ordering contract: results come back per video, in that video's clip
order, bit-identical to the unpacked path — group membership only changes
which padded rows surround a clip, and the row itself is independent of
its neighbors (the forward is row-wise; parity asserted in
tests/test_packer.py).

Reference contrast: the reference's only cross-video parallelism is
launching extra whole processes per GPU (reference README.md:70-84), each
still running batch=1 slices; it has no batch packing of any kind.

Concurrency design (all state under one lock; D2H copies outside it):

  - ``add`` appends to the shared buffer; a full buffer dispatches the
    jitted forward immediately (dispatch is async — enqueue only).
  - ``close_video`` blocks until all of that video's clips have
    materialized. Progress is guaranteed: whoever observes work in flight
    drains the oldest group (a second lock keeps drains submit-ordered);
    when every open video is simultaneously closing and clips still sit
    in the unfilled buffer, the buffer is flushed ragged — so the system
    cannot deadlock even when all ``video_workers`` threads close at once
    with a part-filled group.
  - ``depth`` bounds un-materialized device groups, same role as
    FeatureStream's depth.
  - A group that fails on device (dispatch raises, or the D2H read
    surfaces a runtime error) poisons exactly its member videos: their
    pending counts are released and ``close_video`` re-raises for each,
    so the failure stays per-video (every member is reported failed, the
    rest of the corpus completes) instead of wedging the whole run.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np


class ClipPacker:
    def __init__(self, runner, batch: int, depth: int = 4):
        self.runner = runner
        self.batch = int(batch)
        self.depth = max(int(depth), 1)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._drain_lock = threading.Lock()     # serializes D2H
        self._dispatch_lock = threading.Lock()  # serializes group dispatch
        self._buf: List[tuple] = []          # [(handle, idx, stack), ...]
        self._inflight: deque = deque()      # [(device_array, manifest)]
        self._results: Dict[int, Dict[int, np.ndarray]] = {}
        self._counts: Dict[int, int] = {}    # clips added per handle
        self._pending: Dict[int, int] = {}   # clips not yet materialized
        self._errors: Dict[int, Exception] = {}  # poisoned-group handles
        self._open = 0
        self._closing = 0
        self._next_handle = 0

    # -- per-video API (each video's decode thread) ------------------------

    def open_video(self) -> int:
        with self._lock:
            h = self._next_handle
            self._next_handle += 1
            self._results[h] = {}
            self._counts[h] = 0
            self._pending[h] = 0
            self._open += 1
            return h

    def add(self, handle: int, stack: np.ndarray) -> None:
        """Append one clip stack; dispatches when the shared group fills."""
        to_dispatch = None
        with self._lock:
            err = self._errors.get(handle)
            if err is not None:
                # an earlier group containing our clips already failed:
                # stop this video now (the caller's except-path aborts it)
                # instead of decoding + dispatching clips whose only
                # possible outcome is a close_video failure
                raise RuntimeError(
                    "a packed clip group containing this video's clips "
                    f"failed on device: {err}") from err
            self._buf.append((handle, self._counts[handle], stack))
            self._counts[handle] += 1
            self._pending[handle] += 1
            if len(self._buf) >= self.batch:
                to_dispatch, self._buf = self._buf, []
        if to_dispatch is not None:
            # a dispatch failure contains OUR newest clip: propagate so the
            # caller's extractor aborts this video now (members poisoned)
            self._dispatch(to_dispatch)
            with self._lock:
                drain = len(self._inflight) > self.depth
            if drain:
                try:
                    self._drain_oldest()
                except Exception:
                    pass  # the failed group's members are poisoned; each
                    # surfaces at its own close_video, not at this add

    def abort_video(self, handle: int) -> None:
        """Error-path cleanup (per-video isolation): discard the video's
        buffered clips and stop counting it as open. Without this, a video
        that dies after open_video() would leave ``_open`` elevated forever
        and the all-closing flush rule could never fire — wedging every
        other worker's close_video. Rows of its already-dispatched clips
        are dropped at drain time (the results entry is gone)."""
        with self._lock:
            self._buf = [e for e in self._buf if e[0] != handle]
            self._results.pop(handle, None)
            self._counts.pop(handle, None)
            self._pending.pop(handle, None)
            self._errors.pop(handle, None)
            self._open -= 1
            self._cond.notify_all()

    def close_video(self, handle: int) -> np.ndarray:
        """Block until every clip of ``handle`` materialized; return the
        (n_clips, ...) feature rows in add order."""
        with self._lock:
            self._closing += 1
        try:
            while True:
                to_flush = None
                with self._lock:
                    # pending counts buffered AND in-flight clips, so zero
                    # means everything of ours has materialized. A poisoned
                    # handle breaks out regardless of the count — the error
                    # (raised below) is the result, and waiting on counts a
                    # failed drain may not have balanced would hang instead
                    # of surfacing it.
                    if self._pending[handle] == 0 or handle in self._errors:
                        break
                    if not self._inflight:
                        if self._buf and self._closing >= self._open:
                            # every open video is closing: nobody will fill
                            # the group — flush it ragged (the only ragged
                            # dispatch in the system)
                            to_flush, self._buf = self._buf, []
                        else:
                            # other videos are still decoding; their adds
                            # will fill the buffer. The timeout guards the
                            # race where the last feeder transitions to
                            # closing between our check and the wait.
                            self._cond.wait(timeout=0.05)
                            continue
                if to_flush is not None:
                    try:
                        self._dispatch(to_flush)
                    except Exception:
                        continue  # members poisoned; ours surfaces below
                try:
                    self._drain_oldest()
                except Exception:
                    pass  # poisoned members (possibly us) surface below
        finally:
            with self._lock:
                self._closing -= 1
                self._open -= 1
                rows = self._results.pop(handle)
                n = self._counts.pop(handle)
                self._pending.pop(handle)
                err = self._errors.pop(handle, None)
        if err is not None:
            raise RuntimeError(
                "a packed clip group containing this video's clips failed "
                f"on device: {err}") from err
        if n == 0:
            return np.empty((0,), np.float32)
        return np.stack([rows[i] for i in range(n)])

    # -- internals ---------------------------------------------------------

    def _dispatch(self, items: List[tuple]) -> None:
        """Stack + enqueue a group WITHOUT the main lock held (the host
        copy of a B=128 group is tens of MB — holding the lock there would
        stall every decode thread). The dispatch lock keeps the inflight
        order consistent with dispatch order."""
        with self._dispatch_lock:
            manifest = [(h, idx) for h, idx, _ in items]
            try:
                # np.stack inside the try: a shape mismatch or MemoryError
                # here has already consumed the clips from _buf, so it must
                # poison the members exactly like a device failure
                group = np.stack([s for _, _, s in items])
                dev = self.runner.dispatch(group)
            except Exception as e:
                self._poison(manifest, e)
                raise
            with self._lock:
                self._inflight.append((dev, manifest))
                self._cond.notify_all()

    def _poison(self, manifest, exc: Exception) -> None:
        """A group died on device: release its members' pending counts and
        record the error so each member's ``close_video`` raises instead of
        spinning forever on clips that will never materialize."""
        with self._lock:
            for h, _idx in manifest:
                if h in self._pending:
                    self._pending[h] -= 1
                    self._errors[h] = exc
            self._cond.notify_all()

    def _drain_oldest(self) -> None:
        """Materialize the oldest in-flight group (if any) and route its
        rows to their videos. D2H happens outside the main lock so decode
        threads keep feeding; the drain lock keeps materialization
        submit-ordered."""
        with self._drain_lock:
            with self._lock:
                if not self._inflight:
                    return
                dev, manifest = self._inflight.popleft()
            # ANY failure after the pop (the blocking D2H is the expected
            # one, but also e.g. a routing bug below) must poison the
            # members — once the group left _inflight, nobody else can
            # materialize it, and un-poisoned members would spin in
            # close_video forever instead of surfacing the error
            try:
                from ..utils.profiling import profiler
                # same stage contract as FeatureStream._pop: under async
                # dispatch this is the host's *stall* time on the device,
                # which is what the per-stage roofline breakdown
                # (trace_report / bench_pipeline) needs attributed —
                # without it a packed run's device time is invisible
                with profiler.stage("forward"):
                    host = np.asarray(dev)  # blocking D2H
                with self._lock:
                    for row, (h, idx) in enumerate(manifest):
                        if h in self._results:
                            self._results[h][idx] = host[row]
                            self._pending[h] -= 1
                    self._cond.notify_all()
            except Exception as e:
                self._poison(manifest, e)
                raise
