"""Fleet-shared persistent XLA compile cache: never compile twice, anywhere.

PR 7 made repeat *data* work free (cache.py) and PR 8 made fleet
membership dynamic (parallel/queue.py), but a joining or restarted host
still paid the full XLA compile before its first claim — elasticity in
name only, because scaling up was slow by construction. JAX already
ships a persistent compilation cache (one directory of serialized
executables, keyed per-program by XLA), and cli.py has pointed it at a
per-machine directory since round 1. What that leaves unsolved at fleet
scale:

  - **sharing is unsafe unverified**: a shared directory mixes entries
    from every jax/jaxlib/libtpu combination (deserialization failures,
    or worse: XLA:CPU executables bake in the compiling host's CPU
    features — a cross-microarch hit can SIGILL);
  - **nothing is content-addressed**: there is no name for "the warm set
    of family X under config Y on runtime Z", so a joining host cannot
    know — let alone promise — that it will compile nothing;
  - **nothing verifies**: a torn or bit-rotted entry is handed straight
    to the XLA deserializer.

This module wraps JAX's cache in the same discipline the feature cache
proved out:

  **entry** = one directory per ``(family, config fingerprint,
  environment fingerprint)`` triple at
  ``{root}/{family}/{key[:2]}/{key}/``, where

    - the **config fingerprint** reuses cache.py's canonicalization:
      NON_SEMANTIC_KEYS dropped, the extractor's resolved
      ``resize_mode``/``ingest`` overlaid — two configs that compile the
      same programs key identically (``resize=auto`` ≡ its resolution);
    - the **environment fingerprint** covers jax, jaxlib, the backend
      platform + device kind, libtpu when present, and (CPU backend
      only) a hash of the host's CPU feature flags — a version bump or a
      different microarchitecture resolves to a *different* entry
      instead of a wrong hit.

  **verify-before-trust**: ``seal()`` (called when a run exits cleanly)
  records every cache file's sha256 in ``_sums.json`` (atomic write, the
  sink discipline). ``attach()`` re-hashes on the way in: a file whose
  recorded sum mismatches (bit rot, tampering) or that was never sealed
  (a writer died mid-run) is deleted — a clean miss XLA recompiles and
  re-stores, never a corrupt executable served.

  **warm promise**: an entry whose ``_entry.json`` manifest exists and
  whose sealed files all verify is *warm* — a joining host can check
  this before claiming (the canary gate's warm fast path,
  parallel/queue.py) and ``vft-warmup <family> ...`` populates it ahead
  of time, so join latency is a measured number (``python bench.py
  bench_coldstart``) instead of a compile stall.

Enabled by ``compile_cache=``/``compile_cache_dir=`` in all 8 configs
(``auto`` = on for TPU runs; CPU runs need an explicit dir — their
executables are microarch-scoped, and tests must stay hermetic). The
attach point is process-global (JAX has ONE cache directory per
process): first attach wins, multi-family runs attach one combined
entry. Hit/miss counters ride the existing ``jax.monitoring`` listeners
(telemetry/recorder.py) into every heartbeat's ``compile_cache`` section
and ``vft-fleet``. See docs/performance.md "Never compile twice, fleet
edition".
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import sys
import threading
from typing import Any, Dict, List, Optional, Tuple

#: schema identifier stamped into every entry manifest; bump on breaking change
SCHEMA_VERSION = "vft.compile_cache/1"

#: per-entry metadata files (live next to JAX's own ``*-cache`` files)
MANIFEST_NAME = "_entry.json"
SUMS_NAME = "_sums.json"

#: JAX cache artifacts: ``<program>-cache`` executables (verified) and
#: ``<program>-atime`` LRU bookkeeping (ignored — mutated on every read)
_CACHE_SUFFIX = "-cache"
_ATIME_SUFFIX = "-atime"


def _safe(name: str) -> str:
    """Filesystem-safe directory component (multi-family entries embed
    comma-joined family lists)."""
    return re.sub(r"[^A-Za-z0-9._,-]+", "-", str(name))


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def default_root() -> str:
    return os.environ.get(
        "VFT_COMPILE_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "video_features_tpu", "compile_cache"))


# -- fingerprints -------------------------------------------------------------

def _cpu_features_fingerprint() -> str:
    """Hash of this host's CPU feature flags: XLA:CPU executables bake
    them in, so they are part of the environment identity (two hosts
    with identical flag sets may share entries; different microarchs may
    not — the SIGILL hazard cli.py's per-machine cache sidestepped by
    never sharing)."""
    import platform
    flags = ""
    try:
        with open("/proc/cpuinfo", encoding="utf-8") as f:
            for line in f:
                if line.lower().startswith("flags"):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    blob = f"{platform.machine()}|{flags}"
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def env_fingerprint(jax_version: Optional[str] = None,
                    jaxlib_version: Optional[str] = None,
                    backend: Optional[str] = None,
                    device_kind: Optional[str] = None,
                    libtpu_version: Optional[str] = None,
                    ) -> Tuple[Dict[str, Any], str]:
    """The runtime identity a compiled executable depends on, as
    ``(components dict, sha256 hex)``. Every component is overridable so
    tests can pin "what if jaxlib bumped" without installing anything —
    a changed component changes the fingerprint, which resolves to a
    different entry directory: the *miss-on-version-change* contract."""
    if jax_version is None or backend is None or device_kind is None:
        import jax
        jax_version = jax_version or jax.__version__
        if backend is None:
            backend = jax.default_backend()
        if device_kind is None:
            try:
                device_kind = jax.devices()[0].device_kind
            except Exception:
                device_kind = "?"
    if jaxlib_version is None:
        try:
            import jaxlib
            jaxlib_version = jaxlib.__version__
        except Exception:
            jaxlib_version = "?"
    if libtpu_version is None:
        try:
            from importlib import metadata
            for dist in ("libtpu", "libtpu-nightly"):
                try:
                    libtpu_version = metadata.version(dist)
                    break
                except metadata.PackageNotFoundError:
                    continue
        except Exception:
            pass
    env: Dict[str, Any] = {
        "jax": str(jax_version),
        "jaxlib": str(jaxlib_version),
        "backend": str(backend),
        "device_kind": str(device_kind),
        "libtpu": libtpu_version,
        "python": f"{sys.version_info.major}.{sys.version_info.minor}",
    }
    if env["backend"] == "cpu":
        env["cpu_features"] = _cpu_features_fingerprint()
    fp = hashlib.sha256(
        repr(sorted(env.items(), key=lambda kv: kv[0])).encode()).hexdigest()
    return env, fp


def config_fingerprint(args: Dict[str, Any],
                       resolved: Optional[Dict[str, Any]] = None) -> str:
    """cache.py's canonical resolved-config fingerprint, reused verbatim:
    the compile cache and the feature cache must agree on what
    "operationally different, semantically identical" means."""
    from .cache import config_fingerprint as _fp
    return _fp(args, resolved)


def resolved_overlay(args) -> Dict[str, Any]:
    """The ``resize=auto`` resolution predicted from the config ALONE.

    The feature cache reads the resolution off the constructed extractor
    (``resize_mode``), but the compile cache cannot wait that long: the
    expensive init-time compiles (flax ``model.init`` of a 20-iteration
    RAFT scan costs seconds) happen DURING construction, so the entry
    must be attached before it. This predictor mirrors
    ``BaseExtractor._resolve_resize_mode``'s auto rule — device for
    file-sink runs, host for print/show_pred — and is used by attach,
    warmup and the serve loop alike, so every driver computes the same
    key for the same config. (A family without a fused device resize
    resolves host internally while this predicts device; both the warmup
    and the run predict identically, so entries still line up — the only
    cost is that such a config does not share an entry with an explicit
    ``resize=host`` twin.)"""
    resolved: Dict[str, Any] = {}
    rz = args.get("resize") or "auto"
    if rz == "auto":
        save_sink = args.get("on_extraction", "print") in (
            "save_numpy", "save_pickle")
        resolved["resize"] = ("device" if save_sink
                              and not args.get("show_pred") else "host")
    ingest = args.get("ingest")
    if ingest is not None:
        resolved["ingest"] = ingest
    return resolved


def entry_key(family: str, config_fp: str, env_fp: str) -> str:
    """One sha256 over the triple: the entry directory's name."""
    return hashlib.sha256(
        f"{family}\n{config_fp}\n{env_fp}".encode()).hexdigest()


# -- the entry ---------------------------------------------------------------

class CompileCacheEntry:
    """One ``(family, config, environment)`` triple's directory of
    serialized XLA executables, with sealed-sum verification."""

    def __init__(self, root: str, family: str, config_fp: str,
                 env_fp: str, env: Optional[Dict[str, Any]] = None) -> None:
        self.root = str(root)
        self.family = str(family)
        self.config_fp = config_fp
        self.env_fp = env_fp
        self.env = dict(env or {})
        self.key = entry_key(self.family, config_fp, env_fp)
        self.dir = os.path.join(self.root, _safe(self.family),
                                self.key[:2], self.key)
        #: attach-time verdicts, published into the heartbeat section
        self.warm_at_attach = False
        self.verified = 0
        self.dropped = 0

    # -- inspection --------------------------------------------------------
    def _cache_files(self) -> List[str]:
        try:
            return sorted(n for n in os.listdir(self.dir)
                          if n.endswith(_CACHE_SUFFIX))
        except OSError:
            return []

    def _read_json(self, name: str) -> Optional[dict]:
        try:
            with open(os.path.join(self.dir, name), encoding="utf-8") as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else None
        except (OSError, ValueError):
            return None

    def sums(self) -> Dict[str, dict]:
        doc = self._read_json(SUMS_NAME) or {}
        files = doc.get("files")
        return dict(files) if isinstance(files, dict) else {}

    def manifest(self) -> Optional[dict]:
        return self._read_json(MANIFEST_NAME)

    def is_warm(self) -> bool:
        """True when this triple carries the warm promise: a sealed
        manifest of the right schema/fingerprints whose recorded files
        all still exist on disk (verify() has already deleted any whose
        bytes rotted)."""
        man = self.manifest()
        if man is None or man.get("schema") != SCHEMA_VERSION:
            return False
        if man.get("config_fp") != self.config_fp or \
                man.get("env_fp") != self.env_fp:
            return False
        sums = self.sums()
        if not sums:
            return False
        return all(os.path.exists(os.path.join(self.dir, name))
                   for name in sums)

    # -- verify / seal ------------------------------------------------------
    def verify(self) -> Dict[str, int]:
        """Verify-before-trust, the feature cache's discipline applied to
        executables: re-hash every JAX cache file against the sealed
        sums. A mismatch (bit rot, truncation, tampering) or an unsealed
        file (its writer died before seal — completeness unprovable) is
        DELETED, so XLA sees a clean miss and recompiles, instead of
        deserializing garbage. Returns ``{"verified": n, "dropped": n}``
        and records both on the entry for the heartbeat."""
        sums = self.sums()
        verified = dropped = 0
        for name in self._cache_files():
            path = os.path.join(self.dir, name)
            rec = sums.get(name)
            ok = False
            if isinstance(rec, dict):
                try:
                    ok = _sha256_file(path) == rec.get("sha256")
                except OSError:
                    ok = False
            if ok:
                verified += 1
                continue
            reason = "sha mismatch" if rec is not None else "never sealed"
            print(f"compile cache: dropped {name} ({reason}) — a clean "
                  f"recompile replaces it ({self.dir})", file=sys.stderr)
            for victim in (path, path[:-len(_CACHE_SUFFIX)] + _ATIME_SUFFIX):
                try:
                    os.unlink(victim)
                except OSError:
                    pass
            dropped += 1
        self.verified, self.dropped = verified, dropped
        return {"verified": verified, "dropped": dropped}

    def seal(self) -> int:
        """Record the current cache files' sums + the entry manifest
        (both atomic — telemetry/jsonl.py): from here on, these
        executables are vouched for and the entry is *warm*. Called when
        a run exits; a run that dies first simply leaves unsealed files
        for the next attach to drop. Returns the sealed file count."""
        import time

        from .telemetry.jsonl import write_json_atomic
        files: Dict[str, dict] = {}
        for name in self._cache_files():
            path = os.path.join(self.dir, name)
            try:
                files[name] = {"sha256": _sha256_file(path),
                               "bytes": os.path.getsize(path)}
            except OSError:
                continue  # racing eviction: the file simply isn't sealed
        write_json_atomic(os.path.join(self.dir, SUMS_NAME),
                          {"schema": SCHEMA_VERSION, "files": files,
                           "time": round(time.time(), 3)})
        write_json_atomic(os.path.join(self.dir, MANIFEST_NAME), {
            "schema": SCHEMA_VERSION,
            "family": self.family,
            "config_fp": self.config_fp,
            "env_fp": self.env_fp,
            "env": self.env,
            "files": len(files),
            "sealed_time": round(time.time(), 3),
        })
        return len(files)

    def activate(self) -> None:
        """Point THIS process's JAX persistent compilation cache at the
        entry directory. Process-global by JAX's design — which is
        exactly why attach() is first-wins."""
        import jax
        jax.config.update("jax_compilation_cache_dir", self.dir)
        # small executables are worth caching too (cli.py's rationale)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:
            pass  # knob absent on older jax: the default caches everything
        # JAX latches its cache state at the FIRST compile: a process
        # that compiled anything before attach (extractor init work,
        # library callers) latched "no cache" and would silently ignore
        # the dir update — reset so the next compile re-initializes
        # against the entry directory
        try:
            from jax._src import compilation_cache as _jcc
            if getattr(_jcc, "_cache_initialized", False) or \
                    getattr(_jcc, "_cache_checked", False):
                _jcc.reset_cache()
        except Exception:
            pass  # private API drifted: pre-first-compile attaches still work


# -- process-global attach ----------------------------------------------------

_lock = threading.Lock()
_active: Optional[CompileCacheEntry] = None


def resolve_root(args) -> Optional[str]:
    """The store root this run should attach to, or None (disabled).
    ``compile_cache=auto`` (the config default) is on wherever sharing
    is unconditionally safe and valuable — TPU runs — and requires an
    explicit ``compile_cache_dir`` on the CPU backend: CPU entries are
    microarch-scoped (env_fingerprint covers the flags), and tests /
    casual CPU runs must not grow a store in $HOME as a side effect."""
    mode = args.get("compile_cache", "auto")
    if mode in (None, False, "", "false", "null", "off"):
        return None
    if mode not in (True, "auto", "true", "on"):
        raise ValueError(f"compile_cache={mode!r}: expected true, false "
                         "or 'auto'")
    explicit = args.get("compile_cache_dir")
    if mode == "auto" and explicit is None:
        import jax
        if jax.default_backend() == "cpu":
            return None
    return str(explicit) if explicit else default_root()


def _attach_entry(root: str, family: str, config_fp: str
                  ) -> CompileCacheEntry:
    """The shared attach tail: build the entry, verify-before-trust,
    record warmth, point JAX at it, publish as the process-global
    active entry (losers of the publish race return the winner)."""
    global _active
    env, env_fp = env_fingerprint()
    entry = CompileCacheEntry(root, family, config_fp, env_fp, env=env)
    with _lock:
        if _active is not None:
            return _active
        _active = entry
    os.makedirs(entry.dir, exist_ok=True)
    entry.verify()
    entry.warm_at_attach = entry.is_warm()
    entry.activate()
    return entry


def attach(family: str, args, resolved: Optional[Dict[str, Any]] = None
           ) -> Optional[CompileCacheEntry]:
    """Attach this process to the triple's entry: verify, activate,
    remember. First attach wins (JAX has one cache dir per process);
    later calls return the active entry unchanged. Returns None when
    ``compile_cache`` resolves disabled."""
    with _lock:
        if _active is not None:
            return _active
    root = resolve_root(args)
    if root is None:
        return None
    return _attach_entry(root, family, config_fingerprint(args, resolved))


def attach_for_args(family: str, args) -> Optional[CompileCacheEntry]:
    """Attach from a sanity-checked config, BEFORE the extractor is
    constructed — the init-time compiles (the expensive ones for the
    scan-heavy families) must already land in the entry. The resolution
    overlay is predicted from the config (:func:`resolved_overlay`)."""
    return attach(str(family), args, resolved_overlay(args))


def attach_for_extractor(ext) -> Optional[CompileCacheEntry]:
    """The lazy library-caller hook (extractors/base.py): same key as
    :func:`attach_for_args`, computed from the extractor's own args. The
    CLI/serve drivers attach earlier, pre-construction; this path only
    fires when nothing attached yet."""
    args = getattr(ext, "args", None)
    if args is None:
        return None
    return attach_for_args(str(ext.feature_type), args)


def attach_for_multi_args(per_family) -> Optional[CompileCacheEntry]:
    """Multi-family runs compile N families' programs in ONE process, so
    they attach ONE combined entry: family = the comma-joined list, the
    config fingerprint = a hash over every member family's own resolved
    fingerprint (order-insensitive). ``vft-warmup resnet,clip`` warms
    exactly this triple. ``per_family`` is the load_multi_config dict —
    callable before any extractor exists."""
    families = list(per_family)
    fps = []
    for fam in sorted(families):
        a = per_family[fam]
        fps.append(f"{fam}:{config_fingerprint(a, resolved_overlay(a))}")
    combined = hashlib.sha256("\n".join(fps).encode()).hexdigest()
    with _lock:
        if _active is not None:
            return _active
    root = resolve_root(per_family[families[0]])
    if root is None:
        return None
    return _attach_entry(root, ",".join(families), combined)


def active() -> Optional[CompileCacheEntry]:
    with _lock:
        return _active


def active_info() -> Optional[Dict[str, Any]]:
    """Compact view of the attached entry for heartbeats/reports."""
    entry = active()
    if entry is None:
        return None
    return {"family": entry.family, "entry": entry.key[:12],
            "warm_at_attach": bool(entry.warm_at_attach),
            "verified": entry.verified, "dropped": entry.dropped,
            "dir": entry.dir}


def seal_active() -> int:
    """Seal the attached entry (run exit). Returns sealed file count;
    0 when nothing is attached. Never raises into the caller's finally —
    an unsealed entry only costs the next host a recompile."""
    entry = active()
    if entry is None:
        return 0
    try:
        return entry.seal()
    except Exception as e:
        print(f"compile cache: seal failed ({type(e).__name__}: {e}) — "
              f"entry stays cold, next attach recompiles", file=sys.stderr)
        return 0


def detach_for_tests() -> None:
    """Drop the process-global attach so tests can re-attach. Leaves
    jax's cache dir pointing wherever it was (tests restore it)."""
    global _active
    with _lock:
        _active = None


# -- ahead-of-time warmup (vft-warmup) ----------------------------------------

def _synth_clip(path: str, frames: int = 48, w: int = 320,
                h: int = 240, fps: float = 19.62) -> str:
    """A small synthetic clip with natural-ish low-frequency content
    (the tests' stand-in recipe) so warmup needs no corpus. Shapes are
    what compile keys on, not pixels — but pass a representative video
    (``video_paths=``) when source resolution feeds a device-resize
    program you want warm."""
    import cv2
    import numpy as np
    wtr = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"mp4v"),
                          fps, (w, h))
    if not wtr.isOpened():
        raise RuntimeError("cv2 cannot encode the synthetic warmup clip; "
                           "pass video_paths=<clip> instead")
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    for t in range(frames):
        frame = np.stack([
            127 + 120 * np.sin(xx / 40 + t / 9),
            127 + 120 * np.sin(yy / 30 - t / 13),
            127 + 120 * np.sin((xx + yy) / 50 + t / 7),
        ], axis=-1)
        wtr.write(frame.clip(0, 255).astype(np.uint8))
    wtr.release()
    return path


def _warmup_one(family: str, overrides: Dict[str, Any]) -> Dict[str, Any]:
    """Warm ONE family's triple in THIS process: construct the real
    extractor under the real (sanity-checked) config, run one throwaway
    extraction so every first-video program compiles into the entry,
    seal. The warmup subprocesses vft-warmup spawns call this; tests may
    call it directly."""
    import contextlib
    import tempfile
    import time

    from .config import load_config, sanity_check
    from .registry import get_extractor_cls
    from .telemetry.recorder import _install_monitoring, _mon_snapshot, \
        compile_cache_summary

    overrides = dict(overrides or {})
    # the warmup run itself is throwaway; its CONFIG must still resolve
    # like production (a save sink resolves resize=auto -> device, the
    # shape production file-sink runs compile)
    overrides.setdefault("on_extraction", "save_numpy")
    # the entry point exists to populate the store: an absent/auto key
    # attaches even on CPU (the operator asked for warmth explicitly)
    if overrides.get("compile_cache") in (None, "auto"):
        overrides["compile_cache"] = True
    video = overrides.pop("video_paths", None)
    if isinstance(video, (list, tuple)):
        video = video[0] if video else None
    with tempfile.TemporaryDirectory(prefix="vft_warmup_") as td:
        if video is None:
            video = _synth_clip(os.path.join(td, "warmup.mp4"))
        overrides["video_paths"] = [str(video)]
        overrides["output_path"] = os.path.join(td, "out")
        overrides["tmp_path"] = os.path.join(td, "tmp")
        cfg = load_config(family, overrides)
        sanity_check(cfg)
        _install_monitoring()
        baseline = _mon_snapshot()
        t0 = time.perf_counter()
        # attach BEFORE construction: the init-time compiles are part of
        # the warm set (the same order the CLI driver uses)
        entry = attach_for_args(family, cfg)
        if entry is None:
            return {"family": family, "status": "disabled",
                    "note": "compile_cache resolved disabled "
                            "(compile_cache=false?)"}
        warm_before = entry.warm_at_attach
        ext = get_extractor_cls(family)(cfg)
        with contextlib.redirect_stdout(sys.stderr):
            ext._extract(str(video))
        sealed = entry.seal()
        summary = compile_cache_summary(baseline)
        return {"family": family, "status": "ok", "entry": entry.key[:12],
                "dir": entry.dir, "warm_before": bool(warm_before),
                "compiled": int(summary.get("misses", 0)),
                "reused": int(summary.get("hits", 0)),
                "sealed_files": sealed,
                "seconds": round(time.perf_counter() - t0, 2)}


def warmup_main(argv: Optional[List[str]] = None) -> None:
    """``vft-warmup <family>[,<family>...] ... [key=value ...]``: compile
    every listed family's programs into the shared store ahead of time,
    one fresh subprocess per family (JAX holds one cache dir per
    process, and a cold subprocess is exactly the joining-host shape the
    warmth is for). Multi-family triples (``resnet,clip``) warm as one
    combined entry — the same entry a ``feature_type=resnet,clip`` run
    attaches."""
    argv = list(sys.argv[1:] if argv is None else argv)
    families: List[str] = []
    overrides: List[str] = []
    for a in argv:
        (overrides if "=" in a else families).append(a)
    if not families:
        raise SystemExit(
            "Usage: vft-warmup <family>[,<family>...] ... [key=value ...]\n"
            "e.g.   vft-warmup resnet clip compile_cache_dir=/srv/vft/cc\n"
            "(docs/performance.md 'Never compile twice, fleet edition')")
    from .config import parse_dotlist
    from .registry import parse_feature_types
    over = parse_dotlist(overrides)
    failures = 0
    for spec in families:
        fams = parse_feature_types(spec)  # validates names
        if len(fams) > 1:
            # combined triple: warmed by a real multi-family CLI run in
            # the subprocess (attach_for_multi keys it)
            result = _spawn_warmup_multi(spec, over)
        else:
            result = _spawn_warmup(fams[0], over)
        if result.get("status") == "ok":
            tag = "warm already, re-verified" if result.get("warm_before") \
                else f"compiled {result.get('compiled', '?')} program(s)"
            print(f"vft-warmup: {spec}: {tag} in "
                  f"{result.get('seconds', '?')}s -> entry "
                  f"{result.get('entry')} ({result.get('sealed_files')} "
                  f"sealed file(s), {result.get('dir')})")
        else:
            failures += 1
            print(f"vft-warmup: {spec}: FAILED — "
                  f"{result.get('note') or result.get('error')}",
                  file=sys.stderr)
    if failures:
        raise SystemExit(1)


_WARMUP_WORKER = """\
import json, sys
result = {}
try:
    from video_features_tpu.compile_cache import _warmup_one
    result = _warmup_one(sys.argv[1], json.loads(sys.argv[2]))
except BaseException as e:
    result = {"family": sys.argv[1], "status": "error",
              "error": f"{type(e).__name__}: {e}"}
print("VFT_WARMUP_RESULT " + json.dumps(result))
"""

_WARMUP_MULTI_WORKER = """\
import contextlib, json, os, sys, tempfile, time
result = {}
try:
    from video_features_tpu import compile_cache
    from video_features_tpu.cli import main as cli_main
    spec, over = sys.argv[1], json.loads(sys.argv[2])
    if over.get("compile_cache") in (None, "auto"):
        over["compile_cache"] = True
    over.setdefault("on_extraction", "save_numpy")
    video = over.pop("video_paths", None)
    if isinstance(video, list):
        video = video[0] if video else None
    with tempfile.TemporaryDirectory(prefix="vft_warmup_") as td:
        if video is None:
            video = compile_cache._synth_clip(os.path.join(td, "w.mp4"))
        argv = [f"feature_type={spec}", f"output_path={td}/out",
                f"tmp_path={td}/tmp", f"video_paths=[{video}]"]
        argv += [f"{k}={json.dumps(v) if isinstance(v, (bool, type(None))) else v}"
                 for k, v in over.items()]
        t0 = time.perf_counter()
        with contextlib.redirect_stdout(sys.stderr):
            cli_main(argv)
        entry = compile_cache.active()
        if entry is None:
            result = {"family": spec, "status": "disabled",
                      "note": "compile_cache resolved disabled"}
        else:
            result = {"family": spec, "status": "ok",
                      "entry": entry.key[:12], "dir": entry.dir,
                      "warm_before": bool(entry.warm_at_attach),
                      "compiled": None, "sealed_files": entry.seal(),
                      "seconds": round(time.perf_counter() - t0, 2)}
except BaseException as e:
    result = {"family": sys.argv[1], "status": "error",
              "error": f"{type(e).__name__}: {e}"}
print("VFT_WARMUP_RESULT " + json.dumps(result))
"""


def _run_warmup_worker(code: str, spec: str, over) -> Dict[str, Any]:
    import subprocess

    from .config import _plain
    proc = subprocess.run(
        [sys.executable, "-c", code, spec, json.dumps(_plain(dict(over)))],
        capture_output=True, text=True)
    for line in reversed((proc.stdout or "").splitlines()):
        if line.startswith("VFT_WARMUP_RESULT "):
            try:
                return json.loads(line[len("VFT_WARMUP_RESULT "):])
            except ValueError:
                break
    tail = (proc.stderr or proc.stdout or "")[-800:]
    return {"family": spec, "status": "error",
            "error": f"warmup subprocess rc={proc.returncode}: {tail}"}


def _spawn_warmup(family: str, over) -> Dict[str, Any]:
    return _run_warmup_worker(_WARMUP_WORKER, family, over)


def _spawn_warmup_multi(spec: str, over) -> Dict[str, Any]:
    return _run_warmup_worker(_WARMUP_MULTI_WORKER, spec, over)


if __name__ == "__main__":
    warmup_main()
