"""TPU-native video feature extraction framework.

A from-scratch JAX/XLA/Flax/Pallas re-design of the capabilities of
``video_features`` (reference mounted at /root/reference): given video files,
extract per-video feature arrays with eight pretrained model families
(R(2+1)D, I3D RGB+Flow, S3D, ResNet, CLIP, VGGish, RAFT, PWC-Net).

Compute path: jit-compiled Flax modules with static-shape, shape-bucketed clip
batches, sharded over a `jax.sharding.Mesh` (ICI data-parallel; multi-host via
deterministic video->host assignment). Iterative correlation volumes (RAFT/PWC)
use Pallas TPU kernels. The host side (decode, windowing, sinks) streams
fixed-shape batches into the device pipeline.

CLI and output contracts mirror the reference:
  - ``python main.py feature_type=r21d video_paths=...`` dotlist interface
    (reference main.py:7-51)
  - per-video outputs named ``{stem}_{key}.npy`` / ``.pkl``
    (reference utils/utils.py:53-57)
  - idempotent skip-if-exists with load-validation corruption check
    (reference models/_base/base_extractor.py:95-127)
"""

__version__ = "0.1.0"

SUPPORTED_FEATURE_TYPES = (
    "i3d", "r21d", "s3d", "vggish",
    "resnet", "raft", "pwc", "clip",
)
