"""``vft-fleet``: one live view of the whole fleet from its artifacts.

The per-run report tools each read ONE output dir: telemetry_report
renders one host's manifest + heartbeats, trace_report one host's
timeline. A fleet — N ``fleet=queue`` workers co-owning an out_root, or
N ``vft-serve`` processes sharing a spool — has no single place an
operator can ask "is everyone alive, who is the straggler, what is the
cache doing, are we inside the SLO". This module is that place: point it
at the shared root and it merges every host's heartbeats, the queue
counts, cache hit rates, per-family throughput (from span records) and
serve SLO attainment into one report, flagging the host the rest of the
fleet idles behind.

    vft-fleet /shared/out                      # one-shot report
    vft-fleet /shared/out --watch              # live refresh (2s)
    vft-fleet /shared/out --prom /var/lib/node_exporter/vft_fleet.prom
    vft-fleet /shared/out --stitch             # one Perfetto file, all hosts
    vft-fleet /shared/out --request 3f2a9c1b   # everything one request touched

Everything is reconstructed from artifacts (heartbeats, ``_run.json``,
``_telemetry.jsonl``, ``_health.jsonl``, ``_trace.json``, the ``_queue``
and spool dirs) — no live process, agent or scrape endpoint required,
exactly the discipline of the per-run tools. Works on a dead fleet too.

**Stitching** (``--stitch``): every host's ``_trace.json`` under the
root merges into ONE Chrome-trace file with one process lane per host,
aligned on each trace's **wall-clock anchor** (``otherData.start_unix``,
stamped by telemetry/trace.py at recorder start): event time becomes
``anchor + ts``, rebased to the earliest anchor — real cross-host time,
so a steal on host B renders *after* the lease expiry on host A that
caused it. A trace without an anchor (pre-anchor artifacts) falls back
to offset 0 and is flagged in ``otherData.unanchored``.

**Request lookup** (``--request``): the serve plane stamps every span
record, health digest, failure-journal entry, trace span and response
with the originating request id (telemetry/context.py); this flag greps
the fleet's artifacts for one id and prints every record it produced,
wherever it ran.

Installed as the ``vft-fleet`` console script;
``scripts/fleet_report.py`` is the bare-checkout wrapper. See
docs/observability.md "One view of the fleet".
"""
from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from .telemetry.heartbeat import (HEARTBEAT_GLOB, STALL_INTERVALS,
                                  matches_run)
from .telemetry.jsonl import read_jsonl
from .telemetry.metrics import prometheus_text
from .telemetry.trace import TRACE_FILENAME, TRACE_OUTPUT_NAMES

SPANS_FILENAME = "_telemetry.jsonl"
MANIFEST_FILENAME = "_run.json"
HEALTH_FILENAME = "_health.jsonl"
FAILURES_FILENAME = "_failures.jsonl"

#: stitched-trace format tag (otherData.schema)
STITCH_SCHEMA = "vft.trace_fleet/1"

#: pid base for stitched host lanes: each host's events are remapped to
#: a distinct pid so Perfetto renders one process group per host
STITCH_PID_BASE = 1000

#: flight-recorder bundles (telemetry/alerts.py) hold frozen COPIES of
#: heartbeats/journals/traces; every artifact collector below must skip
#: this subtree or captured snapshots resurrect as ghost hosts
INCIDENTS_DIRNAME = "_incidents"


def _in_incident(p: Path) -> bool:
    return INCIDENTS_DIRNAME in p.parts


def _load_json(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def _fmt_age(seconds: float) -> str:
    if seconds < 90:
        return f"{seconds:.0f}s"
    if seconds < 5400:
        return f"{seconds / 60:.1f}m"
    return f"{seconds / 3600:.1f}h"


def fleet_stragglers(hbs: List[dict], now: float) -> set:
    """host_ids binding the fleet: a host still holding active fleet
    claims while the shared queue's pending is empty AND at least one
    other live fleet host sits idle — everyone else is waiting on it
    (the per-host ``fleet.idle_wait`` trace spans are the same signal in
    time). Shared by telemetry_report.py and the fleet aggregator."""
    live = []
    for hb in hbs:
        fl = hb.get("fleet")
        if not isinstance(fl, dict) or hb.get("final"):
            continue
        interval = float(hb.get("interval_s", 30.0) or 30.0)
        if now - float(hb.get("time", 0)) > STALL_INTERVALS * interval:
            continue
        live.append((str(hb.get("host_id")), fl))
    if len(live) < 2:
        return set()
    idle = [h for h, fl in live if not fl.get("active_claims")]
    if not idle:
        return set()
    return {h for h, fl in live
            if fl.get("active_claims")
            and not (fl.get("queue") or {}).get("pending", 0)}


# -- collection ---------------------------------------------------------------

def collect_heartbeats(root: str, now: Optional[float] = None) -> List[dict]:
    """Every heartbeat under ``root`` (recursively — fleet workers home
    theirs at the out_root, multi-family runs at the common root, serve
    at the spool), classified against its own directory's manifest:

    ``{"path", "dir", "hb", "state", "age_s", "prior_run"}`` with state
    one of ``live`` / ``STALLED`` / ``FINISHED`` / ``unreadable``.
    Prior-run files (a reused output dir; heartbeat demonstrably from an
    older run than the sibling manifest) are flagged, not dropped — the
    renderer shows them as ignored, the aggregates skip them."""
    now = time.time() if now is None else now
    out: List[dict] = []
    seen: set = set()
    root_p = Path(root)
    paths = [p for p in sorted(root_p.rglob(HEARTBEAT_GLOB))
             if not _in_incident(p)]
    # rglob misses nothing below, but the root itself may BE a file list
    for p in paths:
        rp = str(p.resolve())
        if rp in seen:
            continue
        seen.add(rp)
        entry: Dict[str, Any] = {"path": str(p), "dir": str(p.parent)}
        hb = _load_json(str(p))
        if hb is None:
            entry.update(hb=None, state="unreadable", age_s=None,
                         prior_run=False)
            out.append(entry)
            continue
        man = _load_json(os.path.join(str(p.parent), MANIFEST_FILENAME))
        prior = man is not None and not matches_run(
            hb, man.get("run_id"), man.get("started_time"))
        age = max(0.0, now - float(hb.get("time", now) or now))
        interval = float(hb.get("interval_s", 30.0) or 30.0)
        if hb.get("final"):
            state = "FINISHED"
        elif age > STALL_INTERVALS * interval:
            state = "STALLED"
        else:
            state = "live"
        entry.update(hb=hb, state=state, age_s=round(age, 3),
                     prior_run=bool(prior))
        out.append(entry)
    return out


def collect_family_throughput(root: str) -> Dict[str, dict]:
    """Per-family tallies off every ``_telemetry.jsonl`` under the root:
    records, done/error counts, mean seconds per video — the
    whole-fleet per-family throughput no single host's heartbeat can
    see."""
    fams: Dict[str, dict] = {}
    for path in sorted(Path(root).rglob(SPANS_FILENAME)):
        if _in_incident(path):
            continue
        for rec in read_jsonl(path):
            fam = str(rec.get("feature_type") or "?")
            f = fams.setdefault(fam, {"records": 0, "done": 0, "error": 0,
                                      "wall_s": 0.0})
            f["records"] += 1
            st = rec.get("status")
            if st == "done":
                f["done"] += 1
                f["wall_s"] += float(rec.get("wall_s") or 0.0)
            elif st in ("error", "quarantined"):
                f["error"] += 1
    for f in fams.values():
        f["s_per_video"] = (round(f["wall_s"] / f["done"], 3)
                            if f["done"] else None)
        f["wall_s"] = round(f["wall_s"], 3)
    return fams


def _queue_counts(root: str, entries: List[dict]) -> Optional[dict]:
    """Fleet-queue counts: preferred from the ``_queue`` dir itself (the
    ground truth both workers and this tool read), falling back to the
    freshest live heartbeat's ``fleet.queue`` section."""
    qroot = os.path.join(str(root), "_queue")
    if os.path.isdir(qroot):
        counts = {}
        for d in ("pending", "done", "quarantined"):
            try:
                counts[d] = sum(1 for n in os.listdir(
                    os.path.join(qroot, d)) if n.endswith(".json"))
            except OSError:
                counts[d] = 0
        claimed = 0
        try:
            for h in os.listdir(os.path.join(qroot, "claimed")):
                try:
                    claimed += sum(1 for n in os.listdir(
                        os.path.join(qroot, "claimed", h))
                        if n.endswith(".json"))
                except OSError:
                    pass
        except OSError:
            pass
        counts["claimed"] = claimed
        return counts
    best = None
    for e in entries:
        hb = e.get("hb") or {}
        fl = hb.get("fleet")
        if not isinstance(fl, dict) or e.get("prior_run"):
            continue
        if best is None or float(hb.get("time", 0)) > \
                float((best.get("hb") or {}).get("time", 0)):
            best = e
    if best is None:
        return None
    return dict(((best.get("hb") or {}).get("fleet") or {})
                .get("queue") or {})


def _newest_started_time(root: str) -> Optional[float]:
    """The freshest manifest's ``started_time`` under the root — the
    prior-run cutoff for alert gating (an alert whose last transition
    predates every current run is a previous run's business)."""
    best: Optional[float] = None
    for p in sorted(Path(str(root)).rglob(MANIFEST_FILENAME)):
        if _in_incident(p):
            continue
        man = _load_json(str(p))
        st = (man or {}).get("started_time")
        try:
            if st is not None:
                best = float(st) if best is None else max(best, float(st))
        except (TypeError, ValueError):
            continue
    return best


def collect_alerts(root: str) -> List[dict]:
    """Active (pending/firing) alert episodes off ``_alerts.jsonl``,
    prior-run excluded against the newest sibling manifest
    (telemetry/alerts.py owns the journal contract)."""
    try:
        from .telemetry.alerts import current_alerts
        return current_alerts(str(root),
                              started_time=_newest_started_time(root))
    except Exception:
        return []


def collect_scenarios(root: str) -> List[dict]:
    """Every recorded-drill verdict under the root (``_scenario.json``,
    loadgen.py): the traffic-scenario observatory — rendered as the
    ``== scenarios ==`` section and exported as ``vft_scenario_*``
    gauges. Sorted by artifact time so the freshest drill renders
    last."""
    out: List[dict] = []
    for p in sorted(Path(str(root)).rglob("_scenario.json")):
        if _in_incident(p):
            continue
        doc = _load_json(str(p))
        if doc is not None and \
                str(doc.get("schema", "")).startswith("vft.scenario/"):
            out.append(doc)
    out.sort(key=lambda d: float(d.get("time") or 0.0))
    return out


def aggregate(root: str, now: Optional[float] = None) -> dict:
    """The one-view fleet snapshot: everything the renderer, the prom
    exporter and the tests consume, as plain JSON-safe data."""
    now = time.time() if now is None else now
    entries = collect_heartbeats(root, now=now)
    current = [e for e in entries
               if e.get("hb") is not None and not e["prior_run"]]
    hbs = [e["hb"] for e in current]
    stragglers = fleet_stragglers(hbs, now)

    cache = {"hits": 0, "misses": 0, "bypasses": 0}
    by_family_cache: Dict[str, Dict[str, int]] = {}
    compile_cache = {"hits": 0, "misses": 0, "warm_hosts": 0,
                     "attached_hosts": 0, "dropped": 0}
    cc_entries: set = set()
    slo_hosts: List[dict] = []
    slo_totals = {"requests": 0, "violations": 0}
    # per-tenant roll-up (the gateway arc, gateway.py): answered/violated
    # from serve heartbeats, door rejections + sheds from gateway
    # heartbeats — one attainment line per tenant, fleet-wide
    tenant_totals: Dict[str, Dict[str, object]] = {}

    def _tenant(t: str) -> Dict[str, object]:
        return tenant_totals.setdefault(
            str(t), {"requests": 0, "violations": 0, "rejects": 0})
    idle_inputs = {"idle_wait_s_total": 0.0, "uptime_s": 0.0,
                   "fleet_hosts": 0}
    # storage accounting (gc.py GcMonitor): every host samples the SAME
    # shared root, so the fleet view is the freshest host's snapshot,
    # not a sum — summing would multiply the tree by n_hosts
    gc_section: Optional[dict] = None
    gc_time = float("-inf")
    for e in current:
        hb = e["hb"]
        cc = hb.get("compile_cache")
        if isinstance(cc, dict):
            compile_cache["hits"] += int(cc.get("hits") or 0)
            compile_cache["misses"] += int(cc.get("misses") or 0)
            compile_cache["dropped"] += int(cc.get("dropped") or 0)
            if cc.get("entry"):
                compile_cache["attached_hosts"] += 1
                cc_entries.add(str(cc["entry"]))
            if cc.get("warm_at_attach"):
                compile_cache["warm_hosts"] += 1
        fl = hb.get("fleet")
        if isinstance(fl, dict) and e["state"] == "live":
            idle_inputs["idle_wait_s_total"] += \
                float(fl.get("idle_wait_s_total") or 0.0)
            idle_inputs["uptime_s"] += float(hb.get("uptime_s") or 0.0)
            idle_inputs["fleet_hosts"] += 1
        ca = hb.get("cache") or {}
        for k in ("hits", "misses", "bypasses"):
            per = ca.get(k) or {}
            cache[k] += sum(int(v) for v in per.values())
            for fam, v in per.items():
                by_family_cache.setdefault(fam, {}).setdefault(k, 0)
                by_family_cache[fam][k] += int(v)
        serve = hb.get("serve")
        if isinstance(serve, dict):
            slo = serve.get("slo") or {}
            slo_hosts.append({
                "host_id": hb.get("host_id"), "state": serve.get("state"),
                "hb_state": e["state"],
                "pending": serve.get("pending"),
                "inflight": serve.get("inflight"),
                "active_requests": serve.get("active_requests") or [],
                "requests": serve.get("requests") or {}, "slo": slo})
            slo_totals["requests"] += int(slo.get("requests") or 0)
            slo_totals["violations"] += int(slo.get("violations") or 0)
            for t, v in (serve.get("tenants") or {}).items():
                tt = _tenant(t)
                tt["requests"] += int(v.get("requests") or 0)
                tt["violations"] += int(v.get("violations") or 0)
                tt["rejects"] += int(v.get("rejects") or 0)
        gw = hb.get("gateway")
        if isinstance(gw, dict):
            for t, v in (gw.get("tenants") or {}).items():
                tt = _tenant(t)
                tt["rejects"] += (int(v.get("rejected") or 0)
                                  + int(v.get("shed") or 0))
        g_sec = hb.get("gc")
        if isinstance(g_sec, dict):
            try:
                t_hb = float(hb.get("time") or 0.0)
            except (TypeError, ValueError):
                t_hb = 0.0
            if t_hb > gc_time:
                gc_time = t_hb
                gc_section = dict(g_sec)
    for tt in tenant_totals.values():
        n = int(tt["requests"])
        tt["attainment_pct"] = (
            round(100.0 * (n - int(tt["violations"])) / n, 2)
            if n else None)
    consulted = cache["hits"] + cache["misses"]
    cache["hit_rate"] = (round(cache["hits"] / consulted, 4)
                         if consulted else None)
    cc_consulted = compile_cache["hits"] + compile_cache["misses"]
    compile_cache["hit_rate"] = (
        round(compile_cache["hits"] / cc_consulted, 4)
        if cc_consulted else None)
    compile_cache["entries"] = sorted(cc_entries)
    n_req = slo_totals["requests"]
    slo_totals["attainment_pct"] = (
        round(100.0 * (n_req - slo_totals["violations"]) / n_req, 2)
        if n_req else None)

    return {
        "root": str(root),
        "time": now,
        "hosts": entries,
        "n_hosts": {
            "live": sum(1 for e in current if e["state"] == "live"),
            "stalled": sum(1 for e in current if e["state"] == "STALLED"),
            "finished": sum(1 for e in current
                            if e["state"] == "FINISHED"),
            "prior_run": sum(1 for e in entries if e["prior_run"]),
            "unreadable": sum(1 for e in entries
                              if e["state"] == "unreadable"),
        },
        "stragglers": sorted(stragglers),
        "queue": _queue_counts(root, entries),
        "cache": cache,
        "cache_by_family": by_family_cache,
        "compile_cache": compile_cache,
        "capacity_inputs": idle_inputs,
        "families": collect_family_throughput(root),
        "serve": {"hosts": slo_hosts, "totals": slo_totals,
                  "tenants": tenant_totals},
        # active alert episodes (telemetry/alerts.py): rendered, prom'd
        # as ALERTS gauges and gated by --fail-on-alert; evaluation
        # itself belongs to the in-process engines and vft-alert
        "alerts": collect_alerts(root),
        # roofline roll-up (telemetry/roofline.py): every host's
        # _roofline*.json merged — flops/forward sums, MFU recomputed
        # over the fleet totals, verdict re-derived; None when no host
        # ran with roofline=true
        "roofline": _roofline_rollup(root),
        # storage accounting (gc.py): the freshest host's usage snapshot
        # of the shared planes; None when no host ran with gc=true
        "gc": gc_section,
        # recorded traffic drills (loadgen.py): each _scenario.json
        # verdict with its windowed SLO-attainment curve
        "scenarios": collect_scenarios(root),
        # certify verdict artifacts (telemetry/parity.py): per-seam
        # numerics error attribution, rendered as == parity == and
        # exported as vft_parity_* gauges; the parity_drift alert rule
        # reads the same collection
        "parity": _parity_verdicts(root),
    }


def _parity_verdicts(root: str) -> List[dict]:
    try:
        from .telemetry.parity import collect_verdicts
        return collect_verdicts(str(root))
    except Exception:
        return []


def _roofline_rollup(root: str) -> Optional[dict]:
    try:
        from .telemetry.roofline import aggregate_rooflines
        return aggregate_rooflines(str(root))
    except Exception:
        return None


# -- capacity decision plane --------------------------------------------------

class CapacityPlanner:
    """Scale-up / scale-down / hold recommendations with hysteresis —
    the *decision* half of elastic capacity (ROADMAP item 3); actuation
    stays with the operator.

    Feed it successive :func:`aggregate` snapshots (``--watch`` does,
    every pass) and it derives three signals:

      - **queue depth per live host** (``queue.pending / live``): work
        is piling up faster than the fleet drains it;
      - **idle-wait stall share**: the fraction of fleet wall-time spent
        in ``fleet.idle_wait`` (hosts starved while siblings hold the
        last leases — more hosts would NOT help; fewer would);
      - **SLO attainment + slope** over the observation window: serving
        below target and not recovering means capacity, not luck, is
        the problem.

    Hysteresis keeps the recommendation actionable instead of flappy: a
    non-``hold`` *pressure* must repeat ``confirm_ticks`` consecutive
    observations before it becomes the recommendation, and once the
    recommendation changes it is pinned for ``cooldown_s`` (scaling
    actions take time to land; re-deciding mid-flight oscillates).
    Thresholds and the clock are injectable for tests.

    **Persistence**: with a ``state_path`` (or via :meth:`for_root`) the
    streak/cooldown/slope state survives ``vft-fleet`` restarts —
    without it, every restart reset the hysteresis and a freshly
    relaunched watcher could re-recommend a scale action the previous
    one had just cooled down from. When no state file exists yet, the
    slope baseline seeds from the retained heartbeat history
    (telemetry/history.py), so even the FIRST observation of a new
    watcher has a real window behind it.
    """

    #: recommendation -> prometheus gauge value
    SCALE = {"scale_up": 1, "hold": 0, "scale_down": -1}

    STATE_FILENAME = "_capacity_state.json"
    STATE_SCHEMA = "vft.capacity_state/1"

    def __init__(self, *, slo_target_pct: float = 95.0,
                 up_pending_per_host: float = 2.0,
                 down_idle_share: float = 0.5,
                 confirm_ticks: int = 2, cooldown_s: float = 120.0,
                 clock=time.time,
                 state_path: Optional[str] = None) -> None:
        self.slo_target_pct = float(slo_target_pct)
        self.up_pending_per_host = float(up_pending_per_host)
        self.down_idle_share = float(down_idle_share)
        self.confirm_ticks = max(1, int(confirm_ticks))
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self.state_path = state_path
        self._prev: Optional[dict] = None  # last observation's raw inputs
        self._want: Optional[str] = None
        self._streak = 0
        self._recommendation = "hold"
        self._last_change: Optional[float] = None
        if state_path is not None:
            self._load_state()

    @classmethod
    def for_root(cls, root: str, **kw) -> "CapacityPlanner":
        """A planner keyed on the fleet root: state in
        ``{root}/_capacity_state.json``, slope baseline seeded from the
        root's retained history when no state file exists yet."""
        p = cls(state_path=os.path.join(str(root), cls.STATE_FILENAME),
                **kw)
        if p._prev is None:
            p._seed_prev_from_history(str(root))
        return p

    # -- persistence --------------------------------------------------------
    def _load_state(self) -> None:
        st = _load_json(str(self.state_path))
        if st is None or st.get("schema") != self.STATE_SCHEMA:
            return
        self._want = st.get("want")
        self._streak = int(st.get("streak") or 0)
        self._recommendation = str(st.get("recommendation") or "hold")
        lc = st.get("last_change")
        self._last_change = float(lc) if lc is not None else None
        prev = st.get("prev")
        self._prev = dict(prev) if isinstance(prev, dict) else None

    def _save_state(self) -> None:
        if self.state_path is None:
            return
        from .telemetry.jsonl import write_json_atomic
        try:
            write_json_atomic(str(self.state_path), {
                "schema": self.STATE_SCHEMA,
                "want": self._want,
                "streak": self._streak,
                "recommendation": self._recommendation,
                "last_change": self._last_change,
                "prev": self._prev,
            })
        except OSError as e:
            print(f"vft-fleet: cannot persist capacity state to "
                  f"{self.state_path}: {type(e).__name__}: {e}",
                  file=sys.stderr)

    def _seed_prev_from_history(self, root: str) -> None:
        """Baseline the idle/attainment slopes from the newest retained
        sample per host (telemetry/history.py) — real data instead of a
        null first window."""
        from .telemetry.history import read_history
        series = read_history(root)
        if not series:
            return
        idle = up = req = vio = 0.0
        t_max = None
        for samples in series.values():
            s = samples[-1]
            t = float(s.get("time") or 0.0)
            t_max = t if t_max is None else max(t_max, t)
            fl = s.get("fleet") or {}
            idle += float(fl.get("idle_wait_s_total") or 0.0)
            up += float(s.get("uptime_s") or 0.0)
            slo = s.get("slo") or {}
            req += float(slo.get("requests") or 0)
            vio += float(slo.get("violations") or 0)
        if t_max is None:
            return
        self._prev = {
            "idle_wait_s_total": idle, "uptime_s": up,
            "attainment_pct": (round(100.0 * (req - vio) / req, 2)
                               if req else None),
            "time": t_max,
        }

    # -- signal derivation --------------------------------------------------
    def _signals(self, agg: dict, now: float) -> dict:
        live = int((agg.get("n_hosts") or {}).get("live") or 0)
        q = agg.get("queue")
        pending = claimed = None
        if isinstance(q, dict):
            pending = int(q.get("pending") or 0)
            claimed = int(q.get("claimed") or 0)
        pending_per_host = (round(pending / max(1, live), 3)
                            if pending is not None else None)
        # idle share: prefer the delta between this observation and the
        # last (the live stall rate); first observation falls back to
        # the cumulative share since fleet start
        ci = agg.get("capacity_inputs") or {}
        idle_now = float(ci.get("idle_wait_s_total") or 0.0)
        up_now = float(ci.get("uptime_s") or 0.0)
        idle_share = None
        if ci.get("fleet_hosts"):
            prev = self._prev or {}
            d_idle = idle_now - float(prev.get("idle_wait_s_total", 0.0))
            d_up = up_now - float(prev.get("uptime_s", 0.0))
            if self._prev is not None and d_up > 0.5:
                idle_share = max(0.0, min(1.0, d_idle / d_up))
            elif up_now > 0:
                idle_share = max(0.0, min(1.0, idle_now / up_now))
        att = (agg.get("serve") or {}).get("totals", {}) \
            .get("attainment_pct")
        att = float(att) if att is not None else None
        slope = None
        if att is not None and self._prev is not None and \
                self._prev.get("attainment_pct") is not None:
            dt_min = (now - float(self._prev["time"])) / 60.0
            if dt_min > 1e-3:
                slope = round(
                    (att - float(self._prev["attainment_pct"])) / dt_min, 3)
        return {"live": live, "pending": pending, "claimed": claimed,
                "pending_per_host": pending_per_host,
                "idle_share": (round(idle_share, 4)
                               if idle_share is not None else None),
                "attainment_pct": att,
                "attainment_slope_pct_per_min": slope,
                "idle_wait_s_total": idle_now, "uptime_s": up_now,
                "time": now}

    def _pressure(self, s: dict) -> Tuple[str, List[str]]:
        reasons: List[str] = []
        want = "hold"
        if s["pending"] and not s["live"]:
            return "scale_up", [f"{s['pending']} item(s) pending with no "
                                "live host"]
        if s["pending_per_host"] is not None and \
                s["pending_per_host"] >= self.up_pending_per_host:
            want = "scale_up"
            reasons.append(f"queue depth {s['pending_per_host']}/host >= "
                           f"{self.up_pending_per_host}")
        if s["attainment_pct"] is not None and \
                s["attainment_pct"] < self.slo_target_pct and \
                (s["attainment_slope_pct_per_min"] is None
                 or s["attainment_slope_pct_per_min"] <= 0):
            want = "scale_up"
            reasons.append(
                f"SLO attainment {s['attainment_pct']}% < "
                f"{self.slo_target_pct}% and not recovering "
                f"(slope {s['attainment_slope_pct_per_min']}%/min)")
        if want == "hold" and s["live"] > 1 and s["pending"] == 0 and \
                (s["claimed"] or 0) == 0 and s["idle_share"] is not None \
                and s["idle_share"] >= self.down_idle_share:
            want = "scale_down"
            reasons.append(f"queue drained and idle-wait share "
                           f"{s['idle_share']:.0%} >= "
                           f"{self.down_idle_share:.0%}")
        if not reasons:
            reasons.append("signals inside bands")
        return want, reasons

    # -- the observation step ----------------------------------------------
    def observe(self, agg: dict, now: Optional[float] = None) -> dict:
        now = self.clock() if now is None else float(now)
        s = self._signals(agg, now)
        want, reasons = self._pressure(s)
        if want == self._want:
            self._streak += 1
        else:
            self._want, self._streak = want, 1
        flipped = False
        if want != self._recommendation:
            confirmed = self._streak >= self.confirm_ticks
            cooled = (self._last_change is None
                      or now - self._last_change >= self.cooldown_s)
            if confirmed and cooled:
                self._recommendation = want
                self._last_change = now
                flipped = True
            elif confirmed and not cooled:
                reasons.append(
                    f"pinned by cooldown ({self.cooldown_s:.0f}s since "
                    "last change not elapsed)")
            else:
                reasons.append(
                    f"awaiting confirmation ({self._streak}/"
                    f"{self.confirm_ticks} consecutive)")
        self._prev = {"idle_wait_s_total": s["idle_wait_s_total"],
                      "uptime_s": s["uptime_s"],
                      "attainment_pct": s["attainment_pct"], "time": now}
        self._save_state()
        out = {"recommendation": self._recommendation,
               "pressure": want, "streak": self._streak,
               "changed": flipped, "reasons": reasons}
        out.update({k: s[k] for k in ("live", "pending", "claimed",
                                      "pending_per_host", "idle_share",
                                      "attainment_pct",
                                      "attainment_slope_pct_per_min")})
        return out


def render_capacity(rec: dict) -> List[str]:
    lines = [f"== capacity ==  recommendation="
             f"{rec['recommendation'].upper()}"
             + (f"  (pressure={rec['pressure']} x{rec['streak']})"
                if rec["pressure"] != rec["recommendation"] else "")]
    sig = (f"  signals: live={rec['live']}")
    if rec.get("pending") is not None:
        sig += (f" pending={rec['pending']} "
                f"({rec['pending_per_host']}/host)")
    if rec.get("idle_share") is not None:
        sig += f" idle_share={rec['idle_share']:.0%}"
    if rec.get("attainment_pct") is not None:
        sig += f" slo_attainment={rec['attainment_pct']}%"
        if rec.get("attainment_slope_pct_per_min") is not None:
            sig += f" (slope {rec['attainment_slope_pct_per_min']}%/min)"
    lines.append(sig)
    for r in rec.get("reasons", []):
        lines.append(f"  - {r}")
    return lines


# -- rendering ----------------------------------------------------------------

def render(agg: dict, capacity: Optional[dict] = None) -> List[str]:
    lines = [f"fleet report: {agg['root']}"]
    n = agg["n_hosts"]
    lines.append(
        f"== hosts ==  {n['live']} live / {n['stalled']} stalled / "
        f"{n['finished']} finished"
        + (f" / {n['prior_run']} prior-run (ignored)"
           if n["prior_run"] else "")
        + (f" / {n['unreadable']} unreadable" if n["unreadable"] else ""))
    for e in agg["hosts"]:
        hb = e.get("hb")
        if hb is None:
            lines.append(f"  {os.path.basename(e['path'])}: unreadable")
            continue
        if e["prior_run"]:
            lines.append(f"  {hb.get('host_id')}: PRIOR RUN "
                         f"(run_id={hb.get('run_id')}) — ignored")
            continue
        tag = {"live": "alive", "STALLED": "STALLED?",
               "FINISHED": "FINISHED"}[e["state"]]
        line = (f"  {hb.get('host_id')}: {tag}  "
                f"age={_fmt_age(e['age_s'])}  "
                f"done={hb.get('videos_done', 0)}  "
                f"videos/s={hb.get('videos_per_s')}")
        fl = hb.get("fleet")
        if isinstance(fl, dict):
            line += (f"  [fleet claimed={fl.get('claimed', 0)} "
                     f"done={fl.get('done', 0)} "
                     f"stolen={fl.get('stolen', 0)} "
                     f"active={fl.get('active_claims', 0)}]")
        if str(hb.get("host_id")) in agg["stragglers"]:
            line += "  STRAGGLER (fleet idle behind this host)"
        lines.append(line)
    if agg.get("alerts"):
        from .telemetry.alerts import render_alerts
        lines += render_alerts(agg["alerts"])
    if agg["queue"] is not None:
        q = agg["queue"]
        lines.append(
            f"== fleet queue ==  pending={q.get('pending', 0)}  "
            f"claimed={q.get('claimed', 0)}  done={q.get('done', 0)}"
            + (f"  quarantined={q['quarantined']}"
               if q.get("quarantined") else ""))
    ca = agg["cache"]
    if any(ca.get(k) for k in ("hits", "misses", "bypasses")):
        lines.append(
            f"== cache ==  hits={ca['hits']}  misses={ca['misses']}  "
            f"bypasses={ca['bypasses']}"
            + (f"  hit_rate={ca['hit_rate']}"
               if ca.get("hit_rate") is not None else ""))
    cc = agg.get("compile_cache") or {}
    if cc.get("attached_hosts") or cc.get("hits") or cc.get("misses"):
        lines.append(
            f"== compile cache ==  hits={cc.get('hits', 0)}  "
            f"misses={cc.get('misses', 0)}  "
            f"warm_hosts={cc.get('warm_hosts', 0)}/"
            f"{cc.get('attached_hosts', 0)}"
            + (f"  dropped={cc['dropped']}" if cc.get("dropped") else "")
            + (f"  entries={','.join(cc['entries'])}"
               if cc.get("entries") else ""))
    rf = agg.get("roofline")
    if rf and rf.get("families"):
        from .telemetry.roofline import render_verdict
        dev = rf.get("device") or {}
        parts = []
        for fam, f in sorted(rf["families"].items()):
            mfu = f.get("mfu")
            parts.append(
                f"{fam} mfu="
                + (f"{100 * mfu:.1f}%" if mfu is not None else "?")
                + f" {render_verdict(f.get('verdict'))}")
        lines.append(
            f"== roofline ==  peak={dev.get('peak_tflops')} TFLOPS "
            f"[{dev.get('source')}]  " + "; ".join(parts)
            + "  (vft-roofline for the full table)")
    gc = agg.get("gc")
    if isinstance(gc, dict):
        used = float(gc.get("used_bytes") or 0)
        quota = gc.get("quota_bytes")
        line = f"== storage ==  used={used / 1e9:.2f}GB"
        if quota:
            line += (f"  quota={float(quota) / 1e9:.2f}GB "
                     f"({100.0 * used / float(quota):.0f}%)")
        planes = gc.get("planes") or {}
        top = sorted(planes.items(), key=lambda kv: -float(kv[1] or 0))
        if top:
            line += "  " + " ".join(
                f"{p}={float(b or 0) / 1e9:.2f}GB" for p, b in top[:4])
        lines.append(line + "  (vft-gc for the full report)")
    if capacity is not None:
        lines += render_capacity(capacity)
    fams = agg["families"]
    if fams:
        lines.append("== per-family throughput (fleet-wide spans) ==")
        for fam, f in sorted(fams.items()):
            lines.append(
                f"  {fam:<10} done={f['done']:<6} error={f['error']:<4}"
                + (f" {f['s_per_video']}s/video"
                   if f.get("s_per_video") is not None else ""))
    serve = agg["serve"]
    if serve["hosts"]:
        t = serve["totals"]
        lines.append(
            f"== serve SLO ==  requests={t['requests']}  "
            f"violations={t['violations']}"
            + (f"  attainment={t['attainment_pct']}%"
               if t.get("attainment_pct") is not None else ""))
        for h in serve["hosts"]:
            slo = h["slo"]
            svc = slo.get("service") or {}
            qw = slo.get("queue_wait") or {}
            line = (f"  {h['host_id']}: {h.get('state')}  "
                    f"pending={h.get('pending')}  "
                    f"inflight={h.get('inflight')}")
            if slo.get("requests"):
                line += (f"  service p50/p95/p99="
                         f"{svc.get('p50')}/{svc.get('p95')}/"
                         f"{svc.get('p99')}s"
                         f"  wait p95={qw.get('p95')}s")
                if slo.get("slo_s") is not None:
                    line += (f"  slo={slo['slo_s']}s "
                             f"violations={slo.get('violations', 0)}"
                             f" attainment={slo.get('attainment_pct')}%")
            lines.append(line)
    tenants = serve.get("tenants") or {}
    if tenants:
        lines.append("== tenants ==")
        for t, tt in sorted(tenants.items()):
            line = (f"  {t:<12} requests={tt.get('requests', 0):<6} "
                    f"violations={tt.get('violations', 0):<4} "
                    f"rejects={tt.get('rejects', 0)}")
            if tt.get("attainment_pct") is not None:
                line += f"  attainment={tt['attainment_pct']}%"
            lines.append(line)
    for sc in agg.get("scenarios") or []:
        lines += render_scenario(sc)
    for pv in agg.get("parity") or []:
        lines += render_parity(pv)
    return lines


def render_parity(pv: dict) -> List[str]:
    """The ``== parity ==`` block for one certify verdict: the flip
    under certification, PASS/FAIL, and one max_abs/band + cos/floor
    entry per seam in pipeline order — a FAIL leads with the first
    drifted seam, the attribution the observatory exists for."""
    from .telemetry.parity import SEAMS
    head = (f"== parity ==  {pv.get('family')}"
            + (f" flip={pv.get('flip')}" if pv.get("flip") else "")
            + f": {pv.get('verdict')}")
    if pv.get("first_drift"):
        head += f"  first_drift={pv['first_drift']}"
    parts = []
    for seam in SEAMS:
        m = (pv.get("seams") or {}).get(seam)
        if not isinstance(m, dict):
            continue
        mark = "" if m.get("ok") else "!"
        parts.append(f"{mark}{seam}={m.get('max_abs')}/"
                     f"{m.get('tol_max_abs')}")
    if parts:
        head += "  " + " ".join(parts)
    return [head + "  (vft-parity for the full table)"]


_SPARK = "▁▂▃▄▅▆▇█"


def _spark(vals: List[Optional[float]]) -> str:
    """Attainment-curve sparkline: 0..100% maps onto 8 block heights
    (absolute scale, so two drills' curves compare at a glance); a
    window with no admitted traffic renders as '·'."""
    out = []
    for v in vals:
        if v is None:
            out.append("·")
        else:
            out.append(_SPARK[max(0, min(7, int(float(v) / 100.0 * 7.999)))])
    return "".join(out)


def render_scenario(sc: dict) -> List[str]:
    """The ``== scenarios ==`` block for one drill verdict: headline
    tallies, then one line per tenant with its windowed SLO-attainment
    curve over the scenario timeline."""
    lines = [f"== scenarios ==  {sc.get('scenario')}: "
             f"{sc.get('verdict')}  "
             f"offered={sc.get('offered', 0)}  "
             f"admitted={sc.get('admitted', 0)}  "
             f"completed={sc.get('completed', 0)}  "
             f"expired={sc.get('expired', 0)}  "
             f"429={sc.get('rejected', 0)}  shed={sc.get('shed', 0)}"
             + (f"  [audit FAIL]"
                if not (sc.get("audit") or {}).get("pass", True) else "")]
    curve = sc.get("curve") or []
    for t, tb in sorted((sc.get("tenants") or {}).items()):
        vals = [(w.get("tenants") or {}).get(t, {}).get("attainment_pct")
                for w in curve]
        line = (f"  {t:<12} attainment="
                + (f"{tb['attainment_pct']}%"
                   if tb.get("attainment_pct") is not None else "n/a"))
        if curve:
            line += (f"  curve={_spark(vals)} "
                     f"({curve[0].get('t1', 0)}s windows, virtual)")
        lines.append(line)
    unmet = [o for o in sc.get("objectives") or [] if not o.get("met")]
    for o in unmet:
        what = next((k for k in o if k.startswith(("min_", "max_"))), "?")
        scope = f"tenant={o['tenant']} " if o.get("tenant") else ""
        lines.append(f"  UNMET: {scope}{what}={o.get(what)} "
                     f"actual={o.get('actual')}")
    return lines


# -- prometheus export --------------------------------------------------------

def build_prom_dump(agg: dict, capacity: Optional[dict] = None) -> dict:
    """Fleet-level gauges in the telemetry/metrics.py dump shape, so
    :func:`prometheus_text` renders them — one textfile for the whole
    fleet next to the per-host ones telemetry_report exports."""
    series: List[dict] = []

    def g(name: str, value, **labels) -> None:
        if value is None:
            return
        series.append({"name": name, "kind": "gauge",
                       "labels": {k: str(v) for k, v in labels.items()},
                       "value": float(value)})

    for state, count in agg["n_hosts"].items():
        g("vft_fleet_hosts", count, state=state)
    for e in agg["hosts"]:
        hb = e.get("hb")
        if hb is None or e["prior_run"]:
            continue
        g("vft_fleet_videos_done", hb.get("videos_done", 0),
          host_id=hb.get("host_id"))
        g("vft_fleet_videos_per_s", hb.get("videos_per_s", 0.0),
          host_id=hb.get("host_id"))
    for h in agg["stragglers"]:
        g("vft_fleet_straggler", 1, host_id=h)
    if agg["queue"] is not None:
        for k, v in agg["queue"].items():
            g("vft_fleet_queue_items", v, bucket=k)
    ca = agg["cache"]
    for k in ("hits", "misses", "bypasses"):
        g(f"vft_fleet_cache_{k}_total", ca.get(k, 0))
    g("vft_fleet_cache_hit_rate", ca.get("hit_rate"))
    cc = agg.get("compile_cache") or {}
    for k in ("hits", "misses"):
        g(f"vft_fleet_compile_cache_{k}_total", cc.get(k, 0))
    g("vft_fleet_compile_cache_hit_rate", cc.get("hit_rate"))
    g("vft_fleet_compile_cache_warm_hosts", cc.get("warm_hosts", 0))
    if capacity is not None:
        g("vft_fleet_capacity_recommendation",
          CapacityPlanner.SCALE.get(capacity["recommendation"], 0))
        g("vft_fleet_capacity_pressure",
          CapacityPlanner.SCALE.get(capacity["pressure"], 0))
        g("vft_fleet_capacity_pending_per_host",
          capacity.get("pending_per_host"))
        g("vft_fleet_capacity_idle_share", capacity.get("idle_share"))
    rf = agg.get("roofline")
    if rf:
        for fam, f in (rf.get("families") or {}).items():
            g("vft_roofline_mfu", f.get("mfu"), family=fam)
            g("vft_roofline_effective_tflops", f.get("effective_tflops"),
              family=fam)
            g("vft_roofline_dispatches_total", f.get("dispatches"),
              family=fam)
        g("vft_roofline_peak_tflops",
          (rf.get("device") or {}).get("peak_tflops"))
    gc = agg.get("gc")
    if isinstance(gc, dict):
        g("vft_gc_used_bytes", gc.get("used_bytes"))
        if gc.get("quota_bytes"):
            g("vft_gc_quota_bytes", gc["quota_bytes"])
        for plane, b in sorted((gc.get("planes") or {}).items()):
            g("vft_gc_plane_bytes", b, plane=plane)
        for tenant, b in sorted((gc.get("tenants") or {}).items()):
            g("vft_gc_tenant_bytes", b, tenant=tenant)
    for fam, f in agg["families"].items():
        g("vft_fleet_family_done", f["done"], family=fam)
        g("vft_fleet_family_errors", f["error"], family=fam)
        g("vft_fleet_family_s_per_video", f.get("s_per_video"),
          family=fam)
    t = agg["serve"]["totals"]
    g("vft_fleet_serve_requests_total", t["requests"])
    g("vft_fleet_serve_slo_violations_total", t["violations"])
    g("vft_fleet_serve_slo_attainment_pct", t.get("attainment_pct"))
    for name, tt in sorted((agg["serve"].get("tenants") or {}).items()):
        g("vft_tenant_requests_total", tt.get("requests", 0), tenant=name)
        g("vft_tenant_rejects_total", tt.get("rejects", 0), tenant=name)
        g("vft_tenant_slo_violations_total", tt.get("violations", 0),
          tenant=name)
        g("vft_tenant_slo_attainment_pct", tt.get("attainment_pct"),
          tenant=name)
    for h in agg["serve"]["hosts"]:
        # both splits of the per-host SLO block: service alone hid
        # queue-wait regressions from the prom view (vft-lint VFT005
        # surfaced the declared-but-never-exported name)
        svc = (h["slo"].get("service") or {})
        qw = (h["slo"].get("queue_wait") or {})
        for p in ("p50", "p95", "p99"):
            g("vft_fleet_serve_service_seconds", svc.get(p),
              host_id=h["host_id"], quantile=p)
            g("vft_fleet_serve_queue_wait_seconds", qw.get(p),
              host_id=h["host_id"], quantile=p)
    for sc in agg.get("scenarios") or []:
        name = sc.get("scenario")
        g("vft_scenario_pass", 1 if sc.get("verdict") == "PASS" else 0,
          scenario=name)
        for k in ("offered", "admitted", "completed", "expired",
                  "rejected", "shed"):
            g(f"vft_scenario_{k}", sc.get(k, 0), scenario=name)
        for t, tb in sorted((sc.get("tenants") or {}).items()):
            g("vft_scenario_attainment_pct", tb.get("attainment_pct"),
              scenario=name, tenant=t)
    for pv in agg.get("parity") or []:
        fam = pv.get("family")
        flip = pv.get("flip") or "none"
        g("vft_parity_verdict_pass",
          1 if pv.get("verdict") == "PASS" else 0, family=fam, flip=flip)
        for seam, m in sorted((pv.get("seams") or {}).items()):
            if isinstance(m, dict):
                g("vft_parity_seam_error", m.get("max_abs"),
                  family=fam, seam=seam)
    if agg.get("alerts"):
        # ALERTS{alertname, alertstate, severity, scope} 1 — the exact
        # series shape Prometheus-native alert evaluators export, so
        # existing Alertmanager routing consumes the fleet's alerts with
        # zero translation (telemetry/alerts.py)
        from .telemetry.alerts import alerts_prom_series
        series.extend(alerts_prom_series(agg["alerts"]))
    return {"series": series}


# -- trace stitching ----------------------------------------------------------

def find_trace_files(root: str) -> List[Path]:
    """Every trace artifact under ``root``: ``_trace.json``
    (single-writer dirs) plus the per-host ``_trace_{host_id}.json``
    fleet workers and serve siblings write — excluding stitched/merged
    OUTPUT files, which must never feed back in as inputs."""
    return [p for p in sorted(Path(root).rglob("_trace*.json"))
            if p.name not in TRACE_OUTPUT_NAMES and not _in_incident(p)]


def _host_label(doc: dict, trace_dir: str) -> str:
    """Lane name for one host's trace: the recorder's own host_id stamp
    when present, else the heartbeat host_id that shares the trace's
    directory (pid-qualified, fleet-unique), else host+pid metadata."""
    other = doc.get("otherData") or {}
    if other.get("host_id"):
        return str(other["host_id"])
    pid = other.get("pid")
    candidates = sorted(_glob.glob(os.path.join(trace_dir,
                                                HEARTBEAT_GLOB)))
    ids = []
    for p in candidates:
        hb = _load_json(p)
        if hb is None:
            continue
        if pid is not None and hb.get("pid") == pid:
            return str(hb.get("host_id"))
        ids.append(str(hb.get("host_id")))
    if len(ids) == 1:
        return ids[0]
    host = other.get("host") or "host"
    return f"{host}-{pid}" if pid is not None else str(host)


def stitch_traces(docs: List[Tuple[str, dict]]) -> dict:
    """Merge N hosts' trace docs into one Chrome-trace file on one
    wall-clock timeline.

    ``docs`` is ``[(lane_label, doc), ...]``. Each doc's events keep
    every field (the per-``ph`` required sets check_trace_schema pins)
    except: ``ts`` shifts by the doc's wall-clock anchor offset against
    the earliest anchor, and ``pid`` remaps to a per-host value so
    Perfetto renders one process group per host, titled with the lane
    label. Docs without an anchor stay at offset 0 (aligned to the
    earliest-anchored host's start) and are listed in
    ``otherData.unanchored``."""
    anchors = [
        (doc.get("otherData") or {}).get("start_unix") for _, doc in docs]
    known = [float(a) for a in anchors if isinstance(a, (int, float))]
    t0 = min(known) if known else None
    events: List[dict] = []
    hosts: List[dict] = []
    unanchored: List[str] = []
    for i, (label, doc) in enumerate(docs):
        pid = STITCH_PID_BASE + i
        anchor = anchors[i]
        offset_us = (float(anchor) - t0) * 1e6 \
            if isinstance(anchor, (int, float)) and t0 is not None else 0.0
        if not isinstance(anchor, (int, float)):
            unanchored.append(label)
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "args": {"name": label}})
        for ev in doc.get("traceEvents", []):
            if not isinstance(ev, dict):
                continue
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue  # replaced by the host lane title above
            ev = dict(ev)
            ev["pid"] = pid
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = round(ev["ts"] + offset_us, 3)
            events.append(ev)
        hosts.append({"host_id": label, "pid": pid,
                      "start_unix": anchor,
                      "offset_ms": round(offset_us / 1e3, 3)})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "schema": STITCH_SCHEMA,
            "hosts": hosts,
            "anchor_unix": t0,
            "unanchored": unanchored,
            "aligned": bool(known) and not unanchored,
        },
    }


def stitch(root: str, out_path: Optional[str] = None
           ) -> Tuple[Optional[str], dict]:
    """Find every ``_trace.json`` under ``root``, stitch, write.
    Returns ``(written path or None, stitched doc)``."""
    found = find_trace_files(root)
    docs: List[Tuple[str, dict]] = []
    for p in found:
        doc = _load_json(str(p))
        if doc is None or not isinstance(doc.get("traceEvents"), list):
            print(f"vft-fleet: skipping unreadable/non-trace {p}",
                  file=sys.stderr)
            continue
        docs.append((_host_label(doc, str(p.parent)), doc))
    if not docs:
        return None, {"traceEvents": [], "otherData": {
            "schema": STITCH_SCHEMA, "hosts": [], "anchor_unix": None,
            "unanchored": [], "aligned": False}}
    merged = stitch_traces(docs)
    out = out_path or os.path.join(str(root), "_trace_fleet.json")
    from .utils.sinks import _write_bytes_atomic
    # the stitched trace lands in the shared fleet root: atomic, so a
    # concurrently-watching Perfetto reader never loads a torn document
    _write_bytes_atomic(out, json.dumps(merged).encode("utf-8"))
    return out, merged


# -- request lookup -----------------------------------------------------------

def find_request(root: str, request_id: str) -> List[str]:
    """Every artifact record one request produced, fleet-wide: span
    records, health digests, failure-journal entries, trace spans, the
    spool request/response files and fleet-queue claims carrying the id
    (telemetry/context.py stamps them all in serve mode)."""
    rid = str(request_id)
    hits: List[str] = []
    root_p = Path(root)
    for name, kind in ((SPANS_FILENAME, "span"), (HEALTH_FILENAME,
                       "health"), (FAILURES_FILENAME, "failure")):
        for path in sorted(root_p.rglob(name)):
            if _in_incident(path):
                continue
            for rec in read_jsonl(path):
                if rec.get("request_id") == rid or rec.get("id") == rid:
                    tail = (f"status={rec.get('status')}" if kind == "span"
                            else f"key={rec.get('key')} sig="
                                 f"{str(rec.get('sig'))[:12]}"
                            if kind == "health"
                            else f"category={rec.get('category')}")
                    hits.append(f"{kind}  {path}  video="
                                f"{rec.get('video')}  {tail}")
    for path in find_trace_files(root):
        doc = _load_json(str(path))
        if doc is None:
            continue
        for ev in doc.get("traceEvents", []):
            if not isinstance(ev, dict):
                continue
            args = ev.get("args") or {}
            if rid in (args.get("request"), args.get("id"),
                       args.get("request_id")):
                hits.append(f"trace  {path}  {ev.get('name')} "
                            f"ts={ev.get('ts')} dur={ev.get('dur')}")
    for sub in ("requests", "done"):
        for path in sorted(root_p.rglob(os.path.join(sub,
                                                     f"{rid}.json"))):
            hits.append(f"spool  {path}")
    for path in sorted(root_p.rglob("*.json")):
        if "_queue" not in path.parts and "claimed" not in path.parts:
            continue
        rec = _load_json(str(path))
        if rec is not None and rid in (rec.get("request_id"),
                                       rec.get("id")):
            hits.append(f"claim  {path}  host={rec.get('host_id')}")
    return hits


# -- CLI ----------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="one-view fleet report over a shared out_root/spool")
    ap.add_argument("root", help="the fleet's shared output root (or a "
                                 "vft-serve spool dir)")
    ap.add_argument("--watch", action="store_true",
                    help="live refresh until interrupted")
    ap.add_argument("--every", type=float, default=2.0,
                    help="--watch refresh period in seconds (default 2)")
    ap.add_argument("--iterations", type=int, default=0,
                    help="--watch passes before exiting (0 = forever; "
                         "1 = single-pass, for scripts/tests)")
    ap.add_argument("--prom", metavar="FILE", default=None,
                    help="write a fleet-level Prometheus textfile")
    ap.add_argument("--stitch", nargs="?", const="", metavar="OUT",
                    default=None,
                    help="merge every host's _trace.json into one "
                         "wall-clock-aligned Perfetto file (default "
                         "{root}/_trace_fleet.json)")
    ap.add_argument("--request", metavar="ID", default=None,
                    help="print every artifact record one request id "
                         "produced, fleet-wide")
    ap.add_argument("--fail-on-alert", action="store_true",
                    help="exit 1 while any alert episode is firing "
                         "(prior-run excluded) — the fleet-level twin of "
                         "telemetry_report's gate (telemetry/alerts.py)")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.root):
        print(f"error: {args.root} is not a directory", file=sys.stderr)
        return 2

    if args.request:
        hits = find_request(args.root, args.request)
        if not hits:
            print(f"request {args.request}: no artifacts under "
                  f"{args.root}")
            return 1
        print(f"request {args.request}: {len(hits)} record(s)")
        for h in hits:
            print(f"  {h}")
        return 0

    # capacity decision plane: one planner across every --watch pass,
    # PERSISTED at the root (`_capacity_state.json`) so hysteresis,
    # cooldown and the slope baseline survive watcher restarts — and
    # seeded from the retained history series when starting fresh
    planner = CapacityPlanner.for_root(args.root)
    capacity = None
    agg = None
    passes = 0
    while True:
        agg = aggregate(args.root)
        capacity = planner.observe(agg)
        text = "\n".join(render(agg, capacity=capacity))
        if args.watch and passes > 0:
            # ANSI clear+home: the operator's top(1) for the fleet
            sys.stdout.write("\x1b[2J\x1b[H")
        print(text)
        passes += 1
        if not args.watch or (args.iterations and
                              passes >= args.iterations):
            break
        try:
            time.sleep(max(0.05, args.every))
        except KeyboardInterrupt:
            break

    if args.prom:
        agg = aggregate(args.root)
        capacity = planner.observe(agg)
        dump = build_prom_dump(agg, capacity=capacity)
        from .utils.sinks import _write_bytes_atomic
        # the node-exporter textfile collector reads on its own cadence:
        # the textfile convention is write-temp-then-rename for a reason
        _write_bytes_atomic(args.prom,
                            prometheus_text(dump).encode("utf-8"))
        print(f"prometheus textfile: {args.prom} "
              f"({len(dump['series'])} series)")
    if args.stitch is not None:
        out = args.stitch or None
        path, merged = stitch(args.root, out)
        other = merged.get("otherData", {})
        if path is None:
            print(f"stitch: no {TRACE_FILENAME} under {args.root} — "
                  "run hosts with trace=true", file=sys.stderr)
            return 1
        print(f"stitched fleet trace: {path} "
              f"({len(merged['traceEvents'])} events, "
              f"{len(other.get('hosts', []))} host lane(s), "
              + ("wall-clock aligned" if other.get("aligned")
                 else "UNALIGNED — unanchored traces present")
              + ") — open in https://ui.perfetto.dev")
    if args.fail_on_alert:
        firing = [a for a in (agg or {}).get("alerts") or []
                  if a.get("state") == "firing"]
        if firing:
            print("fail-on-alert: "
                  + ", ".join(f"{a['rule']}({a['scope']})"
                              for a in firing), file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
