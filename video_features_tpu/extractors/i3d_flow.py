"""I3D flow stream: RAFT/PWC flow -> flow-quantization transforms -> I3D.

Composes the flow models (models/raft.py, models/pwc.py) into ExtractI3D,
mirroring reference models/i3d/extract_i3d.py:151-157 (flow computed between
consecutive frames of the resized, *uncropped* stack) and the flow transform
chain TensorCenterCrop(224) -> Clamp(-20, 20) -> ToUInt8 -> ScaleTo1_1
(extract_i3d.py:53-59).
"""
from __future__ import annotations

import numpy as np


class FlowStream:
    def __init__(self, parent, args, mesh, dtype, weights_path,
                 allow_random) -> None:
        raise NotImplementedError(
            "I3D flow stream requires the RAFT/PWC flow models; "
            "run with streams=rgb until they land")

    def run(self, group: np.ndarray) -> np.ndarray:
        raise NotImplementedError
