"""I3D flow stream: RAFT/PWC flow -> flow-quantization transforms -> I3D.

Composes the flow models into ExtractI3D, mirroring reference
models/i3d/extract_i3d.py:140-169:

  - flow is computed between consecutive frames of the resized, *uncropped*
    stack; the RAFT path replicate-pads the whole stack to /8 first
    (``padder.pad(rgb_stack)[:-1]`` vs ``[1:]``, extract_i3d.py:153) and the
    flow is never unpadded,
  - so the flow transform chain TensorCenterCrop(224) -> Clamp(-20, 20) ->
    ToUInt8 -> ScaleTo1_1 (extract_i3d.py:53-59) crops the center of the
    *padded* flow field,
  - the quantized flow feeds the 2-channel I3D (Kinetics flow checkpoint).

TPU split of that chain: RAFT + crop + clamp + quantization run in one jitted
pair-batched program (the D2H transfer is the small (T, 224, 224, 2) crop,
not the full-resolution field); the [-1, 1] scaling runs inside the jitted
I3D forward where XLA fuses it into the first conv. ``ToUInt8`` is
``round(128 + 255/40 * x)`` on *floats* — values can reach 256.0 at the +20
clamp boundary and torch's round is half-to-even, matching ``jnp.round`` —
so the intermediate stays float32 rather than an actual uint8 cast
(reference models/transforms.py:168-176). The PWC path (extract_i3d.py:
154-155) skips the padder: PWCNet handles sizing internally.
"""
from __future__ import annotations

from functools import partial

import jax.numpy as jnp
import numpy as np

from ..models import i3d as i3d_model
from ..models import raft as raft_model
from ..parallel.mesh import DataParallelApply, cast_floating
from ..weights import store


def _crop_quantize(flow: jnp.ndarray, crop: int) -> jnp.ndarray:
    """TensorCenterCrop -> Clamp(-20,20) -> ToUInt8 (extract_i3d.py:53-59)."""
    hp, wp = flow.shape[1], flow.shape[2]
    i, j = (hp - crop) // 2, (wp - crop) // 2  # TensorCenterCrop floor rule
    flow = flow[:, i:i + crop, j:j + crop, :]
    flow = jnp.clip(flow, -20.0, 20.0)
    return jnp.round(128.0 + 255.0 / 40.0 * flow)


def _raft_quantized_flow(model: raft_model.RAFT, crop: int, params,
                         pairs_u8):
    """(B, 2, H, W, 3) uint8 -> (B, crop, crop, 2) quantized flow floats."""
    flow, _ = raft_model.padded_flow(model, params,
                                     pairs_u8.astype(jnp.float32))
    return _crop_quantize(flow, crop)


def _pwc_quantized_flow(model, crop: int, params, pairs_u8):
    """PWC twin of :func:`_raft_quantized_flow` — input-resolution flow, no
    padding (the crop happens on the unpadded field)."""
    x = pairs_u8.astype(jnp.float32)
    flow = model.apply({"params": params}, x[:, 0], x[:, 1])
    return _crop_quantize(flow, crop)


#: HBM budget for one pair-batch forward's correlation pyramid — the
#: dominant RAFT allocation, (pairs, P, Hsum, Wp) f32 (kernels/corr_lookup
#: stack_aligned_pyramid). The fallback 7 GiB picks 4 stacks/forward at the
#: 224px flagship geometry (6.6 GB, measured fine on 16 GB v5e incl.
#: towers) and scales down automatically for larger source resolutions.
_FLOW_PYRAMID_BUDGET_FALLBACK = 7 * 1024 ** 3


def _flow_pyramid_budget() -> int:
    """Size the pyramid budget from the actual device HBM when the runtime
    reports it (advisor r4: the 7 GiB constant assumed a 16 GB v5e — a
    smaller-HBM chip would OOM at k=4, a larger one under-batch). Uses the
    same 7/16 fraction the measured v5e number embodied; falls back to the
    constant when memory_stats is unavailable (CPU backend, older runtimes).
    """
    try:
        import jax
        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        if limit:
            return int(limit * 7 / 16)
    except Exception:
        pass
    return _FLOW_PYRAMID_BUDGET_FALLBACK


def _stacks_per_forward(t: int, h: int, w: int, cap: int = 4) -> int:
    """How many stacks' pair batches to fuse into one flow forward.

    Round-4 measurement (scripts/bench_i3d_variants.py, interleaved): 1 ->
    2 -> 4 stacks per RAFT forward measured 3.94 -> 4.41 -> 4.50 stacks/s
    unfused and 5.90 -> 6.34 fused at 64f@224px on v5e — more queries per
    launch amortize per-dispatch and per-scan-iteration fixed costs.
    Power-of-two result (wire buckets pad power-of-two), capped by the
    pyramid HBM budget at this geometry."""
    from ..kernels.corr_lookup import stacked_plane_cells
    h8, w8 = -(-h // 8), -(-w // 8)  # RAFT pads inputs to /8 (InputPadder)
    per_stack = t * (h8 * w8) * 4 * stacked_plane_cells(
        h8, w8, levels=raft_model.CORR_LEVELS)
    budget = _flow_pyramid_budget()
    k = 1
    while k * 2 <= cap and (k * 2) * per_stack <= budget:
        k *= 2
    return k


def _pwc_stacks_per_forward(t: int, h: int, w: int, cap: int = 4,
                            bytes_per_el: int = 2) -> int:
    """PWC twin of :func:`_stacks_per_forward`.

    PWC's dominant live set is not an all-pairs pyramid but the per-pair
    decoder activations: two extractor pyramids (~15·HpWp elements/pair
    summed over levels) plus the /4-resolution DenseNet concat stack
    (peak ~565 channels -> ~35·HpWp) and smaller coarse levels (~20·HpWp),
    ≈ 70·Hp·Wp elements/pair — ~9 MB/pair bf16 at 256x256 (validated:
    256 pairs = 2.3 GB ran clean on v5e in the round-5 A/B).
    ``bytes_per_el`` is 2 under precision=bfloat16, 4 for f32 runs (the
    default precision) — the caller passes the flow dtype's width.
    Power-of-two k under the device-derived budget, same wire-bucket
    rationale."""
    hp, wp = -(-h // 64) * 64, -(-w // 64) * 64
    per_pair = 70 * hp * wp * bytes_per_el
    per_stack = t * per_pair
    budget = _flow_pyramid_budget()
    k = 1
    while k * 2 <= cap and (k * 2) * per_stack <= budget:
        k *= 2
    return k


class FlowStream:

    def __init__(self, parent, args, mesh, dtype, allow_random) -> None:
        self.parent = parent
        self._flow_dtype = dtype  # sizes the PWC stack-batch HBM budget
        # stacks fused per flow forward: 'auto' (geometry-sized at dispatch,
        # see _stacks_per_forward) or a forced integer
        raw_sb = args.get("flow_stack_batch", "auto")
        self.stack_batch = None if raw_sb in (None, "auto") else int(raw_sb)
        if self.stack_batch is not None and self.stack_batch < 1:
            raise ValueError(
                f"flow_stack_batch={self.stack_batch}: need >= 1 or 'auto'")
        crop = parent.central_crop_size
        if parent.flow_type == "raft":
            # corr-lookup dispatch from config keys (validated in
            # sanity_check), installed before the first traced forward —
            # env vars stay perf-probe overrides (models/raft.py)
            raft_model.configure_corr_lookup(args.get("corr_lookup_impl"),
                                             args.get("fuse_convc1"))
            # the reference hardcodes the sintel checkpoint for the i3d flow
            # sub-model (extract_i3d.py:178); flow_iters trades flow accuracy
            # for speed (fewer GRU refinement steps) — default is the
            # reference's fixed 20 (raft.py:118). Under precision=bfloat16
            # the RAFT conv stacks run bf16 too (models/raft.py RAFT.dtype):
            # the ~0.1 px flow drift is well under the ToUInt8 quantization
            # step this stream applies anyway. The standalone RAFT extractor
            # stays f32 — there the flow field IS the output.
            raw = args.get("flow_iters")
            iters = raft_model.ITERS if raw is None else int(raw)
            if iters < 1:
                raise ValueError(
                    f"flow_iters={iters}: RAFT needs at least one GRU "
                    "refinement iteration")
            flow_model = raft_model.RAFT(iters=iters, dtype=dtype)
            flow_params = store.resolve_params(
                "raft_sintel", raft_model.init_params,
                raft_model.params_from_torch,
                weights_path=args.get("flow_model_weights_path"),
                allow_random=allow_random)
            flow_params = cast_floating(flow_params, dtype)
            self._quant_fn = partial(_raft_quantized_flow, flow_model, crop)
            self.pair_runner = DataParallelApply(
                self._quant_fn, flow_params,
                mesh=mesh, fixed_batch=parent.stack_size)
        elif parent.flow_type == "pwc":
            # PWC path: no padder — the net resizes to /64 internally and
            # returns input-resolution flow (extract_i3d.py:154-155).
            # Under precision=bfloat16 the conv stacks run bf16 like RAFT's
            # (models/pwc.py PWCNet.dtype; flow/warp math stays f32):
            # measured drift 0.015 px max — an order of magnitude under
            # the ToUInt8 quantization step this stream applies.
            from ..models import pwc as pwc_model
            flow_model = pwc_model.PWCNet(dtype=dtype)
            flow_params = store.resolve_params(
                "pwc_sintel", pwc_model.init_params,
                pwc_model.params_from_torch,
                weights_path=args.get("flow_model_weights_path"),
                allow_random=allow_random)
            self._quant_fn = partial(_pwc_quantized_flow, flow_model, crop)
            self.pair_runner = DataParallelApply(
                self._quant_fn, flow_params,
                mesh=mesh, fixed_batch=parent.stack_size)
        else:
            raise NotImplementedError(
                f"flow_type={parent.flow_type!r}; reference supports "
                "raft/pwc (extract_i3d.py:151-157)")

        from .i3d import _i3d_forward
        i3d_params = store.resolve_params(
            "i3d_flow", partial(i3d_model.init_params, "flow"),
            i3d_model.params_from_torch,
            weights_path=args.get("flow_weights_path"),
            allow_random=allow_random)
        # cast once for both runners
        i3d_params = cast_floating(i3d_params, dtype)
        self.runner = DataParallelApply(
            partial(_i3d_forward, parent.model, dtype, True),
            i3d_params, mesh=mesh, fixed_batch=parent.clip_batch_size)
        if parent.show_pred:
            parent.logits_runners["flow"] = DataParallelApply(
                partial(_i3d_forward, parent.model, dtype, False),
                i3d_params, mesh=mesh, fixed_batch=parent.clip_batch_size)

    def run(self, group: np.ndarray, stack_base: int) -> np.ndarray:
        """group: (G, stack+1, H, W, 3) uint8 resized frames -> (G, 1024).

        The flow->i3d handoff stays on device: each stack's pair batch is
        *dispatched* (async, no D2H) and the quantized crops — the largest
        intermediate, (G, T, 224, 224, 2) float32 — are stacked as device
        arrays and fed straight to the I3D runner. Only the (G, 1024)
        features cross back to the host (the reference round-trips every
        stack through host tensors between its two models)."""
        flow_in = self._device_flow(group)
        out = self.runner(flow_in)
        self.parent.maybe_show_pred("flow", flow_in, stack_base)
        return out

    def dispatch(self, group: np.ndarray):
        """Async twin of :meth:`run` (no show_pred): the whole flow->i3d
        chain enqueued, un-materialized (G_padded, 1024) device array out."""
        return self.runner.dispatch(self._device_flow(group))

    def dispatch_resized(self, resized_u8):
        """resize=device path: same chain but over the already-on-device
        resized (G, T+1, oh, ow, 3) uint8 group — pairs are formed by lazy
        device slices, so nothing extra crosses H2D and no frame is resized
        twice. The base pair runner works unchanged (it accepts uint8/float
        frames at the resized geometry)."""
        return self.runner.dispatch(self._device_flow(resized_u8))

    def _device_flow(self, group):
        t = group.shape[1] - 1  # T pairs from T+1 frames
        # np/jnp both work: raw host groups arrive as np, resized device
        # groups as jax arrays (rows sliced lazily). Multiple stacks' pair
        # batches fuse into ONE flow forward (k*T pairs): more queries per
        # launch amortize per-dispatch and per-scan-iteration fixed costs
        # (+45% stacks/s at 64f@224px going 1 -> 4, round-4 interleaved
        # A/B); k is geometry-budgeted so the correlation pyramid of a
        # large source cannot blow HBM (_stacks_per_forward).
        xp = jnp if not isinstance(group, np.ndarray) else np
        if self.stack_batch is not None:
            k = self.stack_batch
        elif self.parent.flow_type == "raft":
            k = _stacks_per_forward(t, *group.shape[2:4])
        else:
            # PWC budget models the decoder live set, not RAFT's all-pairs
            # pyramid (_pwc_stacks_per_forward). Round-5 interleaved A/B
            # at 64f@224px on v5e: 1 -> 2 stacks/forward took bf16 PWC
            # from 6.78 to 11.33 stacks/s (scripts/bench_i3d_variants.py
            # p1b/p2b medians).
            k = _pwc_stacks_per_forward(
                t, *group.shape[2:4],
                bytes_per_el=jnp.dtype(self._flow_dtype).itemsize)
        outs = []
        for i in range(0, len(group), k):
            chunk = group[i:i + k]            # (kc, T+1, H, W, 3)
            kc = chunk.shape[0]
            pairs = xp.stack([chunk[:, :-1], chunk[:, 1:]], axis=2)
            pairs = pairs.reshape((kc * t,) + pairs.shape[2:])
            # dispatch() keeps padded rows (the wire bucket may exceed
            # kc*t), so slice back to the valid pairs — a lazy device slice
            q = self.pair_runner.dispatch(pairs)[:kc * t]
            outs.append(q.reshape((kc, t) + q.shape[1:]))
        return jnp.concatenate(outs) if len(outs) > 1 else outs[0]
