"""RAFT flow extractor.

Parity target: reference models/raft/extract_raft.py (+ base_flow_extractor):
sintel/kitti checkpoints, optional edge resize, replicate pad to /8
(InputPadder 'sintel' mode) before the net and unpad after
(base_flow_extractor.py:90, 108-114).
"""
from __future__ import annotations

from functools import partial

import jax.numpy as jnp

from ..config import Config
from ..models import raft as raft_model
from ..parallel.mesh import get_mesh
from ..weights import store
from .flow import OpticalFlowExtractor


def _raft_forward(model: raft_model.RAFT, params, pairs_u8):
    """(B, 2, H, W, 3) uint8 -> (B, H, W, 2) flow; pad/unpad inside jit."""
    flow, ((pt, pb), (pl, pr)) = raft_model.padded_flow(
        model, params, pairs_u8.astype(jnp.float32))
    hp, wp = flow.shape[1], flow.shape[2]
    return flow[:, pt:hp - pb, pl:wp - pr, :].astype(jnp.float32)


class ExtractRAFT(OpticalFlowExtractor):

    def __init__(self, args: Config) -> None:
        super().__init__(args)
        # corr-lookup dispatch from config (validated in sanity_check);
        # installed here — before anything is traced — so the old
        # set-the-env-before-first-trace footgun cannot occur. The env
        # vars remain perf-probe overrides (models/raft.py).
        raft_model.configure_corr_lookup(args.get("corr_lookup_impl"),
                                         args.get("fuse_convc1"))
        finetuned_on = args.get("finetuned_on", "sintel")
        if finetuned_on not in ("sintel", "kitti"):
            raise NotImplementedError(
                f"finetuned_on={finetuned_on!r}; reference supports "
                "sintel/kitti (extract_raft.py:6-9)")
        # iters trades flow accuracy for speed (fewer GRU refinement steps);
        # default is the reference's fixed 20 (raft.py:118)
        raw = args.get("iters")
        iters = raft_model.ITERS if raw is None else int(raw)
        if iters < 1:
            raise ValueError(
                f"iters={iters}: RAFT needs at least one GRU refinement "
                "iteration")
        # precision=bfloat16: conv stacks on the MXU-native dtype (pyramid,
        # lookup and coords stay f32 — models/raft.py). ~0.1 px drift on
        # the output flow field; default f32 remains the bit-parity path.
        dtype = (jnp.bfloat16 if self.precision == "bfloat16"
                 else jnp.float32)
        self.model = raft_model.RAFT(iters=iters, dtype=dtype)
        params = store.resolve_params(
            f"raft_{finetuned_on}", raft_model.init_params,
            raft_model.params_from_torch,
            weights_path=args.get("weights_path"),
            allow_random=bool(args.get("allow_random_weights", False)))
        if dtype is not jnp.float32:
            from ..parallel.mesh import cast_floating
            params = cast_floating(params, dtype)
        mesh = self._data_mesh()
        self._init_flow_runner(partial(_raft_forward, self.model), params,
                               mesh)
