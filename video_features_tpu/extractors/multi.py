"""Multi-family driver: compose per-family extractors over ONE decode.

``feature_type=resnet,clip,s3d`` runs every requested family per video
with a single shared decode pass (parallel/fanout.py) instead of N
invocations each paying the full cv2 decode cost — the last
order-of-magnitude-class end-to-end win on decode-bound hosts
(docs/performance.md "Decode once, extract many").

Composition, not reimplementation: each family keeps its OWN extractor
instance, config (per-family dotted overrides like
``clip.extraction_fps=2``), output directory + idempotent skip, retry
policy, failure journal, telemetry span, and output-health gate
(``health=true`` digests into the family's own ``_health.jsonl``, and a
family whose features go non-finite quarantines alone —
telemetry/health.py) — the MultiExtractor only coordinates. Per video:

  1. **Skip sweep** — families whose outputs already exist are tallied
     ``skipped`` up front; when EVERY family skips, no decoder (or wav
     rip) is even constructed.
  2. **Shared session** — remaining visual families subscribe to one
     :class:`~..parallel.fanout.FrameBus` (union frame plan, per-family
     bounded queues); audio families share one wav rip.
  3. **Per-family threads** — each family runs its existing
     ``safe_extract`` lifecycle (retries, quarantine, journal, span) on
     its own thread, so all families' transforms and device programs are
     in flight together and one family's POISON failure or quarantine
     cannot touch its siblings' outputs (tests/test_multi_family.py pins
     both the bit-identity and the isolation).

Retry attempts after a mid-stream failure cannot rejoin the one-shot
shared pass; they fall back to a private ``VideoSource`` (correctness
over sharing for the rare retry). The decode degradation ladder is
likewise a private-source concern, so ``safe_extract`` runs with
``decode_mode=None`` here.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..config import Config
from ..parallel import fanout
from ..registry import AUDIO_FAMILIES, get_extractor_cls
from ..utils import sinks
from ..utils.faults import FailureJournal, RetryPolicy


class MultiExtractor:
    """Drives N per-family extractors through shared-decode sessions."""

    def __init__(self, per_family_args: Dict[str, Config]) -> None:
        self.families: List[str] = list(per_family_args)
        self.args = dict(per_family_args)
        self.extractors = {f: get_extractor_cls(f)(a)
                           for f, a in per_family_args.items()}
        self.policies = {f: RetryPolicy.from_config(a)
                         for f, a in per_family_args.items()}
        # per-family journal in the family's own (namespaced) output dir:
        # quarantine verdicts must not leak across families
        self.journals = {
            f: (FailureJournal(a.output_path)
                if a.get("on_extraction", "print") != "print" else None)
            for f, a in per_family_args.items()}
        first = next(iter(per_family_args.values()))
        raw_depth = first.get("fanout_depth")
        self.fanout_depth = (fanout.DEFAULT_DEPTH if raw_depth is None
                             else int(raw_depth))
        if self.fanout_depth < 2:
            raise ValueError(
                f"fanout_depth={self.fanout_depth}: need >= 2")
        self.keep_tmp = any(bool(a.get("keep_tmp_files", False))
                            for a in per_family_args.values())

    # ------------------------------------------------------------------
    def run_video(self, video_path: str, recorder=None,
                  failures: Optional[list] = None) -> Dict[str, str]:
        """One video through every family; returns {family: status} with
        the same status vocabulary as ``safe_extract``."""
        from ..telemetry import NOOP_SPAN

        statuses: Dict[str, str] = {}
        pending: List[str] = []
        for f in self.families:
            ext = self.extractors[f]
            # precedence note (docs/performance.md): this sweep is the
            # FILENAME skip only — cache lookups happen inside each
            # family's _extract, where a hit returns before the family
            # ever subscribes to the bus (so an all-hit video still
            # costs zero decode: every family marks done() without a
            # subscription and the bus has no plan to walk)
            if sinks.is_already_exist(ext.on_extraction, ext.output_path,
                                      video_path, ext.output_feat_keys):
                # up-front per-family skip: when every family lands here
                # the video costs ZERO decode (no bus, no wav rip)
                from .. import telemetry
                telemetry.inc("vft_cache_bypass_total", family=str(f))
                statuses[f] = "skipped"
                if recorder is not None:
                    with recorder.video_span(video_path,
                                             feature_type=f) as span:
                        span.annotate(status="skipped", cache="bypass")
            else:
                pending.append(f)
        if not pending:
            return statuses

        visual = [f for f in pending if f not in AUDIO_FAMILIES]
        session = fanout.SharedDecodeSession(video_path, visual,
                                             depth=self.fanout_depth)

        def family_job(f: str) -> None:
            from ..telemetry import trace
            ext = self.extractors[f]
            span_cm = (recorder.video_span(video_path, feature_type=f)
                       if recorder is not None else NOOP_SPAN)
            try:
                # the family's whole per-video job as one timeline span:
                # on its thread lane it brackets subscribe-wait, transform
                # ("decode"), forward and write (trace=true; no-op off)
                with fanout.use_session(session), \
                        trace.span("family", family=f,
                                   video=str(video_path)):
                    with span_cm as span:
                        status = sinks.safe_extract(
                            ext._extract, video_path,
                            policy=self.policies[f],
                            journal=self.journals.get(f),
                            decode_mode=None,
                            on_terminal_failure=(
                                None if failures is None else
                                lambda rec: failures.append(
                                    {**rec, "family": f})))
                        span.annotate(status=status)
                        ms = session.shared_ms(f)
                        if ms is not None:
                            span.annotate(decode_shared_ms=ms)
                statuses[f] = status
            except BaseException:
                # safe_extract re-raises only KeyboardInterrupt/SystemExit
                # -class exits; on a thread those kill just this family
                statuses.setdefault(f, "error")
                raise
            finally:
                # barrier release for families that never subscribed
                # (skipped on re-check, quarantined, failed pre-decode)
                session.family_done(f)

        threads = [threading.Thread(target=family_job, args=(f,),
                                    name=f"vft-family-{f}", daemon=True)
                   for f in pending]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            session.cleanup(keep_tmp=self.keep_tmp)
        for f in pending:  # a thread that died abnormally left no status
            statuses.setdefault(f, "error")
        return statuses
