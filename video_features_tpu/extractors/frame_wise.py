"""Frame-wise extraction pipeline (ResNet, CLIP).

Re-design of reference models/_base/base_framewise_extractor.py:11-88 around a
static-shape jitted device step:

  host:   cv2 stream -> per-frame PIL resize/crop -> uint8 HWC frames
  device: fixed-(B,H,W,3) uint8 batch -> /255 -> normalize -> backbone -> (B,D)

The uint8 H2D transfer is 4x smaller than shipping float32 (HBM/PCIe
bandwidth is the usual bottleneck); scaling and normalization are fused by XLA
into the first conv. Ragged final batches are padded to the fixed shape and
the padded rows dropped on host, so only one executable is compiled per video
resolution. The batch axis is sharded over the mesh's data axis
(parallel/mesh.py), which is this framework's replacement for the reference's
"one process per GPU" scale-out.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ..config import Config
from ..parallel.mesh import DataParallelApply
from ..utils.io import Prefetcher, VideoSource
from .base import BaseExtractor


class FrameWiseExtractor(BaseExtractor):
    """Generic frame-wise driver; families plug in transform + device fn.

    Subclasses set:
      - ``self.host_transform(rgb HWC uint8) -> HWC uint8`` (resize+crop)
      - ``self.runner`` (DataParallelApply over the backbone)
      - ``self.maybe_show_pred(feats np.ndarray)``
    """

    #: wire formats: uint8 is the default AND the parity path (PIL resize
    #: outputs uint8, so nothing is lost); 'yuv420' opts into packed I420 at
    #: 1.5 bytes/pixel with colorspace conversion on device (H2D-bound hosts)
    supported_ingest = ("uint8", "yuv420")

    def __init__(self, args: Config) -> None:
        super().__init__(args)
        self.model_name = args.get("model_name")
        self.batch_size = int(args.batch_size)
        self.extraction_fps = args.get("extraction_fps")
        self.extraction_total = args.get("extraction_total")
        self.output_feat_keys = [self.feature_type, "fps", "timestamps_ms"]
        self.host_transform: Optional[Callable] = None
        self.runner: Optional[DataParallelApply] = None
        self.ingest = self._resolve_ingest(args, "uint8")
        #: resize=device (the default for save runs since the defaults
        #: flip, via resize=auto) moves the dominant host cost — PIL's
        #: antialiased filtering, ~1.3 ms/frame vs ~0.34 ms of cv2 decode —
        #: onto the MXU as two coefficient matmuls (ops/preprocess.py
        #: device_resize, within 2 LSB of PIL). The host then only decodes;
        #: raw frames ship as decoder-native uint8 BGR (3 B/px) or, under
        #: ingest=yuv420, as packed I420 planes (1.5 B/px) with the BT.601
        #: conversion fused on device in front of the resize
        #: (ops/colorspace.py). Subclasses declare resize_spec/crop_size/
        #: base_fwd/runner_builder to opt in.
        self.resize_mode = self._resolve_resize_mode(args)
        self.resize_spec = None  # (size, interpolation, to_smaller_edge)
        self.crop_size: Optional[int] = None
        self.base_fwd: Optional[Callable] = None
        self.runner_builder: Optional[Callable] = None

    def encode_wire_u8(self, u8: np.ndarray) -> np.ndarray:
        """uint8 HWC frame -> the configured wire format (transform tail)."""
        if self.ingest == "uint8":
            return u8
        from ..ops import colorspace
        return colorspace.rgb_to_yuv420(u8)

    def _device_resize_runner(self, in_h: int, in_w: int,
                              packed: bool = False) -> DataParallelApply:
        """Per-source-resolution runner: PIL-coefficient resize + center crop
        fused in front of the family's device forward. Cached so each
        resolution compiles once (same executable-per-resolution economy as
        the host path); all runners share the committed device param arrays
        (DataParallelApply's device_put of an already-committed tree with the
        same sharding is a no-op), so weights live in HBM once.

        ``packed`` (ingest=yuv420): the wire carries (in_h*3/2, in_w)
        packed I420 planes; the fused program prepends the BT.601 I420->RGB
        conversion (ops/colorspace.py, rounded back onto the uint8 lattice)
        to the resize."""
        def build():
            from ..ops import preprocess as pp
            size, interp, smaller = self.resize_spec
            if isinstance(size, int):
                ow, oh = pp.resize_edge_size(in_w, in_h, size, smaller)
            else:
                oh, ow = size
            resize = pp.make_device_resizer(in_h, in_w, oh, ow, interp)
            c = self.crop_size
            i, j = pp.center_crop_offsets(oh, ow, c, c)
            base = self.base_fwd

            if packed:
                from ..ops import colorspace

                def fwd(params, packed_u8):
                    # 1.5 B/px I420 wire: YUV->RGB, resize and crop all
                    # fuse into one device program in front of the
                    # backbone; the host never converts or resizes
                    rgb = colorspace.yuv420_frame_to_rgb_u8(
                        packed_u8, in_h, in_w)
                    x = resize(rgb)
                    return base(params, x[:, i:i + c, j:j + c, :])
            else:
                def fwd(params, raw_u8):
                    # frames arrive decoder-native BGR (channel_order
                    # below): the RGB reorder is a reversed gather XLA
                    # fuses into the resize matmul's input read — the host
                    # never runs a full-resolution cvtColor in this mode
                    x = resize(raw_u8[..., ::-1])
                    return base(params, x[:, i:i + c, j:j + c, :])

            return self.runner_builder(fwd)

        return self._cached_resize_runner((in_h, in_w, packed), build)

    def _wire_order(self, video_path: str) -> str:
        """Delivery format for resize=device: decoder-native BGR, or packed
        I420 under ingest=yuv420 (halving the raw wire again). I420 needs
        even frame dims; odd sources fall back to the BGR raw wire for
        that video — same features, 2x the bytes."""
        if self.ingest != "yuv420":
            return "bgr"
        from ..utils.io import get_video_props
        props = get_video_props(video_path)
        if props["height"] % 2 or props["width"] % 2:
            print(f"WARNING: {video_path} has odd dimensions "
                  f"{props['height']}x{props['width']}; I420 needs even "
                  "dims — shipping raw BGR for this video instead")
            return "bgr"
        return "i420"

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        device_resize = self.resize_mode == "device"
        wire_order = self._wire_order(video_path) if device_resize else "rgb"
        video = self.video_source(
            video_path,
            batch_size=self.batch_size,
            fps=self.extraction_fps,
            total=self.extraction_total,
            # device_resize: host ships raw decoded frames — decoder-
            # native BGR (the reorder rides the device resize for free)
            # or packed I420 planes under ingest=yuv420
            transform=None if device_resize else self.host_transform,
            channel_order=wire_order,
        )
        vid_feats: List[np.ndarray] = []
        timestamps_ms: List[float] = []
        # decode-ahead: the next batch decodes while this one is on-device;
        # batches are dispatched asynchronously and materialized at the end
        # (no per-batch D2H stall) unless show_pred needs per-batch values
        stream = None
        for batch, times, _ in Prefetcher(video):
            if stream is None:
                # the resize matrices come from the first *decoded* frame's
                # shape — container metadata can disagree with it (e.g.
                # rotation tags auto-applied by cv2). Packed I420 frames
                # are (H*3/2, W); recover the true source height.
                if device_resize:
                    fh, fw = batch[0].shape[:2]
                    packed = wire_order == "i420"
                    if packed:
                        fh = fh * 2 // 3
                    runner = self._device_resize_runner(fh, fw, packed)
                else:
                    runner = self.runner
                stream = self.feature_stream(
                    runner,
                    on_result=lambda feats, ctx: self.maybe_show_pred(feats))
            # runner pads ragged tails to fixed_batch
            stream.submit(np.stack(batch))
            timestamps_ms.extend(times)
        if stream is not None:
            for bi, feats in enumerate(stream.finish()):
                if self.parity:
                    # backbone seam: the per-batch activations exactly as
                    # they come off the device runner
                    from ..telemetry import parity as _parity
                    _parity.tap("backbone", self.feature_type, feats,
                                video=str(video_path),
                                feature_type=self.feature_type, index=bi)
                vid_feats.extend(list(feats))
        return {
            self.feature_type: np.array(vid_feats),
            "fps": np.array(video.fps),
            "timestamps_ms": np.array(timestamps_ms),
        }

    def maybe_show_pred(self, feats: np.ndarray) -> None:
        pass
