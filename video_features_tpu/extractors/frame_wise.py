"""Frame-wise extraction pipeline (ResNet, CLIP).

Re-design of reference models/_base/base_framewise_extractor.py:11-88 around a
static-shape jitted device step:

  host:   cv2 stream -> per-frame PIL resize/crop -> uint8 HWC frames
  device: fixed-(B,H,W,3) uint8 batch -> /255 -> normalize -> backbone -> (B,D)

The uint8 H2D transfer is 4x smaller than shipping float32 (HBM/PCIe
bandwidth is the usual bottleneck); scaling and normalization are fused by XLA
into the first conv. Ragged final batches are padded to the fixed shape and
the padded rows dropped on host, so only one executable is compiled per video
resolution. The batch axis is sharded over the mesh's data axis
(parallel/mesh.py), which is this framework's replacement for the reference's
"one process per GPU" scale-out.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ..config import Config
from ..parallel.mesh import DataParallelApply
from ..utils.io import Prefetcher, VideoSource
from .base import BaseExtractor


class FrameWiseExtractor(BaseExtractor):
    """Generic frame-wise driver; families plug in transform + device fn.

    Subclasses set:
      - ``self.host_transform(rgb HWC uint8) -> HWC uint8`` (resize+crop)
      - ``self.runner`` (DataParallelApply over the backbone)
      - ``self.maybe_show_pred(feats np.ndarray)``
    """

    #: wire formats: uint8 is the default AND the parity path (PIL resize
    #: outputs uint8, so nothing is lost); 'yuv420' opts into packed I420 at
    #: 1.5 bytes/pixel with colorspace conversion on device (H2D-bound hosts)
    supported_ingest = ("uint8", "yuv420")

    def __init__(self, args: Config) -> None:
        super().__init__(args)
        self.model_name = args.get("model_name")
        self.batch_size = int(args.batch_size)
        self.extraction_fps = args.get("extraction_fps")
        self.extraction_total = args.get("extraction_total")
        self.output_feat_keys = [self.feature_type, "fps", "timestamps_ms"]
        self.host_transform: Optional[Callable] = None
        self.runner: Optional[DataParallelApply] = None
        self.ingest = self._resolve_ingest(args, "uint8")

    def encode_wire_u8(self, u8: np.ndarray) -> np.ndarray:
        """uint8 HWC frame -> the configured wire format (transform tail)."""
        if self.ingest == "uint8":
            return u8
        from ..ops import colorspace
        return colorspace.rgb_to_yuv420(u8)

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        video = VideoSource(
            video_path,
            batch_size=self.batch_size,
            fps=self.extraction_fps,
            total=self.extraction_total,
            transform=self.host_transform,
        )
        vid_feats: List[np.ndarray] = []
        timestamps_ms: List[float] = []
        # decode-ahead: the next batch decodes while this one is on-device;
        # batches are dispatched asynchronously and materialized at the end
        # (no per-batch D2H stall) unless show_pred needs per-batch values
        stream = self.feature_stream(
            self.runner, on_result=lambda feats, ctx: self.maybe_show_pred(feats))
        for batch, times, _ in Prefetcher(video):
            # runner pads ragged tails to fixed_batch
            stream.submit(np.stack(batch))
            timestamps_ms.extend(times)
        for feats in stream.finish():
            vid_feats.extend(list(feats))
        return {
            self.feature_type: np.array(vid_feats),
            "fps": np.array(video.fps),
            "timestamps_ms": np.array(timestamps_ms),
        }

    def maybe_show_pred(self, feats: np.ndarray) -> None:
        pass
