"""VGGish audio extractor.

Parity target: reference models/vggish/extract_vggish.py — rip the audio
track of an ``.mp4`` to wav (ffmpeg, two-step via aac), or accept a bare
``.wav``; run the full waveform through the mel frontend into 0.96 s
examples and the VGG embedding net; output key list is just ``[vggish]``
(no fps/timestamps — extract_vggish.py:27); ``show_pred`` is unsupported
(extract_vggish.py:25-26); temp audio files are removed unless
``keep_tmp_files`` (extract_vggish.py:53-56).

TPU split: the numpy mel frontend runs on host (ops/audio.py), the conv
stack runs as fixed-(B, 96, 64, 1) batches sharded over the mesh. The
reference forwards all examples in one variable-size batch; batching +
padding here keeps one compiled executable for any video length.
"""
from __future__ import annotations

from functools import partial
from pathlib import Path
from typing import Dict

import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..models import vggish as vggish_model
from ..ops import audio
from ..parallel.mesh import DataParallelApply, cast_floating, get_mesh
from ..utils.io import extract_wav_from_mp4
from ..weights import store
from .base import BaseExtractor


def _device_forward(model: vggish_model.VGGish, dtype, params, batch):
    x = batch.astype(dtype)
    return model.apply({"params": params}, x).astype(jnp.float32)


def _device_forward_waveform(model: vggish_model.VGGish, dtype, params,
                             chunks):
    """(B, 15600) waveform chunks -> (B, 128): the whole mel frontend
    (framing, periodic-Hann STFT, HTK mel matmul, log — ops/audio.py
    logmel_examples_jnp) fused into the jitted VGG forward, so the host
    only mono-mixes/resamples/slices (frontend=device)."""
    x = audio.logmel_examples_jnp(chunks).astype(dtype)
    return model.apply({"params": params}, x).astype(jnp.float32)


class ExtractVGGish(BaseExtractor):

    def __init__(self, args: Config) -> None:
        super().__init__(args)
        if self.show_pred:
            raise NotImplementedError(
                "show_pred is unsupported for vggish "
                "(reference extract_vggish.py:25-26)")
        self.output_feat_keys = [self.feature_type]
        self.batch_size = int(args.get("batch_size") or 32)
        self.model = vggish_model.VGGish()
        params = store.resolve_params(
            "vggish", vggish_model.init_params,
            vggish_model.params_from_torch,
            weights_path=args.get("weights_path"),
            allow_random=bool(args.get("allow_random_weights", False)))
        dtype = jnp.bfloat16 if self.precision == "bfloat16" else jnp.float32
        mesh = self._data_mesh()
        self.frontend = args.get("frontend") or "host"
        if self.frontend not in ("host", "device"):
            raise NotImplementedError(f"frontend={self.frontend!r}")
        fwd = (_device_forward_waveform if self.frontend == "device"
               else _device_forward)
        self.runner = DataParallelApply(
            partial(fwd, self.model, dtype),
            cast_floating(params, dtype),
            mesh=mesh, fixed_batch=self.batch_size)

        # PCA+quantize postprocessing is identity-by-default in the reference
        # (vggish_slim.py:95-99); opt in with postprocess=true + pca weights
        self._pca = None
        if bool(args.get("postprocess", False)):
            pca_path = store.find_checkpoint("vggish_pca",
                                             args.get("pca_weights_path"))
            if pca_path is None:
                raise FileNotFoundError(
                    "postprocess=true needs the PCA params; drop "
                    "vggish_pca_params-970ea276.pth (or the .npz twin) into "
                    f"{store.weights_dir()} or pass pca_weights_path=...")
            self._pca = vggish_model.load_pca_params(str(pca_path))

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        ext = Path(video_path).suffix
        wav_path, aac_path = None, None
        if ext == ".mp4":
            from ..parallel import fanout
            session = fanout.current_session()
            if session is not None:
                # multi-family run: ONE wav rip per video shared by every
                # audio family; the session owns the temp files' cleanup
                # (after all audio consumers finish), so wav_path stays
                # None and the removal below is skipped
                audio_path = session.shared_wav(video_path, self.tmp_path,
                                                extract_wav_from_mp4)
            else:
                wav_path, aac_path = extract_wav_from_mp4(video_path,
                                                          self.tmp_path)
                audio_path = wav_path
        elif ext == ".wav":
            audio_path = video_path
        else:
            raise NotImplementedError(
                f"vggish accepts .mp4 or .wav, got {ext!r} "
                "(reference extract_vggish.py:42-48)")

        data, rate = audio.read_wav(audio_path)
        if self.frontend == "device":
            examples = audio.chunk_waveform(data, rate)  # (N, 15600)
        else:
            examples = audio.waveform_to_examples(data, rate)  # (N,96,64,1)
        stream = self.feature_stream(self.runner)  # vggish has no show_pred
        for start in range(0, len(examples), self.batch_size):
            stream.submit(examples[start:start + self.batch_size])
        feats = stream.finish()
        vggish_stack = (np.concatenate(feats) if feats
                        else np.zeros((0, vggish_model.EMBEDDING_SIZE),
                                      dtype=np.float32))
        if self._pca is not None:
            vggish_stack = vggish_model.postprocess(vggish_stack, *self._pca)

        if not self.keep_tmp_files and wav_path is not None:
            import os
            os.remove(wav_path)
            os.remove(aac_path)
        return {self.feature_type: vggish_stack}
