"""S3D clip-stack extractor.

Parity target: reference models/s3d/extract_s3d.py — defaults stack=step=64,
extraction_fps=25 (forced even when None, extract_s3d.py:29), transform
[0,1]-float -> scale-factor Resize(224) -> CenterCrop(224) with NO
normalization by design (extract_s3d.py:30-35), `model(x, features=True)`
skipping the classifier. Output key: ['s3d'].
"""
from __future__ import annotations

from functools import partial

import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..models import s3d as s3d_model
from ..ops import colorspace
from ..ops import host_transforms as ht
from ..ops import preprocess as pp
from ..parallel.mesh import DataParallelApply, cast_floating, get_mesh
from ..utils.labels import show_predictions_on_dataset
from ..weights import store
from .clip_stack import ClipStackExtractor


def _device_forward_yuv420(model: s3d_model.S3D, dtype, features, params,
                           batch):
    """Packed-I420 uint8 (B, T, 224*224*3/2) -> features; colorspace
    conversion on device (ops/colorspace.py), 1.5 bytes/pixel wire."""
    rgb = colorspace.yuv420_packed_to_rgb(batch, 224, 224) / 255.0
    return _device_forward(model, dtype, features, params, rgb)


def _device_forward(model: s3d_model.S3D, dtype, features, params, batch):
    # uint8 wire format (precision=bfloat16): /255 on device; the pipeline is
    # H2D-bound, so shipping 1 byte/px instead of 4 is a 4x transfer win
    if batch.dtype == jnp.uint8:
        batch = batch.astype(jnp.float32) / 255.0
    x = batch.astype(dtype)
    return model.apply({"params": params}, x,
                       features=features).astype(jnp.float32)


class ExtractS3D(ClipStackExtractor):

    supported_ingest = ("yuv420", "uint8", "float32")
    frame_channel_order = "bgr"  # RGB reorder deferred into the transform

    def __init__(self, args: Config) -> None:
        super().__init__(args, default_stack=64, default_step=64)
        if self.extraction_fps is None:
            self.extraction_fps = 25  # reference extract_s3d.py:29

        self.model = s3d_model.S3D(num_classes=400)
        params = store.resolve_params(
            "s3d_kinetics400", s3d_model.init_params,
            s3d_model.params_from_torch,
            weights_path=args.get("weights_path"),
            allow_random=bool(args.get("allow_random_weights", False)))

        dtype = jnp.bfloat16 if self.precision == "bfloat16" else jnp.float32
        mesh = self._data_mesh()
        # cast once for both runners
        params = cast_floating(params, dtype)
        fwd = (_device_forward_yuv420 if self.ingest == "yuv420"
               else _device_forward)
        self.runner = DataParallelApply(
            partial(fwd, self.model, dtype, True),
            params, mesh=mesh, fixed_batch=self.clip_batch_size)
        self._logits_runner = DataParallelApply(
            partial(fwd, self.model, dtype, False),
            params, mesh=mesh, fixed_batch=self.clip_batch_size) \
            if self.show_pred else None

        # a picklable callable (ops/host_transforms.py), not a closure:
        # video_decode=process ships it to spawned decode workers
        self.host_transform = ht.S3DTransform(self.ingest)

    def maybe_show_pred(self, feats: np.ndarray, slices, group=None) -> None:
        # the reference runs the model a second time with features=False on
        # the same stack (extract_s3d.py:95-99)
        if self.show_pred and group is not None:
            logits = self._logits_runner(group)
            for row, (s, e) in zip(np.asarray(logits), slices):
                print(f"At frames ({s}, {e})")
                show_predictions_on_dataset(row[None], "kinetics")
