"""Extraction lifecycle shared by all families.

Mirrors reference models/_base/base_extractor.py:11-127:
``_extract`` = skip-if-exists -> ``extract`` -> sink dispatch, with per-video
error isolation handled by the caller via ``utils.sinks.safe_extract``.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..config import Config
from ..utils import sinks


class BaseExtractor:
    output_feat_keys: List[str]

    def __init__(self, args: Config) -> None:
        self.feature_type = args.feature_type
        self.on_extraction = args.get("on_extraction", "print")
        self.tmp_path = str(args.tmp_path)
        self.output_path = str(args.output_path)
        self.keep_tmp_files = bool(args.get("keep_tmp_files", False))
        self.device = args.get("device", "auto")
        self.precision = args.get("precision", "float32")
        import jax
        if self.device == "cpu":
            # hard-pin: site customizations may force the accelerator plugin
            # into jax_platforms after env vars are read; an explicit cpu run
            # must never initialize (and thereby claim) the TPU
            jax.config.update("jax_platforms", "cpu")
        if self.precision == "float32":
            # full-fp32 accumulation for parity with the torch reference;
            # 'bfloat16' mode keeps the MXU-native fast path instead
            jax.config.update("jax_default_matmul_precision", "highest")
        self.show_pred = bool(args.get("show_pred", False))
        # health=true (telemetry/health.py): digest every feature tensor
        # at the sink boundary into {output_path}/_health.jsonl and refuse
        # to write NaN/Inf (routed through the faults taxonomy as POISON).
        # Off by default; the disabled cost is this one attribute read.
        self.health = bool(args.get("health", False))
        # parity=true (telemetry/parity.py): per-seam numerics digests
        # (decode -> transform -> backbone -> head) into
        # {output_path}/_parity.jsonl. Off by default; taps are only
        # installed when this attribute is set, so the off path is
        # byte-identical (no transform wrapper, no per-batch branch
        # beyond this one attribute read).
        self.parity = bool(args.get("parity", False))
        # cache=true (cache.py): content-addressed feature cache keyed on
        # (input sha256, resolved-config fingerprint, weights sha). The
        # weights capture must start BEFORE the subclass __init__ resolves
        # its params (weights/store.py resolve_params records what it
        # loaded into this list); the FeatureCache handle itself is built
        # lazily on first _extract, after every resolved attribute
        # (resize_mode, ingest) exists.
        self.cache_enabled = bool(args.get("cache", False))
        if self.cache_enabled:
            from ..weights import store as _wstore
            self._weights_capture = _wstore.start_weights_capture()
        self._cache = None
        self._cache_built = False
        # compile_cache= (compile_cache.py): the fleet-shared persistent
        # XLA store. The CLI/serve drivers attach explicitly right after
        # construction; this lazy flag covers library callers who invoke
        # _extract directly (attach is process-global first-wins, so the
        # double path cannot double-attach).
        self._compile_cache_checked = False
        # roofline= (telemetry/roofline.py): same lazy library-caller
        # coverage — the CLI starts the observer itself; a direct
        # _extract caller gets one homed on output_path, closed (and
        # _roofline.json written) at interpreter exit
        self._roofline_checked = False
        # video_decode=process: each video's decode+transform runs in a
        # spawned worker process (utils/io.py ProcessVideoSource) — lifts
        # the parent-GIL ceiling on numpy/PIL transform work on multi-core
        # hosts. Default 'inline' (decode on the calling/video_workers
        # thread).
        self.video_decode = args.get("video_decode") or "inline"
        if self.video_decode not in ("inline", "process", "parallel"):
            raise NotImplementedError(
                f"video_decode={self.video_decode!r}: expected 'inline', "
                "'process' or 'parallel'")
        # decode_workers: intra-video parallel decode width for
        # video_decode=parallel (utils/io.py ParallelVideoSource)
        raw_dw = args.get("decode_workers")
        self.decode_workers = 2 if raw_dw is None else int(raw_dw)
        if self.decode_workers < 1:
            raise ValueError(
                f"decode_workers={self.decode_workers}: need >= 1")
        # decode_depth: per-worker frame-queue cap (None -> full segment
        # for transformed streams, 64 for raw-frame streams)
        raw_dd = args.get("decode_depth")
        self.decode_depth = None if raw_dd is None else int(raw_dd)
        self.args = args

    def video_source(self, video_path: str, **kwargs):
        """Family-agnostic VideoSource factory honoring video_decode and
        fps_mode (``reencode`` = the reference's lossy temp-file decode
        path for golden/parity runs, utils/io.py module docstring).

        Fault-tolerance hooks (utils/faults.py): when a FaultContext is
        active on this thread, its ``decode_override`` (the degradation
        ladder's demoted mode for a retry) replaces ``video_decode``, and
        the constructed source is registered so the per-video deadline
        watchdog can kill its in-flight decode.

        Shared-decode hook (parallel/fanout.py): inside a multi-family
        run a SharedDecodeSession is installed on this thread; the first
        attempt subscribes to the video's single shared decode pass and
        gets a SharedFrameSource with the same observable surface. A
        declined subscription (retry attempt, unsupported knob) falls
        through to a private source below — isolation over sharing."""
        from ..parallel import fanout
        from ..utils import faults
        if self.parity:
            # parity taps the decode and transform seams by wrapping the
            # host transform BEFORE the shared-decode subscribe, so the
            # shared and private paths digest the same tensors on this
            # family's own thread. Only installed when parity=true: a
            # wrapper is never None, and utils/io.py sizes parallel
            # decode queues on `transform is not None` — the off path
            # must stay byte-identical.
            from ..telemetry import parity as _parity
            kwargs["transform"] = _parity.TransformTap(
                kwargs.get("transform"), str(video_path), self.feature_type)
        session = fanout.current_session()
        if session is not None:
            sub = session.subscribe(self.feature_type, **kwargs)
            if sub is not None:
                # the bus registered it with the fault context already
                # (before its arrival barrier, so the watchdog can cancel
                # a family stuck waiting for its siblings)
                from .. import telemetry
                if telemetry.current_span() is not None:
                    telemetry.annotate(video_fps=sub.fps,
                                       video_frames=len(sub))
                    telemetry.event("source", mode="shared",
                                    cls=type(sub).__name__)
                return sub
        from ..utils.io import (ParallelVideoSource, ProcessVideoSource,
                                VideoSource)
        ctx = faults.current_context()
        mode = self.video_decode
        if ctx is not None and ctx.decode_override:
            mode = ctx.decode_override
        cls = {"process": ProcessVideoSource,
               "parallel": ParallelVideoSource}.get(mode, VideoSource)
        if cls is ParallelVideoSource:
            kwargs.setdefault("decode_workers", self.decode_workers)
            if self.decode_depth is not None:
                kwargs.setdefault("depth", self.decode_depth)
        if self.args.get("fps_mode", "select") == "reencode":
            kwargs.setdefault("fps_mode", "reencode")
            kwargs.setdefault("tmp_path", self.args.get("tmp_path", "tmp"))
            kwargs.setdefault("keep_tmp", self.keep_tmp_files)
        from ..telemetry import trace as _trace
        # probing can be slow (container metadata recount, reencode temp
        # file, worker spawn): give it its own timeline span (no-op when
        # trace=false)
        with _trace.span("source_probe", video=str(video_path), mode=mode):
            src = cls(video_path, **kwargs)
        if ctx is not None:
            ctx.register(src)
        # telemetry (no-ops without an active span): the source's probed
        # properties give the span its fps/frame-count fields, and the
        # event records which decode class actually served each attempt
        # (the ladder may have demoted it)
        from .. import telemetry
        if telemetry.current_span() is not None:
            try:
                n_frames = len(src)
            except Exception:
                n_frames = None
            telemetry.annotate(video_fps=getattr(src, "fps", None),
                               video_frames=n_frames)
            telemetry.event("source", mode=mode, cls=type(src).__name__)
        return src

    def _data_mesh(self):
        """Device mesh for this extractor's runners.

        ``mesh_devices`` (config) pins the width explicitly — how tests and
        the driver dryrun shard real extractors over the virtual CPU mesh.
        Default: all local devices on TPU; one on CPU (a single-core host
        gains nothing from virtual sharding, and an explicit ``device=cpu``
        run must not enumerate the TPU)."""
        from ..parallel.mesh import get_mesh
        n = self.args.get("mesh_devices")
        if n is not None:
            return get_mesh(n_devices=int(n))
        return get_mesh(n_devices=1) if self.device == "cpu" else get_mesh()

    def feature_stream(self, runner, depth: int = 4, on_result=None):
        """Async dispatch stream over ``runner`` (parallel/mesh.py
        FeatureStream). When show_pred needs per-batch host values, the
        stream degrades to synchronous (depth=0) with ``on_result`` fired
        per batch — one code path either way."""
        if self.show_pred and on_result is not None:
            return runner.stream(depth=0, callback=on_result)
        return runner.stream(depth=depth)

    def _resolve_resize_mode(self, args: Config,
                             device_capable: bool = True) -> str:
        """Shared ``resize=auto|host|device`` validation + the per-source-
        resolution runner cache used by every device-resize pipeline
        (frame-wise, flow, i3d): a lock-guarded (video_workers share it)
        FIFO-bounded dict keyed by source (h, w).

        ``auto`` (the config default since the defaults flip) resolves to
        ``device`` — the measured ~3x host frame-rate lever, within 2 LSB
        of PIL (docs/performance.md §"Device resize") — for file-sink runs
        of families with a fused device resize, and falls back to ``host``
        for ``print``/``show_pred`` runs (the interactive/parity paths,
        which need host-side frames) and for ``device_capable=False``
        families (e.g. a flow family without ``side_size`` has no resize
        in the pipeline at all). Explicit ``host``/``device`` are honored
        as before."""
        import threading
        mode = args.get("resize") or "auto"
        if mode not in ("auto", "host", "device"):
            raise NotImplementedError(f"resize={mode!r}: expected 'auto', "
                                      "'host' or 'device'")
        self._resize_runners: Dict = {}
        self._resize_lock = threading.Lock()
        if mode == "auto":
            save_sink = self.on_extraction in ("save_numpy", "save_pickle")
            mode = ("device" if device_capable and save_sink
                    and not self.show_pred else "host")
        return mode

    def _cached_resize_runner(self, key, build):
        """Build-once per source resolution, bounded to 8 executables."""
        with self._resize_lock:
            runner = self._resize_runners.get(key)
            if runner is None:
                if len(self._resize_runners) >= 8:
                    self._resize_runners.pop(
                        next(iter(self._resize_runners)), None)
                runner = self._resize_runners[key] = build()
            return runner

    def _resolve_ingest(self, args: Config, default: str) -> str:
        """Validate the host->device wire format against the subclass's
        ``supported_ingest`` (shared by the clip-stack and frame-wise
        pipelines — see their class docs for the format semantics)."""
        ingest = args.get("ingest") or default
        if ingest not in getattr(self, "supported_ingest", ()):
            raise NotImplementedError(
                f"ingest={ingest!r}; {type(self).__name__} supports "
                f"{self.supported_ingest}")
        return ingest

    # -- lifecycle ---------------------------------------------------------
    def feature_cache(self):
        """This extractor's content-addressed cache handle (cache.py), or
        None when ``cache=false``. Built once, lazily: the fingerprints
        need the subclass's resolved attributes and weights capture."""
        if not self._cache_built:
            self._cache_built = True
            if self.cache_enabled:
                from ..cache import FeatureCache
                self._cache = FeatureCache.for_extractor(self)
        return self._cache

    def _extract(self, video_path: str) -> Optional[Dict[str, np.ndarray]]:
        from .. import telemetry
        if not self._compile_cache_checked:
            # before the first compile, after every resolved attribute
            # exists — the same lazy point the feature cache uses
            self._compile_cache_checked = True
            from ..compile_cache import attach_for_extractor
            attach_for_extractor(self)
        if not self._roofline_checked:
            self._roofline_checked = True
            from ..telemetry.roofline import ensure_for_extractor
            ensure_for_extractor(self)
        # Precedence: cache hit > filename skip (docs/performance.md).
        # The cache key proves the CONTENT + config + weights match; the
        # filename skip only proves a file with the right name loads —
        # so a hit re-serves through the sink path (which still skips the
        # physical write when the files already exist), keeping outputs
        # correct even when a stale same-stem file is present.
        cache = self.feature_cache()
        if cache is not None:
            feats = cache.lookup(video_path, self.output_feat_keys)
            if feats is not None:
                telemetry.inc("vft_cache_hit_total",
                              family=str(self.feature_type))
                telemetry.annotate(cache="hit")
                self.action_on_extraction(feats, video_path)
                return feats
        if sinks.is_already_exist(self.on_extraction, self.output_path,
                                  video_path, self.output_feat_keys):
            # work avoided WITHOUT consulting cache content: the same
            # bypass counter fires whether cache=true (a miss that the
            # filename contract absorbed) or cache=false, so
            # telemetry_report can always show WHY work was avoided
            telemetry.inc("vft_cache_bypass_total",
                          family=str(self.feature_type))
            telemetry.annotate(cache="bypass")
            return None
        if cache is not None:
            telemetry.inc("vft_cache_miss_total",
                          family=str(self.feature_type))
            telemetry.annotate(cache="miss")
        feats = self.extract(video_path)
        self.action_on_extraction(feats, video_path)
        if cache is not None:
            # store AFTER the sink path: the health gate (NaN/Inf ->
            # POISON) and any sink failure must keep bad features out of
            # the store exactly as they keep them off disk. A store
            # FAILURE, though, is contained: the artifacts are already
            # durable, and failing (or retrying) the whole video over a
            # cache write would turn an optimization into a liability —
            # the atomic entry write guarantees no torn entry was left
            try:
                cache.store(video_path, feats)
            except Exception as e:
                telemetry.inc("vft_cache_store_failures_total",
                              family=str(self.feature_type))
                print(f"cache: store failed for {video_path} "
                      f"({type(e).__name__}: {e}) — features are on disk, "
                      "entry skipped")
        return feats

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def action_on_extraction(self, feats: Dict[str, np.ndarray],
                             video_path: str) -> None:
        if self.health:
            # digest + gate BEFORE any sink write: a non-finite feature
            # raises (POISON) so it journals/quarantines instead of being
            # silently persisted; the digest record of the bad tensor is
            # already in _health.jsonl for the post-mortem
            from ..telemetry import health
            from ..utils.profiling import profiler
            with profiler.stage("health"):
                health.check_features(feats, video_path, self.feature_type,
                                      self.output_path)
        if self.parity:
            # head seam: the per-key feature tensors exactly as the sink
            # is about to persist them (certify's in-process arms tap
            # this seam themselves off the extract() return)
            from ..telemetry import parity as _parity
            for key in sorted(feats):
                _parity.tap("head", key, feats[key], video=str(video_path),
                            feature_type=self.feature_type)
        # re-check before overwrite: another worker may have just written it
        # (reference base_extractor.py:72-76)
        if self.on_extraction != "print" and sinks.is_already_exist(
                self.on_extraction, self.output_path, video_path,
                self.output_feat_keys):
            return
        sinks.action_on_extraction(feats, video_path, self.output_path,
                                   self.on_extraction)
