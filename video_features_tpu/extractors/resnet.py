"""ResNet frame-wise extractor.

Parity target: reference models/resnet/extract_resnet.py (Resize 256 ->
CenterCrop 224 -> ToTensor -> ImageNet Normalize; fc swapped for Identity with
the classifier kept for show_pred). Output keys: ['resnet', 'fps',
'timestamps_ms'] (reference base_framewise_extractor.py:44).
"""
from __future__ import annotations

from functools import partial

import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..models import resnet as resnet_model
from ..ops import host_transforms as ht
from ..ops import preprocess as pp
from ..parallel.mesh import DataParallelApply, cast_floating, get_mesh
from ..utils.labels import show_predictions_on_dataset
from ..weights import store
from .frame_wise import FrameWiseExtractor


def _device_forward(model: resnet_model.ResNet, dtype, params, batch_u8):
    """uint8 (B,224,224,3) -> (B,D): /255, ImageNet-normalize, backbone."""
    x = batch_u8.astype(jnp.float32) / 255.0
    x = (x - jnp.asarray(pp.IMAGENET_MEAN)) / jnp.asarray(pp.IMAGENET_STD)
    x = x.astype(dtype)
    return model.apply({"params": params}, x).astype(jnp.float32)


def _device_forward_yuv420(model: resnet_model.ResNet, dtype, params,
                           packed):
    """Packed-I420 uint8 (B, 224*224*3/2) -> (B,D); colorspace conversion on
    device (ops/colorspace.py, [0,255] floats) into the shared forward."""
    from ..ops import colorspace
    rgb = colorspace.yuv420_packed_to_rgb(packed, 224, 224)
    return _device_forward(model, dtype, params, rgb)


class ExtractResNet(FrameWiseExtractor):

    def __init__(self, args: Config) -> None:
        super().__init__(args)
        if self.model_name not in resnet_model.VARIANTS:
            raise NotImplementedError(f"Model {self.model_name} not found.")
        self.model = resnet_model.ResNet(self.model_name)
        self.head = resnet_model.Classifier()

        params = store.resolve_params(
            self.model_name,
            partial(resnet_model.init_params, self.model_name),
            resnet_model.params_from_torch,
            weights_path=args.get("weights_path"),
            allow_random=bool(args.get("allow_random_weights", False)))
        self.head_params = params["head"]

        dtype = jnp.bfloat16 if self.precision == "bfloat16" else jnp.float32
        mesh = self._data_mesh()
        uint8_fwd = partial(_device_forward, self.model, dtype)
        fwd = (partial(_device_forward_yuv420, self.model, dtype)
               if self.ingest == "yuv420" else uint8_fwd)
        self.runner = DataParallelApply(
            fwd, cast_floating(params["backbone"], dtype),
            mesh=mesh, fixed_batch=self.batch_size)
        # per-resolution device-resize runners reuse the committed device
        # arrays: one replicated weight copy in HBM no matter how many
        # source resolutions a run sees
        committed = self.runner.params
        self.runner_builder = lambda f: DataParallelApply(
            f, committed, mesh=mesh, fixed_batch=self.batch_size)
        # resize=device (frame_wise.py): Resize(256) bilinear + CenterCrop
        # 224 on the MXU, host ships raw frames
        self.resize_spec = (256, "bilinear", True)
        self.crop_size = 224
        self.base_fwd = uint8_fwd

        # a picklable callable (ops/host_transforms.py), not a closure:
        # video_decode=process ships it to spawned decode workers
        self.host_transform = ht.ResizeCropTransform(256, 224, "bilinear",
                                                     self.ingest)

    def maybe_show_pred(self, feats: np.ndarray) -> None:
        if self.show_pred:
            logits = self.head.apply({"params": self.head_params},
                                     jnp.asarray(feats))
            show_predictions_on_dataset(np.asarray(logits), "imagenet")
