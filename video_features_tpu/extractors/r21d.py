"""R(2+1)D clip-stack extractor.

Parity target: reference models/r21d/extract_r21d.py — three model flavors
with per-flavor default stack/step (16/16, 32/32, 8/8), transform stack
[0,1]-float -> bilinear Resize(128,171) (non-antialiased) -> K400 Normalize ->
CenterCrop(112) (extract_r21d.py:50-55), fc swapped for Identity with the
Kinetics head kept for show_pred. Output key: ['r21d'] only
(extract_r21d.py:57).
"""
from __future__ import annotations

from functools import partial

import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..models import r21d as r21d_model
from ..ops import colorspace
from ..ops import host_transforms as ht
from ..ops import preprocess as pp
from ..parallel.mesh import DataParallelApply, cast_floating, get_mesh
from ..utils.labels import show_predictions_on_dataset
from ..weights import store
from .clip_stack import ClipStackExtractor


def _device_forward(model: r21d_model.R2Plus1D, dtype, params, batch):
    """(B, T, 112, 112, 3) float [0,1] or uint8 -> (B, 512).

    /255 (uint8 wire format only), K400-normalize, backbone — all fused by
    XLA into the stem conv. The dtype branch is resolved at trace time.
    """
    if batch.dtype == jnp.uint8:
        batch = batch.astype(jnp.float32) / 255.0
    x = (batch - jnp.asarray(r21d_model.R21D_MEAN, batch.dtype)) / \
        jnp.asarray(r21d_model.R21D_STD, batch.dtype)
    x = x.astype(dtype)
    return model.apply({"params": params}, x).astype(jnp.float32)


def _device_forward_yuv420(model: r21d_model.R2Plus1D, dtype, params, batch):
    """Packed-I420 uint8 (B, T, 112*112*3/2) -> (B, 512).

    On-device colorspace conversion (ops/colorspace.py) into the shared
    normalize + backbone; the wire carries 1.5 bytes/pixel instead of 3.
    """
    rgb = colorspace.yuv420_packed_to_rgb(batch, 112, 112) / 255.0
    return _device_forward(model, dtype, params, rgb)


class ExtractR21D(ClipStackExtractor):

    supported_ingest = ("yuv420", "uint8", "float32")
    frame_channel_order = "bgr"  # RGB reorder deferred into the transform

    def __init__(self, args: Config) -> None:
        if args.model_name not in r21d_model.VARIANTS:
            raise NotImplementedError(f"Model {args.model_name} not found.")
        _, default_stack = r21d_model.VARIANTS[args.model_name]
        super().__init__(args, default_stack=default_stack,
                         default_step=default_stack)

        self.model = r21d_model.R2Plus1D(self.model_name)
        self.head = r21d_model.Classifier()

        params = store.resolve_params(
            self.model_name,
            partial(r21d_model.init_params, self.model_name),
            r21d_model.params_from_torch,
            weights_path=args.get("weights_path"),
            allow_random=bool(args.get("allow_random_weights", False)))
        self.head_params = params["head"]

        dtype = jnp.bfloat16 if self.precision == "bfloat16" else jnp.float32
        mesh = self._data_mesh()
        fwd = (_device_forward_yuv420 if self.ingest == "yuv420"
               else _device_forward)
        self.runner = DataParallelApply(
            partial(fwd, self.model, dtype),
            cast_floating(params["backbone"], dtype),
            mesh=mesh, fixed_batch=self.clip_batch_size)

        # a picklable callable (ops/host_transforms.py), not a closure:
        # video_decode=process ships it to spawned decode workers
        self.host_transform = ht.R21DTransform(self.ingest)

    def maybe_show_pred(self, feats: np.ndarray, slices, group=None) -> None:
        if self.show_pred:
            logits = np.asarray(self.head.apply({"params": self.head_params},
                                                jnp.asarray(feats)))
            for row, (s, e) in zip(logits, slices):
                print(f"At frames ({s}, {e})")
                show_predictions_on_dataset(row[None], "kinetics")
