"""CLIP frame-wise extractor.

Parity target: reference models/clip/extract_clip.py — frame-wise features
from ``model.encode_image``; transforms built from the model's own input
resolution (Resize(R, BICUBIC) smaller-edge -> CenterCrop(R) -> ToTensor ->
Normalize(CLIP mean/std), extract_clip.py:69-78); ``custom`` checkpoints
infer their architecture from the state_dict (extract_clip.py:55-61 +
clip_src build_model); ``show_pred`` is zero-shot over "a photo of {label}"
Kinetics-400 prompts or user ``pred_texts`` (extract_clip.py:32-40, 86-108),
with the cosine-similarity logits computed in float64 exactly like the
reference's ``.to(torch.double)``.

Output keys: ``[clip, fps, timestamps_ms]``.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..models import clip as clip_model
from ..ops import host_transforms as ht
from ..ops import preprocess as pp
from ..parallel.mesh import (DataParallelApply, TP_RULES_TRANSFORMER,
                             cast_floating, get_mesh, param_specs_by_rules)
from ..utils.labels import KINETICS_CLASS_PATH, show_predictions_on_dataset
from ..weights import store
from .frame_wise import FrameWiseExtractor


def model_key(model_name: str) -> str:
    """'ViT-B/32' -> 'clip_ViT-B-32' (matches the OpenAI CDN filenames)."""
    return "clip_" + model_name.replace("/", "-").replace("@", "-")


def _encode_image(model: clip_model.CLIP, dtype, params, batch_u8):
    """uint8 (B,R,R,3) -> (B,embed): /255, CLIP-normalize, visual tower."""
    x = batch_u8.astype(jnp.float32) / 255.0
    x = (x - jnp.asarray(pp.CLIP_MEAN)) / jnp.asarray(pp.CLIP_STD)
    x = x.astype(dtype)
    return model.apply({"params": params}, x,
                       method="encode_image").astype(jnp.float32)


def _encode_image_yuv420(model: clip_model.CLIP, dtype, size, params,
                         packed):
    """Packed-I420 uint8 (B, R*R*3/2) -> (B,embed); colorspace conversion on
    device (ops/colorspace.py, [0,255] floats) into the shared forward."""
    from ..ops import colorspace
    rgb = colorspace.yuv420_packed_to_rgb(packed, size, size)
    return _encode_image(model, dtype, params, rgb)


class ExtractCLIP(FrameWiseExtractor):

    def __init__(self, args: Config) -> None:
        super().__init__(args)
        allow_random = bool(args.get("allow_random_weights", False))
        weights_path = args.get("weights_path")
        # vision_attn=blockwise: streaming-softmax attention in the vision
        # tower (models/clip.py MHA) — opt-in for the big-token towers
        # (ViT-L/14@336 runs 577 patch tokens) where the dense per-layer
        # (B*H, T, T) score tensor dominates activation memory. Values are
        # identical; the text tower always stays dense (77 tokens).
        vision_attn = str(args.get("vision_attn") or "dense")
        if vision_attn not in ("dense", "blockwise"):
            raise ValueError(f"vision_attn={vision_attn!r}: expected "
                             "'dense' or 'blockwise'")

        if self.model_name == "custom":
            # architecture comes from the checkpoint itself
            # (extract_clip.py:55-61; build_model, clip_src/model.py:399-436)
            if not weights_path:
                raise FileNotFoundError(
                    "model_name=custom requires weights_path=<checkpoint>")
            from ..weights.torch_import import load_torch_state_dict
            sd = load_torch_state_dict(weights_path)
            self.cfg = clip_model.config_from_state_dict(sd)
            params = clip_model.params_from_torch(sd)
            self.model = clip_model.CLIP(self.cfg, vision_attn=vision_attn)
        elif self.model_name in clip_model.CONFIGS:
            self.cfg = clip_model.CONFIGS[self.model_name]
            self.model = clip_model.CLIP(self.cfg, vision_attn=vision_attn)
            params = store.resolve_params(
                model_key(self.model_name),
                partial(clip_model.init_params, self.model_name),
                clip_model.params_from_torch,
                weights_path=weights_path, allow_random=allow_random)
        else:
            raise NotImplementedError(f"Model {self.model_name} not found")
        if vision_attn == "blockwise" and not self.cfg.is_vit:
            # only the ViT towers route attn_impl (models/clip.py CLIP.setup);
            # a silent no-op on RN* would betray the documented contract
            raise ValueError(
                f"vision_attn=blockwise requires a ViT vision tower; "
                f"{self.model_name} uses the modified-ResNet trunk whose "
                "only attention is the 50-token AttentionPool2d head "
                "(nothing to blockwise)")

        dtype = jnp.bfloat16 if self.precision == "bfloat16" else jnp.float32
        # model_parallel=N: 2-D (data, model) mesh with Megatron-style
        # sharding of the transformer blocks and the RN* attention-pool head
        # (parallel/mesh.py TP_RULES_TRANSFORMER; conv trunks stay
        # replicated) — for the large ViT checkpoints where weight residency
        # or per-batch latency matters more than pure data-parallel
        # throughput. N must divide the device count.
        mp = int(args.get("model_parallel") or 1)
        param_specs = None
        if mp > 1:
            # honor device=cpu: enumerate only the CPU backend's devices
            # (never touching the TPU), same contract as the mp==1 branch
            backend = "cpu" if self.device == "cpu" else None
            n = len(jax.devices(backend) if backend else jax.devices())
            if n % mp:
                raise ValueError(f"model_parallel={mp} must divide the "
                                 f"device count ({n})")
            mesh = get_mesh(axis_names=("data", "model"),
                            shape=(n // mp, mp), backend=backend)
            param_specs = param_specs_by_rules(params, TP_RULES_TRANSFORMER)
        else:
            mesh = (get_mesh(n_devices=1) if self.device == "cpu"
                    else get_mesh())
        input_size = self.cfg.image_resolution
        uint8_fwd = partial(_encode_image, self.model, dtype)
        if self.ingest == "yuv420":
            if input_size % 2:
                raise NotImplementedError(
                    f"ingest=yuv420 needs an even input resolution (I420 "
                    f"chroma subsampling); {self.model_name} uses "
                    f"{input_size}")
            fwd = partial(_encode_image_yuv420, self.model, dtype, input_size)
        else:
            fwd = uint8_fwd
        self.runner = DataParallelApply(
            fwd, cast_floating(params, dtype),
            mesh=mesh, fixed_batch=self.batch_size, param_specs=param_specs)
        # per-resolution device-resize runners reuse the committed device
        # arrays: one (possibly TP-sharded) weight copy in HBM total
        committed = self.runner.params
        self.runner_builder = lambda f: DataParallelApply(
            f, committed, mesh=mesh, fixed_batch=self.batch_size,
            param_specs=param_specs)
        # resize=device (frame_wise.py): Resize(R) bicubic + CenterCrop R on
        # the MXU, host ships raw frames
        self.resize_spec = (input_size, "bicubic", True)
        self.crop_size = input_size
        self.base_fwd = uint8_fwd

        # a picklable callable (ops/host_transforms.py), not a closure:
        # video_decode=process ships it to spawned decode workers
        self.host_transform = ht.ResizeCropTransform(
            input_size, input_size, "bicubic", self.ingest)

        self._text_feats: Optional[np.ndarray] = None
        if self.show_pred:
            pred_texts = args.get("pred_texts")
            if pred_texts is None:
                with open(KINETICS_CLASS_PATH) as f:
                    self.pred_texts: List[str] = [
                        f"a photo of {x.strip()}" for x in f]
            else:
                self.pred_texts = list(pred_texts)
            from ..utils.tokenizer import ClipTokenizer
            self._tokens = ClipTokenizer(args.get("bpe_path")).tokenize(
                self.pred_texts, context_length=self.cfg.context_length)
            self._logit_scale = float(np.asarray(params["logit_scale"]))
            self._text_params = params
            self._encode_text = jax.jit(
                partial(self.model.apply, method="encode_text"))

    def maybe_show_pred(self, feats: np.ndarray) -> None:
        if not self.show_pred:
            return
        if self._text_feats is None:
            self._text_feats = np.asarray(self._encode_text(
                {"params": self._text_params}, jnp.asarray(self._tokens)))
        v = feats.astype(np.float64)
        t = self._text_feats.astype(np.float64)
        v = v / np.linalg.norm(v, axis=1, keepdims=True)
        t = t / np.linalg.norm(t, axis=1, keepdims=True)
        logits = np.exp(self._logit_scale) * v @ t.T
        show_predictions_on_dataset(logits, self.pred_texts)
