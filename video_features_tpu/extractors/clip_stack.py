"""Clip-stack extraction pipeline (R(2+1)D, S3D).

Re-design of the reference's whole-video + per-slice serial loop
(reference models/r21d/extract_r21d.py:60-94, models/s3d/extract_s3d.py:40-75):

  host:   stream-decode -> per-frame resize/crop -> per-frame wire array
          (float32 (H, W, 3) by default; uint8, or packed-I420 uint8
          (H*W*3/2,), under the compressed ingest modes)
          -> `form_slices` windows (trailing partial stack dropped, same
          observable contract as reference utils/utils.py:59-68)
  device: (clip_batch, stack, *frame_wire_shape) fixed-shape jitted forward,
          the clip-batch axis sharded over the mesh's data axis.

Where the reference runs batch=1 slices sequentially (extract_r21d.py:84-88),
clips here are batched into one jitted call — each 3D-conv matmul gets a
bigger batch dim for the MXU and ragged tails are padded, so exactly one
executable per (stack_size, H, W) is compiled.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from ..config import Config
from ..parallel.mesh import DataParallelApply
from ..utils.io import Prefetcher, VideoSource
from ..utils.lists import form_slices
from .base import BaseExtractor


class ClipStackExtractor(BaseExtractor):
    """Families plug in ``host_transform``, ``runner``, defaults, show_pred."""

    #: host->device wire formats a family supports. The pipeline is
    #: H2D-bandwidth-bound, so precision=bfloat16 defaults to uint8 (3 B/px;
    #: <=1/510 quantization noise, below bf16 input rounding) instead of
    #: float32 (12 B/px, the bit-exact golden default). Families may add
    #: opt-in 'yuv420' (packed I420, 1.5 B/px, colorspace on device — the
    #: maximum-throughput mode bench.py measures).
    supported_ingest = ("uint8", "float32")

    #: families whose host transform is entirely channel-independent
    #: (float conversion, resize, crop) set 'bgr' and reorder channels on
    #: their smallest intermediate instead — this skips a full-resolution
    #: cv2.cvtColor per decoded frame, bit-identically (utils/io.py
    #: _FrameStream).
    #:
    #: INVARIANT (a subclass that overrides either side must keep both in
    #: step): ``host_transform`` consumes frames in EXACTLY this channel
    #: order — declaring 'bgr' without the transform performing (or
    #: deferring) the RGB reorder silently channel-swaps every feature.
    #: tests/test_extractors_shared.py asserts the wiring equivalence for
    #: every registered family; the per-family torch-oracle E2E tests pin
    #: the actual values.
    frame_channel_order = "rgb"

    def __init__(self, args: Config, default_stack: int, default_step: int) -> None:
        super().__init__(args)
        self.model_name = args.get("model_name")
        self.stack_size = args.get("stack_size") or default_stack
        self.step_size = args.get("step_size") or default_step
        self.extraction_fps = args.get("extraction_fps")
        self.clip_batch_size = int(args.get("clip_batch_size") or 8)
        self.output_feat_keys = [self.feature_type]
        self.host_transform: Optional[Callable] = None
        self.runner: Optional[DataParallelApply] = None
        self.ingest = self._resolve_ingest(
            args, "uint8" if self.precision == "bfloat16" else "float32")
        # cross_video_batching=true: ONE clip buffer shared across the
        # video_workers threads, so device groups dispatch only when FULL
        # (parallel/packer.py) — lifts sustained throughput on short-video
        # corpora toward the fixed-shape bench steady state and makes big
        # clip_batch_size (128 is the v5e sweet spot) practical there.
        # Per-video outputs are identical to the unpacked path (row-wise
        # forward; asserted in tests/test_packer.py).
        self.cross_video = bool(args.get("cross_video_batching", False))
        if self.cross_video and self.show_pred:
            raise NotImplementedError(
                "cross_video_batching=true is incompatible with "
                "show_pred=true (predictions print per video group; packed "
                "groups interleave videos)")
        self._packer = None
        self._packer_lock = threading.Lock()

    def encode_wire(self, x01: np.ndarray) -> np.ndarray:
        """[0, 1] float HWC frame -> the configured wire format (the tail of
        every family's host transform)."""
        if self.ingest == "float32":
            return x01
        from ..ops import colorspace, preprocess as pp
        u8 = pp.quantize_u8(x01)
        if self.ingest == "uint8":
            return u8
        return colorspace.rgb_to_yuv420(u8)

    def _get_packer(self):
        from ..parallel.packer import ClipPacker
        with self._packer_lock:
            if self._packer is None:
                self._packer = ClipPacker(self.runner,
                                          batch=self.clip_batch_size)
            return self._packer

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        src = self.video_source(video_path, batch_size=1,
                                fps=self.extraction_fps,
                          transform=self.host_transform,
                          channel_order=self.frame_channel_order)
        if self.cross_video:
            return self._extract_packed(src)
        return self._extract_grouped(src)

    def _iter_stacks(self, src: VideoSource):
        """Yield ((start, end), (stack, *frame_wire_shape)) clip windows
        under the form_slices drop-partial contract (reference
        utils/utils.py:59-68), one window at a time:

          - step >= stack (every family's default): disjoint windows are
            formed on the fly — frames between windows are dropped as
            decoded, and the Prefetcher's decode-ahead thread overlaps the
            consumer (bounded host memory; the reference reads the whole
            video up front and warns "could run out of memory here",
            extract_r21d.py:75-77);
          - step < stack: every frame participates in several windows, so
            the full frame sequence is materialized and windows are sliced
            from it (yielding per window keeps peak memory at sequence +
            one group, not sequence x stack/step)."""
        if self.step_size < self.stack_size:
            frames = [f for f, _, _ in src.frames()]
            if not frames:
                return
            all_frames = np.stack(frames)  # (T, *frame_wire_shape)
            for s, e in form_slices(len(frames), self.stack_size,
                                    self.step_size):
                yield (s, e), all_frames[s:e]
            return
        gap = self.step_size - self.stack_size
        current: List[np.ndarray] = []
        start_idx = 0
        until_next = 0  # frames to drop before the next window starts
        for f, _, idx in Prefetcher(src.frames()):
            if until_next > 0:
                until_next -= 1
                continue
            if not current:
                start_idx = idx
            current.append(f)
            if len(current) == self.stack_size:
                yield (start_idx, start_idx + self.stack_size), \
                    np.stack(current)
                current.clear()
                until_next = gap
        # a trailing partial stack is dropped by falling off the loop

    def _extract_grouped(self, src: VideoSource) -> Dict[str, np.ndarray]:
        """Per-video async groups: windows batch into clip_batch_size
        groups dispatched through this video's own FeatureStream (submit
        returns immediately; only a depth-overflow pop or the final
        finish() blocks on D2H), so decode and device compute overlap. The
        trailing group goes out ragged (padded on dispatch)."""
        vid_feats: List[np.ndarray] = []
        stacks: List[np.ndarray] = []
        windows: List = []
        stream = self._make_stream()

        def flush():
            group = np.stack(stacks)
            stream.submit(group, ctx=(list(windows), group))
            stacks.clear()
            windows.clear()

        for window, stack in self._iter_stacks(src):
            windows.append(window)
            stacks.append(stack)
            if len(stacks) == self.clip_batch_size:
                flush()
        if stacks:
            flush()
        for bi, feats in enumerate(stream.finish()):
            if self.parity:
                # backbone seam: per-group clip activations off the device
                from ..telemetry import parity as _parity
                _parity.tap("backbone", self.feature_type, feats,
                            video=str(src.path),
                            feature_type=self.feature_type, index=bi)
            vid_feats.extend(list(feats))
        return {self.feature_type: np.array(vid_feats)}

    def _extract_packed(self, src: VideoSource) -> Dict[str, np.ndarray]:
        """Cross-video group packing: clips go straight into the shared
        packer (one per extractor, fed by all video_workers threads) and
        come back per video in clip order; groups dispatch only when full
        (parallel/packer.py). The abort path keeps per-video error
        isolation from wedging other workers' close waits."""
        packer = self._get_packer()
        handle = packer.open_video()
        try:
            for _, stack in self._iter_stacks(src):
                packer.add(handle, stack)
        except BaseException:
            packer.abort_video(handle)
            raise
        feats = packer.close_video(handle)
        if self.parity:
            # backbone seam: the packer returns this video's clips in
            # order as one array — a single index-0 record per video
            from ..telemetry import parity as _parity
            _parity.tap("backbone", self.feature_type, feats,
                        video=str(src.path), feature_type=self.feature_type)
        return {self.feature_type: feats}

    def _make_stream(self):
        return self.feature_stream(
            self.runner,
            on_result=lambda feats, ctx: self.maybe_show_pred(feats, *ctx))

    def maybe_show_pred(self, feats: np.ndarray, slices,
                        group: Optional[np.ndarray] = None) -> None:
        pass
