"""Extractor layer: per-family orchestration (load weights, window the video,
run the jitted forward, collect features). Mirrors the reference's L3
(reference models/*/extract_*.py + models/_base/) re-designed around
static-shape jitted device steps."""
