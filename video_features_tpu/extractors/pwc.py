"""PWC-Net flow extractor.

Parity target: reference models/pwc/extract_pwc.py (+ base_flow_extractor):
single sintel checkpoint, optional edge resize; no InputPadder — PWCNet
resizes to /64 multiples internally and rescales the flow back
(pwc_net.py:267-296). The reference's GPU-only restriction
(utils/utils.py:104-105) came from the CuPy CUDA correlation kernel; the
XLA cost volume in models/pwc.py has no such constraint.
"""
from __future__ import annotations

from functools import partial

import jax.numpy as jnp

from ..config import Config
from ..models import pwc as pwc_model
from ..parallel.mesh import get_mesh
from ..weights import store
from .flow import OpticalFlowExtractor


def _pwc_forward(model: pwc_model.PWCNet, params, pairs_u8):
    """(B, 2, H, W, 3) uint8 -> (B, H, W, 2) flow."""
    x = pairs_u8.astype(jnp.float32)
    return model.apply({"params": params}, x[:, 0], x[:, 1]).astype(
        jnp.float32)


class ExtractPWC(OpticalFlowExtractor):

    def __init__(self, args: Config) -> None:
        super().__init__(args)
        # precision=bfloat16: conv stacks + cost volumes on the MXU-native
        # dtype (flow tensors/warp grid/heads stay f32 — models/pwc.py).
        # Measured drift 0.015 px max; default f32 is the bit-parity path.
        dtype = (jnp.bfloat16 if self.precision == "bfloat16"
                 else jnp.float32)
        self.model = pwc_model.PWCNet(dtype=dtype)
        params = store.resolve_params(
            "pwc_sintel", pwc_model.init_params, pwc_model.params_from_torch,
            weights_path=args.get("weights_path"),
            allow_random=bool(args.get("allow_random_weights", False)))
        mesh = self._data_mesh()
        self._init_flow_runner(partial(_pwc_forward, self.model), params,
                               mesh)
