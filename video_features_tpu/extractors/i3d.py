"""I3D two-stream extractor.

Parity target: reference models/i3d/extract_i3d.py — streaming cv2 loop that
accumulates ``stack_size + 1`` resized frames (N+1 RGB frames -> N flow
frames; the rgb stream also uses ``stack[:-1]`` so both streams have equal
feature length, extract_i3d.py:148-159), runs each stream's I3D on
center-cropped 224 inputs scaled to [-1, 1], and records one
``timestamps_ms`` entry per completed stack = the POS_MSEC after the last
read frame, i.e. the pts of the frame just decoded:
``last_idx / fps * 1000`` (extract_i3d.py:122; cv2's ffmpeg backend reports
the decoded frame's own pts, pinned by the recorded golden refs in
tests/test_golden.py — a next-frame ``last_idx + 1`` rule is one frame off).

Re-design for TPU: frames are kept uint8 on host (PIL resize output;
``ToFloat`` only changes dtype so this is lossless), stacks are grouped into
a fixed-shape ``(clip_batch, T, 224, 224, C)`` batch, and scaling to [-1, 1]
happens inside the jitted forward where XLA fuses it into the first conv.
The flow stream runs RAFT/PWC over the same grouped stacks on device.

Output keys: ``streams + [fps, timestamps_ms]`` (extract_i3d.py:62).
"""
from __future__ import annotations

from functools import partial
from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..models import i3d as i3d_model
from ..ops import host_transforms as ht
from ..ops import preprocess as pp
from ..parallel.mesh import DataParallelApply, cast_floating, get_mesh
from ..utils.io import Prefetcher, VideoSource
from ..utils.labels import show_predictions_on_dataset
from ..weights import store
from .base import BaseExtractor


def _i3d_forward(model: i3d_model.I3D, dtype, features, params, batch):
    # batch: (B, T, 224, 224, C) — uint8 rgb or quantized-flow floats in
    # [0, 256]; both streams share ScaleTo1_1 (transforms.py:146-149)
    x = batch.astype(dtype)
    x = x * (2.0 / 255.0) - 1.0
    return model.apply({"params": params}, x,
                       features=features).astype(jnp.float32)


class ExtractI3D(BaseExtractor):

    def __init__(self, args: Config) -> None:
        super().__init__(args)
        streams = args.get("streams")
        self.streams: List[str] = (["rgb", "flow"] if streams is None
                                   else [streams])
        for stream in self.streams:
            if stream not in ("rgb", "flow"):
                raise NotImplementedError(f"Unknown I3D stream: {stream}")
        self.flow_type = args.get("flow_type", "pwc")  # reference default
        self.min_side_size = 256
        self.central_crop_size = 224
        self.extraction_fps = args.get("extraction_fps")
        self.stack_size = args.get("stack_size") or 64
        self.step_size = args.get("step_size") or 64
        self.clip_batch_size = int(args.get("clip_batch_size") or 8)
        self.output_feat_keys = self.streams + ["fps", "timestamps_ms"]

        dtype = jnp.bfloat16 if self.precision == "bfloat16" else jnp.float32
        self._dtype = dtype
        mesh = self._data_mesh()
        self.model = i3d_model.I3D(num_classes=400)
        self.runners: Dict[str, DataParallelApply] = {}
        self.logits_runners: Dict[str, DataParallelApply] = {}
        weights_path = args.get("weights_path")
        allow_random = bool(args.get("allow_random_weights", False))

        if "rgb" in self.streams:
            params = store.resolve_params(
                "i3d_rgb", partial(i3d_model.init_params, "rgb"),
                i3d_model.params_from_torch, weights_path=weights_path,
                allow_random=allow_random)
            # cast once for both runners
            params = cast_floating(params, dtype)
            self.runners["rgb"] = DataParallelApply(
                partial(_i3d_forward, self.model, dtype, True),
                params, mesh=mesh, fixed_batch=self.clip_batch_size)
            if self.show_pred:
                self.logits_runners["rgb"] = DataParallelApply(
                    partial(_i3d_forward, self.model, dtype, False),
                    params, mesh=mesh, fixed_batch=self.clip_batch_size)
        if "flow" in self.streams:
            self._init_flow_stream(args, mesh, dtype, allow_random)

        # ResizeImproved(256) smaller-edge PIL bilinear, kept uint8
        # (extract_i3d.py:41-46; PILToTensor+ToFloat only change layout).
        # A picklable callable (ops/host_transforms.py), not a closure:
        # video_decode=process ships it to spawned decode workers.
        transform = ht.MinSideResize(self.min_side_size)

        # resize=device: the 256-edge PIL filtering (~1.3 ms/frame/core) is
        # the host bottleneck for this family; run it as coefficient matmuls
        # in front of both streams instead (ops/preprocess.py device_resize)
        # and ship raw decoded frames. show_pred needs per-stack host frames
        # at the resized geometry, so it keeps the host path.
        self.resize_mode = self._resolve_resize_mode(args)
        if self.resize_mode == "device" and self.show_pred:
            print("WARNING: resize=device is unsupported with show_pred; "
                  "using resize=host")
            self.resize_mode = "host"
        self.host_transform = None if self.resize_mode == "device" \
            else transform

    def _runners_for(self, in_h: int, in_w: int):
        """Per-source-resolution (resize_runner, rgb_runner) pair. The
        resize runner resizes a whole raw (G, T+1, h, w, 3) uint8 group
        ONCE on device (uint8 out, exactly the host path's PIL-uint8
        semantics); both streams then consume the resized device array —
        raw frames cross H2D once and each frame is resized once. Committed
        backbone params are shared with the base runners (one HBM copy);
        bounded cache, one entry per source resolution."""
        def build():
            mesh = (self.runners.get("rgb")
                    or self._flow_stream.pair_runner).mesh
            ow, oh = pp.resize_edge_size(in_w, in_h, self.min_side_size)
            resize_frames = pp.make_device_resizer(in_h, in_w, oh, ow)
            resize_runner = DataParallelApply(
                lambda params, g_u8: resize_frames(g_u8), {},
                mesh=mesh, fixed_batch=self.clip_batch_size)
            rgb_runner = None
            if "rgb" in self.streams:
                base = self.runners["rgb"]
                c = self.central_crop_size
                ci, cj = (oh - c) // 2, (ow - c) // 2  # TensorCenterCrop

                def rgb_fwd(params, resized_u8):  # (G, T+1, oh, ow, 3)
                    x = resized_u8[:, :-1, ci:ci + c, cj:cj + c, :]
                    return _i3d_forward(self.model, self._dtype, True,
                                        params, x)

                rgb_runner = DataParallelApply(
                    rgb_fwd, base.params, mesh=base.mesh,
                    fixed_batch=self.clip_batch_size)
            return (resize_runner, rgb_runner)

        return self._cached_resize_runner((in_h, in_w), build)

    def _init_flow_stream(self, args, mesh, dtype, allow_random) -> None:
        from . import i3d_flow
        self._flow_stream = i3d_flow.FlowStream(
            self, args, mesh, dtype, allow_random)

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        src = self.video_source(video_path, batch_size=1,
                                fps=self.extraction_fps,
                          transform=self.host_transform)
        frames: List[np.ndarray] = []
        stacks: List[np.ndarray] = []
        timestamps_ms: List[float] = []
        feats: Dict[str, List] = {s: [] for s in self.streams}
        stacks_done = 0
        res_runners = None  # (resize_runner, rgb_runner) under resize=device
        from ..parallel.mesh import FeatureStream
        # bounded cross-group pipeline: one runner-less stream per i3d
        # stream; flush() dispatches itself and hands the device arrays in,
        # so decode of group k+1 overlaps device compute of group k while at
        # most 2 groups' results wait un-materialized
        queues = {s: FeatureStream(None, depth=2) for s in self.streams}

        def flush():
            nonlocal stacks_done
            if not stacks:
                return
            group = np.stack(stacks)  # (G, T+1, H, W, 3) uint8
            stacks.clear()
            if self.show_pred:
                # per-stream host values needed: synchronous, prints in order
                for stream in self.streams:
                    out = self.run_stream(stream, group, stacks_done)
                    feats[stream].extend(list(out))
            else:
                # both streams dispatched before either synchronizes: the
                # (cheap) rgb forward executes while the host assembles the
                # flow chain, and only the (G, 1024) features come back
                if res_runners is not None:
                    # resize=device: raw group crosses H2D once, resized
                    # once, and the uint8 result feeds both streams
                    resized = res_runners[0].dispatch(group)[:len(group)]
                    for s in self.streams:
                        dev = (res_runners[1].dispatch(resized) if s == "rgb"
                               else self._flow_stream.dispatch_resized(resized))
                        queues[s].submit_device(dev, len(group))
                else:
                    for s in self.streams:
                        queues[s].submit_device(
                            self.dispatch_stream(s, group), len(group))
            stacks_done += len(group)

        # decode-ahead roughly one stack while the previous stack is on-device
        for frame, _, idx in Prefetcher(src.frames(),
                                        depth=max(2, self.stack_size)):
            if res_runners is None and self.resize_mode == "device":
                # resize matrices from the first *decoded* frame's shape
                # (container metadata may disagree, e.g. rotation tags)
                res_runners = self._runners_for(*frame.shape[:2])
            frames.append(frame)
            if len(frames) - 1 == self.stack_size:
                stacks.append(np.stack(frames))
                # POS_MSEC = pts of the last read frame (extract_i3d.py:122;
                # golden-pinned in tests/test_golden.py)
                timestamps_ms.append(idx / src.fps * 1000.0)
                frames = frames[self.step_size:]
                if len(stacks) == self.clip_batch_size:
                    flush()
        flush()
        for s in self.streams:
            for out in queues[s].finish():
                feats[s].extend(list(out))

        out = {s: np.array(v) for s, v in feats.items()}
        out["fps"] = np.array(src.fps)
        out["timestamps_ms"] = np.array(timestamps_ms)
        return out

    def run_stream(self, stream: str, group: np.ndarray,
                   stack_base: int) -> np.ndarray:
        """group: (G, stack+1, H, W, 3) uint8 resized frames -> (G, 1024).

        ``stack_base`` = stacks already processed before this group, so both
        streams print the same stack indices under show_pred (the reference
        threads one stack_counter through run_on_a_stack, extract_i3d.py:140).
        """
        if stream == "rgb":
            g = self._rgb_crop(group)
            out = self.runners["rgb"](g)
            self.maybe_show_pred("rgb", g, stack_base)
            return out
        return self._flow_stream.run(group, stack_base)

    def dispatch_stream(self, stream: str, group: np.ndarray):
        """Async twin of :meth:`run_stream` (no show_pred): enqueues the
        stream's device work and returns the un-materialized (G_padded, 1024)
        device array."""
        if stream == "rgb":
            return self.runners["rgb"].dispatch(self._rgb_crop(group))
        return self._flow_stream.dispatch(group)

    def _rgb_crop(self, group: np.ndarray) -> np.ndarray:
        """Crop on host (pure slice, parity-exact; 30% less H2D traffic),
        drop the +1 frame the flow stream needs (extract_i3d.py:158-159)."""
        c = self.central_crop_size
        i = (group.shape[2] - c) // 2  # TensorCenterCrop floor rule
        j = (group.shape[3] - c) // 2
        return group[:, :-1, i:i + c, j:j + c]

    def maybe_show_pred(self, stream: str, device_in: np.ndarray,
                        stack_base: int) -> None:
        if not self.show_pred:
            return
        logits = self.logits_runners[stream](device_in)
        for i, row in enumerate(np.asarray(logits)):
            print(f"At stack {stack_base + i} ({stream} stream)")
            show_predictions_on_dataset(row[None], "kinetics")
