"""Pair-wise optical-flow extraction pipeline (RAFT, PWC).

Re-design of reference models/_base/base_flow_extractor.py:17-154:

  host:   streaming decode of ``batch_size + 1`` frames with 1-frame overlap
          between batches (N+1 frames -> N flows; reference
          base_flow_extractor.py:77-85), optional PIL edge resize, uint8
  device: fixed-shape (B, 2, H, W, 3) uint8 pair batch -> replicate-pad to
          the model's stride multiple -> flow net -> unpad -> (B, H, W, 2)

The reference ships frames to the GPU as float32 and pads with a host-side
InputPadder; here the 4x-smaller uint8 batch is shipped and both the
[0,255] cast and the replicate padding run inside the jitted function (pad
amounts are static under jit). Timestamps: the duplicate overlap timestamp
between consecutive batches is dropped (base_flow_extractor.py:94-95).

Feature layout parity: the reference stores flows channel-first
``(N, 2, H, W)`` (``model(...)`` output `.tolist()`ed); we transpose our
NHWC device output on the host to keep saved arrays byte-compatible.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ..config import Config
from ..parallel.mesh import DataParallelApply
from ..utils.io import Prefetcher, VideoSource
from ..utils import flow_viz
from .base import BaseExtractor


class OpticalFlowExtractor(BaseExtractor):
    """Families plug in ``runner`` ((B,2,H,W,3) uint8 -> (B,H,W,2) float)."""

    def __init__(self, args: Config) -> None:
        super().__init__(args)
        self.batch_size = int(args.get("batch_size") or 1)
        self.side_size = args.get("side_size")
        self.resize_to_smaller_edge = bool(args.get("resize_to_smaller_edge",
                                                    True))
        self.extraction_fps = args.get("extraction_fps")
        self.extraction_total = args.get("extraction_total")
        self.output_feat_keys = [self.feature_type, "fps", "timestamps_ms"]
        self.runner: Optional[DataParallelApply] = None
        #: set by subclasses for resize=device: the family forward taking
        #: uint8 pairs at the (resized) working geometry, and a builder
        #: producing a runner around a wrapped fwd with shared committed
        #: params (same pattern as frame_wise.py)
        self.base_fwd: Optional[Callable] = None
        self.runner_builder: Optional[Callable] = None

        #: resize=device (only meaningful with side_size): the per-frame PIL
        #: edge resize moves onto the MXU in front of the flow net; the host
        #: ships raw decoded frames. At small side_size the flow nets outrun
        #: a CPU core's PIL filtering, so this keeps the chip fed. Without
        #: side_size there is no resize in the pipeline at all, so the
        #: 'auto' default resolves to host.
        self.resize_mode = self._resolve_resize_mode(
            args, device_capable=self.side_size is not None)
        if self.side_size is None:
            self.resize_mode = "host"  # explicit resize=device: no-op too
        if self.resize_mode == "device" and self.show_pred:
            # show_pred overlays flow on the (resized) RGB frames, which the
            # host no longer has under device resize
            print("WARNING: resize=device is unsupported with show_pred; "
                  "using resize=host")
            self.resize_mode = "host"

        if self.side_size is not None and self.resize_mode == "host":
            from ..ops import preprocess as pp
            side = int(self.side_size)
            smaller = self.resize_to_smaller_edge

            def transform(rgb: np.ndarray) -> np.ndarray:
                return pp.pil_resize(rgb, side, to_smaller_edge=smaller)

            self.host_transform: Optional[Callable] = transform
        else:
            self.host_transform = None

    def _init_flow_runner(self, fwd, params, mesh) -> None:
        """Family-shared runner construction: the base runner plus the
        committed-param builder the device-resize cache wraps."""
        self.base_fwd = fwd
        self.runner = DataParallelApply(fwd, params, mesh=mesh,
                                        fixed_batch=self.batch_size)
        committed = self.runner.params  # one HBM copy across resolutions
        self.runner_builder = lambda f: DataParallelApply(
            f, committed, mesh=mesh, fixed_batch=self.batch_size)

    def _device_resize_runner(self, in_h: int, in_w: int) -> DataParallelApply:
        """Per-source-resolution runner: edge resize fused in front of the
        flow forward; committed params shared (one HBM copy)."""
        def build():
            from ..ops import preprocess as pp
            ow, oh = pp.resize_edge_size(in_w, in_h, int(self.side_size),
                                         self.resize_to_smaller_edge)
            resize = pp.make_device_resizer(in_h, in_w, oh, ow)
            base = self.base_fwd

            def fwd(params, raw_pairs_u8):  # (B, 2, in_h, in_w, 3)
                return base(params, resize(raw_pairs_u8))

            return self.runner_builder(fwd)

        return self._cached_resize_runner((in_h, in_w), build)

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        video = self.video_source(
            video_path,
            batch_size=self.batch_size + 1,  # N+1 frames -> N flows
            fps=self.extraction_fps,
            total=self.extraction_total,
            transform=self.host_transform,
            overlap=1,
        )
        vid_feats: List[np.ndarray] = []
        timestamps_ms: List[float] = []
        first = True
        stream = None
        # decode-ahead: the next batch decodes while this one is on-device
        for batch, ts, _ in Prefetcher(video):
            if len(batch) < 2:
                # a single-frame video (or trailing lone frame in the first
                # batch) yields no pairs
                timestamps_ms.extend(ts if first else ts[1:])
                first = False
                continue
            if stream is None:
                # resize=device keys the fused-resize runner off the first
                # decoded frame's shape; async dispatch with a shallow
                # window: each pending output is a full (B, H, W, 2) float
                # field, so at most 2 wait on-device at once
                runner = (self._device_resize_runner(*batch[0].shape[:2])
                          if self.resize_mode == "device" else self.runner)
                stream = self.feature_stream(
                    runner, depth=2,
                    on_result=lambda flows, a: self.maybe_show_pred(flows, a))
            arr = np.stack(batch)  # (n, H, W, 3) uint8
            pairs = np.stack([arr[:-1], arr[1:]], axis=1)
            stream.submit(pairs, ctx=arr)
            timestamps_ms.extend(ts if first else ts[1:])
            first = False
        if stream is not None:
            for bi, flows in enumerate(stream.finish()):
                # (n-1, H, W, 2) float32 per batch
                if self.parity:
                    # backbone seam: the raw per-batch flow field off the
                    # device, before the (0,3,1,2) sink transpose
                    from ..telemetry import parity as _parity
                    _parity.tap("backbone", self.feature_type, flows,
                                video=str(video_path),
                                feature_type=self.feature_type, index=bi)
                vid_feats.extend(list(flows.transpose(0, 3, 1, 2)))
        return {
            self.feature_type: np.array(vid_feats),
            "fps": np.array(video.fps),
            "timestamps_ms": np.array(timestamps_ms),
        }

    def maybe_show_pred(self, flows: np.ndarray, rgb_batch: np.ndarray) -> None:
        """Reference base_flow_extractor.py:139-154: show each flow frame
        under its first RGB frame in a cv2 window; headless fallback writes
        PNGs into tmp_path."""
        if not self.show_pred:
            return
        import cv2
        from pathlib import Path
        for i, flow in enumerate(flows):  # flows: (n, H, W, 2) NHWC
            img = rgb_batch[i].astype(np.float32)
            vis = flow_viz.flow_to_image(flow)
            stacked = np.concatenate([img, vis.astype(np.float32)], axis=0)
            bgr = stacked[:, :, ::-1] / 255.0
            try:
                cv2.imshow("Press any key to see the next frame...", bgr)
                cv2.waitKey()
            except cv2.error:
                out = Path(self.tmp_path) / f"flow_pred_{i}.png"
                out.parent.mkdir(parents=True, exist_ok=True)
                cv2.imwrite(str(out), (bgr * 255).astype(np.uint8))
                print(f"show_pred: no display; wrote {out}")
