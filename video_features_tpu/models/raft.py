"""RAFT optical flow as a JAX/Flax program, NHWC, static shapes.

Parity target: the reference's RAFT (reference models/raft/raft_src/
{raft,corr,update,extractor}.py, the princeton-vl network at 20 iterations,
test_mode — raft.py:118-177):

  - ``BasicEncoder`` fnet (instance norm, output 256) and cnet (batch norm,
    output 256 = 128 hidden + 128 context) at 1/8 resolution
    (extractor.py:116-189). Instance norms are affine-free and use batch
    statistics even at eval (torch InstanceNorm2d defaults), so they are
    pure functions here.
  - All-pairs correlation ``corr = <f1, f2> / sqrt(256)`` -> 4-level
    avg-pooled pyramid (corr.py:13-27).
  - Per-iteration windowed lookup (radius 4 -> 81 taps/level, 324 channels)
    via bilinear sampling with zeros padding + align_corners=True semantics
    (corr.py:29-50, utils/utils.py:59-73). The reference enumerates window
    taps with the x-offset varying slowest (its meshgrid(dy,dx) quirk adds
    "dy" to x) — replicated exactly so the 324 channels line up with the
    pretrained motion-encoder weights.
  - ``BasicUpdateBlock``: motion encoder convs, two-pass (1,5)/(5,1)
    ``SepConvGRU``, flow head, and a 9-way convex-upsample mask scaled by
    0.25 (update.py:86-144).
  - 20 GRU iterations as a ``lax.scan`` (XLA compiles the loop body once);
    the convex 8x upsample runs once on the final flow instead of per
    iteration (the reference computes it every iteration and discards all
    but the last, raft.py:154-175 — same result, 19 fewer upsamples).

Design notes (TPU): everything is fixed-shape; the correlation volume is the
memory hot spot (B * (HW/64)^2 floats) exactly as in the reference; the
lookup is 4 ``take_along_axis`` gathers per corner which XLA lowers to
dynamic-gather — no data-dependent shapes anywhere.

Input images: (B, H, W, 3) float32 in [0, 255]; H, W divisible by 8
(callers pad with ``pad_to_multiple`` replicate padding = the reference's
InputPadder, raft.py:30-48). Output: (B, H, W, 2) flow in pixels.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from .common import BNInf
from ..weights import torch_import as ti

CORR_LEVELS = 4
CORR_RADIUS = 4
HIDDEN_DIM = 128
CONTEXT_DIM = 128
ITERS = 20


def instance_norm(x: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """torch InstanceNorm2d(affine=False, track_running_stats=False) at eval:
    per-sample, per-channel normalization over H, W with biased variance.
    Statistics accumulate in f32 regardless of activation dtype (bf16 mode
    keeps the convs on the MXU-native dtype, norm internals stay exact)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(1, 2), keepdims=True)
    var = jnp.var(x32, axis=(1, 2), keepdims=True)
    return ((x32 - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype)


class ResidualBlock(nn.Module):
    planes: int
    norm_fn: str  # 'instance' | 'batch' | 'none'
    stride: int = 1

    def _norm(self, name: str, x: jnp.ndarray) -> jnp.ndarray:
        if self.norm_fn == "batch":
            return BNInf(name=name)(x)
        if self.norm_fn == "instance":
            return instance_norm(x)
        return x

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        y = nn.Conv(self.planes, (3, 3), strides=self.stride,
                    padding=1, name="conv1")(x)
        y = nn.relu(self._norm("norm1", y))
        y = nn.Conv(self.planes, (3, 3), padding=1, name="conv2")(y)
        y = nn.relu(self._norm("norm2", y))
        if self.stride != 1:
            x = nn.Conv(self.planes, (1, 1), strides=self.stride,
                        name="downsample_0")(x)
            x = self._norm("downsample_1", x)
        return nn.relu(x + y)


class BasicEncoder(nn.Module):
    """extractor.py:116-189; all convs carry bias (torch default)."""
    output_dim: int
    norm_fn: str

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = nn.Conv(64, (7, 7), strides=2, padding=3, name="conv1")(x)
        if self.norm_fn == "batch":
            x = BNInf(name="norm1")(x)
        elif self.norm_fn == "instance":
            x = instance_norm(x)
        x = nn.relu(x)
        for i, (dim, stride) in enumerate([(64, 1), (96, 2), (128, 2)]):
            x = ResidualBlock(dim, self.norm_fn, stride,
                              name=f"layer{i + 1}_0")(x)
            x = ResidualBlock(dim, self.norm_fn, 1, name=f"layer{i + 1}_1")(x)
        return nn.Conv(self.output_dim, (1, 1), name="conv2")(x)


class _Convc1Params(nn.Module):
    """Parameter-only twin of ``nn.Conv(256, (1, 1), name='convc1')`` —
    identical tree path, shapes, and init, so weight transplant and
    checkpoints are unchanged; the conv itself runs inside the fused
    Pallas lookup+projection kernel (kernels/corr_lookup.py
    corr_lookup_proj)."""
    features: int = 256
    in_features: int = CORR_LEVELS * (2 * CORR_RADIUS + 1) ** 2

    @nn.compact
    def __call__(self):
        k = self.param("kernel", nn.initializers.lecun_normal(),
                       (1, 1, self.in_features, self.features))
        b = self.param("bias", nn.initializers.zeros, (self.features,))
        return k, b


class BasicMotionEncoder(nn.Module):
    """update.py:86-104.

    ``fuse_meta`` (static) switches convc1 into the fused Pallas
    lookup+projection kernel: ``corr`` is then the sublane-stacked pyramid
    plane (kernels/corr_lookup.py stack_aligned_pyramid) and ``coords``
    the level-0 query centers — the (B, H, W, 324) lookup intermediate
    never materializes (round-4 profiling: its relayout boundary cost
    ~17 ms per 64-pair forward on v5e)."""
    fuse_meta: Optional[Tuple[Any, ...]] = None

    @nn.compact
    def __call__(self, flow: jnp.ndarray, corr: jnp.ndarray,
                 coords: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        if self.fuse_meta is not None:
            from ..kernels import interpret_mode
            from ..kernels.corr_lookup import corr_lookup_proj
            k, b = _Convc1Params(name="convc1")()
            cor = corr_lookup_proj(corr, self.fuse_meta, coords,
                                   k.reshape(k.shape[2], k.shape[3]), b,
                                   interpret=interpret_mode())
            cor = cor.astype(flow.dtype)
        else:
            cor = nn.relu(nn.Conv(256, (1, 1), name="convc1")(corr))
        cor = nn.relu(nn.Conv(192, (3, 3), padding=1, name="convc2")(cor))
        flo = nn.relu(nn.Conv(128, (7, 7), padding=3, name="convf1")(flow))
        flo = nn.relu(nn.Conv(64, (3, 3), padding=1, name="convf2")(flo))
        out = nn.relu(nn.Conv(126, (3, 3), padding=1, name="conv")(
            jnp.concatenate([cor, flo], axis=-1)))
        return jnp.concatenate([out, flow], axis=-1)


class SepConvGRU(nn.Module):
    """Two-pass separable GRU (update.py:39-65)."""
    hidden_dim: int = HIDDEN_DIM

    @nn.compact
    def __call__(self, h: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
        for suffix, kernel, pad in (("1", (1, 5), (0, 2)), ("2", (5, 1), (2, 0))):
            hx = jnp.concatenate([h, x], axis=-1)
            pad2 = [(pad[0], pad[0]), (pad[1], pad[1])]
            z = nn.sigmoid(nn.Conv(self.hidden_dim, kernel, padding=pad2,
                                   name=f"convz{suffix}")(hx))
            r = nn.sigmoid(nn.Conv(self.hidden_dim, kernel, padding=pad2,
                                   name=f"convr{suffix}")(hx))
            q = jnp.tanh(nn.Conv(self.hidden_dim, kernel, padding=pad2,
                                 name=f"convq{suffix}")(
                jnp.concatenate([r * h, x], axis=-1)))
            h = (1 - z) * h + z * q
        return h


class FlowHead(nn.Module):
    hidden_dim: int = 256

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = nn.relu(nn.Conv(self.hidden_dim, (3, 3), padding=1,
                            name="conv1")(x))
        return nn.Conv(2, (3, 3), padding=1, name="conv2")(x)


class UpdateIter(nn.Module):
    """One RAFT iteration: corr lookup + BasicUpdateBlock (update.py:123-144;
    the mask head is applied separately, see RAFT.__call__). Shaped as a
    ``lax.scan`` body: (carry, broadcast-inputs) -> (carry, None).

    ``corr_meta`` (static) marks the broadcast ``pyramid`` input as
    lane-dense-packed for the fused Pallas lookup (kernels/corr_lookup.py
    pack_pyramid); ``None`` means raw (B, P, Hl, Wl) levels. ``fuse_meta``
    (static) marks it as the sublane-stacked plane of the fused
    lookup+convc1 kernel (the TPU default since round 4)."""
    corr_meta: Optional[Tuple[Any, ...]] = None
    fuse_meta: Optional[Tuple[Any, ...]] = None

    @nn.compact
    def __call__(self, carry, inputs):
        net, coords1 = carry
        pyramid, inp, coords0 = inputs
        flow = (coords1 - coords0).astype(net.dtype)
        if self.fuse_meta is not None:
            motion = BasicMotionEncoder(fuse_meta=self.fuse_meta,
                                        name="encoder")(
                flow, pyramid, coords1)
        else:
            # the lookup runs in f32 (coords + pyramid precision); under
            # bf16 mode its (B,H,W,324) output and the flow join the hidden
            # state's dtype so the update convs stay on the MXU-native
            # dtype. coords stay f32 through the carry: delta promotes back
            # on add.
            corr = corr_lookup(pyramid, coords1,
                               packed_meta=self.corr_meta).astype(net.dtype)
            motion = BasicMotionEncoder(name="encoder")(flow, corr)
        x = jnp.concatenate([inp, motion], axis=-1)
        net = SepConvGRU(name="gru")(net, x)
        delta = FlowHead(name="flow_head")(net)
        return (net, coords1 + delta.astype(coords1.dtype)), None


class MaskHead(nn.Module):
    """update.py:130-133 (`update_block.mask` Sequential) with the 0.25
    gradient-balance scale from update.py:143."""

    @nn.compact
    def __call__(self, net: jnp.ndarray) -> jnp.ndarray:
        x = nn.relu(nn.Conv(256, (3, 3), padding=1, name="mask_0")(net))
        return 0.25 * nn.Conv(64 * 9, (1, 1), name="mask_2")(x)


# ---- correlation volume --------------------------------------------------

def build_corr_pyramid(fmap1: jnp.ndarray, fmap2: jnp.ndarray,
                       num_levels: int = CORR_LEVELS) -> List[jnp.ndarray]:
    """All-pairs correlation + avg-pool pyramid (corr.py:13-27, 52-60).

    fmaps: (B, H, W, C). Returns per level (B, H*W, Hl, Wl)."""
    b, h, w, c = fmap1.shape
    f1 = fmap1.reshape(b, h * w, c)
    f2 = fmap2.reshape(b, h * w, c)
    # f32 accumulation/output even from bf16 fmaps: the pyramid (and hence
    # the lookup) keeps full precision in every mode; the MXU still takes
    # bf16 inputs at native rate
    corr = jnp.einsum("bpc,bqc->bpq", f1, f2,
                      preferred_element_type=jnp.float32) / math.sqrt(c)
    corr = corr.reshape(b, h * w, h, w)
    pyramid = [corr]
    for _ in range(num_levels - 1):
        # torch avg_pool2d(2, stride=2): floor mode drops odd trailing row/col
        hl, wl = corr.shape[2] // 2 * 2, corr.shape[3] // 2 * 2
        corr = corr[:, :, :hl, :wl]
        corr = jax.lax.reduce_window(
            corr, 0.0, jax.lax.add, (1, 1, 2, 2), (1, 1, 2, 2),
            [(0, 0)] * 4) / 4.0
        pyramid.append(corr)
    return pyramid


def _fused_supported(pyramid: Sequence[jnp.ndarray]) -> bool:
    from ..kernels.corr_lookup import fused_lookup_supported
    return fused_lookup_supported(pyramid)


def _pallas_supported(pyramid: Sequence[jnp.ndarray]) -> bool:
    from ..kernels.corr_lookup import pallas_lookup_supported
    return pallas_lookup_supported(pyramid)


#: process-level corr-lookup dispatch defaults, set from CONFIG KEYS
#: (``corr_lookup_impl`` / ``fuse_convc1`` in raft.yml / i3d.yml) at
#: extractor init via :func:`configure_corr_lookup` — i.e. before the
#: first traced forward, by construction
_CORR_CONFIG: dict = {"impl": None, "fuse_convc1": None}

_CORR_IMPLS = ("gather", "onehot", "pallas", "packed")


def configure_corr_lookup(impl=None, fuse_convc1=None) -> None:
    """Install the config-level corr-lookup dispatch choice.

    Called by the RAFT-bearing extractors at init with the validated
    ``corr_lookup_impl``/``fuse_convc1`` config keys. ``None`` leaves a
    knob at its platform auto choice (Pallas+fused on TPU, gather
    elsewhere). The ``VFT_CORR_LOOKUP``/``VFT_FUSE_CONVC1`` env vars
    remain the highest-precedence override — for trace-time perf probes
    (scripts/bench_i3d_variants.py A/Bs) — but config, not environment,
    is now the supported interface, and it is applied before anything
    can have been traced."""
    if impl is not None:
        if impl not in _CORR_IMPLS:
            raise ValueError(f"corr_lookup_impl={impl!r}: expected one of "
                             f"{_CORR_IMPLS} or null (auto)")
        _CORR_CONFIG["impl"] = impl
    if fuse_convc1 is not None:
        _CORR_CONFIG["fuse_convc1"] = bool(fuse_convc1)


def _corr_impl() -> str:
    """Corr-lookup implementation choice, resolved at trace time:
    env override > config key (configure_corr_lookup) > platform auto."""
    import os
    impl = os.environ.get("VFT_CORR_LOOKUP", "").strip().lower()
    if not impl:
        impl = _CORR_CONFIG["impl"] or (
            "pallas" if jax.default_backend() == "tpu" else "gather")
    if impl not in _CORR_IMPLS:
        raise ValueError(f"VFT_CORR_LOOKUP={impl!r}: expected "
                         "'gather', 'onehot', 'pallas' or 'packed'")
    return impl


def _fuse_convc1() -> bool:
    """Fused lookup+convc1 kernel switch on the pallas path (default ON;
    false opts out to the per-level unfused kernels — the round-3
    configuration, kept for A/B). Same precedence as :func:`_corr_impl`:
    env override > config key > auto."""
    import os
    env = os.environ.get("VFT_FUSE_CONVC1", "").strip().lower()
    if env:
        return env not in ("0", "false", "no")
    cfg = _CORR_CONFIG["fuse_convc1"]
    return True if cfg is None else cfg


def corr_lookup(pyramid: Sequence[jnp.ndarray], coords: jnp.ndarray,
                radius: int = CORR_RADIUS,
                packed_meta: Optional[Tuple[Any, ...]] = None) -> jnp.ndarray:
    """Windowed bilinear lookup — implementation dispatcher.

    ``packed_meta`` not None means ``pyramid`` holds lane-dense-packed
    levels (kernels/corr_lookup.py pack_pyramid) and routes straight to the
    fused Pallas kernel — the RAFT scan path, where the pack is hoisted out
    of the 20-iteration GRU loop.

    The ``corr_lookup_impl`` CONFIG key selects ``gather``, ``onehot``,
    ``pallas`` or ``packed`` (kernels/corr_lookup.py; ``packed`` is the
    lane-dense fused-kernel alternative kept as a measured negative
    result — ~10% slower end-to-end than ``pallas`` on v5e despite 5.8x
    fewer DMA bytes). Unset picks ``pallas`` on TPU and ``gather``
    elsewhere. The key is validated at launch (config.sanity_check) and
    installed at extractor init (:func:`configure_corr_lookup`) — before
    the first traced forward, so there is no set-before-first-trace
    ordering to get wrong. ``VFT_CORR_LOOKUP`` remains the
    highest-precedence override for in-process perf probes.

    Measured END-TO-END on TPU v5e with a D2H-fenced timer
    (parallel/mesh.py settle — block_until_ready acks early through dev
    tunnels and once made all impls look equal at ~20 us, a pure artifact):
    full 20-iteration RAFT forward, 16 pairs @224px: gather 4,097 ms,
    one-hot 331 ms, fused Pallas 200 ms. The scalar-indexed corner gathers
    are a catastrophic access pattern for the TPU's vector memory; the
    MXU contraction forms are 12-20x faster, so Pallas is the TPU default
    and gather remains the parity/debug path (and the CPU default, where
    XLA lowers it well).

    Hardware-smoked across resolutions (scripts/validate_kernels_tpu.py):
    no Mosaic faults at any pyramid width 8..42 (odd/small included), and
    pallas == onehot exactly with both ~1e-5 from gather under the
    extractors' precision=float32 matmul-precision pin. Under
    precision=bfloat16 the contraction legitimately drifts ~8e-3 (MXU
    bf16), which is that mode's contract."""
    impl = _corr_impl()
    if packed_meta is not None:
        from ..kernels import interpret_mode
        from ..kernels.corr_lookup import corr_lookup_packed
        return corr_lookup_packed(pyramid, packed_meta, coords, radius,
                                  interpret=interpret_mode())
    if impl == "onehot":
        from ..kernels.corr_lookup import corr_lookup_onehot
        return corr_lookup_onehot(pyramid, coords, radius)
    if impl in ("pallas", "packed"):
        supported = (_pallas_supported(pyramid) if impl == "pallas"
                     else _fused_supported(pyramid))
        if not supported:
            # planes too large for any legal VMEM tile (inputs ~>5800 px on
            # a side): the XLA one-hot twin has identical numerics and no
            # tiling constraint
            from ..kernels.corr_lookup import corr_lookup_onehot
            return corr_lookup_onehot(pyramid, coords, radius)
        from ..kernels import interpret_mode
        if impl == "packed":
            from ..kernels.corr_lookup import pack_pyramid
            packed, metas = pack_pyramid(pyramid)
            from ..kernels.corr_lookup import corr_lookup_packed
            return corr_lookup_packed(packed, metas, coords, radius,
                                      interpret=interpret_mode())
        from ..kernels.corr_lookup import corr_lookup_pallas
        return corr_lookup_pallas(pyramid, coords, radius,
                                  interpret=interpret_mode())
    return corr_lookup_gather(pyramid, coords, radius)


def corr_lookup_gather(pyramid: Sequence[jnp.ndarray], coords: jnp.ndarray,
                       radius: int = CORR_RADIUS) -> jnp.ndarray:
    """Windowed bilinear lookup (corr.py:29-50).

    coords: (B, H, W, 2) (x, y) at level-0 resolution. Returns
    (B, H, W, levels*(2r+1)^2) with the reference's channel order: per level,
    the x-offset varies slowest across the 81 taps (corr.py:37-43 adds its
    meshgrid's dy to the x coordinate), then levels are concatenated.
    """
    b, h, w, _ = coords.shape
    p = h * w
    n_taps = (2 * radius + 1) ** 2
    d = jnp.linspace(-radius, radius, 2 * radius + 1, dtype=jnp.float32)
    off_slow = jnp.repeat(d, 2 * radius + 1)  # added to x (the dy quirk)
    off_fast = jnp.tile(d, 2 * radius + 1)    # added to y
    cx = coords[..., 0].reshape(b, p, 1)
    cy = coords[..., 1].reshape(b, p, 1)

    out = []
    for lvl, corr in enumerate(pyramid):
        hl, wl = corr.shape[2], corr.shape[3]
        corr_flat = corr.reshape(b, p, hl * wl)
        x = cx / (2 ** lvl) + off_slow  # (B, P, 81)
        y = cy / (2 ** lvl) + off_fast
        x0 = jnp.floor(x)
        y0 = jnp.floor(y)
        wx1 = x - x0
        wy1 = y - y0
        acc = jnp.zeros((b, p, n_taps), dtype=corr.dtype)
        for xi, wxf in ((x0, 1.0 - wx1), (x0 + 1, wx1)):
            for yi, wyf in ((y0, 1.0 - wy1), (y0 + 1, wy1)):
                # zeros padding: out-of-range corners contribute nothing
                valid = ((xi >= 0) & (xi <= wl - 1) &
                         (yi >= 0) & (yi <= hl - 1))
                idx = (jnp.clip(yi, 0, hl - 1) * wl +
                       jnp.clip(xi, 0, wl - 1)).astype(jnp.int32)
                val = jnp.take_along_axis(corr_flat, idx, axis=2)
                acc = acc + jnp.where(valid, wxf * wyf * val, 0.0)
        out.append(acc.reshape(b, h, w, n_taps))
    return jnp.concatenate(out, axis=-1)


def convex_upsample(flow: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Learned 8x convex-combination upsample (raft.py:104-115), NHWC.

    flow: (B, H, W, 2); mask: (B, H, W, 576). Returns (B, 8H, 8W, 2)."""
    b, h, w, _ = flow.shape
    # f32 softmax + combination even from a bf16 mask head
    mask = mask.astype(jnp.float32).reshape(b, h, w, 9, 8, 8)
    mask = jax.nn.softmax(mask, axis=3)
    # 3x3 neighborhoods of 8*flow (torch F.unfold k=3 pad=1, row-major taps)
    fpad = jnp.pad(8.0 * flow, ((0, 0), (1, 1), (1, 1), (0, 0)))
    taps = [fpad[:, dy:dy + h, dx:dx + w, :]
            for dy in range(3) for dx in range(3)]
    nb = jnp.stack(taps, axis=3)  # (B, H, W, 9, 2)
    up = jnp.einsum("bhwkij,bhwkc->bhwijc", mask, nb)  # (B, H, W, 8, 8, 2)
    return up.transpose(0, 1, 3, 2, 4, 5).reshape(b, 8 * h, 8 * w, 2)


def pad_to_multiple(x: np.ndarray, mult: int = 8,
                    mode: str = "sintel") -> Tuple[Tuple[int, int], Tuple[int, int]]:
    """InputPadder pad amounts (raft.py:30-40) for an (..., H, W, C) shape.

    Returns ((top, bottom), (left, right)) replicate-pad amounts."""
    ht, wd = x.shape[-3], x.shape[-2]
    pad_ht = (((ht // mult) + 1) * mult - ht) % mult
    pad_wd = (((wd // mult) + 1) * mult - wd) % mult
    if mode == "sintel":
        return ((pad_ht // 2, pad_ht - pad_ht // 2),
                (pad_wd // 2, pad_wd - pad_wd // 2))
    return ((0, pad_ht), (pad_wd // 2, pad_wd - pad_wd // 2))


def padded_flow(model: "RAFT", params, pairs_f32: jnp.ndarray,
                mode: str = "sintel"):
    """Run RAFT on an (B, 2, H, W, 3) float pair batch with InputPadder
    semantics (replicate-pad to /8, raft.py:30-48). Returns the flow at
    *padded* resolution plus the ((top, bottom), (left, right)) pad amounts
    so callers can unpad (extract_raft) or center-crop the padded field
    (the I3D flow stream, which never unpads — extract_i3d.py:153)."""
    (pt, pb), (pl, pr) = pad_to_multiple(pairs_f32[:, 0], mode=mode)
    pad = ((0, 0), (pt, pb), (pl, pr), (0, 0))
    flow = model.apply({"params": params},
                       jnp.pad(pairs_f32[:, 0], pad, mode="edge"),
                       jnp.pad(pairs_f32[:, 1], pad, mode="edge"))
    return flow, ((pt, pb), (pl, pr))


class RAFT(nn.Module):
    """(B, H, W, 3) [0,255] image pairs -> (B, H, W, 2) flow (pixels).

    ``dtype=jnp.bfloat16`` (with params cast via ``cast_floating``) runs the
    conv stacks — encoders, motion encoder, GRU, flow/mask heads — in the
    MXU-native dtype while the precision-critical state stays f32: the corr
    pyramid (f32-accumulated einsum), the lookup, the iterated coords, norm
    statistics, and the upsample softmax. Flow drift vs f32 is sub-0.1 px
    (well under the I3D flow stream's ToUInt8 quantization step of ~0.16);
    the f32 default is bit-identical to before (every cast is a no-op).

    Precision/perf record: bf16 mode measured +7.5% on the I3D RGB+Flow
    step in round 3 (3.95 -> 4.25 stacks/s, v5e) — the conv stacks go
    MXU-native while the lookup cost is unchanged (it is selection-bound,
    kernels/corr_lookup.py). A bf16 corr PYRAMID was measured twice and
    rejected twice: 0.87x in round 2 (in-kernel upcast outweighed the DMA
    saving), and moot in round 3 — the lane-dense repack proved lookup
    DMA bytes are not the binding constraint at all, so halving them buys
    nothing. The pyramid stays f32 in every mode, which also keeps lookup
    values exact."""
    iters: int = ITERS
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, image1: jnp.ndarray, image2: jnp.ndarray) -> jnp.ndarray:
        image1 = (2 * (image1 / 255.0) - 1.0).astype(self.dtype)
        image2 = (2 * (image2 / 255.0) - 1.0).astype(self.dtype)

        fnet = BasicEncoder(256, "instance", name="fnet")
        # one shared-weight call on the concatenated pair, like the
        # reference's fnet([image1, image2]) (raft.py:132)
        fmaps = fnet(jnp.concatenate([image1, image2], axis=0))
        fmap1, fmap2 = jnp.split(fmaps, 2, axis=0)
        pyramid = build_corr_pyramid(fmap1, fmap2)
        corr_meta = None
        fuse_meta = None
        impl = _corr_impl()
        if impl == "pallas" and _pallas_supported(pyramid):
            # tile-align the loop-invariant pyramid ONCE, outside the scan:
            # the pallas lookup needs (8, 128)-aligned level planes, and XLA
            # does not hoist the pads out of the while body — unhoisted they
            # ran 20x per forward and cost ~30% of the whole RAFT step
            # (kernels/corr_lookup.py align_level; zero pads are exactly the
            # reference's out-of-range zeros rule)
            from ..kernels.corr_lookup import (align_level,
                                               proj_lookup_supported,
                                               stack_aligned_pyramid)
            if _fuse_convc1() and proj_lookup_supported(pyramid):
                # round-4 default: ONE kernel serves all four levels AND
                # the motion encoder's convc1 — the 324-channel lookup
                # intermediate (and its relayout boundary) never exists
                pyramid, fuse_meta = stack_aligned_pyramid(pyramid)
            else:
                pyramid = tuple(align_level(c) for c in pyramid)
            # (measured, not kept as default: a lane-DENSE packed pyramid
            # moves 5.8x fewer bytes but lands ~10% slower end-to-end —
            # the lookup is selection-bound, not DMA-bound. The packed
            # kernel stays available as VFT_CORR_LOOKUP=packed; the
            # negative-result record lives in kernels/corr_lookup.py.)
        elif impl == "packed" and _fused_supported(pyramid):
            # lane-dense-pack ONCE outside the scan; ONE fused kernel
            # serves all four levels per iteration
            from ..kernels.corr_lookup import pack_pyramid
            pyramid, corr_meta = pack_pyramid(pyramid)

        cnet = BasicEncoder(HIDDEN_DIM + CONTEXT_DIM, "batch",
                            name="cnet")(image1)
        net = jnp.tanh(cnet[..., :HIDDEN_DIM])
        inp = nn.relu(cnet[..., HIDDEN_DIM:])

        b, h8, w8, _ = net.shape
        gx, gy = jnp.meshgrid(jnp.arange(w8, dtype=jnp.float32),
                              jnp.arange(h8, dtype=jnp.float32))
        coords0 = jnp.broadcast_to(jnp.stack([gx, gy], axis=-1),
                                   (b, h8, w8, 2))

        # lax.scan compiles ONE iteration body regardless of iters; the
        # reference's Python loop (raft.py:154-171) unrolls 20 copies
        scanned = nn.scan(
            UpdateIter, variable_broadcast="params",
            split_rngs={"params": False}, in_axes=nn.broadcast,
            length=self.iters)(corr_meta=corr_meta, fuse_meta=fuse_meta,
                               name="update_block")
        (net, coords1), _ = scanned((net, coords0), (pyramid, inp, coords0))

        mask = MaskHead(name="update_mask")(net)
        return convex_upsample(coords1 - coords0, mask)


# ---- weight transplant ---------------------------------------------------

def params_from_torch(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """raft-{sintel,kitti}.pth state_dict -> Flax tree.

    torch key layout: ``{fnet,cnet}.{conv1,conv2,layerL.I.*}``,
    ``update_block.{encoder,gru,flow_head,mask.N}``. BN modules are detected
    by their ``running_mean``; ``normK`` keys duplicate ``downsample.1`` in
    torch (same module registered under two names) and are skipped.
    """
    state_dict = ti.strip_module_prefix(state_dict)  # DataParallel ckpts
    params: Dict[str, Any] = {}
    for key, tensor in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        parts = key.split(".")
        leaf = parts[-1]
        mods = parts[:-1]
        # norm3/norm4 duplicate downsample.1 (extractor.py:44-45)
        if any(m in ("norm3", "norm4") for m in mods):
            continue
        # merge Sequential indices into the parent name: layer1.0 ->
        # layer1_0, downsample.0 -> downsample_0, mask.0 -> mask_0
        flat: List[str] = []
        for m in mods:
            if m.isdigit() and flat:
                flat[-1] = f"{flat[-1]}_{m}"
            else:
                flat.append(m)
        # the mask Sequential lives beside the update block in our tree
        if flat[0] == "update_block" and flat[1].startswith("mask_"):
            flat = ["update_mask"] + flat[1:]
        module = flat[-1]
        prefix = "/".join(flat[:-1])
        is_bn = f"{'.'.join(mods)}.running_mean" in state_dict
        if is_bn:
            bnl = {"weight": "scale", "bias": "bias",
                   "running_mean": "mean", "running_var": "var"}[leaf]
            ti.set_in(params, f"{prefix}/{module}/{bnl}", ti.to_np(tensor))
        elif leaf == "weight":
            ti.set_in(params, f"{prefix}/{module}/kernel",
                      ti.conv2d_kernel(tensor))
        else:
            ti.set_in(params, f"{prefix}/{module}/bias", ti.to_np(tensor))
    return params


def init_params(iters: int = ITERS) -> Dict[str, Any]:
    model = RAFT(iters=iters)
    v = model.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, 64, 64, 3)), jnp.zeros((1, 64, 64, 3)))
    return v["params"]
