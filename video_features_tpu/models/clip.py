"""OpenAI CLIP (both visual backbones + text tower) as Flax modules, NHWC.

Parity target: reference models/clip/clip_src/model.py — the image encoder
(``VisionTransformer`` :206-240 or ``ModifiedResNet`` :96-154), the text
transformer with causal mask (:195-203, :328-334), ``QuickGELU``
``x * sigmoid(1.702 x)`` (:166-168), fp32 LayerNorms inside an fp16 model
(:157-163), and the attention-pooled ResNet head ``AttentionPool2d``
(:58-93, query = the mean token).

Design notes (TPU):
  - the converter upcasts the OpenAI checkpoints' fp16 conv/linear tensors
    (model.py:375-396) to float32. With the extractor's ``precision=bfloat16``
    knob both params and activations are cast to bf16 for inference
    (parallel/mesh.py cast_floating) — except the show_pred text path, which
    reads the pre-cast f32 tree; LayerNorms always compute in float32,
    mirroring the reference's fp16-safe LayerNorm.
  - attention is implemented with packed-per-head einsums that XLA maps onto
    the MXU; the (77, 77) causal mask is an additive constant folded into
    the compiled program.
  - per-frame vision attention is over 50-577 patch tokens — "sequence
    scale" in this workload is the *frame batch*, sharded over the mesh's
    data axis (SURVEY §5 "long-context" note).

Config inference from checkpoint shapes replicates ``build_model``
(model.py:399-436), so any OpenAI / fine-tuned state_dict picks its own
architecture, exactly like the reference's ``custom`` path.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from .common import BNInf
from ..weights import torch_import as ti


@dataclasses.dataclass(frozen=True)
class CLIPConfig:
    embed_dim: int
    image_resolution: int
    vision_layers: Union[Tuple[int, int, int, int], int]
    vision_width: int
    vision_patch_size: Optional[int]
    context_length: int
    vocab_size: int
    transformer_width: int
    transformer_heads: int
    transformer_layers: int

    @property
    def is_vit(self) -> bool:
        return not isinstance(self.vision_layers, (tuple, list))


def _cfg(embed_dim, image_resolution, vision_layers, vision_width,
         vision_patch_size, transformer_width, transformer_layers=12):
    return CLIPConfig(
        embed_dim=embed_dim, image_resolution=image_resolution,
        vision_layers=vision_layers, vision_width=vision_width,
        vision_patch_size=vision_patch_size, context_length=77,
        vocab_size=49408, transformer_width=transformer_width,
        transformer_heads=transformer_width // 64,
        transformer_layers=transformer_layers)


# the model zoo the reference downloads from the OpenAI CDN (clip.py:32-42);
# shapes match build_model's inference on those checkpoints
CONFIGS: Dict[str, CLIPConfig] = {
    "RN50": _cfg(1024, 224, (3, 4, 6, 3), 64, None, 512),
    "RN101": _cfg(512, 224, (3, 4, 23, 3), 64, None, 512),
    "RN50x4": _cfg(640, 288, (4, 6, 10, 6), 80, None, 640),
    "RN50x16": _cfg(768, 384, (6, 8, 18, 8), 96, None, 768),
    "RN50x64": _cfg(1024, 448, (3, 15, 36, 10), 128, None, 1024),
    "ViT-B/32": _cfg(512, 224, 12, 768, 32, 512),
    "ViT-B/16": _cfg(512, 224, 12, 768, 16, 512),
    "ViT-L/14": _cfg(768, 224, 24, 1024, 14, 768),
    "ViT-L/14@336px": _cfg(768, 336, 24, 1024, 14, 768),
}


def available_models() -> List[str]:
    return list(CONFIGS)


class LNf32(nn.Module):
    """LayerNorm computed in float32 regardless of activation dtype
    (model.py:157-163)."""

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        y = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln")(
            x.astype(jnp.float32))
        return y.astype(x.dtype)


class MHA(nn.Module):
    """torch ``nn.MultiheadAttention`` semantics with separate q/k/v trees
    (the converter splits torch's packed ``in_proj``); also serves
    ``AttentionPool2d`` via ``out_name='c_proj'`` + a 1-token query.

    ``attn_impl='blockwise'`` scores attention with the streaming-softmax
    recurrence (parallel/sequence.py blockwise_attention) instead of the
    dense (T, T) score matrix — O(T*block) peak score memory, same values
    (softmax in f32 either way). Only the unmasked path switches; masked
    (text-causal) calls at 77 tokens stay dense."""
    embed_dim: int
    num_heads: int
    out_dim: Optional[int] = None
    out_name: str = "out_proj"
    attn_impl: str = "dense"

    @nn.compact
    def __call__(self, q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                 mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        e, h = self.embed_dim, self.num_heads
        hd = e // h

        def heads(x):
            return x.reshape(x.shape[0], x.shape[1], h, hd)

        qh = heads(nn.Dense(e, name="q_proj")(q)) * (hd ** -0.5)
        kh = heads(nn.Dense(e, name="k_proj")(k))
        vh = heads(nn.Dense(e, name="v_proj")(v))
        if self.attn_impl == "blockwise" and mask is None:
            from ..parallel.sequence import blockwise_attention
            out = blockwise_attention(qh, kh, vh, block_size=256, scale=1.0)
        else:
            att = jnp.einsum("bqhd,bkhd->bhqk", qh, kh)
            if mask is not None:
                att = att + mask
            att = jax.nn.softmax(att.astype(jnp.float32),
                                 axis=-1).astype(q.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", att, vh)
        out = out.reshape(q.shape[0], q.shape[1], e)
        return nn.Dense(self.out_dim or e, name=self.out_name)(out)


class ResidualAttentionBlock(nn.Module):
    """model.py:171-193."""
    d_model: int
    n_head: int
    attn_impl: str = "dense"

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        y = LNf32(name="ln_1")(x)
        x = x + MHA(self.d_model, self.n_head, attn_impl=self.attn_impl,
                    name="attn")(y, y, y, mask)
        y = LNf32(name="ln_2")(x)
        hterm = nn.Dense(self.d_model * 4, name="mlp_c_fc")(y)
        hterm = hterm * nn.sigmoid(1.702 * hterm)  # QuickGELU
        return x + nn.Dense(self.d_model, name="mlp_c_proj")(hterm)


class Transformer(nn.Module):
    """model.py:195-203; resblocks unrolled (<=24 layers, one HLO each —
    XLA CSEs the identical block structure at compile time)."""
    width: int
    layers: int
    heads: int
    attn_impl: str = "dense"

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        for i in range(self.layers):
            x = ResidualAttentionBlock(self.width, self.heads,
                                       attn_impl=self.attn_impl,
                                       name=f"resblocks_{i}")(x, mask)
        return x


class VisionTransformer(nn.Module):
    """model.py:206-240. Input (B, R, R, 3) normalized; output (B, embed)."""
    width: int
    layers: int
    patch_size: int
    output_dim: int
    attn_impl: str = "dense"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        p, w = self.patch_size, self.width
        x = nn.Conv(w, (p, p), strides=p, use_bias=False, name="conv1")(x)
        b, gh, gw, _ = x.shape
        x = x.reshape(b, gh * gw, w)
        cls = self.param("class_embedding", nn.initializers.normal(), (w,))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(x.dtype), (b, 1, w)), x], axis=1)
        pos = self.param("positional_embedding", nn.initializers.normal(),
                         (gh * gw + 1, w))
        x = x + pos.astype(x.dtype)
        x = LNf32(name="ln_pre")(x)
        x = Transformer(w, self.layers, w // 64, attn_impl=self.attn_impl,
                        name="transformer")(x)
        x = LNf32(name="ln_post")(x[:, 0])
        proj = self.param("proj", nn.initializers.normal(),
                          (w, self.output_dim))
        return x @ proj.astype(x.dtype)


class Bottleneck(nn.Module):
    """Anti-aliased CLIP bottleneck (model.py:10-55): all convs stride 1, an
    AvgPool2d(stride) after conv2 (and prepended to the downsample conv)."""
    planes: int
    stride: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        out_ch = self.planes * 4
        y = nn.relu(BNInf(name="bn1")(
            nn.Conv(self.planes, (1, 1), use_bias=False, name="conv1")(x)))
        y = nn.relu(BNInf(name="bn2")(
            nn.Conv(self.planes, (3, 3), padding=1, use_bias=False,
                    name="conv2")(y)))
        if self.stride > 1:
            y = nn.avg_pool(y, (self.stride,) * 2, (self.stride,) * 2)
        y = BNInf(name="bn3")(
            nn.Conv(out_ch, (1, 1), use_bias=False, name="conv3")(y))
        if self.stride > 1 or x.shape[-1] != out_ch:
            x = nn.avg_pool(x, (self.stride,) * 2, (self.stride,) * 2)
            x = BNInf(name="downsample_1")(
                nn.Conv(out_ch, (1, 1), use_bias=False,
                        name="downsample_0")(x))
        return nn.relu(y + x)


class ModifiedResNet(nn.Module):
    """model.py:96-154: 3-conv stem + avgpool, anti-aliased bottlenecks,
    attention-pool head."""
    layers: Tuple[int, int, int, int]
    width: int
    output_dim: int
    heads: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        w = self.width
        for i, (ch, stride) in enumerate([(w // 2, 2), (w // 2, 1), (w, 1)]):
            x = nn.relu(BNInf(name=f"bn{i + 1}")(
                nn.Conv(ch, (3, 3), strides=stride, padding=1, use_bias=False,
                        name=f"conv{i + 1}")(x)))
        x = nn.avg_pool(x, (2, 2), (2, 2))
        for stage, (planes, blocks) in enumerate(
                zip((w, w * 2, w * 4, w * 8), self.layers)):
            for blk in range(blocks):
                stride = 2 if (stage > 0 and blk == 0) else 1
                x = Bottleneck(planes, stride,
                               name=f"layer{stage + 1}_{blk}")(x)

        # AttentionPool2d (model.py:58-93): tokens = [mean, HW...], query =
        # the mean token only
        b, hh, ww, c = x.shape
        tokens = x.reshape(b, hh * ww, c)
        tokens = jnp.concatenate(
            [jnp.mean(tokens, axis=1, keepdims=True), tokens], axis=1)
        pos = self.param("attnpool_positional_embedding",
                         nn.initializers.normal(), (hh * ww + 1, c))
        tokens = tokens + pos.astype(tokens.dtype)
        pooled = MHA(c, self.heads, out_dim=self.output_dim,
                     out_name="c_proj", name="attnpool")(
            tokens[:, :1], tokens, tokens)
        return pooled[:, 0]


class CLIP(nn.Module):
    """Image/text encoders (model.py:243-371). Images must already be
    resized/cropped/normalized; text is (B, context_length) int32 from
    utils/tokenizer.py."""
    cfg: CLIPConfig
    #: 'dense' | 'blockwise' — vision-tower attention implementation.
    #: Blockwise (streaming-softmax, parallel/sequence.py) is worthwhile for
    #: the big-token towers (ViT-L/14@336: 577 patch tokens) where the dense
    #: (B*H, 577, 577) score tensor dominates activation memory; values are
    #: identical (f32 softmax either way, parity-tested in tests/test_clip).
    vision_attn: str = "dense"

    def setup(self):
        c = self.cfg
        if c.is_vit:
            self.visual = VisionTransformer(
                width=c.vision_width, layers=c.vision_layers,
                patch_size=c.vision_patch_size, output_dim=c.embed_dim,
                attn_impl=self.vision_attn, name="visual")
        else:
            self.visual = ModifiedResNet(
                layers=tuple(c.vision_layers), width=c.vision_width,
                output_dim=c.embed_dim, heads=c.vision_width * 32 // 64,
                name="visual")
        self.transformer = Transformer(c.transformer_width,
                                       c.transformer_layers,
                                       c.transformer_heads,
                                       name="transformer")
        self.token_embedding = self.param(
            "token_embedding", nn.initializers.normal(0.02),
            (c.vocab_size, c.transformer_width))
        self.positional_embedding = self.param(
            "positional_embedding", nn.initializers.normal(0.01),
            (c.context_length, c.transformer_width))
        self.ln_final = LNf32(name="ln_final")
        self.text_projection = self.param(
            "text_projection", nn.initializers.normal(),
            (c.transformer_width, c.embed_dim))
        self.logit_scale = self.param(
            "logit_scale", nn.initializers.constant(np.log(1 / 0.07)), ())

    def encode_image(self, image: jnp.ndarray) -> jnp.ndarray:
        return self.visual(image)

    def encode_text(self, text: jnp.ndarray) -> jnp.ndarray:
        x = jnp.take(self.token_embedding, text, axis=0)
        x = x + self.positional_embedding
        # additive causal mask: -inf strictly above the diagonal
        # (model.py:328-334); fp32 softmax keeps -inf rows exact
        n = self.cfg.context_length
        mask = jnp.triu(jnp.full((n, n), -jnp.inf, dtype=jnp.float32), k=1)
        x = self.transformer(x, mask)
        x = self.ln_final(x)
        # features from the eot embedding = the highest token id per row
        # (model.py:354-356)
        eot = jnp.argmax(text, axis=-1)
        x = jnp.take_along_axis(x, eot[:, None, None], axis=1)[:, 0]
        return x @ self.text_projection

    def __call__(self, image: jnp.ndarray,
                 text: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        img = self.encode_image(image)
        txt = self.encode_text(text)
        img = img / jnp.linalg.norm(img, axis=1, keepdims=True)
        txt = txt / jnp.linalg.norm(txt, axis=1, keepdims=True)
        logits_per_image = jnp.exp(self.logit_scale) * img @ txt.T
        return logits_per_image, logits_per_image.T


# ---- config inference + weight transplant --------------------------------

def config_from_state_dict(sd: Mapping[str, Any]) -> CLIPConfig:
    """Infer the architecture from checkpoint shapes (build_model,
    model.py:399-436)."""
    if "visual.proj" in sd:
        vision_width = sd["visual.conv1.weight"].shape[0]
        vision_layers = len([k for k in sd
                             if k.startswith("visual.")
                             and k.endswith(".attn.in_proj_weight")])
        vision_patch_size = sd["visual.conv1.weight"].shape[-1]
        grid = round((sd["visual.positional_embedding"].shape[0] - 1) ** 0.5)
        image_resolution = vision_patch_size * grid
    else:
        vision_layers = tuple(
            len({k.split(".")[2] for k in sd
                 if k.startswith(f"visual.layer{b}")}) for b in (1, 2, 3, 4))
        vision_width = sd["visual.layer1.0.conv1.weight"].shape[0]
        out_width = round(
            (sd["visual.attnpool.positional_embedding"].shape[0] - 1) ** 0.5)
        vision_patch_size = None
        image_resolution = out_width * 32
    transformer_width = sd["ln_final.weight"].shape[0]
    return CLIPConfig(
        embed_dim=sd["text_projection"].shape[1],
        image_resolution=image_resolution,
        vision_layers=vision_layers,
        vision_width=vision_width,
        vision_patch_size=vision_patch_size,
        context_length=sd["positional_embedding"].shape[0],
        vocab_size=sd["token_embedding.weight"].shape[0],
        transformer_width=transformer_width,
        transformer_heads=transformer_width // 64,
        transformer_layers=len({k.split(".")[2] for k in sd
                                if k.startswith("transformer.resblocks")}))


def _f32(t) -> np.ndarray:
    """Checkpoint tensors may be fp16 (convert_weights, model.py:375-396)."""
    return ti.to_np(t).astype(np.float32)


def params_from_torch(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """OpenAI CLIP state_dict (ViT or ModifiedResNet) -> Flax tree."""
    sd = ti.strip_module_prefix(state_dict)
    params: Dict[str, Any] = {}
    for key, t in sd.items():
        if key in ("input_resolution", "context_length", "vocab_size"):
            continue  # non-tensor metadata build_model deletes (model.py:430)
        if key.endswith("num_batches_tracked"):
            continue
        parts = key.split(".")
        leaf = parts[-1]
        mods = parts[:-1]

        # raw (module-less) parameters
        if leaf in ("class_embedding", "positional_embedding", "proj",
                    "text_projection", "logit_scale"):
            if mods and mods[-1] == "attnpool":
                # attnpool pos-emb lives beside the pool in our tree
                path = "/".join(mods[:-1] + ["attnpool_positional_embedding"])
            else:
                path = "/".join(mods + [leaf])
            ti.set_in(params, path, _f32(t))
            continue
        if len(mods) >= 1 and mods[-1] == "token_embedding":
            ti.set_in(params, "token_embedding", _f32(t))
            continue

        # packed qkv -> split into q/k/v trees
        if leaf in ("in_proj_weight", "in_proj_bias"):
            qkv = np.split(_f32(t), 3, axis=0)
            flat = _flatten_mods(mods)
            for name, part in zip(("q_proj", "k_proj", "v_proj"), qkv):
                if leaf == "in_proj_weight":
                    ti.set_in(params, "/".join(flat + [name, "kernel"]),
                              np.transpose(part))
                else:
                    ti.set_in(params, "/".join(flat + [name, "bias"]), part)
            continue

        flat = _flatten_mods(mods)
        base = ".".join(mods)
        if f"{base}.running_mean" in sd:  # BatchNorm
            bnl = {"weight": "scale", "bias": "bias", "running_mean": "mean",
                   "running_var": "var"}[leaf]
            ti.set_in(params, "/".join(flat + [bnl]), _f32(t))
        elif leaf == "weight" and flat[-1].startswith("ln"):
            ti.set_in(params, "/".join(flat + ["ln", "scale"]), _f32(t))
        elif leaf == "bias" and flat[-1].startswith("ln"):
            ti.set_in(params, "/".join(flat + ["ln", "bias"]), _f32(t))
        elif leaf == "weight" and t.dim() == 4:
            ti.set_in(params, "/".join(flat + ["kernel"]),
                      np.transpose(_f32(t), (2, 3, 1, 0)))
        elif leaf == "weight":
            ti.set_in(params, "/".join(flat + ["kernel"]),
                      np.transpose(_f32(t)))
        elif leaf == "bias":
            ti.set_in(params, "/".join(flat + ["bias"]), _f32(t))
        else:
            raise ValueError(f"unexpected CLIP key {key}")
    return params


def _flatten_mods(mods: Sequence[str]) -> List[str]:
    """torch dotted path -> our module names: merge Sequential indices
    (resblocks.0 -> resblocks_0, layer1.0 -> layer1_0, downsample.0 ->
    downsample_0) and the mlp Sequential's children (mlp.c_fc -> mlp_c_fc)."""
    flat: List[str] = []
    skip = False
    for i, m in enumerate(mods):
        if skip:
            skip = False
            continue
        if m == "mlp" and i + 1 < len(mods):
            flat.append(f"mlp_{mods[i + 1]}")
            skip = True
        elif (m.isdigit() or m == "-1") and flat:
            flat[-1] = f"{flat[-1]}_{m}"
        else:
            flat.append(m)
    return flat


def init_params(model_name: str = "ViT-B/32") -> Dict[str, Any]:
    cfg = CONFIGS[model_name]
    model = CLIP(cfg)
    r = cfg.image_resolution
    v = model.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, r, r, 3), jnp.float32),
                   jnp.zeros((1, cfg.context_length), jnp.int32))
    return v["params"]
