"""R(2+1)D video networks (18/34-layer) as Flax modules, NDHWC.

The reference obtains these from torchvision (`r2plus1d_18`) and the IG-65M
torch.hub repo (34-layer flavors) at runtime — reference
models/r21d/extract_r21d.py:27-40,105-113 — so the architecture here is the
torchvision ``VideoResNet`` with the R(2+1)D factorized conv: each 3D conv is
a spatial (1,3,3) conv into ``midplanes`` channels followed by a temporal
(3,1,1) conv, with ``midplanes = (in*out*27) // (in*9 + 3*out)`` keeping the
parameter count of the full 3D conv.

Layout is (N, T, H, W, C): XLA tiles the last (channel) dim onto the MXU lane
axis and the factorized convs become large batched matmuls.

Weight transplant: :func:`params_from_torch` maps torchvision/IG-65M
state_dicts (``stem.0``, ``layerX.Y.conv1.0.0`` nested-Sequential keys) onto
this tree.
"""
from __future__ import annotations

from typing import Any, Dict, Mapping, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

from .common import BNInf
from ..weights import torch_import as ti

VARIANTS = {
    # model_name (reference extract_r21d.py:27-40) -> (stage blocks, default stack/step)
    "r2plus1d_18_16_kinetics": ((2, 2, 2, 2), 16),
    "r2plus1d_34_32_ig65m_ft_kinetics": ((3, 4, 6, 3), 32),
    "r2plus1d_34_8_ig65m_ft_kinetics": ((3, 4, 6, 3), 8),
}

FEATURE_DIM = 512
# K400 normalization used by the reference transform stack (extract_r21d.py:50-55)
R21D_MEAN = (0.43216, 0.394666, 0.37645)
R21D_STD = (0.22803, 0.22145, 0.216989)


def midplanes(in_planes: int, out_planes: int) -> int:
    return (in_planes * out_planes * 3 * 3 * 3) // (
        in_planes * 3 * 3 + 3 * out_planes)


def _conv3d(features: int, kernel: Tuple[int, int, int],
            stride: Tuple[int, int, int], pad: Tuple[int, int, int],
            name: str) -> nn.Conv:
    return nn.Conv(features, kernel, strides=stride,
                   padding=[(p, p) for p in pad], use_bias=False, name=name)


class Conv2Plus1D(nn.Module):
    """Factorized 3D conv: spatial (1,3,3) -> BN -> ReLU -> temporal (3,1,1)."""
    out_planes: int
    mid_planes: int
    stride: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        s = self.stride
        x = _conv3d(self.mid_planes, (1, 3, 3), (1, s, s), (0, 1, 1), "conv_s")(x)
        x = BNInf(name="bn_mid")(x)
        x = nn.relu(x)
        x = _conv3d(self.out_planes, (3, 1, 1), (s, 1, 1), (1, 0, 0), "conv_t")(x)
        return x


class BasicBlock(nn.Module):
    planes: int
    stride: int = 1
    has_downsample: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        identity = x
        mid1 = midplanes(x.shape[-1], self.planes)
        out = Conv2Plus1D(self.planes, mid1, self.stride, name="conv1")(x)
        out = BNInf(name="bn1")(out)
        out = nn.relu(out)
        mid2 = midplanes(self.planes, self.planes)
        out = Conv2Plus1D(self.planes, mid2, 1, name="conv2")(out)
        out = BNInf(name="bn2")(out)
        if self.has_downsample:
            s = self.stride
            identity = _conv3d(self.planes, (1, 1, 1), (s, s, s), (0, 0, 0),
                               "downsample_conv")(x)
            identity = BNInf(name="downsample_bn")(identity)
        return nn.relu(out + identity)


class R2Plus1D(nn.Module):
    """Backbone: (N, T, H, W, 3) normalized float -> (N, 512) pooled features."""
    variant: str = "r2plus1d_18_16_kinetics"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        stages, _ = VARIANTS[self.variant]
        # R(2+1)D stem: spatial 7x7 then temporal 3 (torchvision R2Plus1dStem)
        x = _conv3d(45, (1, 7, 7), (1, 2, 2), (0, 3, 3), "stem_conv_s")(x)
        x = BNInf(name="stem_bn_s")(x)
        x = nn.relu(x)
        x = _conv3d(64, (3, 1, 1), (1, 1, 1), (1, 0, 0), "stem_conv_t")(x)
        x = BNInf(name="stem_bn_t")(x)
        x = nn.relu(x)

        in_planes = 64
        for stage_idx, num_blocks in enumerate(stages):
            planes = 64 * (2 ** stage_idx)
            stride = 1 if stage_idx == 0 else 2
            for block_idx in range(num_blocks):
                s = stride if block_idx == 0 else 1
                needs_ds = (s != 1) or (in_planes != planes)
                x = BasicBlock(planes, s, needs_ds,
                               name=f"layer{stage_idx + 1}_{block_idx}")(x)
                in_planes = planes
        # AdaptiveAvgPool3d(1)
        return jnp.mean(x, axis=(1, 2, 3))


class Classifier(nn.Module):
    """The Kinetics-400 fc head (kept aside for show_pred, reference
    extract_r21d.py:116-118)."""
    num_classes: int = 400

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return nn.Dense(self.num_classes, name="fc")(x)


def init_params(variant: str = "r2plus1d_18_16_kinetics") -> Dict[str, Any]:
    """Random {'backbone', 'head'} trees — the msgpack template shape."""
    import jax
    backbone = R2Plus1D(variant).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4, 112, 112, 3)))["params"]
    head = Classifier().init(
        jax.random.PRNGKey(1), jnp.zeros((1, FEATURE_DIM)))["params"]
    return {"backbone": backbone, "head": head}


_BN_LEAF = {"weight": "scale", "bias": "bias",
            "running_mean": "mean", "running_var": "var"}

# nested-Sequential index -> our submodule name, inside one BasicBlock
_BLOCK_KEYMAP = {
    ("conv1", "0", "0"): ("conv1", "conv_s", "kernel"),
    ("conv1", "0", "1"): ("conv1", "bn_mid", None),
    ("conv1", "0", "3"): ("conv1", "conv_t", "kernel"),
    ("conv1", "1"): ("bn1", None),
    ("conv2", "0", "0"): ("conv2", "conv_s", "kernel"),
    ("conv2", "0", "1"): ("conv2", "bn_mid", None),
    ("conv2", "0", "3"): ("conv2", "conv_t", "kernel"),
    ("conv2", "1"): ("bn2", None),
    ("downsample", "0"): ("downsample_conv", "kernel"),
    ("downsample", "1"): ("downsample_bn", None),
}

_STEM_KEYMAP = {
    "0": ("stem_conv_s", "kernel"),
    "1": ("stem_bn_s", None),
    "3": ("stem_conv_t", "kernel"),
    "4": ("stem_bn_t", None),
}


def params_from_torch(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """torchvision/IG-65M VideoResNet state_dict -> {'backbone','head'} trees."""
    backbone: Dict[str, Any] = {}
    head: Dict[str, Any] = {}
    for key, tensor in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        parts = key.split(".")
        if parts[0] == "fc":
            leaf = "kernel" if parts[1] == "weight" else "bias"
            val = ti.linear_kernel(tensor) if leaf == "kernel" else ti.to_np(tensor)
            ti.set_in(head, f"fc/{leaf}", val)
            continue
        if parts[0] == "stem":
            target, kind = _STEM_KEYMAP[parts[1]]
            if kind == "kernel":
                ti.set_in(backbone, f"{target}/kernel", ti.conv3d_kernel(tensor))
            else:
                ti.set_in(backbone, f"{target}/{_BN_LEAF[parts[2]]}",
                          ti.to_np(tensor))
            continue
        # layerX.Y.<nested sequential path>.<leaf>
        block = f"{parts[0]}_{parts[1]}"
        leaf = parts[-1]
        sub = tuple(parts[2:-1])
        mapped = _BLOCK_KEYMAP.get(sub)
        if mapped is None:
            raise KeyError(f"Unrecognized R(2+1)D checkpoint key: {key}")
        if mapped[-1] == "kernel":
            path = "/".join([block, *mapped[:-1], "kernel"])
            ti.set_in(backbone, path, ti.conv3d_kernel(tensor))
        else:
            path = "/".join([block, *mapped[:-1], _BN_LEAF[leaf]])
            ti.set_in(backbone, path, ti.to_np(tensor))
    return {"backbone": backbone, "head": head}
