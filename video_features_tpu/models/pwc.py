"""PWC-Net optical flow as a JAX/Flax program, NHWC, static shapes.

Parity target: reference models/pwc/pwc_src/pwc_net.py (the sniklaus
pytorch-pwc port, sintel checkpoint) as it behaves in its pinned
environment (PyTorch 1.2 + CUDA 10 + CuPy — the reference needs a separate
conda env just for this model, SURVEY §1 "dual-environment split"):

  - 6-level conv ``Extractor`` pyramid, LeakyReLU(0.1) everywhere
    (pwc_net.py:53-119),
  - coarse-to-fine ``Decoder`` per level (pwc_net.py:125-211): upsample
    flow/feat with ConvTranspose(4, stride 2, pad 1); warp the second
    pyramid level by ``flow * dblBackward`` (``Backward`` grid-sample warp
    with the all-ones validity-mask trick, pwc_net.py:25-50); 81-channel
    cost volume; DenseNet-style concat stack,
  - dilated-conv ``Refiner`` added to the finest (1/4) flow
    (pwc_net.py:213-235),
  - input RGB->BGR, /255 (pwc_net.py:255-257), bilinear resize to /64
    multiples (align_corners=False, pwc_net.py:267-275), output upsampled
    back, x20, per-axis rescaled (pwc_net.py:290-296).

The cost volume replaces the reference's runtime-JIT'd CUDA kernel
(correlation.py:47-115: channel c = (dy+4)*9 + (dx+4), mean over channels,
4 px zero padding) with 81 static shifted-window products that XLA fuses —
no native extension, which also kills the reference's dual-env constraint.
The warp replicates torch-1.2 ``grid_sample`` (align_corners=True, zeros
padding) — the behavior of the env the checkpoint was published for.
"""
from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from ..weights import torch_import as ti

CORR_RADIUS = 4

# per-stage channel widths of the feature pyramid (pwc_net.py:57-113)
_PYRAMID = (("moduleOne", 16), ("moduleTwo", 32), ("moduleThr", 64),
            ("moduleFou", 96), ("moduleFiv", 128), ("moduleSix", 196))
# decoder input width at each level is 81 cost channels, plus features +
# 2 flow + 2 upfeat below level 6 (pwc_net.py:129-132) — inferred from the
# input shapes by the compact modules, listed here only for orientation
# magnification applied to the upsampled flow before warping
# (pwc_net.py:137: dblBackward indexed at intLevel+1)
_DBL_BACKWARD = {2: 5.0, 3: 2.5, 4: 1.25, 5: 0.625}


def leaky(x: jnp.ndarray) -> jnp.ndarray:
    return nn.leaky_relu(x, negative_slope=0.1)


def correlation_volume(f1: jnp.ndarray, f2: jnp.ndarray,
                       radius: int = CORR_RADIUS) -> jnp.ndarray:
    """81-channel windowed cost volume (correlation.py:47-115).

    (B, H, W, C) x2 -> (B, H, W, (2r+1)^2); channel (dy+r)*(2r+1)+(dx+r) is
    the channel-mean of ``f1 * shift(f2, dy, dx)`` with zero padding.
    XLA shifted-window formulation with f32 accumulation — the single
    implementation since round 5 (a Pallas twin measured tied and was
    deleted; kernels/cost_volume.py docstring records the numbers).
    """
    from ..kernels.cost_volume import cost_volume
    return cost_volume(f1, f2, radius)


def bilinear_warp(feat: jnp.ndarray, flow: jnp.ndarray) -> jnp.ndarray:
    """``Backward`` (pwc_net.py:25-50): sample ``feat`` at ``grid + flow``
    with torch-1.2 grid_sample semantics (align_corners=True, zeros
    padding), then zero out samples whose all-ones-channel came back < 1
    after the same interpolation (the partial-visibility mask).

    Coordinate math is ALWAYS f32: bf16's 8 mantissa bits resolve only
    ~2 px at x=448, which would quantize the sampling grid itself. Only
    the feature gather/blend runs in the feature dtype."""
    b, h, w, c = feat.shape
    flow32 = flow.astype(jnp.float32)
    gx, gy = jnp.meshgrid(jnp.arange(w, dtype=jnp.float32),
                          jnp.arange(h, dtype=jnp.float32))
    x = gx[None] + flow32[..., 0]
    y = gy[None] + flow32[..., 1]
    x0, y0 = jnp.floor(x), jnp.floor(y)

    sampled = jnp.zeros(feat.shape, jnp.float32)
    ones = jnp.zeros((b, h, w), jnp.float32)
    for xi, wx in ((x0, 1.0 - (x - x0)), (x0 + 1, x - x0)):
        for yi, wy in ((y0, 1.0 - (y - y0)), (y0 + 1, y - y0)):
            valid = ((xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1))
            xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
            yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
            corner = feat[jnp.arange(b)[:, None, None], yc, xc]
            weight = jnp.where(valid, wx * wy, 0.0)
            sampled = sampled + weight[..., None] * corner.astype(jnp.float32)
            ones = ones + weight
    # mask rule (pwc_net.py:47-49): >0.999 -> 1, anything below -> 0
    mask = (ones > 0.999).astype(jnp.float32)
    return (sampled * mask[..., None]).astype(feat.dtype)


def conv_transpose_4s2p1(x: jnp.ndarray, kernel: jnp.ndarray,
                         bias: jnp.ndarray) -> jnp.ndarray:
    """torch ConvTranspose2d(k=4, stride=2, pad=1): input-dilated conv with
    the spatially-flipped kernel and (k-1-p)=2 padding; output = 2x input.

    ``kernel`` is pre-converted to HWIO by the weight importer."""
    return jax.lax.conv_general_dilated(
        x, kernel, window_strides=(1, 1), padding=((2, 2), (2, 2)),
        lhs_dilation=(2, 2),
        dimension_numbers=("NHWC", "HWIO", "NHWC")) + bias


class Extractor(nn.Module):
    """pwc_net.py:53-119: 6 stages of [stride-2 conv, conv, conv]."""
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> List[jnp.ndarray]:
        feats = []
        for stage, ch in _PYRAMID:
            for idx in (0, 2, 4):
                x = leaky(nn.Conv(ch, (3, 3), strides=2 if idx == 0 else 1,
                                  padding=1, dtype=self.dtype,
                                  name=f"{stage}_{idx}")(x))
            feats.append(x)
        return feats


class Decoder(nn.Module):
    """pwc_net.py:125-211: cost volume + DenseNet concat stack. Returns
    (flow, feat). Flow tensors stay f32 in bf16 mode — they feed the warp
    grid, where bf16 resolution is the coordinate itself."""
    level: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, first: jnp.ndarray, second: jnp.ndarray,
                 prev: Optional[Tuple[jnp.ndarray, jnp.ndarray]]):
        if prev is None:
            feat = leaky(correlation_volume(first, second))
        else:
            prev_flow, prev_feat = prev
            up_k = self.param("moduleUpflow_kernel", nn.initializers.normal(),
                              (4, 4, 2, 2))
            up_b = self.param("moduleUpflow_bias", nn.initializers.zeros, (2,))
            flow = conv_transpose_4s2p1(prev_flow.astype(jnp.float32),
                                        up_k.astype(jnp.float32),
                                        up_b.astype(jnp.float32))
            uf_in = prev_feat.shape[-1]
            uf_k = self.param("moduleUpfeat_kernel", nn.initializers.normal(),
                              (4, 4, uf_in, 2))
            uf_b = self.param("moduleUpfeat_bias", nn.initializers.zeros, (2,))
            upfeat = conv_transpose_4s2p1(
                prev_feat.astype(self.dtype), uf_k.astype(self.dtype),
                uf_b.astype(self.dtype))
            warped = bilinear_warp(second, flow * _DBL_BACKWARD[self.level])
            volume = leaky(correlation_volume(first, warped))
            feat = jnp.concatenate(
                [volume, first, flow.astype(self.dtype),
                 upfeat.astype(self.dtype)], axis=-1)

        for name, ch in (("moduleOne", 128), ("moduleTwo", 128),
                         ("moduleThr", 96), ("moduleFou", 64),
                         ("moduleFiv", 32)):
            y = leaky(nn.Conv(ch, (3, 3), padding=1, dtype=self.dtype,
                              name=f"{name}_0")(feat))
            feat = jnp.concatenate([y, feat], axis=-1)  # new features FIRST
        # the flow head accumulates in f32: its output is coordinates
        flow = nn.Conv(2, (3, 3), padding=1, dtype=jnp.float32,
                       name="moduleSix_0")(feat.astype(jnp.float32))
        return flow, feat


class Refiner(nn.Module):
    """pwc_net.py:213-235: dilated context network."""
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        specs = ((128, 1, 0), (128, 2, 2), (128, 4, 4), (96, 8, 6),
                 (64, 16, 8), (32, 1, 10), (2, 1, 12))
        for ch, dil, idx in specs:
            # the last conv emits flow residual (coordinates): f32 head
            dt = self.dtype if idx < 12 else jnp.float32
            y = nn.Conv(ch, (3, 3), padding=dil, kernel_dilation=dil,
                        dtype=dt, name=f"moduleMain_{idx}")(
                x if idx < 12 else x.astype(jnp.float32))
            x = leaky(y) if idx < 12 else y
        return x


def _resize_bilinear(x: jnp.ndarray, h: int, w: int) -> jnp.ndarray:
    """torch F.interpolate(mode='bilinear', align_corners=False) equivalent
    (half-pixel centers, no antialias)."""
    return jax.image.resize(x, (x.shape[0], h, w, x.shape[3]), "bilinear",
                            antialias=False)


class PWCNet(nn.Module):
    """(B, H, W, 3) RGB [0,255] pairs -> (B, H, W, 2) flow in pixels
    (pwc_net.py:238-296).

    ``dtype=jnp.bfloat16`` runs the conv stacks and cost volumes on the
    MXU-native dtype; flow tensors, warp-grid math, the flow heads and the
    cost-volume accumulation stay f32 (they carry coordinates, where bf16
    resolution IS the error). Output is always f32."""
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, image1: jnp.ndarray,
                 image2: jnp.ndarray) -> jnp.ndarray:
        b, h, w, _ = image1.shape
        # RGB -> BGR, /255 (pwc_net.py:255-257)
        image1 = image1[..., ::-1] / 255.0
        image2 = image2[..., ::-1] / 255.0
        hp = -(-h // 64) * 64
        wp = -(-w // 64) * 64
        if (hp, wp) != (h, w):
            image1 = _resize_bilinear(image1, hp, wp)
            image2 = _resize_bilinear(image2, hp, wp)

        extractor = Extractor(dtype=self.dtype, name="moduleExtractor")
        firsts = extractor(image1.astype(self.dtype))
        seconds = extractor(image2.astype(self.dtype))

        prev = None
        # coarse-to-fine: level 6 (1/64) down to 2 (1/4) (pwc_net.py:277-287)
        for level, name in ((6, "moduleSix"), (5, "moduleFiv"),
                            (4, "moduleFou"), (3, "moduleThr"),
                            (2, "moduleTwo")):
            idx = level - 1  # pyramid list is fine-to-coarse
            flow, feat = Decoder(level, dtype=self.dtype, name=name)(
                firsts[idx], seconds[idx], prev)
            prev = (flow, feat)

        flow = prev[0] + Refiner(dtype=self.dtype, name="moduleRefiner")(
            prev[1])
        flow = 20.0 * _resize_bilinear(flow.astype(jnp.float32), h, w)
        scale = jnp.array([w / wp, h / hp], dtype=flow.dtype)
        return flow * scale


# ---- weight transplant ---------------------------------------------------

def params_from_torch(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """pwc_net_sintel.pt state_dict -> Flax tree.

    Keys: ``module{Extractor,Two..Six,Refiner}.module*.N.{weight,bias}``.
    ConvTranspose weights (IOHW) become input-dilated-conv kernels: flip
    spatial dims, transpose to HWIO.
    """
    sd = ti.strip_module_prefix(state_dict)
    params: Dict[str, Any] = {}
    for key, t in sd.items():
        parts = key.split(".")
        leaf = parts.pop()
        flat: List[str] = []
        for m in parts:
            if m.isdigit() and flat:
                flat[-1] = f"{flat[-1]}_{m}"
            else:
                flat.append(m)
        if flat[-1] in ("moduleUpflow", "moduleUpfeat"):
            # stored as raw params, not submodules (Decoder.__call__)
            arr = ti.to_np(t)
            if leaf == "weight":
                arr = np.transpose(arr[:, :, ::-1, ::-1], (2, 3, 0, 1))
            ti.set_in(params, "/".join(flat[:-1] + [f"{flat[-1]}_{'kernel' if leaf == 'weight' else 'bias'}"]), arr)
        elif leaf == "weight":
            ti.set_in(params, "/".join(flat + ["kernel"]),
                      ti.conv2d_kernel(t))
        else:
            ti.set_in(params, "/".join(flat + ["bias"]), ti.to_np(t))
    return params


def init_params() -> Dict[str, Any]:
    model = PWCNet()
    v = model.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, 64, 64, 3)), jnp.zeros((1, 64, 64, 3)))
    return v["params"]
