"""I3D (Inception-v1 inflated to 3D, Kinetics-400) as a Flax module, NDHWC.

Parity target: the reference's I3D (reference models/i3d/i3d_src/i3d_net.py,
the hassony2/kinetics_i3d_pytorch port of DeepMind's TF weights):

  - ``Unit3Dpy`` = Conv3d + BatchNorm3d + ReLU with **TensorFlow SAME
    padding computed from kernel/stride only** (`get_padding_shape`,
    i3d_net.py:8-25): per dim ``pad_along = max(k - s, 0)``, split
    ``(pad_along // 2, pad_along - pad_along // 2)``. This is
    input-size-independent — it is NOT true TF SAME (which depends on
    ``size % stride``); we replicate the reference's formula exactly.
  - ``MaxPool3dTFPadding`` (i3d_net.py:108-120) zero-pads explicitly with
    that same shape then max-pools with ``ceil_mode=True``. Zero padding is
    observable: inputs are in [-1, 1], so padded zeros can win the max at
    the borders. Ceil mode lets the last window overhang the right edge
    (overhang cells never win — replicated here with -inf edge padding).
  - 9 ``Mixed`` inception blocks (i3d_net.py:123-157, wiring :205-224),
    channels ``[b0, b1red, b1out, b2red, b2out, b3proj]``.
  - Head: AvgPool3d((2,7,7), stride 1) (i3d_net.py:226); ``features=True``
    squeezes spatial dims and means over time -> (B, 1024)
    (i3d_net.py:259-264); otherwise a 1x1x1 conv classifier -> time-mean
    logits (+softmax) (i3d_net.py:266-274).

Weight transplant: :func:`params_from_torch` maps the
``i3d_rgb.pt`` / ``i3d_flow.pt`` state_dicts (keys like
``mixed_3b.branch_1.0.conv3d.weight``) onto this tree.
"""
from __future__ import annotations

from typing import Any, Dict, Mapping, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from .common import BNInf
from ..weights import torch_import as ti

FEATURE_DIM = 1024


def tf_same_pads(kernel: Sequence[int],
                 stride: Sequence[int]) -> Tuple[Tuple[int, int], ...]:
    """Reference `get_padding_shape` (i3d_net.py:8-25) as (lo, hi) pairs.

    Returned in (T, H, W) order (the reference builds H,W,T then rotates the
    T pair to the back only because torch's ConstantPad3d wants it last —
    the per-dimension amounts are identical).
    """
    out = []
    for k, s in zip(kernel, stride):
        pad_along = max(k - s, 0)
        lo = pad_along // 2
        out.append((lo, pad_along - lo))
    return tuple(out)


def max_pool_tf_ceil(x: jnp.ndarray, window: Sequence[int],
                     strides: Sequence[int]) -> jnp.ndarray:
    """MaxPool3dTFPadding semantics (i3d_net.py:108-120) on NDHWC.

    Explicit zero padding (padded zeros participate in the max, exactly like
    torch's ConstantPad3d + unpadded MaxPool3d), then ceil-mode pooling: any
    extra right-edge cells needed to reach the ceil output length are -inf so
    they never win.
    """
    pads = tf_same_pads(window, strides)
    x = jnp.pad(x, ((0, 0), *pads, (0, 0)))
    extra = []
    for i, (k, s) in enumerate(zip(window, strides)):
        size = x.shape[1 + i]
        n_out = -(-(size - k) // s) + 1  # ceil((size-k)/s) + 1
        extra.append((0, max((n_out - 1) * s + k - size, 0)))
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max,
        (1, *window, 1), (1, *strides, 1), ((0, 0), *extra, (0, 0)))


class Unit3D(nn.Module):
    """Conv3d + (BN) + (ReLU) with the reference's SAME padding rule."""
    features: int
    kernel: Tuple[int, int, int] = (1, 1, 1)
    stride: Tuple[int, int, int] = (1, 1, 1)
    use_bias: bool = False
    use_bn: bool = True
    relu: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        pads = tf_same_pads(self.kernel, self.stride)
        x = nn.Conv(self.features, self.kernel, strides=self.stride,
                    padding=pads, use_bias=self.use_bias, name="conv")(x)
        if self.use_bn:
            x = BNInf(name="bn")(x)  # torch BatchNorm3d default eps=1e-5
        return nn.relu(x) if self.relu else x


class Mixed(nn.Module):
    """Inception block (i3d_net.py:123-157)."""
    channels: Tuple[int, int, int, int, int, int]

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b0c, b1r, b1c, b2r, b2c, b3c = self.channels
        b0 = Unit3D(b0c, name="branch_0")(x)
        b1 = Unit3D(b1r, name="branch_1_0")(x)
        b1 = Unit3D(b1c, (3, 3, 3), name="branch_1_1")(b1)
        b2 = Unit3D(b2r, name="branch_2_0")(x)
        b2 = Unit3D(b2c, (3, 3, 3), name="branch_2_1")(b2)
        b3 = max_pool_tf_ceil(x, (3, 3, 3), (1, 1, 1))
        b3 = Unit3D(b3c, name="branch_3_1")(b3)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


MIXED_CHANNELS = {
    "mixed_3b": (64, 96, 128, 16, 32, 32),
    "mixed_3c": (128, 128, 192, 32, 96, 64),
    "mixed_4b": (192, 96, 208, 16, 48, 64),
    "mixed_4c": (160, 112, 224, 24, 64, 64),
    "mixed_4d": (128, 128, 256, 24, 64, 64),
    "mixed_4e": (112, 144, 288, 32, 64, 64),
    "mixed_4f": (256, 160, 320, 32, 128, 128),
    "mixed_5b": (256, 160, 320, 32, 128, 128),
    "mixed_5c": (384, 192, 384, 48, 128, 128),
}


class I3D(nn.Module):
    """(N, T, 224, 224, C) float in [-1, 1] -> (N, 1024) features or
    (N, num_classes) logits. C=3 for the rgb stream, C=2 for flow."""
    num_classes: int = 400

    @nn.compact
    def __call__(self, x: jnp.ndarray, features: bool = True) -> jnp.ndarray:
        x = Unit3D(64, (7, 7, 7), (2, 2, 2), name="conv3d_1a_7x7")(x)
        x = max_pool_tf_ceil(x, (1, 3, 3), (1, 2, 2))
        x = Unit3D(64, name="conv3d_2b_1x1")(x)
        x = Unit3D(192, (3, 3, 3), name="conv3d_2c_3x3")(x)
        x = max_pool_tf_ceil(x, (1, 3, 3), (1, 2, 2))
        x = Mixed(MIXED_CHANNELS["mixed_3b"], name="mixed_3b")(x)
        x = Mixed(MIXED_CHANNELS["mixed_3c"], name="mixed_3c")(x)
        x = max_pool_tf_ceil(x, (3, 3, 3), (2, 2, 2))
        for name in ("mixed_4b", "mixed_4c", "mixed_4d", "mixed_4e",
                     "mixed_4f"):
            x = Mixed(MIXED_CHANNELS[name], name=name)(x)
        x = max_pool_tf_ceil(x, (2, 2, 2), (2, 2, 2))
        x = Mixed(MIXED_CHANNELS["mixed_5b"], name="mixed_5b")(x)
        x = Mixed(MIXED_CHANNELS["mixed_5c"], name="mixed_5c")(x)

        # AvgPool3d((2, 7, 7), stride 1) (i3d_net.py:226): a sliding window
        # that must fit — same precondition as torch (raises when T' < 2 or
        # spatial < 7, i.e. crop < 224)
        t, h, w = x.shape[1:4]
        if t < 2 or h < 7 or w < 7:
            raise ValueError(
                f"I3D head needs a (2,7,7) pool window, got {(t, h, w)}; "
                "use stack_size >= 10 and 224x224 crops")
        x = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, (1, 2, 7, 7, 1), (1, 1, 1, 1, 1),
            [(0, 0)] * 5) / (2 * 7 * 7)

        if features:
            # squeeze spatial, mean time (i3d_net.py:259-264)
            return jnp.mean(x[:, :, 0, 0, :], axis=1)
        x = Unit3D(self.num_classes, use_bias=True, use_bn=False,
                   relu=False, name="conv3d_0c_1x1")(x)
        logits = jnp.mean(x[:, :, 0, 0, :], axis=1)
        return logits  # reference also returns softmax; callers softmax


_BN_LEAF = {"weight": "scale", "bias": "bias",
            "running_mean": "mean", "running_var": "var"}


def params_from_torch(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """Reference i3d_{rgb,flow}.pt state_dict -> Flax tree.

    torch keys: ``<block>.conv3d.{weight,bias}``, ``<block>.batch3d.*`` where
    block is ``conv3d_1a_7x7`` | ``mixed_Xy.branch_N[.i]`` | ``conv3d_0c_1x1``.
    """
    params: Dict[str, Any] = {}
    for key, tensor in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        parts = key.split(".")
        module, leaf = parts[-2], parts[-1]
        blocks = parts[:-2]
        # torch Sequential branches: branch_1.0 -> our branch_1_0;
        # plain branch_0 stays (no Sequential index)
        if len(blocks) == 3:
            blocks = [blocks[0], f"{blocks[1]}_{blocks[2]}"]
        prefix = "/".join(blocks)
        if module == "conv3d":
            if leaf == "weight":
                ti.set_in(params, f"{prefix}/conv/kernel",
                          ti.conv3d_kernel(tensor))
            else:
                ti.set_in(params, f"{prefix}/conv/bias", ti.to_np(tensor))
        elif module == "batch3d":
            ti.set_in(params, f"{prefix}/bn/{_BN_LEAF[leaf]}",
                      ti.to_np(tensor))
        else:
            raise ValueError(f"unexpected I3D key {key}")
    return params


def init_params(modality: str = "rgb", num_classes: int = 400) -> Dict[str, Any]:
    model = I3D(num_classes)
    c = 3 if modality == "rgb" else 2
    v = model.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, 16, 224, 224, c)), features=False)
    return v["params"]
