"""Shared Flax building blocks for the inference-only model zoo.

All models here run in NHWC / NDHWC (channels-last) — the layout the TPU's
MXU and XLA's conv tiling want — with weights transplanted from the
reference's NCHW torch checkpoints via `weights/torch_import.py`.

BatchNorm is the inference affine form: every family in the reference runs
under `torch.no_grad()` with `.eval()` (reference models/_base/base_extractor.py),
so running statistics are constants; XLA folds the multiply/add into the
adjacent conv epilogue.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


class BNInf(nn.Module):
    """Inference-mode batchnorm: ``(x - mean) / sqrt(var + eps) * scale + bias``."""
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,))
        bias = self.param("bias", nn.initializers.zeros, (c,))
        mean = self.param("mean", nn.initializers.zeros, (c,))
        var = self.param("var", nn.initializers.ones, (c,))
        inv = jax.lax.rsqrt(var.astype(jnp.float32) + self.eps).astype(x.dtype)
        scale = scale.astype(x.dtype) * inv
        return x * scale + (bias.astype(x.dtype) - mean.astype(x.dtype) * scale)


def max_pool_same_torch(x: jnp.ndarray, window: Sequence[int],
                        strides: Sequence[int],
                        padding: Sequence[Tuple[int, int]]) -> jnp.ndarray:
    """Max pool over the middle (spatial) axes of an N...C tensor.

    Padding value is -inf, i.e. padded cells never win — same as torch
    MaxPool2d/3d with implicit padding.
    """
    dims = (1, *window, 1)
    strides_ = (1, *strides, 1)
    pad = ((0, 0), *padding, (0, 0))
    return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims, strides_, pad)
