"""S3D (separable 3D Inception, Kinetics-400) as a Flax module, NDHWC.

Parity target: the reference's S3D (reference models/s3d/s3d_src/s3d.py,
itself the kylemin/S3D network): an Inception-v1 trunk where every kxkxk conv
is factorized into a spatial (1,k,k) conv and a temporal (k,1,1) conv, each
followed by BatchNorm(eps=1e-3) + ReLU (SepConv3d, s3d.py:66-87); 1x1x1 convs
are plain conv+BN+ReLU (BasicConv3d, s3d.py:52-63). Nine Mixed blocks with
the classic GoogLeNet channel spec (s3d.py:90-348). Head (s3d.py:35-48):
avg_pool3d over (2, H, W) stride 1, optional 1x1x1 conv classifier, then mean
over the remaining time axis. ``features=True`` skips the classifier and
yields the 1024-d embedding the extractor stores.

Weight transplant: :func:`params_from_torch` maps the
``S3D_kinetics400_torchified.pt`` state_dict (``base.<idx>.`` Sequential
keys, s3d.py:9-30) onto this tree.
"""
from __future__ import annotations

from typing import Any, Dict, Mapping, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from .common import BNInf, max_pool_same_torch
from ..weights import torch_import as ti

FEATURE_DIM = 1024

# (branch0_1x1, (b1_reduce, b1_out), (b2_reduce, b2_out), b3_pool_proj)
MIXED_SPECS = {
    "m3b": (64, (96, 128), (16, 32), 32),
    "m3c": (128, (128, 192), (32, 96), 64),
    "m4b": (192, (96, 208), (16, 48), 64),
    "m4c": (160, (112, 224), (24, 64), 64),
    "m4d": (128, (128, 256), (24, 64), 64),
    "m4e": (112, (144, 288), (32, 64), 64),
    "m4f": (256, (160, 320), (32, 128), 128),
    "m5b": (256, (160, 320), (32, 128), 128),
    "m5c": (384, (192, 384), (48, 128), 128),
}

BN_EPS = 1e-3  # s3d.py:56 — NOT the torch default 1e-5


def _conv3d(features: int, kernel: Tuple[int, int, int],
            stride: Tuple[int, int, int], pad: Tuple[int, int, int],
            name: str, use_bias: bool = False) -> nn.Conv:
    return nn.Conv(features, kernel, strides=stride,
                   padding=[(p, p) for p in pad], use_bias=use_bias, name=name)


class BasicConv3d(nn.Module):
    features: int

    @nn.compact
    def __call__(self, x):
        x = _conv3d(self.features, (1, 1, 1), (1, 1, 1), (0, 0, 0), "conv")(x)
        return nn.relu(BNInf(BN_EPS, name="bn")(x))


class SepConv3d(nn.Module):
    features: int
    kernel: int = 3
    stride: int = 1
    pad: int = 1

    @nn.compact
    def __call__(self, x):
        k, s, p = self.kernel, self.stride, self.pad
        x = _conv3d(self.features, (1, k, k), (1, s, s), (0, p, p), "conv_s")(x)
        x = nn.relu(BNInf(BN_EPS, name="bn_s")(x))
        x = _conv3d(self.features, (k, 1, 1), (s, 1, 1), (p, 0, 0), "conv_t")(x)
        return nn.relu(BNInf(BN_EPS, name="bn_t")(x))


class Mixed(nn.Module):
    spec: Tuple

    @nn.compact
    def __call__(self, x):
        b0_out, (b1_red, b1_out), (b2_red, b2_out), b3_out = self.spec
        b0 = BasicConv3d(b0_out, name="branch0_0")(x)
        b1 = BasicConv3d(b1_red, name="branch1_0")(x)
        b1 = SepConv3d(b1_out, name="branch1_1")(b1)
        b2 = BasicConv3d(b2_red, name="branch2_0")(x)
        b2 = SepConv3d(b2_out, name="branch2_1")(b2)
        b3 = max_pool_same_torch(x, (3, 3, 3), (1, 1, 1),
                                 ((1, 1), (1, 1), (1, 1)))
        b3 = BasicConv3d(b3_out, name="branch3_1")(b3)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class S3D(nn.Module):
    """(N, T, 224, 224, 3) float [0,1] -> (N, 1024) features (features=True)
    or (N, 400) logits."""
    num_classes: int = 400

    @nn.compact
    def __call__(self, x: jnp.ndarray, features: bool = True) -> jnp.ndarray:
        x = SepConv3d(64, kernel=7, stride=2, pad=3, name="stem_sep1")(x)
        x = max_pool_same_torch(x, (1, 3, 3), (1, 2, 2),
                                ((0, 0), (1, 1), (1, 1)))
        x = BasicConv3d(64, name="stem_basic")(x)
        x = SepConv3d(192, kernel=3, stride=1, pad=1, name="stem_sep2")(x)
        x = max_pool_same_torch(x, (1, 3, 3), (1, 2, 2),
                                ((0, 0), (1, 1), (1, 1)))
        x = Mixed(MIXED_SPECS["m3b"], name="m3b")(x)
        x = Mixed(MIXED_SPECS["m3c"], name="m3c")(x)
        x = max_pool_same_torch(x, (3, 3, 3), (2, 2, 2),
                                ((1, 1), (1, 1), (1, 1)))
        for name in ("m4b", "m4c", "m4d", "m4e", "m4f"):
            x = Mixed(MIXED_SPECS[name], name=name)(x)
        x = max_pool_same_torch(x, (2, 2, 2), (2, 2, 2),
                                ((0, 0), (0, 0), (0, 0)))
        x = Mixed(MIXED_SPECS["m5b"], name="m5b")(x)
        x = Mixed(MIXED_SPECS["m5c"], name="m5c")(x)

        # head (s3d.py:35-48): (2,H,W) stride-1 avg pool == mean over H,W plus
        # a size-2 sliding mean over time
        if x.shape[1] < 2:
            # the torch reference raises here too (avg_pool3d kernel 2 >
            # input); without this check the empty slice below would
            # silently produce NaN features
            raise ValueError(
                f"S3D needs >=2 temporal positions at the head, got "
                f"{x.shape[1]}; use stack_size >= 16")
        x = jnp.mean(x, axis=(2, 3))               # (N, T, 1024)
        x = (x[:, :-1] + x[:, 1:]) * 0.5           # (N, T-1, 1024)
        if not features:
            x = _conv3d(self.num_classes, (1, 1, 1), (1, 1, 1), (0, 0, 0),
                        "fc", use_bias=True)(x[:, :, None, None, :])
            x = x[:, :, 0, 0, :]
        return jnp.mean(x, axis=1)


_BN_LEAF = {"weight": "scale", "bias": "bias",
            "running_mean": "mean", "running_var": "var"}

# base.<idx> Sequential position -> our module name (s3d.py:9-27)
_BASE_IDX = {"0": "stem_sep1", "2": "stem_basic", "3": "stem_sep2",
             "5": "m3b", "6": "m3c", "8": "m4b", "9": "m4c", "10": "m4d",
             "11": "m4e", "12": "m4f", "14": "m5b", "15": "m5c"}


def params_from_torch(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """Reference S3D state_dict -> Flax tree (fc folded into the same tree)."""
    params: Dict[str, Any] = {}
    for key, tensor in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        parts = key.split(".")
        if parts[0] == "fc":
            # fc.0.{weight,bias}: 1x1x1 Conv3d classifier
            leaf = "kernel" if parts[2] == "weight" else "bias"
            val = ti.conv3d_kernel(tensor) if leaf == "kernel" else ti.to_np(tensor)
            ti.set_in(params, f"fc/{leaf}", val)
            continue
        assert parts[0] == "base", f"unexpected S3D key {key}"
        block = _BASE_IDX[parts[1]]
        rest = parts[2:]
        if rest[0].startswith("branch"):
            # branch1.1.conv_s.weight -> branch1_1/conv_s/...
            sub = f"{rest[0]}_{rest[1]}"
            rest = [sub] + rest[2:]
        module, leaf = rest[-2], rest[-1]
        prefix = "/".join([block] + rest[:-2])
        if module.startswith("bn"):
            ti.set_in(params, f"{prefix}/{module}/{_BN_LEAF[leaf]}",
                      ti.to_np(tensor))
        else:
            ti.set_in(params, f"{prefix}/{module}/kernel",
                      ti.conv3d_kernel(tensor))
    return params


def init_params(num_classes: int = 400) -> Dict[str, Any]:
    model = S3D(num_classes)
    # T=16 is the smallest stack that leaves >=2 temporal positions at the
    # head (time is strided 2x at the stem and both 3D maxpools)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 64, 64, 3)),
                   features=False)
    return v["params"]
