"""ResNet-18/34/50/101/152 (torchvision v1.5 layout) as Flax modules, NHWC.

The reference pulls these from torchvision at runtime
(reference models/resnet/extract_resnet.py:46-51) and swaps ``fc`` for
Identity, keeping the classifier separately for ``show_pred``. Here the
backbone is a Flax module returning pooled 512/2048-d features; the classifier
is an optional separate head applied only for show_pred.

Weight transplant: :func:`params_from_torch` maps a torchvision
``resnet*`` state_dict onto this tree (OIHW->HWIO etc.).
"""
from __future__ import annotations

from typing import Any, Dict, Mapping, Sequence

import jax.numpy as jnp
from flax import linen as nn

from .common import BNInf, max_pool_same_torch
from ..weights import torch_import as ti

# stage block counts and block type per variant
VARIANTS = {
    "resnet18": ((2, 2, 2, 2), "basic"),
    "resnet34": ((3, 4, 6, 3), "basic"),
    "resnet50": ((3, 4, 6, 3), "bottleneck"),
    "resnet101": ((3, 4, 23, 3), "bottleneck"),
    "resnet152": ((3, 8, 36, 3), "bottleneck"),
}

FEATURE_DIMS = {"resnet18": 512, "resnet34": 512, "resnet50": 2048,
                "resnet101": 2048, "resnet152": 2048}


def _conv(features: int, kernel: int, stride: int = 1, pad: int = 0,
          name: str = None) -> nn.Conv:
    return nn.Conv(features, (kernel, kernel), strides=(stride, stride),
                   padding=[(pad, pad), (pad, pad)], use_bias=False, name=name)


class BasicBlock(nn.Module):
    planes: int
    stride: int = 1
    has_downsample: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        identity = x
        out = _conv(self.planes, 3, self.stride, 1, "conv1")(x)
        out = BNInf(name="bn1")(out)
        out = nn.relu(out)
        out = _conv(self.planes, 3, 1, 1, "conv2")(out)
        out = BNInf(name="bn2")(out)
        if self.has_downsample:
            identity = _conv(self.planes, 1, self.stride, 0, "downsample_conv")(x)
            identity = BNInf(name="downsample_bn")(identity)
        return nn.relu(out + identity)


class Bottleneck(nn.Module):
    planes: int
    stride: int = 1
    has_downsample: bool = False
    expansion: int = 4

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        identity = x
        out = _conv(self.planes, 1, 1, 0, "conv1")(x)
        out = BNInf(name="bn1")(out)
        out = nn.relu(out)
        # torchvision puts the stride on the 3x3 (the "v1.5" variant)
        out = _conv(self.planes, 3, self.stride, 1, "conv2")(out)
        out = BNInf(name="bn2")(out)
        out = nn.relu(out)
        out = _conv(self.planes * self.expansion, 1, 1, 0, "conv3")(out)
        out = BNInf(name="bn3")(out)
        if self.has_downsample:
            identity = _conv(self.planes * self.expansion, 1, self.stride, 0,
                             "downsample_conv")(x)
            identity = BNInf(name="downsample_bn")(identity)
        return nn.relu(out + identity)


class ResNet(nn.Module):
    """Backbone forward: (N, H, W, 3) float in [0,1]-normalized space -> (N, D)."""
    variant: str = "resnet50"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        stages, block_kind = VARIANTS[self.variant]
        block_cls = BasicBlock if block_kind == "basic" else Bottleneck
        expansion = 1 if block_kind == "basic" else 4

        x = _conv(64, 7, 2, 3, "conv1")(x)
        x = BNInf(name="bn1")(x)
        x = nn.relu(x)
        x = max_pool_same_torch(x, (3, 3), (2, 2), ((1, 1), (1, 1)))

        in_planes = 64
        for stage_idx, num_blocks in enumerate(stages):
            planes = 64 * (2 ** stage_idx)
            stride = 1 if stage_idx == 0 else 2
            for block_idx in range(num_blocks):
                s = stride if block_idx == 0 else 1
                needs_ds = (s != 1) or (in_planes != planes * expansion)
                x = block_cls(planes, s, needs_ds,
                              name=f"layer{stage_idx + 1}_{block_idx}")(x)
                in_planes = planes * expansion

        # global average pool (torch AdaptiveAvgPool2d(1))
        return jnp.mean(x, axis=(1, 2))


class Classifier(nn.Module):
    """The fc head the reference keeps aside as `class_head`
    (reference extract_resnet.py:54-56)."""
    num_classes: int = 1000

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return nn.Dense(self.num_classes, name="fc")(x)


def init_params(variant: str = "resnet50") -> Dict[str, Any]:
    """Random {'backbone', 'head'} trees — the msgpack template shape."""
    import jax
    backbone = ResNet(variant).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)))["params"]
    head = Classifier().init(
        jax.random.PRNGKey(1), jnp.zeros((1, FEATURE_DIMS[variant])))["params"]
    return {"backbone": backbone, "head": head}


def params_from_torch(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """torchvision resnet state_dict -> {'backbone': ..., 'head': ...} trees."""
    backbone: Dict[str, Any] = {}
    head: Dict[str, Any] = {}
    for key, tensor in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        parts = key.split(".")
        if parts[0] == "fc":
            if parts[1] == "weight":
                ti.set_in(head, "fc/kernel", ti.linear_kernel(tensor))
            else:
                ti.set_in(head, "fc/bias", ti.to_np(tensor))
            continue
        if parts[0].startswith("layer"):
            # layer1.0.conv1.weight -> layer1_0/conv1/kernel
            block = f"{parts[0]}_{parts[1]}"
            rest = parts[2:]
            if rest[0] == "downsample":
                sub = "downsample_conv" if rest[1] == "0" else "downsample_bn"
                rest = [sub] + rest[2:]
            path = [block] + rest
        else:
            path = parts
        _assign_leaf(backbone, path, tensor)
    return {"backbone": backbone, "head": head}


_BN_LEAF = {"weight": "scale", "bias": "bias",
            "running_mean": "mean", "running_var": "var"}


def _assign_leaf(tree: Dict[str, Any], path: Sequence[str], tensor) -> None:
    *prefix, module, leaf = path
    if module.startswith("bn") or module.endswith("_bn"):
        ti.set_in(tree, "/".join([*prefix, module, _BN_LEAF[leaf]]),
                  ti.to_np(tensor))
    elif leaf == "weight":
        ti.set_in(tree, "/".join([*prefix, module, "kernel"]),
                  ti.conv2d_kernel(tensor))
    else:
        ti.set_in(tree, "/".join([*prefix, module, leaf]), ti.to_np(tensor))
