"""VGGish (AudioSet audio embeddings) as a Flax module, NHWC.

Parity target: reference models/vggish/vggish_src/vggish_slim.py — the
harritaylor/torchvggish port of the TF-Slim original:

  - conv stack ``[64, M, 128, M, 256, 256, M, 512, 512, M]`` on 1-channel
    (96, 64) log-mel patches, all 3x3 pad-1 convs + ReLU, 2x2 max pools
    (vggish_slim.py:102-112),
  - the flatten before the MLP goes through an NHWC transpose for
    TF-compat (vggish_slim.py:27-37) — in NHWC layout here, a plain
    ``reshape`` is already that order,
  - embeddings MLP 12288 -> 4096 -> 4096 -> 128, ReLU after every layer
    (vggish_slim.py:19-25),
  - optional ``Postprocessor``: PCA-whitening + clip to [-2, 2] + 8-bit
    quantization to [0, 255] (vggish_slim.py:40-99). ``post_process``
    defaults to False (identity) exactly like the reference's
    ``forward`` (vggish_slim.py:95-99), so raw embeddings are the output
    contract.
"""
from __future__ import annotations

from typing import Any, Dict, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from ..weights import torch_import as ti

EMBEDDING_SIZE = 128
# torch Sequential indices of the parameterized layers (vggish_slim.py:102-112)
_CONV_IDX = (0, 3, 6, 8, 11, 13)
_CONV_CH = (64, 128, 256, 256, 512, 512)
_POOL_AFTER = (0, 3, 8, 13)  # pool follows the conv at these indices
_FC_IDX = (0, 2, 4)
_FC_DIM = (4096, 4096, 128)


class VGGish(nn.Module):
    """(B, 96, 64, 1) float log-mel examples -> (B, 128) embeddings."""

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for idx, ch in zip(_CONV_IDX, _CONV_CH):
            x = nn.relu(nn.Conv(ch, (3, 3), padding=1,
                                name=f"features_{idx}")(x))
            if idx in _POOL_AFTER:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)  # NHWC flatten == vggish_slim.py:30-34
        for idx, dim in zip(_FC_IDX, _FC_DIM):
            x = nn.relu(nn.Dense(dim, name=f"embeddings_{idx}")(x))
        return x


def postprocess(embeddings: np.ndarray, pca_eigen_vectors: np.ndarray,
                pca_means: np.ndarray) -> np.ndarray:
    """PCA-whiten + quantize to [0, 255] (Postprocessor.postprocess,
    vggish_slim.py:63-92). numpy: runs once per video on 128-d vectors."""
    pca = (pca_eigen_vectors @ (embeddings.T - pca_means)).T
    clipped = np.clip(pca, -2.0, 2.0)
    return np.squeeze(np.round((clipped + 2.0) * (255.0 / 4.0)))


def params_from_torch(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """vggish-10086976.pth state_dict -> Flax tree (keys ``features.N.*``,
    ``embeddings.N.*``)."""
    sd = ti.strip_module_prefix(state_dict)
    params: Dict[str, Any] = {}
    for key, t in sd.items():
        mod, idx, leaf = key.split(".")
        name = f"{mod}_{idx}"
        if leaf == "weight":
            kernel = (ti.conv2d_kernel(t) if t.dim() == 4
                      else ti.linear_kernel(t))
            ti.set_in(params, f"{name}/kernel", kernel)
        elif leaf == "bias":
            ti.set_in(params, f"{name}/bias", ti.to_np(t))
        else:
            raise ValueError(f"unexpected VGGish key {key}")
    return params


def load_pca_params(path: str):
    """(pca_eigen_vectors (128, 128), pca_means (128, 1)) from either the
    torchvggish release ``.pth`` (dict of arrays) or an ``.npz`` twin
    (reference models/vggish/checkpoints/vggish_pca_params.npz,
    vggish_postprocess.py:22-91)."""
    if path.endswith(".npz"):
        blob = np.load(path)
    else:
        import torch
        blob = torch.load(path, map_location="cpu", weights_only=False)
    vectors = np.asarray(blob["pca_eigen_vectors"], dtype=np.float32)
    means = np.asarray(blob["pca_means"], dtype=np.float32).reshape(-1, 1)
    return vectors, means


def init_params() -> Dict[str, Any]:
    model = VGGish()
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 96, 64, 1)))
    return v["params"]
