"""Config system: per-feature YAML defaults + CLI dotlist overrides.

Re-designed equivalent of the reference's OmegaConf flow (reference
main.py:9-10, utils/utils.py:71-125,218-229) without the OmegaConf dependency:
plain-YAML defaults in ``video_features_tpu/configs/<feature_type>.yml`` merged
under a parsed ``key=value`` dotlist (CLI wins), then validated and
path-patched by :func:`sanity_check`.

Differences from the reference, by design:
  - ``device`` is ``tpu`` / ``cpu`` / ``auto`` (default). ``cuda*`` values are
    accepted for drop-in compatibility and mapped to ``auto`` with a warning
    (the reference falls back cuda->cpu at utils/utils.py:84-86).
  - PWC-Net runs everywhere (the reference requires a GPU,
    utils/utils.py:104-105, because its correlation is a CuPy CUDA kernel; ours
    is a Pallas/XLA kernel with a pure-XLA interpret path on CPU).
"""
from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Union

import yaml

_CONFIG_DIR = Path(__file__).resolve().parent / "configs"

#: validated config keys that legitimately appear in only SOME family
#: YAMLs — family-specific defaults (flow nets have iteration counts,
#: clip-stack families have windowing, CLIP has a text side). ``vft-lint``
#: rule VFT002 requires every validator-read key to be carried by ALL
#: eight YAMLs unless it is declared here (or in LAUNCH_KEYS below):
#: a key that is neither is a default nobody documented.
OPTIONAL_KEYS = frozenset({
    "batch_size", "bpe_path", "clip_batch_size", "corr_lookup_impl",
    "extraction_fps", "extraction_total", "finetuned_on", "flow_iters",
    "flow_model_weights_path", "flow_stack_batch", "flow_type",
    "flow_weights_path", "fps_mode", "frontend", "fuse_convc1", "ingest",
    "iters", "model_name", "model_parallel", "pca_weights_path",
    "postprocess", "pred_texts", "resize", "resize_to_smaller_edge",
    "side_size", "stack_size", "step_size", "streams", "vision_attn",
})

#: launch-time keys that never ride a family YAML: serve/gateway spool
#: plumbing passed on the vft-serve/vft-gateway command line, and expert
#: decode-pipeline knobs that are deliberately undocumented defaults.
#: Declared so VFT002 can tell "launch-only by design" from "typo'd key
#: nobody validates".
LAUNCH_KEYS = frozenset({
    # profiling hooks (cli.py)
    "profile", "profile_trace_dir",
    # expert decode-pipeline knobs (extractors/base.py, multi.py)
    "video_decode", "decode_workers", "decode_depth", "fanout_depth",
    "cross_video_batching",
    # vft-serve launch keys (serve.py; serve_slo_s rides the YAMLs)
    "spool_dir", "serve_max_pending", "serve_poll_interval_s",
    "serve_idle_exit_s", "serve_max_requests", "serve_workers",
    "serve_warmup_video",
    # vft-gateway launch keys (gateway.py validate_gateway_args)
    "gateway_tenants", "gateway_port", "gateway_host",
    "gateway_max_queued", "gateway_spool_bound", "gateway_max_body_mb",
    "gateway_poll_interval_s", "gateway_expire_grace_s",
    "gateway_default_timeout_s",
    # vft-gc launch keys (gc.py validate_gc_args)
    "gc", "gc_quota_gb", "gc_cache_retention_s",
    "gc_compile_retention_s", "gc_spool_retention_s",
    "gc_inbox_retention_s", "gc_incident_retention_s",
    "gc_quarantine_retention_s", "gc_staging_retention_s",
    "gc_interval_s",
})

#: removed reference flags: accepted, warned about and deleted by
#: sanity_check — exempt from every other key contract.
REMOVED_KEYS = frozenset({"device_ids"})


class Config(dict):
    """A dict with attribute access, nesting-aware, YAML-serializable.

    Stands in for OmegaConf's DictConfig in the reference API surface.
    """

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = value

    def __delattr__(self, name: str) -> None:
        try:
            del self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    @staticmethod
    def _wrap(value: Any) -> Any:
        if isinstance(value, dict) and not isinstance(value, Config):
            return Config({k: Config._wrap(v) for k, v in value.items()})
        if isinstance(value, list):
            return [Config._wrap(v) for v in value]
        return value

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            super().__setitem__(k, Config._wrap(v))

    def __setitem__(self, key, value):
        super().__setitem__(key, Config._wrap(value))

    def to_yaml(self) -> str:
        return yaml.safe_dump(_plain(self), sort_keys=False)


def _plain(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {k: _plain(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_plain(v) for v in obj]
    return obj


def build_cfg_path(feature_type: str) -> Path:
    """Path of the YAML defaults for a feature family.

    Mirrors reference utils/utils.py:218-229 but resolves inside the installed
    package instead of the current working directory.
    """
    path = _CONFIG_DIR / f"{feature_type}.yml"
    return path


def load_yaml(path: Union[str, os.PathLike]) -> Config:
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    return Config(data)


def parse_dotlist(argv: Sequence[str]) -> Config:
    """Parse ``key=value`` CLI arguments (OmegaConf.from_cli equivalent).

    Values go through YAML, so ``batch_size=16`` is an int, ``flow_type=null``
    is None, ``video_paths=[a.mp4,b.mp4]`` is a list. Dots nest:
    ``a.b=1`` -> ``{'a': {'b': 1}}``.
    """
    out: Dict[str, Any] = {}
    for arg in argv:
        if "=" not in arg:
            raise ValueError(
                f"CLI arguments must look like key=value (got {arg!r})")
        key, raw = arg.split("=", 1)
        try:
            value = yaml.safe_load(raw) if raw != "" else None
        except yaml.YAMLError:
            value = raw
        node = out
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return Config(out)


def merge(base: Config, override: Config) -> Config:
    """Deep merge; ``override`` wins (OmegaConf.merge semantics we rely on)."""
    result = Config(dict(base))
    for k, v in override.items():
        if k in result and isinstance(result[k], dict) and isinstance(v, dict):
            result[k] = merge(result[k], v)
        else:
            result[k] = v
    return result


def load_config(feature_type: str,
                overrides: Optional[Union[Config, Dict[str, Any]]] = None,
                ) -> Config:
    """YAML defaults for ``feature_type`` merged under ``overrides``."""
    cfg_path = build_cfg_path(feature_type)
    if not cfg_path.exists():
        raise FileNotFoundError(
            f"Unknown feature_type {feature_type!r}: no config at {cfg_path}")
    cfg = load_yaml(cfg_path)
    if overrides:
        cfg = merge(cfg, Config(dict(overrides)))
    return cfg


def load_multi_config(families: Sequence[str],
                      overrides: Optional[Union[Config, Dict[str, Any]]] = None,
                      ) -> "Dict[str, Config]":
    """Per-family configs for a multi-family run (ordered like ``families``).

    Override routing: top-level CLI keys are SHARED (merged into every
    family's YAML defaults); a key nested under a requested family name is
    that family's private override and wins over the shared layer —
    ``feature_type=resnet,clip extraction_fps=1 clip.extraction_fps=2``
    runs resnet at 1 fps and clip at 2. A nested override for a known
    family that is NOT requested is almost certainly a typo'd run and
    fails loudly instead of silently extracting nothing for it.
    """
    from .registry import _DISPATCH
    families = list(families)
    overrides = Config(dict(overrides or {}))
    shared = {k: v for k, v in overrides.items()
              if k != "feature_type" and k not in families}
    for k in list(shared):
        if k in _DISPATCH and isinstance(shared[k], dict):
            raise ValueError(
                f"per-family override block {k}.* given, but {k!r} is not "
                f"in feature_type={','.join(families)} — add it to the "
                "list or drop the override")
    per: Dict[str, Config] = {}
    for f in families:
        fam_over = overrides.get(f)
        merged = Config(dict(shared))
        if isinstance(fam_over, dict):
            merged = merge(merged, Config(dict(fam_over)))
        cfg = load_config(f, merged)
        cfg.feature_type = f
        per[f] = cfg
    return per


def sanity_check_multi(per_family: "Dict[str, Config]", *,
                       require_videos: bool = True) -> None:
    """Multi-family constraints, then the normal per-family sanity_check
    (which namespaces each family's output/tmp paths under its own
    ``feature_type[/model_name]`` subdir — so sinks and journals never
    collide across families)."""
    for f, args in per_family.items():
        if args.get("on_extraction", "print") == "print":
            raise ValueError(
                "multi-family extraction needs a file sink "
                "(on_extraction=save_numpy or save_pickle): N families' "
                "print dumps would interleave, and the per-family skip/"
                "journal contracts need per-family output dirs")
        if args.get("show_pred"):
            raise ValueError(
                "show_pred=true is unsupported in multi-family runs "
                "(per-batch prediction printing would interleave across "
                "families)")
        if (args.get("fps_mode", "select") or "select") == "reencode":
            raise ValueError(
                "fps_mode=reencode is unsupported in multi-family runs: "
                "each family's reencode provenance is its own lossy "
                "temp-file decode, which cannot share one pass — run "
                "golden-parity extractions one family at a time")
        sanity_check(args, require_videos=require_videos)


def resolve_device(device: Optional[str]) -> str:
    """Map a user device string to 'tpu' or 'cpu'.

    Accepts 'auto' (default), 'tpu', 'cpu', and legacy 'cuda*' strings, which
    are treated as 'auto' for drop-in compatibility with reference configs.
    """
    if device is None:
        device = "auto"
    device = str(device)
    if device.startswith("cuda"):
        print(f"device={device!r} is a CUDA ordinal from the reference CLI; "
              "this framework targets TPU. Treating it as device=auto.")
        device = "auto"
    if device in ("tpu", "cpu"):
        # never touch jax.devices() for an explicit choice: initializing the
        # accelerator plugin claims the chip, which `device=cpu` must not do
        return device
    if device != "auto":
        raise ValueError(f"Unsupported device {device!r}; use tpu|cpu|auto")
    import jax
    platforms = {d.platform for d in jax.devices()}
    return "tpu" if "tpu" in platforms else "cpu"


def sanity_check(args: Config, *, require_videos: bool = True) -> None:
    """Validate user arguments and patch output/tmp paths in place.

    ``require_videos=False`` (vft-serve, serve.py) skips the launch-time
    video-list validation: a server has no corpus at launch — videos
    arrive per request, and per-request failures route through the
    normal per-video fault isolation instead of a launch assert.

    Reproduces the semantics of reference utils/utils.py:71-125:
      - one of video_paths / file_with_video_paths required
      - unique video stems (the output filename contract collides otherwise)
      - output_path != tmp_path
      - i3d stack_size >= 10
      - batch_size must not be None when present
      - extraction_fps / extraction_total mutually exclusive
      - output_path & tmp_path get ``feature_type[/model_name]`` appended with
        '/' replaced by '_' (e.g. CLIP's ViT-B/32 -> ViT-B_32)

    Dropped on purpose: the cuda->cpu fallback (resolve_device handles device
    naming) and the PWC-needs-GPU assert (our PWC correlation is Pallas/XLA).
    """
    from .utils.lists import form_list_from_user_input

    if "device_ids" in args:
        print("WARNING: `device_ids` is a removed reference flag; single-host "
              "multi-chip execution here is automatic over the TPU mesh. "
              "Ignoring it.")
        del args["device_ids"]
    args.device = resolve_device(args.get("device"))

    if require_videos:
        assert args.get("file_with_video_paths") or args.get("video_paths"), \
            "`video_paths` or `file_with_video_paths` must be specified"
        filenames = [Path(p).stem for p in form_list_from_user_input(
            args.get("video_paths"), args.get("file_with_video_paths"),
            to_shuffle=False)]
        assert len(filenames) == len(set(filenames)), \
            "Non-unique video file stems: outputs would overwrite each " \
            "other (same contract as reference video_features issue #54)"
    assert os.path.relpath(str(args.output_path)) != os.path.relpath(str(args.tmp_path)), \
        "The same path for out & tmp"

    if args.get("show_pred") and args.feature_type == "vggish":
        print("Showing class predictions is not implemented for VGGish")

    vw = args.get("video_workers") or 1
    if isinstance(vw, str):
        vw = vw.strip().lower()
        if vw != "auto":
            raise ValueError(f"video_workers={vw!r}: expected an int or "
                             "'auto'")
        args.video_workers = vw
    if (vw == "auto" or int(vw) > 1) and (
            args.get("on_extraction", "print") == "print"
            or args.get("show_pred")):
        # concurrent videos would interleave their stdout dumps line-by-line
        print("WARNING: video_workers > 1 with on_extraction=print or "
              "show_pred would interleave per-video output; forcing "
              "video_workers=1. Use save_numpy/save_pickle for pipelined "
              "multi-video extraction.")
        args.video_workers = 1

    if args.feature_type == "i3d" and args.get("stack_size") is not None:
        assert args.stack_size >= 10, (
            "I3D model does not support inputs shorter than 10 timestamps. "
            f"You have: {args.stack_size}")

    if "batch_size" in args:
        assert args.batch_size is not None, \
            f"Please specify `batch_size`. It is {args.batch_size} now"

    if "extraction_fps" in args and "extraction_total" in args:
        assert not (args.get("extraction_fps") is not None
                    and args.get("extraction_total") is not None), \
            "`extraction_fps` and `extraction_total` are mutually exclusive"

    # fault-tolerance keys (utils/faults.py RetryPolicy.from_config):
    # validated at launch so a typo fails before N videos burn retries
    ra = args.get("retry_attempts")
    if ra is not None and int(ra) < 1:
        raise ValueError(f"retry_attempts={ra!r}: need an int >= 1")
    rb = args.get("retry_backoff_s")
    if rb is not None and float(rb) < 0:
        raise ValueError(f"retry_backoff_s={rb!r}: need a float >= 0")
    vd = args.get("video_deadline_s")
    if vd is not None and float(vd) <= 0:
        raise ValueError(f"video_deadline_s={vd!r}: need a float > 0 "
                         "(or null to disable the per-video deadline)")

    # telemetry keys (telemetry/ subsystem): same launch-time validation
    tel = args.get("telemetry", False)
    if not isinstance(tel, bool):
        raise ValueError(f"telemetry={tel!r}: expected true or false")
    mi = args.get("metrics_interval_s")
    if mi is not None and float(mi) <= 0:
        raise ValueError(f"metrics_interval_s={mi!r}: need a float > 0 "
                         "(the heartbeat/metrics flush period)")
    tr = args.get("trace", False)
    if not isinstance(tr, bool):
        raise ValueError(f"trace={tr!r}: expected true or false (writes "
                         "{output_path}/_trace.json, telemetry/trace.py)")
    he = args.get("health", False)
    if not isinstance(he, bool):
        raise ValueError(f"health={he!r}: expected true or false (digests "
                         "features into {output_path}/_health.jsonl and "
                         "quarantines NaN/Inf outputs, telemetry/health.py)")
    pa = args.get("parity", False)
    if not isinstance(pa, bool):
        raise ValueError(f"parity={pa!r}: expected true or false (per-seam "
                         "numerics digests into {output_path}/_parity.jsonl, "
                         "telemetry/parity.py — render with vft-parity)")
    rf = args.get("roofline", False)
    if not isinstance(rf, bool):
        raise ValueError(f"roofline={rf!r}: expected true or false (MFU "
                         "accounting into {output_path}/_roofline.json, "
                         "telemetry/roofline.py — render with vft-roofline)")
    hi = args.get("history", False)
    if not isinstance(hi, bool):
        raise ValueError(f"history={hi!r}: expected true or false (retained "
                         "heartbeat samples in {output_path}/"
                         "_history_{host_id}.jsonl, telemetry/history.py)")
    al = args.get("alerts", False)
    if not isinstance(al, bool):
        raise ValueError(f"alerts={al!r}: expected true or false (alert "
                         "rules on the heartbeat cadence into "
                         "{output_path}/_alerts.jsonl + _incidents/ "
                         "bundles, telemetry/alerts.py — render with "
                         "vft-alert)")
    if (hi or al) and not args.get("telemetry", False):
        raise ValueError(
            "history=true / alerts=true need telemetry=true: samples and "
            "rule evaluation ride the heartbeat cadence "
            "(docs/observability.md 'Alerting & incident bundles')")

    # feature-cache keys (cache.py): validated at launch like the
    # telemetry switches — a typo'd cache flag must not silently run cold
    ca = args.get("cache", False)
    if not isinstance(ca, bool):
        raise ValueError(f"cache={ca!r}: expected true or false (the "
                         "content-addressed feature cache, cache.py)")
    cd = args.get("cache_dir")
    if cd is not None and not isinstance(cd, str):
        raise ValueError(f"cache_dir={cd!r}: expected a directory path or "
                         "null (null -> VFT_CACHE_DIR or "
                         "~/.cache/video_features_tpu/feature_cache)")
    cs = args.get("cache_scope", "shared") or "shared"
    if cs not in ("shared", "tenant"):
        raise ValueError(f"cache_scope={cs!r}: expected 'shared' (one "
                         "entry per content — cross-tenant dedup, the "
                         "dominant win at scale) or 'tenant' (the "
                         "requesting tenant salts the key: no tenant "
                         "ever observes a hit on another's content — "
                         "docs/serving.md)")

    # gateway keys (gateway.py): tenant table, port, admission bounds —
    # full validation lives with the gateway so vft-gateway and any
    # serve/cli run carrying gateway_* keys fail a typo identically
    if any(str(k).startswith("gateway_") for k in args):
        from .gateway import validate_gateway_args
        validate_gateway_args(args)

    # storage lifecycle keys (gc.py): quotas/retentions — full validation
    # lives with the GC plane so vft-gc and any run carrying gc keys
    # fail a typo identically
    if "gc" in args or any(str(k).startswith("gc_") for k in args):
        from .gc import validate_gc_args
        validate_gc_args(args)

    # compile-cache keys (compile_cache.py): the fleet-shared persistent
    # XLA store — a typo'd switch must not silently compile cold forever
    cc = args.get("compile_cache", "auto")
    if cc not in (True, False, "auto"):
        raise ValueError(f"compile_cache={cc!r}: expected true, false or "
                         "'auto' ('auto' = on for TPU runs; CPU runs need "
                         "an explicit compile_cache_dir — "
                         "docs/performance.md 'Never compile twice, fleet "
                         "edition')")
    ccd = args.get("compile_cache_dir")
    if ccd is not None and not isinstance(ccd, str):
        raise ValueError(f"compile_cache_dir={ccd!r}: expected a directory "
                         "path or null (null -> VFT_COMPILE_CACHE_DIR or "
                         "~/.cache/video_features_tpu/compile_cache)")

    # fleet scheduling keys (parallel/queue.py): validated at launch —
    # a typo'd fleet mode must fail before N hosts start claiming
    fl = args.get("fleet", "static") or "static"
    if fl not in ("static", "queue"):
        raise ValueError(f"fleet={fl!r}: expected 'static' (md5 hash "
                         "sharding fixed at launch) or 'queue' (the "
                         "work-stealing lease queue, docs/fleet.md)")
    if fl == "queue":
        if not args.get("telemetry", False):
            raise ValueError(
                "fleet=queue needs telemetry=true: the heartbeat flusher "
                "thread renews work-item leases and heartbeats are the "
                "fleet membership/liveness signal (docs/fleet.md)")
        if args.get("on_extraction", "print") == "print":
            raise ValueError(
                "fleet=queue needs a file sink (on_extraction=save_numpy "
                "or save_pickle): stolen work relies on the idempotent "
                "skip-if-exists output contract, which print lacks")
    fls = args.get("fleet_lease_s")
    if fls is not None and float(fls) <= 0:
        raise ValueError(f"fleet_lease_s={fls!r}: need a float > 0 (the "
                         "work-item lease period; renewed every heartbeat)")
    fmr = args.get("fleet_max_reclaims")
    if fmr is not None and int(fmr) < 1:
        raise ValueError(f"fleet_max_reclaims={fmr!r}: need an int >= 1 "
                         "(reclaims before an item is quarantined)")
    fca = args.get("fleet_canary", False)
    if not isinstance(fca, bool):
        raise ValueError(f"fleet_canary={fca!r}: expected true or false "
                         "(gate joining hosts on a re-extracted slice, "
                         "docs/fleet.md)")

    # serve SLO key (serve.py): the per-request latency objective in
    # seconds, measured queue-wait + service; a typo'd objective must
    # fail at launch, not silently count zero violations
    slo = args.get("serve_slo_s")
    if slo is not None:
        try:
            slo_f = float(slo)
        except (TypeError, ValueError):
            raise ValueError(f"serve_slo_s={slo!r}: need a float > 0 in "
                             "seconds, or null to disable violation "
                             "counting (docs/serving.md)") from None
        if slo_f <= 0:
            raise ValueError(f"serve_slo_s={slo!r}: need a float > 0 in "
                             "seconds, or null to disable violation "
                             "counting (docs/serving.md)")

    # fault-injection plan (utils/inject.py): the full plan grammar is
    # parsed at launch, so a typo'd site/fault/trigger fails HERE with
    # the offending clause named — never silently runs a chaos-free
    # "chaos" run (docs/chaos.md)
    inj = args.get("inject")
    if inj is not None:
        if not isinstance(inj, str):
            raise ValueError(
                f"inject={inj!r}: expected a plan string like "
                "'seed=1;sink.fsync=enospc@n1' or null (docs/chaos.md)")
        from .utils.inject import parse_plan
        parse_plan(inj)  # raises ValueError naming the bad clause

    # resize=auto|host|device (extractors/base.py _resolve_resize_mode):
    # 'auto' (the default) picks 'device' for save sinks and 'host' for
    # print/show_pred and for families without a fused device resize
    rz = args.get("resize")
    if rz is not None and rz not in ("auto", "host", "device"):
        raise ValueError(f"resize={rz!r}: expected 'auto', 'host' or "
                         "'device'")

    # RAFT corr-lookup dispatch keys (models/raft.py configure_corr_lookup,
    # applied at extractor init — the config-first promotion of the old
    # trace-time env vars; VFT_CORR_LOOKUP/VFT_FUSE_CONVC1 stay as
    # perf-probe overrides)
    cli_impl = args.get("corr_lookup_impl")
    if cli_impl is not None and cli_impl not in ("gather", "onehot",
                                                 "pallas", "packed"):
        raise ValueError(f"corr_lookup_impl={cli_impl!r}: expected null "
                         "(auto), 'gather', 'onehot', 'pallas' or 'packed'")
    fc1 = args.get("fuse_convc1")
    if fc1 is not None and not isinstance(fc1, bool):
        raise ValueError(f"fuse_convc1={fc1!r}: expected true, false or "
                         "null (auto)")

    fps_mode = args.get("fps_mode", "select") or "select"
    if fps_mode not in ("select", "reencode"):
        raise ValueError(
            f"fps_mode={fps_mode!r}: expected 'select' (bit-exact source "
            "frames, the default) or 'reencode' (the reference's lossy "
            "temp-file decode path, for golden-parity runs)")

    # Namespace outputs under feature_type[/model_name], '/'->'_'
    # (reference utils/utils.py:112-125).
    subs: List[str] = [args.feature_type]
    if "model_name" in args and args.model_name is not None:
        subs.append(str(args.model_name))
    out, tmp = str(args.output_path), str(args.tmp_path)
    for p in subs:
        out = os.path.join(out, p.replace("/", "_"))
        tmp = os.path.join(tmp, p.replace("/", "_"))
    args.output_path = out
    args.tmp_path = tmp
