"""``vft-gateway``: the overload-hardened network front door.

Until now the "distributed" story required every client to mount the
spool filesystem (``vft-serve``, serve.py) — the reference toolkit's
shell-level parallelism, inherited. This module is the network ingress a
millions-of-users front door needs, built to *degrade gracefully* rather
than merely to speak HTTP:

  - **zero new dependencies**: stdlib ``ThreadingHTTPServer`` over the
    existing spool contract, UNCHANGED — the gateway is just another
    spool client, and ``vft-serve`` workers need no protocol change;
  - **tenant identity** from an API-key table (``tenants.yml``:
    key -> tenant, quota, priority class), minted into the request id as
    ``{tenant}-{rid}`` — so every span, health digest, journal entry,
    trace span and alert the request produces is tenant-attributable
    for free (telemetry/context.py ``tenant_of``), and per-tenant SLO
    attainment surfaces in ``vft-fleet`` with no extra plumbing;
  - **admission that sheds instead of collapsing**: per-tenant
    token-bucket rate limits and in-flight quotas answer ``429`` with a
    computed ``Retry-After``; a full gateway queue or a dead backend
    (spool depth + heartbeat liveness — the signals the spool already
    exports) answers ``503``. Accepted requests wait in bounded
    per-priority-class queues and are released into the spool by smooth
    weighted fair-share (high/normal/low = 4/2/1) only while the spool
    backlog is under ``gateway_spool_bound`` — generalizing serve.py's
    ``serve_max_pending`` fast-reject to the network edge. There is no
    unbounded queue anywhere on the path;
  - **end-to-end deadlines**: a client ``timeout_s`` becomes an absolute
    ``deadline`` stamped into the spool request — computed from the
    GATEWAY's clock (duration-relative), so client wall-clock skew
    cannot expire a request early or keep a dead one alive. The gateway
    expires requests still queued at the edge; ``ServeLoop`` cancels
    expired requests at claim time (zero decode/device time burned) and
    between videos (serve.py), writing the terminal
    ``expired/{id}.json`` record either way; and the gateway sweeps
    submitted-but-unanswered requests past ``deadline + grace`` (a
    crashed server, a lost submit) so every accepted request reaches
    exactly one terminal state;
  - **idempotent ingestion**: uploads are content-addressed into
    ``{spool}/inbox/`` by sha256 — a client that retries an identical
    upload gets the stored path back (``dedup: true``), and with the
    content-addressed feature cache (cache.py) a retried extraction of
    identical bytes is a cache hit, not duplicate work;
  - **failure semantics proven, not assumed**: the client-body read and
    the spool submit are injection sites (utils/inject.py
    ``gateway.read`` torn/stall, ``gateway.spool_submit`` enospc/drop;
    serve.py adds ``spool.respond`` drop), the chaos matrix ends in
    ``vft-audit`` PASS (audit.py gateway invariants), and SIGTERM stops
    accepting, flushes in-flight submissions and exits 143 like every
    other worker in the fleet.

**HTTP API** (all request/response bodies JSON unless noted):

  ==========================================  ===========================
  ``POST /v1/extract``                        ``{"video_paths": [...]}``
                                              or ``{"video_urls": [...]}``
                                              (+ optional ``timeout_s``)
                                              -> 202 ``{"id": ...}``;
                                              429/503 when shedding
  ``POST /v1/upload?name=clip.mp4``           raw bytes -> 201/200
                                              ``{"path", "sha256",
                                              "dedup"}`` (octet-stream;
                                              optional
                                              ``X-Content-SHA256``)
  ``GET /v1/requests/{id}``                   terminal record (done or
                                              deadline_exceeded), else
                                              202 with queue state
  ``GET /healthz``                            gateway + backend liveness
                                              (no auth)
  ``GET /metrics``                            Prometheus text of the
                                              gateway registry (no auth)
  ==========================================  ===========================

Auth is ``X-API-Key: <key>`` (or ``Authorization: Bearer <key>``).
Without a tenant table the gateway runs OPEN as the single implicit
tenant ``anon`` — the pre-gateway spool world, reachable over HTTP.

Every admission decision appends to ``{spool}/_gateway_{host_id}.jsonl``
(accepted / rejected / shed / submitted / responded / expired / upload),
the ledger ``vft-audit`` reconciles against the spool's done markers —
per-tenant counts must balance, expired requests must have terminal
records and no responses, and inbox files must all be journaled.

Run it: ``vft-gateway spool_dir=/srv/vft gateway_port=8080
gateway_tenants=/etc/vft/tenants.yml`` (or ``python main.py gateway
...``). docs/serving.md "The network front door" has the full contract.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import signal
import socket
import sys
import threading
import time
import uuid
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from . import serve

INBOX_DIR = "inbox"
GATEWAY_JOURNAL_PREFIX = "_gateway_"
GATEWAY_JOURNAL_GLOB = GATEWAY_JOURNAL_PREFIX + "*.jsonl"

#: journal record schema (one line per admission/lifecycle event)
JOURNAL_SCHEMA = "vft.gateway_event/1"

#: priority classes and their fair-share weights: at sustained
#: saturation the release order converges to 4:2:1 — high-priority
#: tenants degrade LAST, but low never starves (smooth weighted RR)
PRIORITY_WEIGHTS: Dict[str, int] = {"high": 4, "normal": 2, "low": 1}

#: per-tenant defaults when the table omits a field (and the whole
#: ``anon`` tenant when no table is configured)
TENANT_DEFAULTS = {"rate_rps": 50.0, "burst": 100.0,
                   "max_inflight": 64, "priority": "normal"}

_TENANT_NAME_RE = re.compile(r"[a-z0-9_]+\Z")


class Tenant:
    """One row of the API-key table."""

    __slots__ = ("name", "key", "rate_rps", "burst", "max_inflight",
                 "priority")

    def __init__(self, name: str, key: Optional[str], *,
                 rate_rps: float, burst: float, max_inflight: int,
                 priority: str) -> None:
        self.name = name
        self.key = key
        self.rate_rps = float(rate_rps)
        self.burst = float(burst)
        self.max_inflight = int(max_inflight)
        self.priority = str(priority)


def load_tenant_table(path: Optional[str]) -> Dict[str, Tenant]:
    """Parse ``tenants.yml`` into ``{api_key: Tenant}`` — validated
    loudly at launch (a typo'd quota must not silently admit the world).
    ``None`` -> the open single-tenant table (``anon``, keyless).

    Format::

        tenants:
          alpha:
            key: alpha-secret-1     # required per tenant
            rate_rps: 10            # token refill per second
            burst: 20               # bucket capacity
            max_inflight: 8         # accepted-but-unfinished bound
            priority: high          # high | normal | low
    """
    if not path:
        anon = Tenant("anon", None, **TENANT_DEFAULTS)
        return {None: anon}  # type: ignore[dict-item]
    import yaml
    with open(path, encoding="utf-8") as f:
        doc = yaml.safe_load(f) or {}
    rows = doc.get("tenants")
    if not isinstance(rows, dict) or not rows:
        raise ValueError(f"{path}: expected a top-level 'tenants:' "
                         "mapping with at least one tenant")
    out: Dict[str, Tenant] = {}
    for name, row in rows.items():
        name = str(name)
        if not _TENANT_NAME_RE.match(name):
            raise ValueError(
                f"{path}: tenant name {name!r} must match [a-z0-9_]+ — "
                "the name is the request-id prefix and '-' is the "
                "separator (telemetry/context.py tenant_of)")
        row = dict(row or {})
        key = row.get("key")
        if not key or not isinstance(key, str):
            raise ValueError(f"{path}: tenant {name!r} needs a string "
                             "'key' (the API key clients present)")
        if key in out:
            raise ValueError(f"{path}: API key of tenant {name!r} "
                             f"duplicates tenant {out[key].name!r}")
        merged = {**TENANT_DEFAULTS,
                  **{k: row[k] for k in TENANT_DEFAULTS if k in row}}
        if merged["priority"] not in PRIORITY_WEIGHTS:
            raise ValueError(
                f"{path}: tenant {name!r}: priority "
                f"{merged['priority']!r} must be one of "
                f"{'/'.join(PRIORITY_WEIGHTS)}")
        if float(merged["rate_rps"]) <= 0 or float(merged["burst"]) < 1:
            raise ValueError(f"{path}: tenant {name!r}: need "
                             "rate_rps > 0 and burst >= 1")
        if int(merged["max_inflight"]) < 1:
            raise ValueError(f"{path}: tenant {name!r}: need "
                             "max_inflight >= 1")
        out[key] = Tenant(name, key, rate_rps=merged["rate_rps"],
                          burst=merged["burst"],
                          max_inflight=merged["max_inflight"],
                          priority=merged["priority"])
    return out


def validate_gateway_args(args: Dict[str, Any]) -> None:
    """Launch-time validation of the ``gateway_*`` keys (called from
    ``sanity_check`` when any is present, and by ``gateway_main``) —
    same discipline as every other config family: a typo fails HERE."""
    gt = args.get("gateway_tenants")
    if gt is not None:
        if not isinstance(gt, str):
            raise ValueError(f"gateway_tenants={gt!r}: expected a "
                             "tenants.yml path or null (null = open "
                             "single-tenant mode)")
        load_tenant_table(gt)  # raises naming the bad row
    port = args.get("gateway_port")
    if port is not None and (not isinstance(port, int)
                             or not 0 <= int(port) <= 65535):
        raise ValueError(f"gateway_port={port!r}: need an int in "
                         "[0, 65535] (0 = ephemeral, tests)")
    host = args.get("gateway_host")
    if host is not None and not isinstance(host, str):
        raise ValueError(f"gateway_host={host!r}: need a bind address "
                         "string (default 127.0.0.1) or null")
    for key, lo in (("gateway_max_queued", 1), ("gateway_spool_bound", 1),
                    ("gateway_max_body_mb", 1)):
        v = args.get(key)
        if v is not None and int(v) < lo:
            raise ValueError(f"{key}={v!r}: need an int >= {lo}")
    for key in ("gateway_poll_interval_s", "gateway_expire_grace_s",
                "gateway_default_timeout_s"):
        v = args.get(key)
        if v is not None and float(v) <= 0:
            raise ValueError(f"{key}={v!r}: need a float > 0 (or null)")


class TokenBucket:
    """Deterministic token bucket: ``capacity=burst`` tokens refilled at
    ``rate_rps``; ``try_take`` either takes one or reports how long
    until one exists — the number the 429 ``Retry-After`` header
    carries, so well-behaved clients back off exactly enough."""

    def __init__(self, rate_rps: float, burst: float,
                 clock=time.monotonic) -> None:
        self.rate = float(rate_rps)
        self.burst = float(burst)
        self.clock = clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> Tuple[bool, float]:
        with self._lock:
            now = self.clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            return False, (n - self._tokens) / self.rate


class _Pending:
    """One accepted request waiting for fair-share release."""

    __slots__ = ("rid", "tenant", "video_paths", "deadline", "accepted_at",
                 "klass")

    def __init__(self, rid: str, tenant: Tenant, video_paths: List[str],
                 deadline: Optional[float]) -> None:
        self.rid = rid
        self.tenant = tenant
        self.video_paths = list(video_paths)
        self.deadline = deadline
        self.accepted_at = time.time()
        self.klass = tenant.priority


class GatewayServer:
    """The ingress: construct, :meth:`start`, :meth:`stop` (drains).

    Separated from :func:`gateway_main` so tests and the smoke gate can
    drive it in-process on an ephemeral port, exactly like ServeLoop.
    """

    def __init__(self, args: Dict[str, Any],
                 tenants: Optional[Dict[str, Tenant]] = None) -> None:
        self.args = args
        self.spool_dir = str(args["spool_dir"])
        serve.ensure_spool(self.spool_dir)
        self.inbox_dir = os.path.join(self.spool_dir, INBOX_DIR)
        os.makedirs(self.inbox_dir, exist_ok=True)
        self.tenants = (tenants if tenants is not None
                        else load_tenant_table(args.get("gateway_tenants")))
        self.open_mode = None in self.tenants  # keyless anon table
        self.max_queued = int(args.get("gateway_max_queued") or 256)
        self.spool_bound = int(args.get("gateway_spool_bound")
                               or args.get("serve_max_pending") or 64)
        self.poll_s = float(args.get("gateway_poll_interval_s") or 0.25)
        self.expire_grace_s = float(args.get("gateway_expire_grace_s")
                                    or 10.0)
        self.default_timeout_s = args.get("gateway_default_timeout_s")
        self.max_body = int(args.get("gateway_max_body_mb") or 512) << 20

        self._stop = threading.Event()
        self._drained = threading.Event()
        self._lock = threading.Lock()
        self._state = "warming"
        #: {class: deque[_Pending]} — bounded by max_queued in total
        self._queues: Dict[str, deque] = {c: deque()
                                          for c in PRIORITY_WEIGHTS}
        self._credit: Dict[str, float] = {c: 0.0 for c in PRIORITY_WEIGHTS}
        #: accepted-but-not-terminal requests: rid -> state dict
        self._open: Dict[str, dict] = {}
        self._inflight: Dict[str, int] = {}
        self._buckets: Dict[str, TokenBucket] = {}
        self._tenant_tallies: Dict[str, Dict[str, int]] = {}

        # telemetry: heartbeat + journal homed on the SPOOL, like the
        # servers — one `vft-fleet` pass sees gateway and backends alike
        from .config import _plain
        from .telemetry.recorder import TelemetryRecorder
        host_id = f"gw-{socket.gethostname()}-{os.getpid()}"
        self.host_id = host_id
        self.recorder = TelemetryRecorder(
            self.spool_dir,
            run_config=_plain(dict(args)),
            feature_type="gateway",
            interval_s=float(args.get("metrics_interval_s") or 5.0),
            host_id=host_id)
        self.recorder.extra_sections["gateway"] = self._gateway_section
        self.journal_path = os.path.join(
            self.spool_dir, f"{GATEWAY_JOURNAL_PREFIX}"
            f"{re.sub(r'[^A-Za-z0-9._-]+', '-', host_id)}.jsonl")

        port = int(args.get("gateway_port") or 0)
        host = str(args.get("gateway_host") or "127.0.0.1")
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.gateway = self  # type: ignore[attr-defined]
        self.httpd.daemon_threads = True
        self.port = int(self.httpd.server_address[1])
        self._http_thread: Optional[threading.Thread] = None
        self._pump_thread: Optional[threading.Thread] = None

    # -- journal / tallies --------------------------------------------------
    def _j(self, event: str, **fields: Any) -> None:
        from .telemetry import jsonl
        rec = {"schema": JOURNAL_SCHEMA, "event": event,
               "time": round(time.time(), 3)}
        rec.update({k: v for k, v in fields.items() if v is not None})
        jsonl.append_jsonl(self.journal_path, rec)

    def _tally(self, tenant: str, key: str, n: int = 1) -> None:
        with self._lock:
            t = self._tenant_tallies.setdefault(
                tenant, {"accepted": 0, "rejected": 0, "shed": 0,
                         "responded": 0, "expired": 0})
            t[key] = t.get(key, 0) + n
        self.recorder.registry.counter(
            "vft_gateway_requests_total", tenant=tenant, outcome=key).inc(n)

    def _gateway_section(self) -> dict:
        with self._lock:
            queued = {c: len(q) for c, q in self._queues.items()}
            tenants = {t: {**v, "inflight": self._inflight.get(t, 0)}
                       for t, v in sorted(self._tenant_tallies.items())}
            open_count = len(self._open)
            state = self._state
        return {"state": state, "port": self.port,
                "queued": queued, "queued_total": sum(queued.values()),
                "open_requests": open_count,
                "spool_pending": self._spool_pending(),
                "tenants": tenants}

    # -- admission ----------------------------------------------------------
    def tenant_for_key(self, key: Optional[str]) -> Optional[Tenant]:
        if self.open_mode:
            return self.tenants[None]  # type: ignore[index]
        if key is None:
            return None
        return self.tenants.get(key)

    def _bucket(self, tenant: Tenant) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(tenant.name)
            if b is None:
                b = self._buckets[tenant.name] = TokenBucket(
                    tenant.rate_rps, tenant.burst)
            return b

    def _spool_pending(self) -> int:
        try:
            return sum(1 for n in os.listdir(
                os.path.join(self.spool_dir, serve.REQUESTS_DIR))
                if n.endswith(".json"))
        except OSError:
            return 0

    def _backlog_wait_s(self, klass: str) -> float:
        """Estimated seconds until the release loop drains what is
        queued ahead of a new ``klass`` request: queue depth over the
        class's weighted-fair share of the per-tick spool budget. This
        is the backlog half of an honest 429 Retry-After — refill alone
        tells a client when it has a TOKEN, not when the edge queue has
        ROOM, so under backlog refill-only retries thunder back into
        the same full queue."""
        with self._lock:
            depths = {c: len(q) for c, q in self._queues.items()}
        qlen = depths.get(klass, 0)
        if not qlen:
            return 0.0
        active = [c for c, d in depths.items() if d]
        share = PRIORITY_WEIGHTS[klass] / float(
            sum(PRIORITY_WEIGHTS[c] for c in active))
        per_tick = max(1.0, self.spool_bound * share)
        return qlen / per_tick * self.poll_s

    def _shed_reason(self) -> Optional[str]:
        """503-worthy overload, from signals the spool already exports:
        a full edge queue, or a backend the heartbeats say is DEAD
        (exited/stalled). 'absent' (no server started yet) is NOT shed —
        the spool is the decoupling; requests queue and deadlines bound
        the wait."""
        with self._lock:
            if sum(len(q) for q in self._queues.values()) >= \
                    self.max_queued:
                return "queue_full"
        state = serve.server_state(self.spool_dir).get("state")
        if state in ("exited", "stalled"):
            return f"backend_{state}"
        return None

    def admit(self, tenant: Tenant, video_paths: List[str],
              timeout_s: Optional[float]
              ) -> Tuple[int, dict, Dict[str, str]]:
        """The whole admission decision for one extract request:
        ``(http_status, body, extra_headers)``. 202 accepts; 429 is a
        per-tenant quota no (rate or in-flight, with Retry-After); 503
        is systemic shed. Every outcome is journaled."""
        rid = f"{tenant.name}-{uuid.uuid4().hex[:12]}"
        ok, retry_after = self._bucket(tenant).try_take()
        if not ok:
            retry = max(1, int(retry_after
                               + self._backlog_wait_s(tenant.priority)
                               + 0.999))
            self._tally(tenant.name, "rejected")
            self._j("rejected", id=rid, tenant=tenant.name, reason="rate",
                    retry_after_s=retry)
            return (429,
                    {"error": f"tenant {tenant.name} over rate limit "
                              f"({tenant.rate_rps}/s, burst "
                              f"{tenant.burst:g}); retry later",
                     "retry_after_s": retry},
                    {"Retry-After": str(retry)})
        with self._lock:
            inflight = self._inflight.get(tenant.name, 0)
        if inflight >= tenant.max_inflight:
            retry = max(1, int(self.poll_s * 4
                               + self._backlog_wait_s(tenant.priority)
                               + 0.999))
            self._tally(tenant.name, "rejected")
            self._j("rejected", id=rid, tenant=tenant.name,
                    reason="inflight", retry_after_s=retry)
            return (429,
                    {"error": f"tenant {tenant.name} at max_inflight="
                              f"{tenant.max_inflight}; retry later",
                     "retry_after_s": retry},
                    {"Retry-After": str(retry)})
        reason = self._shed_reason()
        if reason:
            retry = max(1, int(self.poll_s * 8 + 0.999))
            self._tally(tenant.name, "shed")
            self._j("shed", id=rid, tenant=tenant.name, reason=reason,
                    retry_after_s=retry)
            return (503,
                    {"error": f"load shed ({reason}); retry later",
                     "retry_after_s": retry},
                    {"Retry-After": str(retry)})
        timeout = (timeout_s if timeout_s is not None
                   else self.default_timeout_s)
        # deadline from the GATEWAY clock + the requested DURATION:
        # client wall-clock skew cannot expire a request early (pinned
        # by tests/test_gateway.py clock-skew case)
        deadline = (round(time.time() + float(timeout), 3)
                    if timeout is not None else None)
        p = _Pending(rid, tenant, video_paths, deadline)
        with self._lock:
            self._queues[p.klass].append(p)
            self._inflight[tenant.name] = \
                self._inflight.get(tenant.name, 0) + 1
            self._open[rid] = {"state": "queued", "tenant": tenant.name,
                               "deadline": deadline}
        self._tally(tenant.name, "accepted")
        self._j("accepted", id=rid, tenant=tenant.name, klass=p.klass,
                videos=len(video_paths), deadline=deadline)
        return (202, {"id": rid, "status": "queued",
                      "class": p.klass, "deadline": deadline}, {})

    # -- ingestion ----------------------------------------------------------
    def store_upload(self, tenant: Tenant, data: bytes,
                     name: Optional[str]) -> Tuple[int, dict]:
        """Content-addressed inbox store: sha256 names the file, so a
        retried identical upload is a dedup hit — never duplicate bytes,
        never duplicate downstream work (the feature cache keys on the
        same content hash)."""
        from .utils.sinks import _write_bytes_atomic
        sha = hashlib.sha256(data).hexdigest()
        ext = ""
        if name:
            suffix = os.path.splitext(os.path.basename(str(name)))[1]
            if re.match(r"\.[A-Za-z0-9]{1,8}\Z", suffix or ""):
                ext = suffix.lower()
        path = os.path.join(self.inbox_dir, f"{sha[:16]}{ext}")
        if os.path.exists(path):
            self._j("upload", tenant=tenant.name, path=path, sha256=sha,
                    bytes=len(data), dedup=True)
            self.recorder.registry.counter(
                "vft_gateway_upload_dedup_total", tenant=tenant.name).inc()
            return 200, {"path": path, "sha256": sha, "dedup": True}
        _write_bytes_atomic(path, data)
        self._j("upload", tenant=tenant.name, path=path, sha256=sha,
                bytes=len(data), dedup=False)
        self.recorder.registry.counter(
            "vft_gateway_upload_stored_total", tenant=tenant.name).inc()
        return 201, {"path": path, "sha256": sha, "dedup": False}

    def fetch_url(self, tenant: Tenant, url: str) -> str:
        """URL-fetch ingestion into the same content-addressed inbox
        (``file://`` and ``http(s)://``). The body streams through the
        ``gateway.read`` injection site like a client upload."""
        from urllib.request import urlopen
        chunks: List[bytes] = []
        total = 0
        with urlopen(url, timeout=30) as r:
            while True:
                _fire_read(total)
                chunk = r.read(1 << 20)
                if not chunk:
                    break
                total += len(chunk)
                if total > self.max_body:
                    raise ValueError(f"{url}: body over "
                                     f"{self.max_body >> 20} MB")
                chunks.append(chunk)
        name = os.path.basename(url.split("?", 1)[0]) or None
        _code, body = self.store_upload(tenant, b"".join(chunks), name)
        return str(body["path"])

    # -- release (weighted fair share) --------------------------------------
    def _pick_class(self) -> Optional[str]:
        """Smooth weighted round-robin over the NON-EMPTY classes:
        credits accumulate by weight, the richest class releases and
        pays the total back — 4:2:1 over any window, no starvation.
        Caller holds the lock."""
        nonempty = [c for c in PRIORITY_WEIGHTS if self._queues[c]]
        if not nonempty:
            return None
        total = sum(PRIORITY_WEIGHTS[c] for c in nonempty)
        best = None
        for c in nonempty:
            self._credit[c] += PRIORITY_WEIGHTS[c]
            if best is None or self._credit[c] > self._credit[best]:
                best = c
        self._credit[best] -= total
        return best

    def _release_some(self) -> None:
        """Move queued requests into the spool while the backlog is
        under ``gateway_spool_bound`` — the spool never grows past the
        admission bound, so a slow backend backs pressure up to the
        edge (where it becomes 429/503) instead of into an unbounded
        directory."""
        while not self._stop.is_set():
            if self._spool_pending() >= self.spool_bound:
                return
            with self._lock:
                klass = self._pick_class()
                p = self._queues[klass].popleft() if klass else None
            if p is None:
                return
            if p.deadline is not None and time.time() >= p.deadline:
                self._expire_edge(p.rid, p.tenant.name, p.deadline,
                                  "queued")
                continue
            if not self._submit(p):
                with self._lock:
                    self._queues[p.klass].appendleft(p)
                return  # transient submit failure: retry next pump pass

    def _submit(self, p: _Pending) -> bool:
        from .utils import inject
        try:
            fault = inject.fire("gateway.spool_submit", request=p.rid)
            if fault is not None and fault.kind == "drop":
                # the submit is LOST after we believe it landed (a dying
                # NFS client, a torn rename): the deadline sweep is the
                # recovery path — past deadline+grace with no terminal
                # record, the gateway writes the expired record itself
                pass
            else:
                serve.submit_request(self.spool_dir, p.video_paths,
                                     request_id=p.rid, deadline=p.deadline)
        except OSError as e:
            self._j("submit_error", id=p.rid, tenant=p.tenant.name,
                    error=f"{type(e).__name__}: {e}")
            return False
        with self._lock:
            st = self._open.get(p.rid)
            if st is not None:
                st["state"] = "submitted"
        self._j("submitted", id=p.rid, tenant=p.tenant.name)
        return True

    # -- terminal bookkeeping ------------------------------------------------
    def _close(self, rid: str, tenant: str, outcome: str,
               status: Optional[str] = None) -> None:
        with self._lock:
            self._open.pop(rid, None)
            if self._inflight.get(tenant, 0) > 0:
                self._inflight[tenant] -= 1
        self._tally(tenant, outcome)
        self._j(outcome, id=rid, tenant=tenant, status=status)

    def _expire_edge(self, rid: str, tenant: str,
                     deadline: Optional[float], where: str) -> None:
        """Terminal ``deadline_exceeded`` written BY THE GATEWAY — for
        requests that never reached a server (still queued at the edge,
        withdrawn from the spool, or lost in flight)."""
        from .telemetry import jsonl
        rec = {"schema": serve.RESPONSE_SCHEMA, "id": rid,
               "status": "deadline_exceeded", "tenant": tenant,
               "time": round(time.time(), 3), "deadline": deadline,
               "expired_at": where, "videos": {}, "processed": 0}
        jsonl.write_json_atomic(
            os.path.join(self.spool_dir, serve.EXPIRED_DIR,
                         f"{rid}.json"), rec)
        self._close(rid, tenant, "expired", status="deadline_exceeded")

    def _sweep(self) -> None:
        """One pump pass of lifecycle bookkeeping: expire edge-queued
        requests past deadline, reap terminal records, and recover
        submitted requests the backend will never answer (withdraw from
        ``requests/`` at deadline, or declare lost past
        ``deadline + gateway_expire_grace_s``)."""
        now = time.time()
        with self._lock:
            expired_edge = []
            for q in self._queues.values():
                keep = deque()
                for p in q:
                    if p.deadline is not None and now >= p.deadline:
                        expired_edge.append(p)
                    else:
                        keep.append(p)
                q.clear()
                q.extend(keep)
            open_now = [(rid, dict(st)) for rid, st in self._open.items()
                        if st["state"] == "submitted"]
        for p in expired_edge:
            self._expire_edge(p.rid, p.tenant.name, p.deadline, "queued")
        for rid, st in open_now:
            term = serve.read_terminal(self.spool_dir, rid)
            if term is not None:
                outcome = ("expired"
                           if term.get("status") == "deadline_exceeded"
                           else "responded")
                self._close(rid, st["tenant"], outcome,
                            status=term.get("status"))
                continue
            deadline = st.get("deadline")
            if deadline is None or now < float(deadline):
                continue
            # past deadline with no terminal record: withdraw the spool
            # request so no server starts it (unlink is atomic against
            # the claim rename — exactly one side wins)
            try:
                os.unlink(os.path.join(self.spool_dir, serve.REQUESTS_DIR,
                                       f"{rid}.json"))
                self._expire_edge(rid, st["tenant"], float(deadline),
                                  "spooled")
                continue
            except OSError:
                pass  # claimed (server will expire it) — or lost
            if now >= float(deadline) + self.expire_grace_s:
                if serve.read_terminal(self.spool_dir, rid) is None:
                    # lost in flight (dropped submit, server died holding
                    # the claim): the gateway is the terminal writer of
                    # last resort, so the caller ALWAYS gets an answer
                    self._expire_edge(rid, st["tenant"], float(deadline),
                                      "lost")

    # -- lifecycle ----------------------------------------------------------
    def _pump(self) -> None:
        while not self._stop.is_set():
            try:
                self._release_some()
                self._sweep()
            except Exception as e:  # the pump must survive anything
                print(f"vft-gateway: pump error: {type(e).__name__}: {e}",
                      file=sys.stderr)
            self._stop.wait(self.poll_s)

    def start(self) -> "GatewayServer":
        self.recorder.start()
        with self._lock:
            self._state = "ready"
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="vft-gateway-http", daemon=True)
        self._http_thread.start()
        self._pump_thread = threading.Thread(
            target=self._pump, name="vft-gateway-pump", daemon=True)
        self._pump_thread.start()
        print(f"vft-gateway: ready — http://{self.httpd.server_address[0]}"
              f":{self.port} spool={self.spool_dir} "
              f"tenants={'open' if self.open_mode else len(self.tenants)}")
        return self

    def stop(self) -> None:
        """SIGTERM semantics: stop ACCEPTING (the listener closes — new
        connections are refused, never silently dropped mid-queue),
        flush every accepted-but-unsubmitted request into the spool
        (they were promised a 202; the backend + deadlines own them
        now), write the final heartbeat, and let :meth:`run` exit 143."""
        if self._stop.is_set():
            return
        with self._lock:
            self._state = "draining"
        if self._http_thread is not None:
            self.httpd.shutdown()  # blocks until serve_forever returns
        self.httpd.server_close()
        self._stop.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=10)
        # flush in-flight submissions, deadline-expired ones excepted
        while True:
            with self._lock:
                klass = next((c for c in PRIORITY_WEIGHTS
                              if self._queues[c]), None)
                p = self._queues[klass].popleft() if klass else None
            if p is None:
                break
            if p.deadline is not None and time.time() >= p.deadline:
                self._expire_edge(p.rid, p.tenant.name, p.deadline,
                                  "queued")
            else:
                self._submit(p)
        self._j("drain", open=len(self._open))
        with self._lock:
            self._state = "exited"
        self.recorder.close()
        self._drained.set()

    def run(self) -> int:
        """Block until signalled (gateway_main wires SIGTERM/SIGINT to
        :meth:`stop`); returns 143 — the fleet's preemption contract."""
        self.start()
        self._stop.wait()
        self._drained.wait(timeout=60)
        return 143


# -- injection helper ---------------------------------------------------------

def _fire_read(progress: int) -> Optional[str]:
    """The ``gateway.read`` chaos site, shared by upload-body reads and
    URL fetches: raise-kind faults raise here (EIO mid-body); ``torn``
    tells the caller to cut the stream short; ``stall`` simulates the
    slow client by sleeping briefly before the read continues."""
    from .utils import inject
    fault = inject.fire("gateway.read", at_byte=progress)
    if fault is None:
        return None
    if fault.kind == "stall":
        time.sleep(0.2)
        return None
    return fault.kind


# -- the HTTP layer -----------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    server_version = "vft-gateway/1"
    protocol_version = "HTTP/1.1"

    @property
    def gw(self) -> GatewayServer:
        return self.server.gateway  # type: ignore[attr-defined]

    def log_message(self, fmt: str, *args: Any) -> None:
        pass  # admission outcomes are journaled; stdio stays quiet

    # -- plumbing -----------------------------------------------------------
    def _send(self, code: int, obj: dict,
              headers: Optional[Dict[str, str]] = None) -> None:
        body = (json.dumps(obj, sort_keys=True) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # the client hung up; the journal already has the truth

    def _tenant(self) -> Optional[Tenant]:
        key = self.headers.get("X-API-Key")
        if key is None:
            auth = self.headers.get("Authorization") or ""
            if auth.startswith("Bearer "):
                key = auth[len("Bearer "):].strip()
        tenant = self.gw.tenant_for_key(key)
        if tenant is None:
            self._send(401, {"error": "unknown or missing API key "
                                      "(X-API-Key / Authorization: "
                                      "Bearer)"})
        return tenant

    def _read_body(self) -> Optional[bytes]:
        """The request body, through the ``gateway.read`` chaos site.
        Returns None after responding (411/413/400) on any read
        problem — a torn client body is a CLIENT error, answered
        explicitly, never a half-ingested request."""
        length = self.headers.get("Content-Length")
        if length is None:
            self._send(411, {"error": "Content-Length required"})
            return None
        length = int(length)
        if length > self.gw.max_body:
            self._send(413, {"error": f"body over "
                                      f"{self.gw.max_body >> 20} MB"})
            return None
        try:
            kind = _fire_read(0)
            if kind == "torn":
                data = self.rfile.read(max(1, length // 2))
            else:
                data = self.rfile.read(length)
        except OSError as e:
            self._send(400, {"error": f"body read failed: {e}"})
            return None
        if len(data) != length:
            self._send(400, {"error": f"torn body: read {len(data)} of "
                                      f"{length} bytes — retry the "
                                      "upload (identical bytes dedup)"})
            return None
        return data

    # -- routes -------------------------------------------------------------
    def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
        from urllib.parse import urlparse
        path = urlparse(self.path).path
        if path == "/healthz":
            gw = self.gw
            self._send(200, {"gateway": gw._gateway_section(),
                             "backend": serve.server_state(gw.spool_dir)})
            return
        if path == "/metrics":
            from .telemetry.metrics import prometheus_text
            text = prometheus_text(self.gw.recorder.registry.to_dict())
            body = text.encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        m = re.match(r"/v1/requests/([A-Za-z0-9_-]+)\Z", path)
        if m:
            tenant = self._tenant()
            if tenant is None:
                return
            rid = m.group(1)
            from .telemetry.context import tenant_of
            owner = tenant_of(rid)
            if owner != tenant.name and \
                    not (owner is None and self.gw.open_mode):
                # tenant isolation: one tenant can never observe (or
                # even probe the existence of) another tenant's request
                self._send(403, {"error": "request belongs to another "
                                          "tenant"})
                return
            term = serve.read_terminal(self.gw.spool_dir, rid)
            if term is not None:
                self._send(200, term)
                return
            with self.gw._lock:
                st = self.gw._open.get(rid)
            if st is not None:
                self._send(202, {"id": rid, "status": st["state"],
                                 "deadline": st.get("deadline")})
                return
            self._send(404, {"error": f"unknown request {rid}"})
            return
        self._send(404, {"error": f"no route {path}"})

    def do_POST(self) -> None:  # noqa: N802
        from urllib.parse import parse_qs, urlparse
        parsed = urlparse(self.path)
        tenant = self._tenant()
        if tenant is None:
            return
        if parsed.path == "/v1/upload":
            ok, retry_after = self.gw._bucket(tenant).try_take()
            if not ok:
                retry = max(1, int(retry_after + 0.999))
                self.gw._tally(tenant.name, "rejected")
                self.gw._j("rejected", tenant=tenant.name,
                           reason="rate_upload", retry_after_s=retry)
                self._send(429, {"error": "over rate limit; retry later",
                                 "retry_after_s": retry},
                           {"Retry-After": str(retry)})
                return
            data = self._read_body()
            if data is None:
                return
            want = self.headers.get("X-Content-SHA256")
            if want and hashlib.sha256(data).hexdigest() != want.lower():
                self._send(400, {"error": "X-Content-SHA256 mismatch — "
                                          "body corrupted in transit"})
                return
            name = (parse_qs(parsed.query).get("name") or [None])[0]
            code, body = self.gw.store_upload(tenant, data, name)
            self._send(code, body)
            return
        if parsed.path == "/v1/extract":
            data = self._read_body()
            if data is None:
                return
            try:
                req = json.loads(data.decode("utf-8"))
                if not isinstance(req, dict):
                    raise ValueError("body must be a JSON object")
            except ValueError as e:
                self._send(400, {"error": f"bad JSON body: {e}"})
                return
            paths = [str(v) for v in req.get("video_paths") or []]
            urls = [str(u) for u in req.get("video_urls") or []]
            if not paths and not urls:
                self._send(400, {"error": "need video_paths and/or "
                                          "video_urls"})
                return
            timeout_s = req.get("timeout_s")
            if timeout_s is not None and float(timeout_s) <= 0:
                self._send(400, {"error": f"timeout_s={timeout_s!r}: "
                                          "need a float > 0 or null"})
                return
            for url in urls:
                try:
                    paths.append(self.gw.fetch_url(tenant, url))
                except Exception as e:
                    self._send(502, {"error": f"fetch {url!r} failed: "
                                              f"{type(e).__name__}: {e}"})
                    return
            code, body, headers = self.gw.admit(
                tenant, paths,
                float(timeout_s) if timeout_s is not None else None)
            self._send(code, body, headers)
            return
        self._send(404, {"error": f"no route {parsed.path}"})


# -- entry point --------------------------------------------------------------

def gateway_main(argv: Optional[List[str]] = None) -> None:
    """Entry point: ``vft-gateway spool_dir=<dir> [key=value ...]``
    (or ``python main.py gateway ...``)."""
    from .config import parse_dotlist
    argv = list(sys.argv[1:] if argv is None else argv)
    cli_args = parse_dotlist(argv)
    if "spool_dir" not in cli_args:
        raise SystemExit(
            "Usage: vft-gateway spool_dir=<dir> [gateway_port=8080] "
            "[gateway_tenants=tenants.yml] [key=value ...]   "
            "(docs/serving.md)")
    validate_gateway_args(cli_args)
    from .utils import inject
    inj = cli_args.get("inject")
    if inj is not None:
        inject.parse_plan(str(inj))  # fail a typo'd plan at launch
    inject_plan = inject.arm_for_run(inj)
    gw = GatewayServer(cli_args)
    if threading.current_thread() is threading.main_thread():
        def _on_term(signo, frame):
            print("vft-gateway: SIGTERM — draining")
            threading.Thread(target=gw.stop, daemon=True).start()
        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)
    try:
        rc = gw.run()
    finally:
        if inject_plan is not None:
            print(inject_plan.summary())
        inject.disarm()
    if rc:
        raise SystemExit(rc)


def main(argv: Optional[List[str]] = None) -> None:
    gateway_main(argv)


if __name__ == "__main__":
    main()
