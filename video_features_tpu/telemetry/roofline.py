"""Roofline observatory: self-measuring MFU accounting per family.

Until round 12 only r2plus1d had MFU accounting, and it lived as a
hand-computed table in docs/performance.md — S3D's and CLIP's throughput
rows had no saturated-vs-sandbagged verdict, and nothing in CI would
notice a change silently halving a family's device efficiency. In the
compiler-first spirit of PAPERS.md (arxiv 2603.09555) the source of
truth here is the compiler's own cost model — ``lowered.cost_analysis()``,
the exact method behind the old hand table — captured automatically:

  - **cost cards** (:meth:`RooflineObserver.observe_dispatch`, hooked in
    ``parallel/mesh.py DataParallelApply.dispatch``/``__call__`` — the
    same pre-construction seam compile_cache.py attaches at, observed at
    the dispatch boundary): for every distinct ``(runner, padded batch
    shape)`` a one-time AOT lowering records XLA-reported FLOPs, bytes
    accessed and the derived arithmetic intensity; every further
    dispatch just bumps a counter (one global read when ``roofline`` is
    off, one dict hit when on);
  - **measured time** rides the existing ``profiler.stage`` call sites
    (utils/profiling.py): the observer chains onto the stage hook and
    accumulates the steady-state ``forward`` (device stall under async
    dispatch; true H2D+forward+D2H on the synchronous path) and ``h2d``
    stage seconds per family — no new timers in the hot loops;
  - **peak registry** (:data:`PEAK_REGISTRY` + :func:`peak_for_device`):
    known device kinds carry their practical peak (v5e: the 127-TFLOPS
    2048^3-bf16-matmul calibration from docs/performance.md) and HBM
    bandwidth; unknown kinds fall back to :func:`measure_peak` — the
    same 2048^3 bf16 matmul plus a fused read-reduce bandwidth probe —
    cached per device kind so the microbench runs once per machine.

Joining the three yields, per family: effective TFLOPS
(``flops_dispatched / forward_seconds``), **MFU** against the practical
peak, and a roofline position that resolves to ONE of four verdicts
(:func:`classify`):

  ====================  ====================================================
  ``compute-bound``     the device window is explained by FLOPs at peak —
                        saturated; faster means a different program
  ``bandwidth-bound``   below the ridge point and the window is explained
                        by bytes at peak HBM bandwidth — fuse or shrink
                        the wire, not the math
  ``launch-overhead-bound``  neither FLOPs nor bytes explain the window:
                        fixed per-dispatch cost dominates — batch wider
                        or fuse launches
  ``host-bound``        (sandbagged) the device sat idle most of the wall
                        clock waiting for the host — decode/transform is
                        the wall, the chip is not the story
  ====================  ====================================================

Artifacts: ``{output_path}/_roofline.json`` under the checked-in
``telemetry/roofline.schema.json`` (per-host in fleet=queue dirs, like
traces), a live ``roofline`` section in heartbeats + ``_run.json``
(telemetry/recorder.py), per-family lines in ``vft-top``, fleet roll-up
+ ``vft_roofline_mfu{family}`` prom gauges in ``vft-fleet``, and the
``vft-roofline`` report (:func:`report_main`) rendering the MFU table
with an optional per-op ``jax.profiler`` merge. bench.py stamps
``mfu``/``effective_tflops`` on its device rows from the same
:func:`program_cost` arithmetic, so ``bench_history.py
--fail-on-regression`` now guards device efficiency, not just
throughput. See docs/observability.md "The roofline pillar".

Caveat worth stating once: under async dispatch ``forward`` is the
host's *stall* time materializing results — a lower bound on device
busy time — so a fully-hidden device reads as a small ``forward`` with
a low ``device_share``, which is exactly the ``host-bound`` verdict;
the MFU number is then a ceiling estimate and the verdict, not the
percentage, is the finding. Device-resident fenced loops (bench.py)
have ``forward == device time`` and their MFU is exact.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .jsonl import write_json_atomic
from .spans import current_span

SCHEMA_VERSION = "vft.roofline/1"
ROOFLINE_FILENAME = "_roofline.json"

#: the four roofline positions (docstring table); the schema enum and
#: check_roofline_schema.py pin this exact set
VERDICTS = ("compute-bound", "bandwidth-bound", "launch-overhead-bound",
            "host-bound")

#: classification thresholds (classify()): device busy share below which
#: the device is sandbagged by the host, and the explained-time floor
#: below which fixed launch overhead is the only remaining account
HOST_BOUND_SHARE = 0.35
LAUNCH_FRAC = 0.15

#: emitter field lists — check_roofline_schema.py asserts these equal the
#: checked-in schema's properties, so emitter and contract cannot drift
ROOFLINE_FIELDS = ("schema", "run_id", "host_id", "feature_type", "time",
                   "wall_s", "device", "families")
DEVICE_FIELDS = ("platform", "device_kind", "peak_tflops", "nominal_tflops",
                 "peak_gbps", "source")
FAMILY_FIELDS = ("programs", "flops_total", "bytes_total", "dispatches",
                 "forward_s", "forward_calls", "h2d_s", "wall_s",
                 "device_share", "arithmetic_intensity", "effective_tflops",
                 "effective_tflops_wall", "mfu", "verdict")
CARD_FIELDS = ("shape", "dtype", "batch", "flops", "bytes", "intensity",
               "dispatches")

#: per-device-kind practical peaks. ``peak_tflops`` is the DENOMINATOR of
#: every MFU here: the measured practical ceiling where we have one (v5e:
#: a 2048^3 bf16 matmul measures ~127 TFLOPS on the bench chip, 64% of
#: nominal 197 — docs/performance.md), the public nominal bf16 spec
#: otherwise. ``peak_gbps`` is HBM bandwidth (public specs). Matching is
#: by normalized substring, so "TPU v5 lite" and "TPU v5e" resolve alike.
PEAK_REGISTRY: Dict[str, Dict[str, float]] = {
    "tpu v5 lite": {"peak_tflops": 127.0, "nominal_tflops": 197.0,
                    "peak_gbps": 819.0},
    "tpu v5e": {"peak_tflops": 127.0, "nominal_tflops": 197.0,
                "peak_gbps": 819.0},
    "tpu v5p": {"peak_tflops": 459.0, "nominal_tflops": 459.0,
                "peak_gbps": 2765.0},
    "tpu v4": {"peak_tflops": 275.0, "nominal_tflops": 275.0,
               "peak_gbps": 1228.0},
    "tpu v3": {"peak_tflops": 123.0, "nominal_tflops": 123.0,
               "peak_gbps": 900.0},
    "tpu v6": {"peak_tflops": 918.0, "nominal_tflops": 918.0,
               "peak_gbps": 1640.0},
}


def roofline_filename(host_id: Optional[str] = None) -> str:
    """``_roofline.json``, or the per-host ``_roofline_{host_id}.json``
    when N fleet=queue workers co-own one output dir (the trace-file
    discipline: the last worker to exit must not overwrite its
    siblings' accounting)."""
    if host_id is None:
        return ROOFLINE_FILENAME
    import re
    safe = re.sub(r"[^A-Za-z0-9._-]+", "-", str(host_id))
    return f"_roofline_{safe}.json"


# -- the compiler's own cost model -------------------------------------------

def program_cost(fn, *args) -> Dict[str, float]:
    """XLA's cost analysis for one jitted program at these argument
    shapes: ``{"flops": F, "bytes": B}`` — the same
    ``lowered.cost_analysis()`` numbers the old hand table in
    docs/performance.md was derived from (5,039 GF/batch for the B=64
    r21d program). One AOT lowering per call; callers cache per shape."""
    lowered = fn.lower(*args)
    ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        ca = {}
    return {"flops": float(ca.get("flops", 0.0) or 0.0),
            "bytes": float(ca.get("bytes accessed", 0.0) or 0.0)}


# -- peak resolution ----------------------------------------------------------

def _peak_cache_root() -> str:
    return os.environ.get(
        "VFT_ROOFLINE_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "video_features_tpu", "roofline"))


def _peak_cache_path(device_kind: str, cache_dir: Optional[str]) -> str:
    import re
    safe = re.sub(r"[^A-Za-z0-9._-]+", "-", str(device_kind)) or "unknown"
    return os.path.join(cache_dir or _peak_cache_root(),
                        f"peak_{safe}.json")


def measure_peak(n: int = 2048, band_elems: int = 1 << 25,
                 calls: int = 4, trials: int = 3) -> Dict[str, float]:
    """Microbench the device's practical roofline corners, the
    performance.md calibration method generalized:

      - **peak_tflops**: a ``n``^3 bf16 matmul (default 2048^3 — the
        exact probe that measured 127 TFLOPS on the v5e bench chip),
        reduced to a scalar IN-GRAPH so the fence is a one-float D2H
        read (``block_until_ready`` alone has acked early through
        tunneled dev chips — parallel/mesh.py ``settle``);
      - **peak_gbps**: a fused multiply-add-reduce over ``band_elems``
        f32 elements — one HBM read pass, scalar out — i.e. achievable
        read bandwidth, the roofline's other roof.

    Best of ``trials``, ``calls`` chained dispatches per trial (the
    device's in-order queue makes the final scalar read fence them
    all). Seconds on a cold CPU, microseconds to re-read once cached —
    see :func:`peak_for_device` for the per-device-kind cache."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(0)
    a = jax.device_put(rng.standard_normal((n, n), dtype=np.float32)
                       .astype(jnp.bfloat16))
    b = jax.device_put(rng.standard_normal((n, n), dtype=np.float32)
                       .astype(jnp.bfloat16))
    mm = jax.jit(lambda x, y: jnp.sum((x @ y).astype(jnp.float32)))
    float(mm(a, b))  # compile + warm
    best_tf = 0.0
    for _ in range(trials):
        t0 = time.perf_counter()
        out = None
        for _ in range(calls):
            out = mm(a, b)
        float(out)  # D2H fence
        dt = time.perf_counter() - t0
        best_tf = max(best_tf, calls * 2.0 * n ** 3 / dt / 1e12)

    x = jax.device_put(np.arange(band_elems, dtype=np.float32))
    rd = jax.jit(lambda v: jnp.sum(v * 1.0001 + 0.5))
    float(rd(x))
    best_gb = 0.0
    for _ in range(trials):
        t0 = time.perf_counter()
        out = None
        for _ in range(calls):
            out = rd(x)
        float(out)
        dt = time.perf_counter() - t0
        best_gb = max(best_gb, calls * band_elems * 4.0 / dt / 1e9)
    return {"peak_tflops": round(best_tf, 3), "peak_gbps": round(best_gb, 2),
            "matmul_n": n, "band_bytes": band_elems * 4}


def registry_peak(device_kind: str) -> Optional[Dict[str, float]]:
    """Registry entry for a device kind (normalized substring match), or
    None for unknown hardware (the microbench fallback's cue)."""
    norm = " ".join(str(device_kind).lower().split())
    for key, entry in PEAK_REGISTRY.items():
        if key in norm or norm in key:
            return dict(entry)
    return None


def peak_for_device(device_kind: Optional[str] = None,
                    platform: Optional[str] = None,
                    cache_dir: Optional[str] = None,
                    measure: bool = True,
                    measure_fn=measure_peak) -> Optional[Dict[str, Any]]:
    """The MFU denominator for this process's device, resolved in
    precedence order:

      1. ``VFT_ROOFLINE_PEAK="tflops,gbps"`` env override (tests, CI
         smokes, operators with their own calibration);
      2. :data:`PEAK_REGISTRY` by device kind;
      3. a cached prior :func:`measure_peak` result for this kind;
      4. the microbench itself (cached for next time) — skipped when
         ``measure=False`` (returns None: heartbeat snapshots must
         never block on a matmul).

    Returns ``{platform, device_kind, peak_tflops, nominal_tflops,
    peak_gbps, source}`` (the schema's ``device`` block)."""
    env = os.environ.get("VFT_ROOFLINE_PEAK")
    if env:
        try:
            tf, gb = (float(v) for v in env.split(",")[:2])
        except ValueError:
            raise ValueError(
                f"VFT_ROOFLINE_PEAK={env!r}: expected 'tflops,gbps' "
                "(e.g. '127,819')") from None
    if device_kind is None or platform is None:
        try:
            import jax
            devs = jax.local_devices()
            if device_kind is None:
                device_kind = getattr(devs[0], "device_kind", "?") \
                    if devs else "?"
            if platform is None:
                platform = devs[0].platform if devs else "?"
        except Exception:
            pass  # env-pinned peaks must work without a live backend
    if env:
        return {"platform": platform, "device_kind": device_kind,
                "peak_tflops": tf, "nominal_tflops": tf, "peak_gbps": gb,
                "source": "env"}
    base = {"platform": platform, "device_kind": device_kind}
    reg = registry_peak(device_kind)
    if reg is not None:
        return {**base, **reg, "source": "registry"}
    cache_path = _peak_cache_path(device_kind, cache_dir)
    try:
        with open(cache_path, encoding="utf-8") as f:
            cached = json.load(f)
        if isinstance(cached, dict) and cached.get("peak_tflops"):
            return {**base, "peak_tflops": float(cached["peak_tflops"]),
                    "nominal_tflops": None,
                    "peak_gbps": float(cached.get("peak_gbps") or 0) or None,
                    "source": "microbench (cached)"}
    except (OSError, ValueError):
        pass
    if not measure:
        return None
    m = measure_fn()
    try:
        write_json_atomic(cache_path, {**m, "device_kind": device_kind,
                                       "time": round(time.time(), 3)})
    except OSError:
        pass  # unwritable cache root: measure again next process
    return {**base, "peak_tflops": m["peak_tflops"], "nominal_tflops": None,
            "peak_gbps": m["peak_gbps"], "source": "microbench"}


# -- the verdict --------------------------------------------------------------

def classify(flops: float, bytes_accessed: float, forward_s: float,
             wall_s: float, peak_tflops: Optional[float],
             peak_gbps: Optional[float]) -> Optional[str]:
    """One of the four :data:`VERDICTS` for a family's run, or None when
    the inputs cannot support a verdict (no dispatches, no peak).

    The attribution is the roofline identity read backwards: the minimum
    device time for the dispatched work is
    ``max(flops/peak_flops, bytes/peak_bw)``; whichever term explains
    the *observed* device window is the bound, and a window neither term
    explains (both fractions under :data:`LAUNCH_FRAC`) is fixed
    per-dispatch overhead. Before any of that, a device window that is a
    small share of the wall clock (< :data:`HOST_BOUND_SHARE`) means the
    chip sat idle waiting to be fed — host-bound, the sandbagged case
    ROADMAP item 5 wanted named."""
    if not flops or forward_s is None or forward_s <= 0 or not wall_s:
        return None
    if forward_s / wall_s < HOST_BOUND_SHARE:
        return "host-bound"
    if not peak_tflops:
        return None
    compute_frac = flops / (peak_tflops * 1e12) / forward_s
    bw_frac = (bytes_accessed / (peak_gbps * 1e9) / forward_s
               if peak_gbps else 0.0)
    if max(compute_frac, bw_frac) < LAUNCH_FRAC:
        return "launch-overhead-bound"
    return "compute-bound" if compute_frac >= bw_frac else "bandwidth-bound"


# -- the observer -------------------------------------------------------------

_lock = threading.Lock()
_active: Optional["RooflineObserver"] = None


def active() -> Optional["RooflineObserver"]:
    return _active


def observe_dispatch(runner, padded) -> None:
    """The mesh-layer hook (parallel/mesh.py DataParallelApply): one
    global read when roofline is off; card capture / dispatch count when
    on. Observation must never fail the pipeline."""
    obs = _active
    if obs is not None:
        try:
            obs.observe_dispatch(runner, padded)
        except Exception:
            pass


def snapshot() -> dict:
    """The heartbeat section: the active observer's light per-family
    summary, ``{}`` when roofline is off (zero footprint — the off-path
    heartbeat is byte-identical to pre-roofline builds modulo this
    constant empty key)."""
    obs = _active
    if obs is None:
        return {}
    try:
        return obs.light_summary()
    except Exception:
        return {}


def ensure_for_extractor(ext) -> None:
    """Library-caller hook (extractors/base.py _extract): a process that
    never went through cli.py still gets an observer homed on the
    extractor's output dir when ``roofline=true``, closed (and its
    ``_roofline.json`` written) at interpreter exit. First observer
    wins, like the compile-cache attach."""
    if _active is not None:
        return
    args = getattr(ext, "args", None)
    if args is None or not bool(args.get("roofline", False)):
        return
    obs = RooflineObserver(str(ext.output_path),
                           default_family=str(ext.feature_type))
    if obs.start() is obs:
        atexit.register(obs.close)


class RooflineObserver:
    """Run-scoped MFU accounting: cost cards per dispatched program +
    per-family forward/h2d stage seconds -> effective TFLOPS, MFU and a
    verdict, written to ``_roofline.json`` at :meth:`close`.

    Process-global like the profiler (one device, one accounting);
    :meth:`start` publishes it (first wins) and chains onto the stage
    hook WITHOUT displacing the telemetry recorder's. The peak resolves
    on a daemon thread so a cold microbench never stalls the pipeline
    start (registry/env/cache hits are instant)."""

    def __init__(self, output_path: str, *,
                 default_family: Optional[str] = None,
                 run_id: Optional[str] = None,
                 host_id: Optional[str] = None) -> None:
        self.output_path = str(output_path)
        self.default_family = default_family
        self.run_id = run_id
        self.host_id = host_id
        self.path = os.path.join(self.output_path,
                                 roofline_filename(host_id))
        self._state = threading.Lock()
        #: (id(runner), shape, dtype) -> card dict (flops None = capture
        #: failed; dispatches still counted)
        self._cards: Dict[Tuple, Dict[str, Any]] = {}
        #: family -> {"forward_s", "forward_calls", "h2d_s"}
        self._stages: Dict[str, Dict[str, float]] = {}
        self._peak: Optional[Dict[str, Any]] = None
        self._peak_thread: Optional[threading.Thread] = None
        self._prev_hook = None
        self._hook_fn = None
        self._t0 = time.perf_counter()
        self._closed = False

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "RooflineObserver":
        global _active
        with _lock:
            if _active is not None:
                return _active
            _active = self
        from ..utils.profiling import profiler
        self._prev_hook = prev = profiler._hook

        def hook(name: str, dt: float) -> None:
            if prev is not None:
                prev(name, dt)
            self._observe_stage(name, dt)

        self._hook_fn = hook
        profiler.set_hook(hook)
        self._t0 = time.perf_counter()
        self._peak_thread = threading.Thread(
            target=self._resolve_peak, name="vft-roofline-peak",
            daemon=True)
        self._peak_thread.start()
        return self

    def close(self, write: bool = True) -> Optional[dict]:
        """Finalize: write ``_roofline.json`` atomically, restore the
        stage hook (only if still ours — the recorder's own close may
        have cleared it already), drop the process-global slot. Returns
        the summary; never raises into a caller's finally."""
        global _active
        if self._closed:
            return None
        self._closed = True
        from ..utils.profiling import profiler
        if profiler._hook is self._hook_fn:
            profiler.set_hook(self._prev_hook)
        with _lock:
            if _active is self:
                _active = None
        try:
            doc = self.summary(resolve_peak=True)
            if write:
                write_json_atomic(self.path, doc)
            return doc
        except Exception as e:
            print(f"roofline: close failed ({type(e).__name__}: {e}) — "
                  "accounting for this run is lost, extraction is not")
            return None

    # -- peak ---------------------------------------------------------------
    def _resolve_peak(self) -> None:
        try:
            peak = peak_for_device()
        except Exception:
            peak = None
        with self._state:
            self._peak = peak

    def peak(self, resolve: bool = False) -> Optional[Dict[str, Any]]:
        with self._state:
            peak = self._peak
        if peak is None and resolve:
            t = self._peak_thread
            if t is not None and t.is_alive():
                t.join(timeout=120.0)
            with self._state:
                peak = self._peak
        return peak

    # -- observation --------------------------------------------------------
    def _family(self) -> str:
        span = current_span()
        if span is not None and getattr(span, "feature_type", None):
            return str(span.feature_type)
        return str(self.default_family or "?")

    def observe_dispatch(self, runner, padded) -> None:
        key = (id(runner), tuple(padded.shape), str(padded.dtype))
        with self._state:
            card = self._cards.get(key)
            if card is not None:
                card["dispatches"] += 1
                return
            # placeholder FIRST: a concurrent sibling dispatching the
            # same shape counts instead of lowering twice
            card = {"family": self._family(),
                    "shape": [int(d) for d in padded.shape],
                    "dtype": str(padded.dtype),
                    "batch": int(padded.shape[0]) if padded.ndim else 1,
                    "flops": None, "bytes": None, "intensity": None,
                    "dispatches": 1}
            self._cards[key] = card
        try:
            cost = program_cost(runner._fn, runner.params, padded)
            flops, nbytes = cost["flops"], cost["bytes"]
            with self._state:
                card["flops"] = flops
                card["bytes"] = nbytes
                card["intensity"] = (round(flops / nbytes, 3)
                                     if nbytes else None)
        except Exception:
            pass  # card stays dispatch-counted, flops unknown

    def _observe_stage(self, name: str, dt: float) -> None:
        if name not in ("forward", "h2d"):
            return
        fam = self._family()
        with self._state:
            st = self._stages.setdefault(
                fam, {"forward_s": 0.0, "forward_calls": 0, "h2d_s": 0.0})
            if name == "forward":
                st["forward_s"] += dt
                st["forward_calls"] += 1
            else:
                st["h2d_s"] += dt

    # -- summaries ----------------------------------------------------------
    def _family_doc(self, fam: str, cards: List[dict], st: Dict[str, float],
                    wall_s: float, peak: Optional[dict]) -> dict:
        flops_total = sum(c["flops"] * c["dispatches"] for c in cards
                          if c.get("flops"))
        bytes_total = sum(c["bytes"] * c["dispatches"] for c in cards
                          if c.get("bytes"))
        dispatches = sum(c["dispatches"] for c in cards)
        fwd = float(st.get("forward_s", 0.0))
        eff = (flops_total / 1e12 / fwd if fwd > 0 and flops_total
               else None)
        eff_wall = (flops_total / 1e12 / wall_s
                    if wall_s > 0 and flops_total else None)
        peak_tf = (peak or {}).get("peak_tflops")
        peak_gb = (peak or {}).get("peak_gbps")
        programs = [{k: c.get(k) for k in CARD_FIELDS}
                    for c in sorted(cards, key=lambda c: -(c["flops"] or 0))]
        return {
            "programs": programs,
            "flops_total": flops_total,
            "bytes_total": bytes_total,
            "dispatches": dispatches,
            "forward_s": round(fwd, 6),
            "forward_calls": int(st.get("forward_calls", 0)),
            "h2d_s": round(float(st.get("h2d_s", 0.0)), 6),
            "wall_s": round(wall_s, 3),
            "device_share": (round(fwd / wall_s, 4) if wall_s > 0
                             else None),
            "arithmetic_intensity": (round(flops_total / bytes_total, 3)
                                     if bytes_total else None),
            "effective_tflops": (round(eff, 4) if eff is not None
                                 else None),
            "effective_tflops_wall": (round(eff_wall, 4)
                                      if eff_wall is not None else None),
            "mfu": (round(eff / peak_tf, 4)
                    if eff is not None and peak_tf else None),
            "verdict": classify(flops_total, bytes_total, fwd, wall_s,
                                peak_tf, peak_gb),
        }

    def summary(self, resolve_peak: bool = False) -> dict:
        """The full ``_roofline.json`` document (schema-shaped)."""
        wall = time.perf_counter() - self._t0
        peak = self.peak(resolve=resolve_peak)
        with self._state:
            cards = [dict(c) for c in self._cards.values()]
            stages = {f: dict(s) for f, s in self._stages.items()}
        by_family: Dict[str, List[dict]] = {}
        for c in cards:
            by_family.setdefault(c.get("family") or "?", []).append(c)
        families = {}
        for fam in sorted(set(by_family) | set(stages)):
            families[fam] = self._family_doc(
                fam, by_family.get(fam, []), stages.get(fam, {}),
                wall, peak)
        device = {k: (peak or {}).get(k) for k in DEVICE_FIELDS}
        if peak is None:
            # kind is knowable even before the resolver thread lands
            try:
                import jax
                devs = jax.local_devices()
                device["platform"] = devs[0].platform if devs else None
                device["device_kind"] = (getattr(devs[0], "device_kind",
                                                 None) if devs else None)
            except Exception:
                pass
            device["source"] = "unresolved"
        return {
            "schema": SCHEMA_VERSION,
            "run_id": self.run_id,
            "host_id": self.host_id,
            "feature_type": self.default_family,
            "time": round(time.time(), 3),
            "wall_s": round(wall, 3),
            "device": device,
            "families": families,
        }

    def light_summary(self) -> dict:
        """The heartbeat-sized view: per-family MFU/verdict without the
        program cards, and WITHOUT forcing the peak (a tick must never
        wait on a microbench — mfu/verdict stay null until the resolver
        thread lands)."""
        doc = self.summary(resolve_peak=False)
        fams = {}
        for fam, f in doc["families"].items():
            fams[fam] = {k: f[k] for k in
                         ("dispatches", "effective_tflops", "mfu",
                          "device_share", "verdict")}
            fams[fam]["gflops_total"] = round(f["flops_total"] / 1e9, 1)
        return {"device": doc["device"], "families": fams}


# -- schema -------------------------------------------------------------------

ROOFLINE_SCHEMA_PATH = os.path.join(os.path.dirname(__file__),
                                    "roofline.schema.json")


def load_roofline_schema() -> dict:
    with open(ROOFLINE_SCHEMA_PATH, encoding="utf-8") as f:
        return json.load(f)


def validate_roofline(doc: dict) -> List[str]:
    from . import schema as tschema
    return tschema.validate(doc, load_roofline_schema())


# -- vft-roofline (the report) ------------------------------------------------

def _load_json(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def find_roofline_files(root: str) -> List[str]:
    """Every ``_roofline*.json`` under ``root`` (per-host fleet files
    included), or the file itself when ``root`` is one."""
    from pathlib import Path
    p = Path(root)
    if p.is_file():
        return [str(p)]
    return [str(q) for q in sorted(p.rglob("_roofline*.json"))]


def aggregate_rooflines(root: str) -> Optional[dict]:
    """Merge every roofline artifact under ``root`` into one per-family
    view (fleet roll-up: flops and forward seconds SUM across hosts,
    effective TFLOPS/MFU recomputed from the sums, the verdict re-derived
    over the merged totals). Returns None when no artifacts exist."""
    docs = [d for d in (_load_json(p) for p in find_roofline_files(root))
            if d is not None and d.get("schema") == SCHEMA_VERSION]
    if not docs:
        return None
    device = docs[0].get("device") or {}
    fams: Dict[str, Dict[str, float]] = {}
    for doc in docs:
        for fam, f in (doc.get("families") or {}).items():
            agg = fams.setdefault(fam, {
                "flops_total": 0.0, "bytes_total": 0.0, "dispatches": 0,
                "forward_s": 0.0, "h2d_s": 0.0, "wall_s": 0.0, "hosts": 0})
            for k in ("flops_total", "bytes_total", "forward_s", "h2d_s",
                      "wall_s"):
                agg[k] += float(f.get(k) or 0.0)
            agg["dispatches"] += int(f.get("dispatches") or 0)
            agg["hosts"] += 1
    peak_tf = device.get("peak_tflops")
    peak_gb = device.get("peak_gbps")
    out = {}
    for fam, a in fams.items():
        eff = (a["flops_total"] / 1e12 / a["forward_s"]
               if a["forward_s"] > 0 and a["flops_total"] else None)
        out[fam] = {
            **{k: round(v, 6) if isinstance(v, float) else v
               for k, v in a.items()},
            "arithmetic_intensity": (
                round(a["flops_total"] / a["bytes_total"], 3)
                if a["bytes_total"] else None),
            "effective_tflops": round(eff, 4) if eff is not None else None,
            "mfu": (round(eff / peak_tf, 4)
                    if eff is not None and peak_tf else None),
            "device_share": (round(a["forward_s"] / a["wall_s"], 4)
                             if a["wall_s"] else None),
            "verdict": classify(a["flops_total"], a["bytes_total"],
                                a["forward_s"], a["wall_s"], peak_tf,
                                peak_gb),
        }
    return {"device": device, "families": out, "n_artifacts": len(docs)}


def render_verdict(verdict: Optional[str]) -> str:
    if verdict == "host-bound":
        return "host-bound (sandbagged)"
    return verdict or "?"


def render_table(agg: dict) -> List[str]:
    dev = agg.get("device") or {}
    lines = [
        "== roofline (per-family MFU) ==",
        f"  device: {dev.get('device_kind')} ({dev.get('platform')})  "
        f"peak={dev.get('peak_tflops')} TFLOPS"
        + (f" / {dev.get('peak_gbps')} GB/s" if dev.get("peak_gbps")
           else "")
        + f"  [{dev.get('source')}]",
        f"  {'family':<12} {'GFLOP':>10} {'AI':>7} {'disp':>6} "
        f"{'fwd s':>8} {'eff TFLOPS':>11} {'MFU':>7} {'dev%':>6}  verdict",
    ]
    for fam, f in sorted((agg.get("families") or {}).items()):
        mfu = f.get("mfu")
        share = f.get("device_share")
        lines.append(
            f"  {fam:<12} {f.get('flops_total', 0) / 1e9:>10.1f} "
            f"{f.get('arithmetic_intensity') or 0:>7.1f} "
            f"{f.get('dispatches', 0):>6} "
            f"{f.get('forward_s', 0):>8.2f} "
            f"{f.get('effective_tflops') if f.get('effective_tflops') is not None else float('nan'):>11.4f} "
            f"{(100 * mfu if mfu is not None else float('nan')):>6.2f}% "
            f"{(100 * share if share is not None else float('nan')):>5.1f}%"
            f"  {render_verdict(f.get('verdict'))}")
    return lines


def _profiler_op_table(profile_dir: str, top: int = 10) -> List[str]:
    """Optional per-op breakdown from a ``jax.profiler`` capture dir
    (``profile_trace_dir=``): total device time by op name, the
    where-inside-the-program complement to the per-program cards. A
    self-contained loader (newest ``*.trace.json[.gz]`` under the dir)
    so the vft-roofline console script works off an installed package,
    not just a checkout."""
    import glob
    import gzip
    cands = sorted(
        glob.glob(os.path.join(profile_dir, "**", "*.trace.json.gz"),
                  recursive=True)
        + glob.glob(os.path.join(profile_dir, "**", "*.trace.json"),
                    recursive=True),
        key=os.path.getmtime)
    if not cands:
        return [f"  (no *.trace.json[.gz] under {profile_dir})"]
    path = cands[-1]
    opener = gzip.open if path.endswith(".gz") else open
    try:
        with opener(path, "rt", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"  (unreadable profiler trace {path}: "
                f"{type(e).__name__}: {e})"]
    totals: Dict[str, float] = {}
    for ev in doc.get("traceEvents", []):
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        dur = ev.get("dur")
        if isinstance(dur, (int, float)):
            name = str(ev.get("name", "?"))
            totals[name] = totals.get(name, 0.0) + float(dur)
    if not totals:
        return [f"  (no complete events in {path})"]
    acc = sum(totals.values())
    lines = [f"== per-op breakdown ({os.path.basename(path)}) ==",
             f"  {'ms':>10} {'share':>7}  op"]
    for name, us in sorted(totals.items(), key=lambda kv: -kv[1])[:top]:
        lines.append(f"  {us / 1e3:>10.1f} {100 * us / acc:>6.1f}%  "
                     f"{name[:90]}")
    return lines


def report_main(argv: Optional[List[str]] = None) -> int:
    """``vft-roofline <output_dir> [--profile DIR] [--top N] [--json]``:
    render the per-family MFU table + verdicts from a run's (or fleet's)
    ``_roofline*.json`` artifacts, optionally merged with a
    ``jax.profiler`` capture for the per-op view."""
    import argparse
    import sys
    ap = argparse.ArgumentParser(
        description="per-family MFU table + roofline verdicts from "
                    "_roofline.json artifacts (roofline=true runs)")
    ap.add_argument("root", nargs="?", default=None,
                    help="a roofline=true run's output dir (or a fleet "
                         "root, or a _roofline.json file)")
    ap.add_argument("--calibrate", action="store_true",
                    help="run measure_peak() NOW on an idle machine and "
                         "overwrite this device kind's cached peak — the "
                         "in-run fallback measures on a busy device and "
                         "can under-read on few-core hosts")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="also render a per-op device-time table from a "
                         "jax.profiler capture (profile_trace_dir=)")
    ap.add_argument("--top", type=int, default=10,
                    help="ops to list under --profile (default 10)")
    ap.add_argument("--json", action="store_true",
                    help="print the aggregated document as JSON instead "
                         "of the table")
    args = ap.parse_args(argv)
    if args.calibrate:
        import jax
        devs = jax.local_devices()
        kind = getattr(devs[0], "device_kind", "?") if devs else "?"
        m = measure_peak()
        path = _peak_cache_path(kind, None)
        write_json_atomic(path, {**m, "device_kind": kind,
                                 "time": round(time.time(), 3)})
        print(f"vft-roofline: calibrated {kind}: "
              f"{m['peak_tflops']} TFLOPS / {m['peak_gbps']} GB/s "
              f"-> {path}")
        if args.root is None:
            return 0
    if args.root is None:
        ap.error("an output dir is required unless --calibrate ran alone")
    agg = aggregate_rooflines(args.root)
    if agg is None:
        print(f"vft-roofline: no {ROOFLINE_FILENAME} under {args.root} — "
              "was the run launched with roofline=true?", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(agg, indent=1, sort_keys=True))
    else:
        print("\n".join(render_table(agg)))
    if args.profile:
        print("\n".join(_profiler_op_table(args.profile, args.top)))
    return 0


if __name__ == "__main__":
    raise SystemExit(report_main())
