"""Thread-safe in-process metrics registry: counters, gauges, histograms.

The fleet-operations counterpart of the per-run ``StageProfiler``
(utils/profiling.py): where the profiler answers "where did this run's
wall time go" interactively, the registry accumulates *series* —
labelled counters (failures by category, retries, quarantine skips),
gauges (videos/s, uptime) and fixed-bucket histograms (decode / forward
/ write latencies, per-video wall time, processed fps) — that serialize
into the run manifest and render as a Prometheus textfile
(``scripts/telemetry_report.py --prom``).

Design constraints, in order:
  1. hot-path cost: one dict lookup + one small lock per update (the
     stage hook fires per decoded frame);
  2. no dependencies: the Prometheus *text exposition format* is ~30
     lines to emit, so there is no client library to install on TPU
     workers;
  3. crash-readable: :meth:`MetricsRegistry.to_dict` is plain JSON and
     round-trips through the manifest, so the report tool can re-render
     metrics from a finished (or dead) run's artifacts alone.
"""
from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

#: default latency buckets (seconds) — spans decode-of-one-frame (~ms)
#: through a whole long-video forward (~minutes)
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

#: frames-per-second buckets for decode/processing-rate histograms
FPS_BUCKETS: Tuple[float, ...] = (
    1.0, 2.0, 5.0, 10.0, 15.0, 24.0, 30.0, 60.0, 120.0, 240.0, 480.0)

LabelItems = Tuple[Tuple[str, str], ...]


class _Metric:
    kind = "?"

    def __init__(self, name: str, labels: LabelItems) -> None:
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, labels: LabelItems) -> None:
        super().__init__(name, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: inc({n}) must be >= 0")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, labels: LabelItems) -> None:
        super().__init__(name, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Metric):
    """Fixed-bucket histogram (cumulative on export, like Prometheus):
    ``observe(v)`` lands in the first bucket with ``v <= le``; the
    implicit ``+Inf`` bucket catches the rest."""

    kind = "histogram"

    def __init__(self, name: str, labels: LabelItems,
                 buckets: Sequence[float] = LATENCY_BUCKETS) -> None:
        super().__init__(name, labels)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {name}: need at least one bucket")
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            s, c = self._sum, self._count
        return {"buckets": [{"le": le, "count": n}
                            for le, n in zip(self.buckets, counts)],
                "inf_count": counts[-1], "sum": s, "count": c}


class MetricsRegistry:
    """Get-or-create registry keyed by (name, labels); name collisions
    across metric kinds are programming errors and raise."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, LabelItems], _Metric] = {}
        self._kinds: Dict[str, str] = {}

    def _get(self, cls, name: str, labels: dict, **kwargs) -> _Metric:
        items: LabelItems = tuple(sorted(
            (str(k), str(v)) for k, v in labels.items()))
        key = (name, items)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                seen = self._kinds.get(name)
                if seen is not None and seen != cls.kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {seen}, "
                        f"requested {cls.kind}")
                m = self._metrics[key] = cls(name, items, **kwargs)
                self._kinds[name] = cls.kind
            elif not isinstance(m, cls):
                raise ValueError(f"metric {name!r} is a {m.kind}")
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None,
                  **labels) -> Histogram:
        if buckets is None:
            return self._get(Histogram, name, labels)
        return self._get(Histogram, name, labels, buckets=buckets)

    def to_dict(self) -> dict:
        """JSON-safe dump of every series — the manifest's ``metrics``
        field, and the input of :func:`prometheus_text`."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: List[dict] = []
        for m in sorted(metrics, key=lambda m: (m.name, m.labels)):
            entry = {"name": m.name, "kind": m.kind,
                     "labels": dict(m.labels)}
            if isinstance(m, Histogram):
                entry.update(m.snapshot())
            else:
                entry["value"] = m.value
            out.append(entry)
        return {"series": out}


def histogram_quantile(snapshot: dict, q: float) -> Optional[float]:
    """Estimate the ``q``-quantile (0..1) of a :meth:`Histogram.snapshot`
    dump, Prometheus ``histogram_quantile`` style: walk the fixed buckets
    until the cumulative count crosses the rank, then interpolate
    linearly inside that bucket. A pure function of the JSON-safe
    snapshot, so serve heartbeats, the run manifest and the fleet
    aggregator all compute percentiles from artifacts alone.

    Returns None for an empty histogram. Observations past the last
    finite bucket (the implicit ``+Inf`` bucket) clamp to the largest
    finite bound — with the default :data:`LATENCY_BUCKETS` that is
    300 s, far beyond any sane serve SLO, so the clamp never hides a
    violation."""
    total = int(snapshot.get("count", 0))
    buckets = snapshot.get("buckets") or []
    if total <= 0 or not buckets:
        return None
    rank = max(0.0, min(1.0, float(q))) * total
    cum = 0.0
    prev_le = 0.0
    for b in buckets:
        c = float(b.get("count", 0))
        le = float(b.get("le", 0.0))
        if c > 0 and cum + c >= rank:
            frac = (rank - cum) / c
            return prev_le + (le - prev_le) * frac
        cum += c
        prev_le = le
    return float(buckets[-1]["le"])


def histogram_quantiles(snapshot: dict,
                        qs: Sequence[float] = (0.5, 0.95, 0.99)
                        ) -> Dict[str, Optional[float]]:
    """``{"p50": ..., "p95": ..., "p99": ...}`` off one snapshot — the
    shape the heartbeat ``serve`` section and report tools render."""
    out: Dict[str, Optional[float]] = {}
    for q in qs:
        v = histogram_quantile(snapshot, q)
        out[f"p{q * 100:g}"] = None if v is None else round(v, 4)
    return out


def _fmt_labels(labels: dict, extra: Optional[dict] = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in sorted(items.items()))
    return "{" + body + "}"


def prometheus_text(dump: dict) -> str:
    """Render a :meth:`MetricsRegistry.to_dict` dump in the Prometheus
    text exposition format (suitable for the node-exporter textfile
    collector). Pure function of the dump so the report tool can export
    metrics from a dead run's manifest."""
    by_name: Dict[str, List[dict]] = {}
    for s in dump.get("series", []):
        by_name.setdefault(s["name"], []).append(s)
    lines: List[str] = []
    for name in sorted(by_name):
        series = by_name[name]
        kind = series[0].get("kind", "untyped")
        lines.append(f"# TYPE {name} {kind}")
        for s in series:
            labels = s.get("labels", {})
            if kind == "histogram":
                cum = 0
                for b in s.get("buckets", []):
                    cum += b["count"]
                    lines.append("%s_bucket%s %d" % (
                        name, _fmt_labels(labels, {"le": repr(b["le"])}),
                        cum))
                cum += s.get("inf_count", 0)
                lines.append("%s_bucket%s %d" % (
                    name, _fmt_labels(labels, {"le": "+Inf"}), cum))
                lines.append("%s_sum%s %s" % (
                    name, _fmt_labels(labels), repr(s.get("sum", 0.0))))
                lines.append("%s_count%s %d" % (
                    name, _fmt_labels(labels), s.get("count", 0)))
            else:
                lines.append("%s%s %s" % (
                    name, _fmt_labels(labels), repr(s.get("value", 0.0))))
    return "\n".join(lines) + ("\n" if lines else "")
