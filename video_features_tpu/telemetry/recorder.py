"""TelemetryRecorder: the run-scoped owner of every telemetry channel.

One recorder per CLI run (cli.py constructs it when ``telemetry=true``):

  - owns the :class:`~.metrics.MetricsRegistry` and installs the stage
    hook on the process-global ``profiler`` (utils/profiling.py), so the
    decode/forward/write context managers that already instrument the
    pipelines feed latency histograms + per-video spans with no new call
    sites in the hot loops;
  - mints :class:`~.spans.VideoSpan`\\ s and appends their records to
    ``{output_path}/_telemetry.jsonl``;
  - runs the heartbeat thread (telemetry/heartbeat.py) and writes this
    host's ``_heartbeat_{host_id}.json``, including the per-interval
    stage delta obtained from ``StageProfiler.drain()`` — the atomic
    snapshot+reset that replaces the racy snapshot-then-reset pair;
  - counts XLA compile-cache hits/misses via ``jax.monitoring`` event
    listeners (installed once per process; recorders read deltas);
  - writes the run manifest (telemetry/manifest.py) at :meth:`close`.

When no recorder is active every instrumentation point in the codebase
is a constant-time no-op: the module-level helpers in
``telemetry/__init__.py`` read one global, the profiler hook is None,
and cli.py hands out ``NOOP_SPAN``.
"""
from __future__ import annotations

import os
import socket
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from ..utils.profiling import StageProfiler, profiler
from . import jsonl, manifest
from .heartbeat import HeartbeatThread, heartbeat_filename
from .metrics import FPS_BUCKETS, LATENCY_BUCKETS, MetricsRegistry
from .spans import VideoSpan, current_span

SPANS_FILENAME = "_telemetry.jsonl"

# -- process-wide compile-cache event counts --------------------------------
# jax.monitoring listeners cannot be unregistered individually, so they are
# installed once and recorders read deltas against a start-of-run baseline.

_mon_lock = threading.Lock()
_mon_counts: Dict[str, int] = {}
_mon_installed = False


def _bump_mon(event: str) -> None:
    with _mon_lock:
        _mon_counts[event] = _mon_counts.get(event, 0) + 1


def _install_monitoring() -> None:
    global _mon_installed
    with _mon_lock:
        if _mon_installed:
            return
        _mon_installed = True
    try:
        from jax import monitoring

        def on_event(event: str, **kw) -> None:
            if "compilation_cache" in event:
                _bump_mon(event)

        def on_duration(event: str, duration: float, **kw) -> None:
            if "compilation_cache" in event:
                _bump_mon(event)

        monitoring.register_event_listener(on_event)
        monitoring.register_event_duration_secs_listener(on_duration)
    except Exception:
        pass  # telemetry degrades, extraction does not


def _mon_snapshot() -> Dict[str, int]:
    with _mon_lock:
        return dict(_mon_counts)


def compile_cache_summary(baseline: Dict[str, int]) -> Dict[str, int]:
    """Delta of compile-cache events since ``baseline``, folded into
    hit/miss totals plus the raw per-event counts."""
    now = _mon_snapshot()
    delta = {k: now.get(k, 0) - baseline.get(k, 0) for k in now
             if now.get(k, 0) != baseline.get(k, 0)}
    out: Dict[str, int] = {"hits": 0, "misses": 0}
    for event, n in delta.items():
        if event.endswith("cache_hits"):
            out["hits"] += n
        elif event.endswith("cache_misses"):
            out["misses"] += n
        out[event] = n
    return out


class TelemetryRecorder:
    """Run-scoped telemetry: construct, :meth:`start`, hand out spans,
    :meth:`close` in a ``finally``."""

    def __init__(self, output_path: str, *,
                 run_config: Optional[dict] = None,
                 feature_type: Optional[str] = None,
                 interval_s: float = 30.0,
                 host_id: Optional[str] = None) -> None:
        self.output_path = str(output_path)
        self.run_config = run_config
        self.feature_type = feature_type
        self.interval_s = float(interval_s)
        self.host_id = host_id or socket.gethostname()
        # run identity: stamped into the manifest AND every heartbeat so
        # report tools can tell THIS run's heartbeats from stale files a
        # prior run left in the same output_path (telemetry_report.py
        # marks + excludes other-run heartbeats instead of summing them)
        self.run_id = uuid.uuid4().hex[:12]
        self.registry = MetricsRegistry()
        self.spans_path = os.path.join(self.output_path, SPANS_FILENAME)
        self.heartbeat_path = os.path.join(
            self.output_path, heartbeat_filename(self.host_id))
        self.manifest_path = os.path.join(
            self.output_path, manifest.MANIFEST_FILENAME)
        # run-long stage totals (manifest) + per-interval delta (heartbeat,
        # drained atomically each tick)
        self._run_stages = StageProfiler()
        self._delta_stages = StageProfiler()
        self._hb = HeartbeatThread(self._tick, self.interval_s)
        self._state_lock = threading.Lock()
        self._last_video: Optional[str] = None
        self._status_counts: Dict[str, int] = {}
        # output-health roll-up (telemetry/health.py digest_features feeds
        # it): per-family record / NaN / Inf totals for the manifest
        self._health: Dict[str, Dict[str, int]] = {}
        self._t0 = time.perf_counter()
        self._start_time = time.time()
        self._mon_baseline: Dict[str, int] = {}
        self._started = False
        self._closed = False
        # extension hook: {section_name: zero-arg callable -> JSONable}.
        # serve.py publishes its readiness/queue state through this — the
        # heartbeat file IS the serve liveness protocol, so the recorder
        # stays the single writer (one atomic replace per tick)
        self.extra_sections: Dict[str, Callable[[], dict]] = {}
        # post-write hooks: called with the heartbeat dict just written
        # (telemetry/history.py appends its retained sample here,
        # telemetry/alerts.py evaluates its rules) — register them
        # BEFORE start() so the t=0 heartbeat is observed too, which is
        # what gives short runs a windowed baseline at all
        self.tick_hooks: List[Callable[[dict], None]] = []
        self._tick_hook_errors = 0
        # span-channel degradation latch (ENOSPC discipline): a failed
        # _telemetry.jsonl append disables the pillar for the run
        self._spans_disabled = False

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "TelemetryRecorder":
        from . import _set_active
        _install_monitoring()
        self._mon_baseline = _mon_snapshot()
        os.makedirs(self.output_path, exist_ok=True)
        _set_active(self)
        profiler.set_hook(self._observe_stage)
        self.write_heartbeat()  # liveness visible before the first video
        self._hb.start()
        self._started = True
        return self

    def close(self, *, tally: Optional[Dict[str, int]] = None,
              wall_s: Optional[float] = None,
              failure_tallies: Optional[Dict[str, int]] = None,
              roofline: Optional[dict] = None) -> None:
        """Stop the heartbeat thread, write a final heartbeat and the run
        manifest. Idempotent; never raises into the caller's finally.
        ``roofline`` is the run's final MFU-accounting summary
        (telemetry/roofline.py), passed explicitly by the driver so a
        later in-process run can never inherit a stale one."""
        if self._closed:
            return
        self._closed = True
        from . import _set_active
        self._hb.stop()
        profiler.set_hook(None)
        _set_active(None)
        try:
            self.write_heartbeat(final=True)
            jsonl.write_json_atomic(self.manifest_path, self.build_manifest(
                tally=tally, wall_s=wall_s, failure_tallies=failure_tallies,
                roofline=roofline))
        except Exception as e:
            print(f"telemetry: failed to write {self.manifest_path}: "
                  f"{type(e).__name__}: {e}")

    # -- spans --------------------------------------------------------------
    def video_span(self, video: str,
                   feature_type: Optional[str] = None) -> VideoSpan:
        # multi-family runs share one recorder but stamp each span with
        # its own family, so per-(video, family) records stay queryable
        return VideoSpan(video, recorder=self,
                         feature_type=feature_type or self.feature_type,
                         host_id=self.host_id)

    def emit_span(self, record: dict) -> None:
        if not self._spans_disabled:
            try:
                jsonl.append_jsonl(self.spans_path, record)
            except OSError as e:
                # a full/readonly disk (ENOSPC) must degrade this pillar,
                # not kill the extraction: drop the span channel for the
                # rest of the run, keep the in-memory counters flowing
                self._spans_disabled = True
                self.registry.counter("vft_telemetry_write_failures_total",
                                      pillar="spans").inc()
                print(f"telemetry: failed to append {self.spans_path} "
                      f"({type(e).__name__}: {e}) — span channel disabled "
                      "for this run")
        status = record.get("status", "?")
        self.registry.counter("vft_videos_total", status=status).inc()
        self.registry.histogram("vft_video_wall_seconds",
                                buckets=LATENCY_BUCKETS).observe(
                                    record.get("wall_s") or 0.0)
        frames, wall = record.get("video_frames"), record.get("wall_s")
        if frames and wall:
            self.registry.histogram("vft_video_processed_fps",
                                    buckets=FPS_BUCKETS).observe(
                                        frames / wall)
        with self._state_lock:
            self._last_video = record.get("video")
            self._status_counts[status] = \
                self._status_counts.get(status, 0) + 1

    # -- output health (telemetry/health.py) ---------------------------------
    def health_observe(self, rec: dict) -> None:
        """Fold one feature digest into the per-family manifest roll-up."""
        fam = str(rec.get("feature_type") or "?")
        nonfinite = int(rec.get("nan", 0)) + int(rec.get("inf", 0))
        with self._state_lock:
            h = self._health.setdefault(
                fam, {"records": 0, "nonfinite_records": 0,
                      "nan": 0, "inf": 0})
            h["records"] += 1
            h["nan"] += int(rec.get("nan", 0))
            h["inf"] += int(rec.get("inf", 0))
            if nonfinite:
                h["nonfinite_records"] += 1

    def health_summary(self) -> Dict[str, Dict[str, int]]:
        with self._state_lock:
            return {f: dict(v) for f, v in self._health.items()}

    # -- stage hook (installed on the global profiler) -----------------------
    def _observe_stage(self, name: str, dt: float) -> None:
        self.registry.histogram("vft_stage_seconds", buckets=LATENCY_BUCKETS,
                                stage=name).observe(dt)
        self._run_stages.add(name, dt)
        self._delta_stages.add(name, dt)
        span = current_span()
        if span is not None:
            span.observe_stage(name, dt)

    # -- heartbeats ----------------------------------------------------------
    def _tick(self) -> None:
        self.write_heartbeat()

    def build_heartbeat(self, final: bool = False) -> dict:
        uptime = time.perf_counter() - self._t0
        with self._state_lock:
            status_counts = dict(self._status_counts)
            last_video = self._last_video
        done = sum(status_counts.values())
        vps = round(status_counts.get("done", 0) / uptime, 4) if uptime \
            else 0.0
        self.registry.gauge("vft_videos_per_second").set(vps)
        self.registry.gauge("vft_uptime_seconds").set(round(uptime, 3))
        # drain(): atomic snapshot+reset — the per-interval stage delta a
        # scraper can turn into rates without double counting
        delta = {k: {"s": round(v[0], 6), "calls": v[1]}
                 for k, v in self._delta_stages.drain().items()}
        hb = {
            "schema": "vft.heartbeat/1",
            "run_id": self.run_id,
            "host": socket.gethostname(),
            "host_id": self.host_id,
            "pid": os.getpid(),
            "feature_type": self.feature_type,
            "time": round(time.time(), 3),
            "started_time": round(self._start_time, 3),
            "uptime_s": round(uptime, 3),
            "interval_s": self.interval_s,
            "final": bool(final),
            "videos": status_counts,
            "videos_done": done,
            "videos_per_s": vps,
            "last_video": last_video,
            # heartbeat self-health (telemetry/heartbeat.py): a host whose
            # ticks were failing looks dead to the fleet; the next
            # successful write carries the evidence, so "alive but the
            # liveness channel broke" is distinguishable from "dead"
            "tick_errors": int(self._hb.tick_errors_total),
            "last_tick_error": self._hb.last_tick_error,
            "stage_delta": delta,
            # fan-out backpressure (parallel/fanout.py): per-family queue
            # depth gauges + cumulative blocked/starved totals, so a
            # heartbeat reader can tell WHICH family is the slow consumer
            # (its queue runs full, put_blocked grows) or the starved one
            # (its queue runs empty, get_starved grows) without the trace
            "fanout": self.fanout_snapshot(),
            # feature-cache effectiveness (cache.py): per-family
            # hit/miss/bypass totals + overall hit rate — the first-class
            # bench number ISSUE 7 makes of repeat-content avoidance
            "cache": self.cache_snapshot(),
            # compile-cache effectiveness (compile_cache.py): XLA
            # hit/miss deltas this run + the attached entry's identity
            # and warmth — how vft-fleet proves a joining host skipped
            # its compiles (ISSUE 11)
            "compile_cache": self.compile_cache_snapshot(),
            # roofline accounting (telemetry/roofline.py): per-family
            # effective TFLOPS / MFU / verdict, live — {} when
            # roofline=false, so the off-path heartbeat stays constant
            "roofline": self.roofline_snapshot(),
            # parity observatory (telemetry/parity.py): per-seam digest
            # tallies, live — {} when parity=false, so the off-path
            # heartbeat stays constant
            "parity": self.parity_snapshot(),
        }
        for name, fn in list(self.extra_sections.items()):
            try:
                hb[name] = fn()
            except Exception:
                hb[name] = {"error": "section callback failed"}
        return hb

    def cache_snapshot(self) -> dict:
        """Per-family feature-cache counters pulled out of the registry:
        ``{hits, misses, bypasses}`` each ``{family: n}``, plus the
        overall ``hit_rate`` over consulted lookups (hits+misses; the
        filename-skip bypasses avoided work without consulting cache
        content, so they don't dilute the rate)."""
        out: Dict[str, Dict[str, float]] = {
            "hits": {}, "misses": {}, "bypasses": {}}
        key_of = {"vft_cache_hit_total": "hits",
                  "vft_cache_miss_total": "misses",
                  "vft_cache_bypass_total": "bypasses"}
        for s in self.registry.to_dict()["series"]:
            key = key_of.get(s["name"])
            fam = s.get("labels", {}).get("family")
            if key is None or fam is None:
                continue
            out[key][fam] = int(s.get("value", 0))
        hits = sum(out["hits"].values())
        consulted = hits + sum(out["misses"].values())
        out["hit_rate"] = round(hits / consulted, 4) if consulted else None
        return out

    def compile_cache_snapshot(self) -> dict:
        """XLA compile-cache counters since run start (the jax.monitoring
        listeners' delta) plus — when this process attached a
        fleet-shared entry (compile_cache.py) — its key, warmth at
        attach, and the verify verdicts. ``hits > 0, misses == 0`` is
        the warm-start acceptance shape."""
        s = compile_cache_summary(self._mon_baseline)
        out: Dict[str, object] = {"hits": int(s.get("hits", 0)),
                                  "misses": int(s.get("misses", 0))}
        try:
            from ..compile_cache import active_info
            info = active_info()
        except Exception:
            info = None
        if info is not None:
            out.update(entry=info["entry"], family=info["family"],
                       warm_at_attach=info["warm_at_attach"],
                       verified=info["verified"], dropped=info["dropped"])
        return out

    def roofline_snapshot(self) -> dict:
        """The active roofline observer's light per-family summary
        (telemetry/roofline.py snapshot), ``{}`` when roofline=false —
        like the compile-cache section, the recorder reads the process-
        global subsystem rather than owning it."""
        try:
            from . import roofline
            return roofline.snapshot()
        except Exception:
            return {}

    def parity_snapshot(self) -> dict:
        """The active parity observer's per-seam record tallies
        (telemetry/parity.py snapshot), ``{}`` when parity=false — the
        recorder reads the process-global subsystem rather than owning
        it, exactly like roofline."""
        try:
            from . import parity
            return parity.snapshot()
        except Exception:
            return {}

    def fanout_snapshot(self) -> dict:
        """Per-family fan-out backpressure series pulled out of the
        registry: ``{queue_depth, put_blocked_ms_total,
        get_starved_ms_total}``, each ``{family: value}`` (empty dicts
        outside multi-family runs)."""
        out: Dict[str, Dict[str, float]] = {
            "queue_depth": {}, "put_blocked_ms_total": {},
            "get_starved_ms_total": {}}
        key_of = {"vft_fanout_queue_depth": "queue_depth",
                  "vft_fanout_put_blocked_ms_total": "put_blocked_ms_total",
                  "vft_fanout_get_starved_ms_total": "get_starved_ms_total"}
        for s in self.registry.to_dict()["series"]:
            key = key_of.get(s["name"])
            fam = s.get("labels", {}).get("family")
            if key is None or fam is None:
                continue
            out[key][fam] = round(float(s.get("value", 0.0)), 3)
        return out

    def write_heartbeat(self, final: bool = False) -> None:
        hb = self.build_heartbeat(final=final)
        jsonl.write_json_atomic(self.heartbeat_path, hb)
        for fn in list(self.tick_hooks):
            try:
                fn(hb)
            except Exception as e:
                # hooks observe; they must never break liveness — but a
                # silently-dead retention/alerting channel is its own
                # incident, so the first failure is named
                self._tick_hook_errors += 1
                if self._tick_hook_errors == 1:
                    print(f"telemetry: heartbeat hook failed: "
                          f"{type(e).__name__}: {e}")

    # -- manifest ------------------------------------------------------------
    def build_manifest(self, *, tally: Optional[Dict[str, int]] = None,
                       wall_s: Optional[float] = None,
                       failure_tallies: Optional[Dict[str, int]] = None,
                       roofline: Optional[dict] = None) -> dict:
        with self._state_lock:
            tally = dict(tally if tally is not None else self._status_counts)
        stage_totals = {k: {"s": round(v[0], 6), "calls": v[1]}
                        for k, v in self._run_stages.snapshot().items()}
        return manifest.build_manifest(
            run_config=self.run_config,
            feature_type=self.feature_type,
            host_id=self.host_id,
            run_id=self.run_id,
            health=self.health_summary(),
            started_time=round(self._start_time, 3),
            wall_s=wall_s if wall_s is not None
            else time.perf_counter() - self._t0,
            tally=tally,
            failure_tallies=failure_tallies,
            stage_totals=stage_totals,
            metrics_dump=self.registry.to_dict(),
            # raw event deltas PLUS the attached fleet-entry identity
            # (compile_cache.py), so the manifest alone answers "did
            # this host join warm" (hits/misses keys win over raw names)
            compile_cache={**compile_cache_summary(self._mon_baseline),
                           **{k: v for k, v in
                              (self.compile_cache_snapshot()).items()
                              if k not in ("hits", "misses")}},
            roofline=roofline,
        )
