"""Output health: per-(video, family) feature digests at the sink boundary.

The third telemetry pillar. Metrics (telemetry/metrics.py) and traces
(telemetry/trace.py) make the pipeline legible in *time*; nothing so far
observed the *outputs*. A bf16 kernel tweak, a weights re-conversion or
an ``fps_mode`` change can shift features well past the value tier's
atol=1e-2 (PARITY.md round 5 measured exactly such a 0.063 delta), and
RAFT/PWC's iterative refinements can emit NaN/Inf that would land in an
``.npy`` nobody inspects. ``health=true`` closes both holes:

  - every feature tensor that reaches the sink gets a cheap **digest**
    (shape/dtype, NaN/Inf counts, finite min/max/mean/std, L2 norm, and
    a quantization-tolerant content signature) appended to
    ``{output_path}/_health.jsonl`` — one record per (video, family,
    output key), shape frozen by ``feature_health.schema.json`` (same
    drift-gate discipline as the span schema:
    ``scripts/check_health_schema.py``);
  - a **non-finite feature is never silently written**: it raises
    :class:`NonFiniteFeatureError` (classified POISON by
    ``utils/faults.py``), so the video routes through the normal retry /
    journal / quarantine machinery instead of poisoning downstream
    consumers;
  - digests attach to the live telemetry when a recorder is active:
    a ``health`` event on the per-video span, the
    ``vft_health_nonfinite_total{family}`` counter, and a roll-up in the
    ``_run.json`` manifest (records / NaN / Inf per family).

Two runs' ``_health.jsonl`` files are the inputs
``scripts/compare_runs.py`` diffs into a regression verdict. Off by
default: with ``health=false`` the only cost is one attribute read per
video (extractors/base.py), and no ``_health.jsonl`` ever appears.
"""
from __future__ import annotations

import hashlib
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .jsonl import append_jsonl

#: schema identifier stamped into every record; bump on breaking change
SCHEMA_VERSION = "vft.feature_health/1"

HEALTH_FILENAME = "_health.jsonl"

HEALTH_SCHEMA_PATH = os.path.join(os.path.dirname(__file__),
                                  "feature_health.schema.json")

#: exactly the top-level keys of every emitted record, in emit order —
#: scripts/check_health_schema.py asserts these equal the JSON Schema's
#: properties, the same emitter<->contract pinning as spans.SPAN_FIELDS
HEALTH_FIELDS = (
    "schema", "video", "feature_type", "request_id", "key", "shape",
    "dtype", "elems", "nan", "inf", "min", "max", "mean", "std", "l2",
    "sig", "time",
)

#: content-signature quantization grid: values are snapped to multiples
#: of SIG_GRID before hashing, chosen at half the value tier's atol=1e-2
#: so two runs whose features differ only by sub-tolerance noise hash
#: identically (unless a value straddles a bucket edge — the signature
#: is a fast-path equality check; compare_runs' stat tolerance bands are
#: the authoritative drift measure)
SIG_GRID = 5e-3


def content_signature(arr: np.ndarray) -> str:
    """Quantization-tolerant sha256 of a feature tensor.

    Values snap to the :data:`SIG_GRID` lattice (float64 accumulate) and
    the integer bucket indices are hashed together with the shape, so
    the signature survives benign noise (bf16 rounding jitter well under
    tolerance) but changes when content genuinely moves. NaN/Inf map to
    dedicated sentinel buckets, so a non-finite value also changes it.
    """
    a = np.asarray(arr)
    if a.dtype == object:
        # pickled object features (no numeric lattice): hash the repr
        return hashlib.sha256(repr(a.tolist()).encode()).hexdigest()
    q = np.round(a.astype(np.float64) / SIG_GRID)
    # sentinel buckets far outside any real feature's range; int64-safe
    q = np.nan_to_num(q, nan=2.0 ** 52, posinf=2.0 ** 53, neginf=-2.0 ** 53)
    q = np.clip(q, -(2.0 ** 53), 2.0 ** 53)
    h = hashlib.sha256(repr(a.shape).encode())
    h.update(q.astype(np.int64).tobytes())
    return h.hexdigest()


def digest_array(key: str, value: Any, *, video: str,
                 feature_type: Optional[str]) -> dict:
    """One feature tensor -> one schema-shaped digest record.

    Cost is a handful of O(n) numpy reductions plus one sha256 pass —
    negligible next to the decode/forward work that produced the tensor
    (bench.py ``bench_health_overhead`` tracks the end-to-end ratio
    against the <=1.05x budget).
    """
    a = np.asarray(value)
    if a.dtype == object or a.size == 0:
        finite = np.zeros(0)
        nan = inf = 0
    else:
        f = a.astype(np.float64, copy=False)
        finite_mask = np.isfinite(f)
        nan = int(np.isnan(f).sum())
        inf = int(a.size - finite_mask.sum() - nan)
        finite = f[finite_mask] if nan or inf else f
    stats = {"min": None, "max": None, "mean": None, "std": None, "l2": None}
    if finite.size:
        stats = {
            "min": float(finite.min()),
            "max": float(finite.max()),
            "mean": float(finite.mean()),
            "std": float(finite.std()),
            "l2": float(np.sqrt(np.square(finite).sum())),
        }
    from .context import current_request_id
    return {
        "schema": SCHEMA_VERSION,
        "video": str(video),
        "feature_type": feature_type,
        # serve-mode correlation (telemetry/context.py): the id of the
        # spool request this digest belongs to; null in batch runs
        "request_id": current_request_id(),
        "key": str(key),
        "shape": [int(s) for s in a.shape],
        "dtype": str(a.dtype),
        "elems": int(a.size),
        "nan": nan,
        "inf": inf,
        "min": stats["min"],
        "max": stats["max"],
        "mean": stats["mean"],
        "std": stats["std"],
        "l2": stats["l2"],
        "sig": content_signature(a),
        "time": round(time.time(), 3),
    }


def digest_features(feats: Dict[str, Any], video: str,
                    feature_type: Optional[str],
                    output_path: Optional[str]) -> List[dict]:
    """Digest every output key of one (video, family) extraction.

    Appends each record to ``{output_path}/_health.jsonl`` (atomic
    O_APPEND, telemetry/jsonl.py) and, when telemetry is live, attaches
    a ``health`` event to the current span, bumps
    ``vft_health_nonfinite_total{family}`` for non-finite tensors and
    feeds the recorder's manifest roll-up. Works with telemetry off too:
    the JSONL artifact alone is what compare_runs consumes.
    """
    from .. import telemetry

    recs = []
    path = (os.path.join(str(output_path), HEALTH_FILENAME)
            if output_path else None)
    for key, value in feats.items():
        rec = digest_array(key, value, video=video,
                           feature_type=feature_type)
        if path is not None:
            append_jsonl(path, rec)
        nonfinite = rec["nan"] + rec["inf"]
        telemetry.event("health", key=rec["key"], nan=rec["nan"],
                        inf=rec["inf"], sig=rec["sig"])
        if nonfinite:
            telemetry.inc("vft_health_nonfinite_total", nonfinite,
                          family=str(feature_type))
        r = telemetry.active()
        if r is not None:
            r.health_observe(rec)
        recs.append(rec)
    return recs


def check_features(feats: Dict[str, Any], video: str,
                   feature_type: Optional[str],
                   output_path: Optional[str]) -> List[dict]:
    """Digest + gate: raise :class:`NonFiniteFeatureError` when any
    output tensor carries NaN/Inf, AFTER the digests are journaled (the
    ``_health.jsonl`` record of the bad tensor is exactly what the
    operator diagnoses with). ``utils/faults.py`` classifies the raise
    POISON: bounded retries, then quarantine — never a silent write."""
    recs = digest_features(feats, video, feature_type, output_path)
    bad = [(r["key"], r["nan"], r["inf"]) for r in recs
           if r["nan"] or r["inf"]]
    if bad:
        detail = ", ".join(f"{k}: {n} NaN / {i} Inf" for k, n, i in bad)
        raise NonFiniteFeatureError(
            f"non-finite feature values for {video} ({detail}) — refusing "
            "to write; see _health.jsonl (health=false disables this gate)")
    return recs


class NonFiniteFeatureError(Exception):
    """A computed feature contains NaN/Inf. Classified POISON by
    ``utils/faults.py`` (by name, so the worker-forwarded string form
    also classifies): the input/feature pair is bad in a way retries
    rarely fix, and the quarantine journal is the right destination."""


def load_health_schema() -> dict:
    import json
    with open(HEALTH_SCHEMA_PATH, encoding="utf-8") as f:
        return json.load(f)


def validate_health(rec: dict) -> List[str]:
    """Violations of the checked-in schema (telemetry/schema.py
    dependency-free validator); empty list == valid."""
    from . import schema as tschema
    return tschema.validate(rec, load_health_schema())
