"""Per-seam numerics observatory: the sixth telemetry pillar.

The health pillar (telemetry/health.py) digests features at the *sink*
boundary only — a drift introduced at decode, transform or backbone is
invisible until it blows the end-to-end band, with no attribution.
``parity=true`` taps the four pipeline seams

    decoded frames -> transformed tensors -> backbone activations
    -> head features

and appends one digest per (video, seam, key, index) to
``{output_path}/_parity.jsonl`` (checked-in contract
``telemetry/parity.schema.json``, PARITY_FIELDS pinned by vft-lint
VFT006). Digests reuse the health pillar's machinery: finite stats plus
the quantization-tolerant content signature on the 5e-3 lattice.

Off by default, one-global-read off path like trace/health: with
``parity=false`` the taps are never installed (extractors/base.py gates
on one attribute) and no artifact appears.

**Certification** is what the observatory exists for:

    vft-parity certify --config raft.yml --flip dtype=bf16

runs a reference arm and a candidate arm in-process over a pinned
corpus, captures every seam in memory, and emits
``_parity_verdict.json`` (``parity_verdict.schema.json``) with
per-seam error attribution — max/mean abs, max rel, min cosine —
against the per-(family, seam) :data:`TOLERANCES` registry. A FAIL
names the FIRST seam that drifted, not just the final feature. The
committed RAFT/PWC bf16 default flips each carry their verdict as
evidence (``evidence/parity/``, docs/numerics.md).

Spawned decode children (``video_decode=process|parallel``) carry no
observer global, so the transform tap degrades to a pure pass-through
there: seam records come from in-process decode paths (the default
thread decode, shared-decode fan-out, and ``certify``, which pins
inline decode).
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .jsonl import append_jsonl, write_json_atomic

#: schema identifiers stamped into every record; bump on breaking change
SCHEMA_VERSION = "vft.parity/1"
#: the certify verdict artifact's version tag (second schema of this
#: emitter module — the loadgen journal/scenario pattern)
VERDICT_SCHEMA = "vft.parity_verdict/1"

PARITY_FILENAME = "_parity.jsonl"
VERDICT_FILENAME = "_parity_verdict.json"

PARITY_SCHEMA_PATH = os.path.join(os.path.dirname(__file__),
                                  "parity.schema.json")
VERDICT_SCHEMA_PATH = os.path.join(os.path.dirname(__file__),
                                   "parity_verdict.schema.json")

#: the four seams, in pipeline order — FAIL attribution reports the
#: FIRST seam (in this order) whose error leaves its tolerance band
SEAMS = ("decode", "transform", "backbone", "head")

VERDICTS = ("PASS", "FAIL")

#: exactly the top-level keys of every emitted record, in emit order —
#: vft-lint VFT006 asserts these equal parity.schema.json's properties
PARITY_FIELDS = (
    "schema", "video", "feature_type", "request_id", "seam", "key",
    "index", "shape", "dtype", "elems", "nan", "inf", "min", "max",
    "mean", "std", "l2", "sig", "time",
)

#: top-level keys of the certify verdict artifact (emit order) —
#: VFT006-pinned against parity_verdict.schema.json
VERDICT_FIELDS = (
    "schema", "family", "host", "flip", "ref", "cand", "corpus",
    "seams", "first_drift", "verdict", "time",
)

#: per-(family, seam) tolerance bands for certify. ``"*"`` is the
#: default family. Every entry carries its written justification — the
#: band is an argued contract, not a magic number. Gating metrics:
#: ``max_abs`` (absolute error ceiling over every captured pair) and
#: ``cos`` (minimum cosine similarity floor). ``mean_abs``/``max_rel``
#: are recorded in the verdict for diagnosis but do not gate: near-zero
#: activations make relative error unboundedly noisy.
TOLERANCES: Dict[Tuple[str, str], Dict[str, Any]] = {
    ("*", "decode"): {
        "max_abs": 1e-6, "cos": 1.0 - 1e-9,
        "why": "decode is uint8 cv2 output on the host; a numerics flip "
               "cannot legally touch it — any drift here means the flip "
               "leaked upstream of the device (or the corpus moved)."},
    ("*", "transform"): {
        "max_abs": 1e-6, "cos": 1.0 - 1e-9,
        "why": "host transforms (PIL resize/crop/normalize) run in "
               "float32 regardless of device precision; exact equality "
               "modulo float32 associativity noise is the contract."},
    ("*", "backbone"): {
        "max_abs": 0.5, "cos": 0.99,
        "why": "bf16 keeps 8 mantissa bits (~0.4% per-element rounding); "
               "conv stacks accumulate it but direction is preserved — "
               "cos>=0.99 is the migration-parity bar the TF->JAX papers "
               "certify components at, max_abs bounds the outliers."},
    ("*", "head"): {
        "max_abs": 0.5, "cos": 0.99,
        "why": "head features inherit backbone drift; same bf16 rounding "
               "argument, measured against the 5e-3 signature lattice "
               "the value tier already grants (atol=1e-2)."},
    ("raft", "backbone"): {
        "max_abs": 2.0, "cos": 0.98,
        "why": "RAFT's iterative refinement re-feeds its own flow "
               "estimate 12x, compounding bf16 rounding; flow is in "
               "pixel units and ToUInt8 sinks absorb <1px drift (RAFT "
               "paper, arxiv 2003.12039) — 2px absolute headroom with "
               "direction pinned at cos>=0.98."},
    ("raft", "head"): {
        "max_abs": 2.0, "cos": 0.98,
        "why": "head == transposed backbone flow for OpticalFlow "
               "families; same band as the backbone seam."},
    ("pwc", "backbone"): {
        "max_abs": 2.0, "cos": 0.98,
        "why": "PWC's cost-volume warping cascade amplifies small input "
               "deltas across pyramid levels like RAFT's refinement "
               "loop; same pixel-unit argument and band."},
    ("pwc", "head"): {
        "max_abs": 2.0, "cos": 0.98,
        "why": "head == transposed backbone flow; same band as the "
               "backbone seam."},
}


def tolerance_for(family: str, seam: str) -> Dict[str, Any]:
    """The registry band for (family, seam), falling back to the
    ``"*"`` default for the seam."""
    band = TOLERANCES.get((str(family), seam))
    if band is None:
        band = TOLERANCES[("*", seam)]
    return band


def validate_tolerances() -> List[str]:
    """Registry self-check (tests pin it): every entry names a known
    seam, carries numeric ``max_abs``/``cos`` bounds and a non-empty
    written justification, and every seam has a ``"*"`` default."""
    errs: List[str] = []
    for (fam, seam), band in TOLERANCES.items():
        where = f"TOLERANCES[({fam!r}, {seam!r})]"
        if seam not in SEAMS:
            errs.append(f"{where}: unknown seam (SEAMS={list(SEAMS)})")
        for k in ("max_abs", "cos"):
            v = band.get(k)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                errs.append(f"{where}: {k}={v!r} is not a number")
        why = band.get("why")
        if not isinstance(why, str) or len(why.strip()) < 20:
            errs.append(f"{where}: missing a written justification "
                        "('why' must argue the band)")
    for seam in SEAMS:
        if ("*", seam) not in TOLERANCES:
            errs.append(f"TOLERANCES: no ('*', {seam!r}) default")
    return errs


# -- the observer ------------------------------------------------------------

#: frames/batches recorded per (video, seam, key): enough to prove
#: bit-stability and attribute drift, bounded so parity=true on a long
#: corpus stays a rounding error next to decode+forward
MAX_PER_KEY = 4
#: certify captures a little deeper — the corpus is pinned and tiny
CERTIFY_PER_KEY = 8


class ParityObserver:
    """Seam-digest recorder. One per run (cli.py lifecycle), installed
    as the module global via :func:`_set_active` — the taps read one
    global and no-op when it is None.

    ``capture=True`` (certify) stores bounded float64 copies of every
    tapped tensor in memory instead of journaling digests.

    ``perturb={seam: eps}`` adds ``eps`` to the *tapped copy* at that
    seam before digest/capture — the pipeline itself is untouched. It
    exists to drill the certify attribution path (tests, the CI smoke):
    an injected drift must FAIL at exactly the perturbed seam.
    """

    def __init__(self, out_root: Optional[str], host_id: Optional[str] = None,
                 max_per_key: int = MAX_PER_KEY, capture: bool = False,
                 perturb: Optional[Dict[str, float]] = None):
        self.out_root = str(out_root) if out_root is not None else None
        # fleet=queue workers co-own out_root: each appends its own
        # _parity_{host_id}.jsonl (single-writer dirs keep _parity.jsonl)
        fname = (PARITY_FILENAME if not host_id
                 else f"_parity_{host_id}.jsonl")
        self.path = (os.path.join(self.out_root, fname)
                     if self.out_root is not None else None)
        self.host_id = host_id
        self.max_per_key = int(max_per_key)
        self.perturb = dict(perturb or {})
        #: (video, family, seam, key) -> records emitted (bounds the
        #: journal per family — multi-family runs share one video path)
        self._counts: Dict[Tuple[str, str, str, str], int] = {}
        self._seam_totals: Dict[str, int] = {}
        self._lock = threading.Lock()
        #: capture mode: (video, seam, key, index) -> float64 ndarray
        self.captured: Optional[Dict[Tuple[str, str, str, int],
                                     np.ndarray]] = {} if capture else None
        self._write_failed = False
        self._closed = False

    def observe(self, seam: str, key: str, value: Any, *, video: str,
                feature_type: Optional[str], index: int) -> None:
        """Digest one tensor at one seam; bounded per (video, seam,
        key). Thread-safe: families tap concurrently under
        video_workers>1 / shared decode."""
        if self._closed or seam not in SEAMS:
            return
        ck = (str(video), str(feature_type), seam, str(key))
        with self._lock:
            n = self._counts.get(ck, 0)
            if n >= self.max_per_key:
                return
            self._counts[ck] = n + 1
            self._seam_totals[seam] = self._seam_totals.get(seam, 0) + 1
        a = np.asarray(value)
        eps = self.perturb.get(seam)
        if eps:
            a = a.astype(np.float64, copy=True) + float(eps)
        if self.captured is not None:
            self.captured[(str(video), seam, str(key), int(index))] = \
                np.asarray(a, dtype=np.float64).copy()
            return
        rec = digest_seam(seam, key, a, video=video,
                          feature_type=feature_type, index=index)
        if self.path is not None and not self._write_failed:
            try:
                append_jsonl(self.path, rec)
            except OSError as e:
                # ENOSPC discipline (telemetry writers, PR 16): a full
                # disk never kills extraction for a diagnostic — latch,
                # name it once, count it
                self._write_failed = True
                print(f"parity: disabling {PARITY_FILENAME} appends "
                      f"after write failure: {type(e).__name__}: {e}")
                from .. import telemetry
                telemetry.inc("vft_telemetry_write_failures_total",
                              pillar="parity")
        from .. import telemetry
        telemetry.inc("vft_parity_records_total",
                      family=str(feature_type), seam=seam)

    def snapshot(self) -> dict:
        """Light heartbeat section: per-seam record tallies (recorder
        ``parity_snapshot`` reads this through the module global)."""
        with self._lock:
            per_seam = dict(self._seam_totals)
        return {"records": sum(per_seam.values()), "seams": per_seam,
                "write_failed": self._write_failed} if per_seam or \
            self._write_failed else {"records": 0, "seams": {},
                                     "write_failed": False}

    def close(self) -> None:
        """Idempotent; appends are already durable (O_APPEND)."""
        self._closed = True


# -- the one-global-read off path --------------------------------------------

_active: Optional[ParityObserver] = None


def _set_active(obs: Optional[ParityObserver]) -> None:
    global _active
    _active = obs


def active() -> Optional[ParityObserver]:
    return _active


def snapshot() -> dict:
    """The active observer's heartbeat section; ``{}`` when off — the
    off-path heartbeat stays constant (roofline discipline)."""
    r = _active
    return r.snapshot() if r is not None else {}


def tap(seam: str, key: str, value: Any, *, video: str,
        feature_type: Optional[str], index: int = 0) -> None:
    """Record one tensor at one seam; one global read when off.

    Call sites additionally gate on the extractor's ``self.parity``
    attribute (like health), so a multi-family run records only the
    families that asked."""
    r = _active
    if r is not None:
        r.observe(seam, key, value, video=video,
                  feature_type=feature_type, index=index)


class TransformTap:
    """Picklable transform wrapper covering the decode and transform
    seams in one callable.

    Installed by ``extractors/base.py video_source()`` around the
    family's host transform (only when ``parity=true``), BEFORE the
    shared-decode subscribe — so shared and private decode paths tap
    identically on the family's own thread. Frames arrive sequentially
    per source, so the plain index counter is deterministic. In a
    spawned decode child the module global is unset and the tap is a
    pure pass-through of the inner transform.
    """

    def __init__(self, inner: Optional[Callable], video: str,
                 feature_type: Optional[str]):
        self.inner = inner
        self.video = str(video)
        self.feature_type = feature_type
        self._idx = 0

    def __call__(self, frame: np.ndarray) -> np.ndarray:
        r = _active
        if r is None:
            return self.inner(frame) if self.inner is not None else frame
        idx = self._idx
        self._idx = idx + 1
        r.observe("decode", "frame", frame, video=self.video,
                  feature_type=self.feature_type, index=idx)
        out = self.inner(frame) if self.inner is not None else frame
        r.observe("transform", "frame", out, video=self.video,
                  feature_type=self.feature_type, index=idx)
        return out


# -- digests -----------------------------------------------------------------

def digest_seam(seam: str, key: str, value: Any, *, video: str,
                feature_type: Optional[str], index: int) -> dict:
    """One seam tensor -> one PARITY_FIELDS-shaped record, reusing the
    health pillar's digest machinery (finite stats + the 5e-3-lattice
    content signature)."""
    from . import health
    base = health.digest_array(key, value, video=video,
                               feature_type=feature_type)
    return {
        "schema": SCHEMA_VERSION,
        "video": base["video"],
        "feature_type": base["feature_type"],
        "request_id": base["request_id"],
        "seam": str(seam),
        "key": base["key"],
        "index": int(index),
        "shape": base["shape"],
        "dtype": base["dtype"],
        "elems": base["elems"],
        "nan": base["nan"],
        "inf": base["inf"],
        "min": base["min"],
        "max": base["max"],
        "mean": base["mean"],
        "std": base["std"],
        "l2": base["l2"],
        "sig": base["sig"],
        "time": base["time"],
    }


def load_parity_schema() -> dict:
    with open(PARITY_SCHEMA_PATH, encoding="utf-8") as f:
        return json.load(f)


def load_verdict_schema() -> dict:
    with open(VERDICT_SCHEMA_PATH, encoding="utf-8") as f:
        return json.load(f)


def validate_parity(rec: dict) -> List[str]:
    from . import schema as tschema
    return tschema.validate(rec, load_parity_schema())


def validate_verdict(doc: dict) -> List[str]:
    from . import schema as tschema
    return tschema.validate(doc, load_verdict_schema())


def collect_verdicts(root: str) -> List[dict]:
    """Every ``_parity_verdict*.json`` under ``root`` (time-ordered),
    skipping frozen incident-bundle snapshots — the collector vft-fleet
    aggregation and the ``parity_drift`` alert rule share."""
    out: List[dict] = []
    for p in sorted(Path(root).rglob(VERDICT_FILENAME[:-5] + "*.json")):
        if "_incidents" in p.parts:
            continue
        try:
            doc = json.loads(p.read_text(encoding="utf-8"))
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            continue
        if isinstance(doc, dict) and str(doc.get("schema", "")).startswith(
                "vft.parity_verdict/"):
            out.append(doc)
    out.sort(key=lambda d: float(d.get("time") or 0.0))
    return out


# -- certify: reference arm vs candidate arm ---------------------------------

def _normalize_flip(flip: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """``--flip key=value`` -> (ref_overrides, cand_overrides).

    ``dtype=bf16`` is the first-class spelling: candidate runs
    ``precision=bfloat16`` against a pinned ``precision=float32``
    reference — regardless of what the YAML default currently says, so
    a certify re-run stays meaningful AFTER the default flips.
    """
    if "=" not in flip:
        raise SystemExit(f"--flip {flip!r}: expected key=value")
    key, val = flip.split("=", 1)
    key, val = key.strip(), val.strip()
    if key in ("dtype", "precision"):
        cand = {"bf16": "bfloat16", "bfloat16": "bfloat16",
                "f32": "float32", "float32": "float32"}.get(val)
        if cand is None:
            raise SystemExit(f"--flip {flip!r}: dtype must be bf16 or "
                             "float32")
        return {"precision": "float32"}, {"precision": cand}
    # generic flip: candidate override only, reference = YAML default
    return {}, {key: val}


def _pair_metrics(pairs: List[Tuple[np.ndarray, np.ndarray]]) -> dict:
    """Error attribution over aligned (ref, cand) tensor pairs."""
    max_abs = mean_num = 0.0
    mean_den = 0
    max_rel = 0.0
    cos_min = 1.0
    for r, c in pairs:
        d = np.abs(r - c)
        if d.size:
            max_abs = max(max_abs, float(d.max()))
            mean_num += float(d.sum())
            mean_den += d.size
            max_rel = max(max_rel, float(
                (d / (np.abs(r) + 1e-8)).max()))
        rn = float(np.sqrt(np.square(r).sum()))
        cn = float(np.sqrt(np.square(c).sum()))
        if rn == 0.0 and cn == 0.0:
            cos = 1.0
        elif rn == 0.0 or cn == 0.0:
            cos = 0.0
        else:
            cos = float(np.dot(r.ravel(), c.ravel()) / (rn * cn))
        cos_min = min(cos_min, cos)
    return {"pairs": len(pairs),
            "max_abs": round(max_abs, 9),
            "mean_abs": round(mean_num / mean_den, 9) if mean_den else 0.0,
            "max_rel": round(max_rel, 9),
            "cos": round(cos_min, 9)}


def compare_captures(ref: Dict[Tuple[str, str, str, int], np.ndarray],
                     cand: Dict[Tuple[str, str, str, int], np.ndarray],
                     family: str) -> Tuple[dict, Optional[str], str]:
    """(per-seam verdict table, first drifted seam or None, PASS/FAIL).

    Seams evaluate in pipeline order; a FAIL names the FIRST one out of
    band — that is the attribution the observatory exists for."""
    seams: Dict[str, dict] = {}
    first: Optional[str] = None
    for seam in SEAMS:
        rkeys = {k for k in ref if k[1] == seam}
        ckeys = {k for k in cand if k[1] == seam}
        shared = sorted(rkeys & ckeys)
        band = tolerance_for(family, seam)
        note = None
        pairs: List[Tuple[np.ndarray, np.ndarray]] = []
        for k in shared:
            a, b = ref[k], cand[k]
            if a.shape != b.shape:
                note = (f"shape drift at {k[2]}#{k[3]}: "
                        f"{list(a.shape)} vs {list(b.shape)}")
                break
            pairs.append((a, b))
        if note is None and rkeys != ckeys:
            miss = sorted(rkeys ^ ckeys)[:3]
            note = (f"record-set drift: {len(rkeys)} ref vs {len(ckeys)} "
                    f"cand captures (e.g. {[f'{m[2]}#{m[3]}' for m in miss]})")
        m = _pair_metrics(pairs)
        ok = (note is None and m["pairs"] > 0
              and m["max_abs"] <= float(band["max_abs"])
              and m["cos"] >= float(band["cos"]))
        if note is None and m["pairs"] == 0:
            note = "no captures at this seam"
        m.update(tol_max_abs=float(band["max_abs"]),
                 tol_cos=float(band["cos"]), why=band["why"],
                 ok=bool(ok), note=note)
        seams[seam] = m
        if not ok and first is None:
            first = seam
    return seams, first, ("PASS" if first is None else "FAIL")


def _certify_arm(family: str, overrides: Dict[str, Any],
                 videos: List[str], perturb: Optional[Dict[str, float]],
                 label: str) -> Dict[Tuple[str, str, str, int], np.ndarray]:
    """Run one arm in-process with an in-memory capture observer."""
    import jax

    from ..config import load_config, sanity_check
    from ..registry import get_extractor_cls

    obs = ParityObserver(out_root=None, capture=True,
                         max_per_key=CERTIFY_PER_KEY, perturb=perturb)
    prev = _active
    _set_active(obs)
    try:
        # extractors latch jax_default_matmul_precision='highest' for
        # float32 runs (extractors/base.py); both in-process arms must
        # start from the stock default or the candidate bf16 arm
        # inherits the reference arm's latch
        jax.config.update("jax_default_matmul_precision", None)
        args = load_config(family, dict(overrides))
        sanity_check(args)
        print(f"parity certify: {label} arm "
              f"({ {k: overrides[k] for k in sorted(overrides) if k in ('precision',)} or 'yaml defaults'})",
              file=sys.stderr)
        ex = get_extractor_cls(family)(args)
        for v in videos:
            feats = ex.extract(str(v))
            for key, val in feats.items():
                obs.observe("head", key, val, video=str(v),
                            feature_type=family, index=0)
    finally:
        _set_active(prev)
        jax.config.update("jax_default_matmul_precision", None)
        obs.close()
    return obs.captured or {}


def _default_corpus() -> List[str]:
    sample = (Path(__file__).resolve().parents[2] / "tests" / "assets"
              / "v_synth_sample.mp4")
    return [str(sample)] if sample.exists() else []


def _file_sha(path: str) -> Optional[str]:
    import hashlib
    try:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()
    except OSError:
        return None


def certify(family: str, flip: Optional[str] = None,
            videos: Optional[List[str]] = None,
            frames: int = 6, out_dir: Optional[str] = None,
            perturb: Optional[Dict[str, float]] = None,
            extra_overrides: Optional[Dict[str, Any]] = None) -> dict:
    """A/B certification engine; returns the verdict document (also
    written atomically to ``{out_dir}/_parity_verdict.json``)."""
    import socket
    import tempfile

    videos = list(videos or _default_corpus())
    if not videos:
        raise SystemExit("parity certify: no corpus — pass --videos or "
                         "vendor tests/assets/v_synth_sample.mp4")
    ref_flip, cand_flip = _normalize_flip(flip) if flip else ({}, {})
    with tempfile.TemporaryDirectory(prefix="vft_parity_") as td:
        base = {
            "parity": True, "cache": False, "telemetry": False,
            "allow_random_weights": True, "on_extraction": "print",
            "retry_attempts": 1, "batch_size": 4,
            "extraction_total": int(frames),
            "video_paths": list(videos),
            "output_path": os.path.join(td, "out"),
            "tmp_path": os.path.join(td, "tmp"),
        }
        base.update(extra_overrides or {})
        ref_caps = _certify_arm(family, dict(base, **ref_flip), videos,
                                None, "reference")
        cand_caps = _certify_arm(family, dict(base, **cand_flip), videos,
                                 perturb, "candidate")
    seams, first, verdict = compare_captures(ref_caps, cand_caps, family)
    doc = {
        "schema": VERDICT_SCHEMA,
        "family": str(family),
        "host": socket.gethostname(),
        "flip": flip,
        "ref": {k: str(v) for k, v in sorted(ref_flip.items())},
        "cand": {k: str(v) for k, v in sorted(cand_flip.items())},
        "corpus": [{"video": os.path.basename(v), "sha256": _file_sha(v)}
                   for v in videos],
        "seams": seams,
        "first_drift": first,
        "verdict": verdict,
        "time": round(time.time(), 3),
    }
    if out_dir is not None:
        os.makedirs(out_dir, exist_ok=True)
        write_json_atomic(os.path.join(out_dir, VERDICT_FILENAME), doc)
    return doc


def render_verdict(doc: dict) -> List[str]:
    lines = [f"== parity verdict: {doc.get('family')} "
             f"[{doc.get('verdict')}] =="
             + (f" flip={doc.get('flip')}" if doc.get("flip") else "")]
    for seam in SEAMS:
        m = (doc.get("seams") or {}).get(seam)
        if not m:
            continue
        mark = "ok " if m.get("ok") else "DRIFT"
        lines.append(
            f"  {mark} {seam:9s} pairs={m.get('pairs'):3d} "
            f"max_abs={m.get('max_abs'):.3g}/{m.get('tol_max_abs'):.3g} "
            f"mean_abs={m.get('mean_abs'):.3g} "
            f"cos={m.get('cos'):.6f}>={m.get('tol_cos')}"
            + (f"  [{m['note']}]" if m.get("note") else ""))
    if doc.get("first_drift"):
        lines.append(f"  first drifted seam: {doc['first_drift']} — "
                     "upstream seams are clean; the drift enters here")
    return lines


def certify_main(argv: Optional[List[str]] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="vft-parity certify",
        description="A/B-certify a numerics flip with per-seam error "
                    "attribution (docs/numerics.md)")
    p.add_argument("--config", help="family YAML name, e.g. raft.yml")
    p.add_argument("--family", help="family name (alternative to --config)")
    p.add_argument("--flip", default=None,
                   help="candidate-arm flip, e.g. dtype=bf16 "
                        "(omit for an identity A/B harness check)")
    p.add_argument("--videos", default=None,
                   help="comma-separated pinned corpus (default: the "
                        "vendored synth sample)")
    p.add_argument("--frames", type=int, default=6,
                   help="extraction_total per arm (default 6)")
    p.add_argument("--out", default=".",
                   help="directory for _parity_verdict.json")
    p.add_argument("--perturb", action="append", default=[],
                   metavar="SEAM=EPS",
                   help="drill knob: add EPS to the candidate arm's "
                        "tapped copies at SEAM (attribution must name it)")
    p.add_argument("--set", action="append", default=[], metavar="KEY=VAL",
                   help="extra config override for BOTH arms")
    a = p.parse_args(argv)
    family = a.family or (Path(a.config).stem if a.config else None)
    if not family:
        p.error("one of --config / --family is required")
    perturb: Dict[str, float] = {}
    for spec in a.perturb:
        seam, _, eps = spec.partition("=")
        if seam not in SEAMS:
            p.error(f"--perturb {spec!r}: seam must be one of {list(SEAMS)}")
        perturb[seam] = float(eps)
    extra: Dict[str, Any] = {}
    for spec in a.set:
        import yaml
        k, _, v = spec.partition("=")
        try:
            extra[k] = yaml.safe_load(v) if v != "" else None
        except yaml.YAMLError:
            extra[k] = v
    videos = [v for v in (a.videos or "").split(",") if v] or None
    doc = certify(family, flip=a.flip, videos=videos, frames=a.frames,
                  out_dir=a.out, perturb=perturb or None,
                  extra_overrides=extra or None)
    print("\n".join(render_verdict(doc)))
    print(f"verdict -> {os.path.join(a.out, VERDICT_FILENAME)}")
    return 0 if doc["verdict"] == "PASS" else 1


def report_main(argv: Optional[List[str]] = None) -> int:
    """``vft-parity <run_dir>``: summarize (and optionally gate on) a
    run's ``_parity.jsonl``."""
    import argparse
    from .jsonl import read_jsonl
    p = argparse.ArgumentParser(
        prog="vft-parity",
        description="Per-seam numerics observatory: summarize a run's "
                    "_parity.jsonl, or `vft-parity certify` a flip")
    p.add_argument("run_dir")
    p.add_argument("--validate", action="store_true",
                   help="exit 1 when any record violates the schema")
    a = p.parse_args(argv)
    # single-writer dirs keep _parity.jsonl; fleet=queue workers write
    # per-host _parity_{host_id}.jsonl — summarize whichever exist
    paths = sorted(Path(a.run_dir).glob("_parity*.jsonl"))
    if not paths:
        print(f"no {PARITY_FILENAME} under {a.run_dir} (parity=false?)")
        return 1 if a.validate else 0
    tallies: Dict[Tuple[str, str], int] = {}
    violations = 0
    for path in paths:
        for rec in read_jsonl(path):
            errs = validate_parity(rec)
            if errs:
                violations += 1
                for e in errs[:3]:
                    print(f"  INVALID: {e}")
            k = (str(rec.get("feature_type")), str(rec.get("seam")))
            tallies[k] = tallies.get(k, 0) + 1
    print(f"== parity records: {', '.join(str(p) for p in paths)} ==")
    for (fam, seam) in sorted(tallies):
        print(f"  {fam:12s} {seam:9s} {tallies[(fam, seam)]:5d}")
    verds = collect_verdicts(a.run_dir)
    for doc in verds:
        print("\n".join(render_verdict(doc)))
    if violations:
        print(f"{violations} schema-invalid record(s)")
    return 1 if (a.validate and violations) else 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "certify":
        return certify_main(argv[1:])
    return report_main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
