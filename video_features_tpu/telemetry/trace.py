"""Pipeline tracing: a Chrome-trace-event timeline of the host pipeline.

The stage *sums* the rest of the telemetry subsystem records
(``_telemetry.jsonl`` spans, heartbeat ``stage_delta``) can say decode
took 2x less total time while end-to-end stayed ~1x — but not WHY:
which FrameBus queue blocked, which family starved, where the critical
path ran. This module answers that with a timeline: every
``profiler.stage`` call site, every fan-out backpressure stall, every
retry backoff becomes one event in ``{output_path}/_trace.json``,
written in the Chrome trace-event format that Perfetto
(https://ui.perfetto.dev), ``chrome://tracing`` and TensorBoard all
consume — the same format ``jax.profiler`` emits for the device
timeline, so ``scripts/trace_report.py --merge`` can splice host and
device into one view.

Design constraints, in order:

  1. **zero hot-path cost when off** (the default): the module-level
     helpers read ONE global; :func:`span` returns a shared no-op
     context manager, exactly the ``NOOP_SPAN`` discipline of
     telemetry/spans.py. Per-frame call sites additionally guard on
     :func:`active` so even the kwargs dict is never built.
  2. **low overhead when on**: events append to per-THREAD buffers
     (no lock on the hot path — each buffer is owned by exactly one
     writer thread; the recorder lock is taken once per thread at
     buffer creation and once at drain);
  3. **bounded**: per-thread buffers cap at
     :data:`MAX_EVENTS_PER_THREAD`; overflow is counted and surfaced
     in the file's ``otherData``, never silently lost or unbounded;
  4. **crash-consistent**: the file materializes only at
     :meth:`TraceRecorder.close` via the same temp+fsync+``os.replace``
     discipline as every other telemetry artifact (telemetry/jsonl.py)
     — a reader can see a complete trace or no trace, never a torn one.
     ``scripts/trace_report.py`` still fails with a CLEAR message (not
     a JSON traceback) on a file torn by pre-PR writers or disk faults.

Enabled by ``trace=true`` on the CLI (cli.py owns the recorder
lifecycle, like ``telemetry=true``); composes with — but does not
require — ``telemetry=true``. Event vocabulary and the per-``ph``
required fields are pinned by :data:`REQUIRED_X_FIELDS` /
:data:`KNOWN_SPAN_NAMES`, which ``scripts/check_trace_schema.py``
validates against a real smoke run so emitter and checker cannot
drift (docs/observability.md "Reading the pipeline timeline").
"""
from __future__ import annotations

import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.profiling import profiler
from . import jsonl

TRACE_FILENAME = "_trace.json"

#: stitched/merged outputs share the ``_trace`` prefix but are never
#: inputs: trace discovery (trace_report, vft-fleet --stitch) skips them
TRACE_OUTPUT_NAMES = ("_trace_fleet.json", "_trace_merged.json")


def trace_filename(host_id: Optional[str] = None) -> str:
    """The trace artifact name: ``_trace.json`` for a single-writer
    output dir, ``_trace_{host_id}.json`` when N hosts co-own one dir
    (fleet=queue workers, vft-serve siblings on a spool) — otherwise the
    last host to close would silently overwrite every other host's
    timeline, and ``vft-fleet --stitch`` could never show the fleet.
    Sanitation matches telemetry/heartbeat.py heartbeat_filename."""
    if host_id is None:
        return TRACE_FILENAME
    import re
    safe = re.sub(r"[^A-Za-z0-9._-]+", "-", str(host_id))
    return f"_trace_{safe}.json"

#: trace format identifier stamped into ``otherData``
TRACE_SCHEMA = "vft.trace/1"

#: required keys per event phase — scripts/check_trace_schema.py
#: validates every emitted event against exactly these, so the emitter
#: and the CI gate cannot drift
REQUIRED_X_FIELDS = ("ph", "ts", "dur", "pid", "tid", "name")
REQUIRED_I_FIELDS = ("ph", "ts", "pid", "tid", "name")
REQUIRED_C_FIELDS = ("ph", "ts", "pid", "name", "args")
REQUIRED_M_FIELDS = ("ph", "pid", "name", "args")

#: the span vocabulary the instrumentation emits (beyond the
#: profiler.stage names, which arrive verbatim: decode/forward/write).
#: scripts/trace_report.py's stall ranking and critical-path verdict
#: key off these names — keep the three lists in sync.
KNOWN_SPAN_NAMES = (
    "video_attempt",        # one safe_extract attempt (args: video, attempt)
    "family",               # one family's whole per-video job (multi runs)
    "fanout.decode_pass",   # the FrameBus union decode pass, whole video
    "fanout.put_blocked",   # decoder blocked: a family's queue was full
    "fanout.get_starved",   # family blocked: waiting on the decoder
    "fanout.subscribe_wait",  # family blocked at the arrival barrier
    "prefetch.next",        # decode-ahead producer pulling one batch
    "prefetch.put_blocked",  # producer blocked: consumer fell behind
    "retry_backoff",        # fault-runtime sleep between attempts
    "wav_rip",              # ffmpeg audio rip (shared or private)
    "source_probe",         # private VideoSource construction/probing
    "fleet.claim",          # work-queue claim attempt (parallel/queue.py)
    "fleet.steal",          # instant: claimed a reclaimed item
    "fleet.reclaim",        # instant: expired lease pushed back to pending
    "fleet.idle_wait",      # queue empty, other hosts hold live leases
    "fleet.canary",         # joining-host canary re-extraction
)

#: stall names ranked by scripts/trace_report.py "top stalls" —
#: fleet.idle_wait is the per-host idle TAIL (this worker out of work
#: while a straggler finishes), the makespan cost work-stealing shrinks
STALL_SPAN_NAMES = ("fanout.put_blocked", "fanout.get_starved",
                    "fanout.subscribe_wait", "prefetch.put_blocked",
                    "retry_backoff", "fleet.idle_wait")

#: stalls shorter than this never become trace events (they still
#: accumulate into the telemetry counters): a healthy pipeline performs
#: thousands of sub-millisecond queue waits per video, and recording
#: each would cost more than the stall it observes
STALL_MIN_S = 0.001

#: per-thread event cap: first N kept, overflow counted in ``otherData``
MAX_EVENTS_PER_THREAD = 500_000

#: the active run's TraceRecorder, or None (tracing disabled)
_active: Optional["TraceRecorder"] = None


def _set_active(recorder: Optional["TraceRecorder"]) -> None:
    global _active
    _active = recorder


def active() -> Optional["TraceRecorder"]:
    """The active :class:`TraceRecorder`, if any (one global read).

    Hot per-frame call sites hold the result in a local and skip even
    the kwargs construction when it is None."""
    return _active


class _NoopTraceSpan:
    """``trace=false`` hot path: a single shared, state-free ``with``."""

    __slots__ = ()

    def __enter__(self) -> "_NoopTraceSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        return None


NOOP_TRACE_SPAN = _NoopTraceSpan()


# -- module-level helpers (no-ops when tracing is off) -----------------------

def span(name: str, **args: Any):
    """Context manager timing a block into one complete ('X') event."""
    r = _active
    if r is None:
        return NOOP_TRACE_SPAN
    return _TraceSpan(r, name, args)


def complete(name: str, t0: float, dur_s: float, **args: Any) -> None:
    """Record an externally-timed block (``t0`` from
    ``time.perf_counter()``) as one complete event."""
    r = _active
    if r is not None:
        r.complete(name, t0, dur_s, **args)


def instant(name: str, **args: Any) -> None:
    """Record a point-in-time marker."""
    r = _active
    if r is not None:
        r.instant(name, **args)


def counter(name: str, value: float, series: str = "value") -> None:
    """Record one sample of a counter track (rendered as a graph lane)."""
    r = _active
    if r is not None:
        r.counter(name, value, series)


class _TraceSpan:
    """The armed ``with`` returned by :func:`span`: times the block and
    emits on exit (exceptional exits included — a failed attempt is
    exactly the kind of span an operator wants on the timeline)."""

    __slots__ = ("_r", "_name", "_args", "_t0")

    def __init__(self, recorder: "TraceRecorder", name: str,
                 args: Dict[str, Any]) -> None:
        self._r = recorder
        self._name = name
        self._args = args
        self._t0 = 0.0

    def __enter__(self) -> "_TraceSpan":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._r.complete(self._name, self._t0,
                         time.perf_counter() - self._t0, **self._args)
        return None


class _ThreadBuf:
    __slots__ = ("events", "dropped", "tid", "tname")

    def __init__(self, tid: int, tname: str) -> None:
        self.events: List[dict] = []
        self.dropped = 0
        self.tid = tid
        self.tname = tname


class TraceRecorder:
    """Run-scoped trace collection: construct, :meth:`start`, let the
    instrumentation points feed it, :meth:`close` in a ``finally``.

    Also installs itself as the :class:`StageProfiler` trace hook, so
    every existing ``profiler.stage("decode"|"forward"|"write")`` call
    site becomes a timeline span with zero new code in the hot loops —
    the same piggyback the telemetry recorder uses for histograms.
    """

    def __init__(self, output_path: str, *,
                 pid: Optional[int] = None,
                 host_id: Optional[str] = None,
                 max_events_per_thread: int = MAX_EVENTS_PER_THREAD) -> None:
        self.output_path = str(output_path)
        self.host_id = host_id
        self.trace_path = os.path.join(self.output_path,
                                       trace_filename(host_id))
        self.pid = os.getpid() if pid is None else int(pid)
        self.max_events_per_thread = int(max_events_per_thread)
        self._t0 = time.perf_counter()
        self._start_unix = time.time()
        self._lock = threading.Lock()
        self._bufs: List[_ThreadBuf] = []
        self._tls = threading.local()
        self._closed = False

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "TraceRecorder":
        os.makedirs(self.output_path, exist_ok=True)
        _set_active(self)
        profiler.set_trace_hook(self._observe_stage)
        return self

    def close(self) -> Optional[str]:
        """Uninstall the hooks and drain every thread buffer into
        ``_trace.json`` (atomic temp+rename — complete or absent, never
        torn). Idempotent; never raises into the caller's finally.
        Returns the written path, or None."""
        if self._closed:
            return None
        self._closed = True
        profiler.set_trace_hook(None)
        if _active is self:
            _set_active(None)
        try:
            jsonl.write_json_atomic(self.trace_path, self.build_trace(),
                                    indent=None)
            return self.trace_path
        except Exception as e:
            # ENOSPC discipline: a failed trace drain is the loss of one
            # diagnostic artifact, never a crashed run — named once, and
            # counted on the active recorder when there is one
            from . import inc
            inc("vft_telemetry_write_failures_total", pillar="trace")
            print(f"trace: failed to write {self.trace_path}: "
                  f"{type(e).__name__}: {e}")
            return None

    # -- event emission (any thread) ----------------------------------------
    def _buf(self) -> _ThreadBuf:
        b = getattr(self._tls, "buf", None)
        if b is None:
            b = _ThreadBuf(threading.get_ident(),
                           threading.current_thread().name)
            with self._lock:
                self._bufs.append(b)
            self._tls.buf = b
        return b

    def _ts_us(self, perf_t: float) -> float:
        return round((perf_t - self._t0) * 1e6, 3)

    def _emit(self, ev: dict) -> None:
        if self._closed:
            return  # a straggler thread after drain: drop, never corrupt
        b = self._buf()
        if len(b.events) >= self.max_events_per_thread:
            b.dropped += 1
            return
        b.events.append(ev)

    def span(self, name: str, **args: Any) -> _TraceSpan:
        return _TraceSpan(self, name, args)

    def complete(self, name: str, t0: float, dur_s: float,
                 **args: Any) -> None:
        ev = {"ph": "X", "name": str(name), "ts": self._ts_us(t0),
              "dur": round(dur_s * 1e6, 3), "pid": self.pid,
              "tid": threading.get_ident(), "cat": "host"}
        if args:
            ev["args"] = args
        self._emit(ev)

    def instant(self, name: str, **args: Any) -> None:
        ev = {"ph": "i", "name": str(name),
              "ts": self._ts_us(time.perf_counter()), "pid": self.pid,
              "tid": threading.get_ident(), "cat": "host", "s": "t"}
        if args:
            ev["args"] = args
        self._emit(ev)

    def counter(self, name: str, value: float,
                series: str = "value") -> None:
        self._emit({"ph": "C", "name": str(name),
                    "ts": self._ts_us(time.perf_counter()), "pid": self.pid,
                    "tid": threading.get_ident(), "cat": "host",
                    "args": {series: value}})

    # -- the StageProfiler trace hook ---------------------------------------
    def _observe_stage(self, name: str, t0: float, dt: float) -> None:
        # stage names (decode/forward/write) arrive verbatim; thread
        # identity is the attribution axis — the bus decode thread, each
        # family thread and each prefetch thread get their own lane
        self.complete(name, t0, dt)

    # -- drain --------------------------------------------------------------
    def build_trace(self) -> dict:
        with self._lock:
            bufs = list(self._bufs)
        events: List[dict] = []
        dropped = 0
        for b in bufs:
            events.extend(b.events)
            dropped += b.dropped
        events.sort(key=lambda e: e.get("ts", -1.0))
        meta: List[dict] = [{
            "ph": "M", "name": "process_name", "pid": self.pid,
            "args": {"name": f"vft-host {socket.gethostname()}"}}]
        for b in bufs:
            meta.append({"ph": "M", "name": "thread_name", "pid": self.pid,
                         "tid": b.tid, "args": {"name": b.tname}})
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "schema": TRACE_SCHEMA,
                "host": socket.gethostname(),
                "host_id": self.host_id,
                "pid": self.pid,
                # the wall-clock anchor: event time = start_unix + ts/1e6.
                # trace_report --merge and vft-fleet --stitch align
                # timelines from different hosts/runs on it
                "start_unix": round(self._start_unix, 3),
                "wall_s": round(time.perf_counter() - self._t0, 3),
                "events": len(events),
                "dropped_events": dropped,
            },
        }
