"""The metric-name registry: every ``vft_*`` series, declared once.

74+ series names flow from emitters (``telemetry/__init__.py`` helpers,
``recorder.py``, serve/gateway/queue/cache counters) through heartbeat
sections to renderers (``telemetry_report``, ``vft-fleet``) and two
Prometheus exports — connected by nothing but string equality. This
module is the single source of truth ``vft-lint`` rule **VFT005**
resolves every reference against: an emitter rename that forgets a
renderer (or a new series that never gets registered) fails the lint at
review time instead of silently exporting a dead series.

Contract:

  * every string in the package or ``scripts/`` that fully matches
    ``vft_[a-z0-9_]+`` must be a key here (the lint enforces it);
  * ``kind`` is the Prometheus semantic type. **counters end in
    ``_total``** (enforced); fleet-*aggregated* monotonic sums keep the
    ``_total`` suffix even though ``vft-fleet --prom`` exports them as
    gauge samples of another process's counters — the suffix names the
    semantics, the export kind names the transport;
  * dynamically-built names (``f"vft_fleet_cache_{k}_total"``) must have
    every expansion declared here — the lint pattern-matches the
    f-string skeleton against the registry.

This module is import-light on purpose (no deps): emitters and tools may
import it, but the lint never imports anything — it reads the literal.
"""
from __future__ import annotations

#: name -> Prometheus kind ("counter" | "gauge" | "histogram")
METRICS = {
    # -- run lifecycle (telemetry/recorder.py) ------------------------------
    "vft_videos_total": "counter",
    "vft_video_wall_seconds": "histogram",
    "vft_video_processed_fps": "histogram",
    "vft_stage_seconds": "histogram",
    "vft_videos_per_second": "gauge",
    "vft_uptime_seconds": "gauge",

    # -- fault tolerance (utils/faults.py, utils/sinks.py) ------------------
    "vft_failures_total": "counter",
    "vft_video_retries_total": "counter",
    "vft_video_recoveries_total": "counter",
    "vft_decode_demotions_total": "counter",
    "vft_deadline_expirations_total": "counter",
    "vft_quarantine_skips_total": "counter",

    # -- shared-decode fan-out (parallel/fanout.py) -------------------------
    "vft_fanout_queue_depth": "gauge",
    "vft_fanout_put_blocked_ms_total": "counter",
    "vft_fanout_get_starved_ms_total": "counter",
    "vft_fanout_decode_errors_total": "counter",

    # -- output health (telemetry/health.py) --------------------------------
    "vft_health_nonfinite_total": "counter",

    # -- heartbeat flusher (telemetry/heartbeat.py) -------------------------
    "vft_heartbeat_tick_errors_total": "counter",

    # -- feature cache (cache.py via extractors/base.py, multi.py) ----------
    "vft_cache_hit_total": "counter",
    "vft_cache_miss_total": "counter",
    "vft_cache_bypass_total": "counter",
    "vft_cache_store_failures_total": "counter",

    # -- fleet queue (parallel/queue.py) ------------------------------------
    "vft_fleet_claimed_total": "counter",
    "vft_fleet_stolen_total": "counter",
    "vft_fleet_reclaimed_total": "counter",
    "vft_fleet_requeued_total": "counter",
    "vft_fleet_quarantined_total": "counter",

    # -- chaos plane (utils/inject.py) --------------------------------------
    "vft_inject_fired_total": "counter",

    # -- serve mode (serve.py) ----------------------------------------------
    "vft_serve_queue_wait_seconds": "histogram",
    "vft_serve_service_seconds": "histogram",
    "vft_serve_slo_violations_total": "counter",
    "vft_serve_deadline_exceeded_total": "counter",
    "vft_serve_reclaimed_total": "counter",
    "vft_tenant_requests_total": "counter",
    "vft_tenant_slo_violations_total": "counter",
    "vft_tenant_rejects_total": "counter",

    # -- gateway ingress (gateway.py) ---------------------------------------
    "vft_gateway_requests_total": "counter",
    "vft_gateway_upload_stored_total": "counter",
    "vft_gateway_upload_dedup_total": "counter",

    # -- fleet aggregator exports (fleet_report.py --prom): gauge samples
    #    of the fleet-wide roll-up; *_total names are sums of the
    #    per-host counters above and keep counter semantics
    "vft_fleet_hosts": "gauge",
    "vft_fleet_videos_done": "gauge",
    "vft_fleet_videos_per_s": "gauge",
    "vft_fleet_straggler": "gauge",
    "vft_fleet_queue_items": "gauge",
    "vft_fleet_cache_hits_total": "counter",
    "vft_fleet_cache_misses_total": "counter",
    "vft_fleet_cache_bypasses_total": "counter",
    "vft_fleet_cache_hit_rate": "gauge",
    "vft_fleet_compile_cache_hits_total": "counter",
    "vft_fleet_compile_cache_misses_total": "counter",
    "vft_fleet_compile_cache_hit_rate": "gauge",
    "vft_fleet_compile_cache_warm_hosts": "gauge",
    "vft_fleet_capacity_recommendation": "gauge",
    "vft_fleet_capacity_pressure": "gauge",
    "vft_fleet_capacity_pending_per_host": "gauge",
    "vft_fleet_capacity_idle_share": "gauge",
    "vft_fleet_family_done": "gauge",
    "vft_fleet_family_errors": "gauge",
    "vft_fleet_family_s_per_video": "gauge",
    "vft_fleet_serve_requests_total": "counter",
    "vft_fleet_serve_slo_violations_total": "counter",
    "vft_fleet_serve_slo_attainment_pct": "gauge",
    "vft_fleet_serve_service_seconds": "gauge",
    "vft_fleet_serve_queue_wait_seconds": "gauge",
    "vft_tenant_slo_attainment_pct": "gauge",

    # -- traffic scenarios (loadgen.py; vft-fleet == scenarios == + --prom) -
    "vft_loadgen_offered_total": "counter",
    "vft_loadgen_admitted_total": "counter",
    "vft_loadgen_rejected_total": "counter",
    "vft_loadgen_shed_total": "counter",
    "vft_loadgen_completed_total": "counter",
    "vft_loadgen_expired_total": "counter",
    "vft_scenario_pass": "gauge",
    "vft_scenario_offered": "gauge",
    "vft_scenario_admitted": "gauge",
    "vft_scenario_completed": "gauge",
    "vft_scenario_expired": "gauge",
    "vft_scenario_rejected": "gauge",
    "vft_scenario_shed": "gauge",
    "vft_scenario_attainment_pct": "gauge",

    # -- parity observatory (telemetry/parity.py; vft-fleet == parity ==) ---
    "vft_parity_records_total": "counter",
    "vft_parity_seam_error": "gauge",
    "vft_parity_verdict_pass": "gauge",

    # -- roofline observatory (telemetry/roofline.py via vft-fleet) ---------
    "vft_roofline_mfu": "gauge",
    "vft_roofline_effective_tflops": "gauge",
    "vft_roofline_dispatches_total": "counter",
    "vft_roofline_peak_tflops": "gauge",

    # -- telemetry writer self-health (recorder/history/trace pillars) ------
    "vft_telemetry_write_failures_total": "counter",

    # -- storage lifecycle plane (gc.py via vft-gc / vft-fleet) -------------
    "vft_gc_plane_bytes": "gauge",
    "vft_gc_tenant_bytes": "gauge",
    "vft_gc_used_bytes": "gauge",
    "vft_gc_quota_bytes": "gauge",
    "vft_gc_evicted_total": "counter",
    "vft_gc_evicted_bytes_total": "counter",
    "vft_gc_retained_total": "counter",
    "vft_gc_sweeps_total": "counter",
    "vft_gc_sweep_errors_total": "counter",
}


def kind_of(name: str) -> str:
    """The declared kind, or raise — emitters may use this to assert a
    name is registered before first emission (tests do)."""
    return METRICS[name]
