"""Run manifest: ``{output_path}/_run.json``, written once at exit.

The manifest makes a run auditable and reproducible from its artifacts
alone: the exact config it ran with, the code version (git commit +
package versions), the hardware it saw (device/mesh topology,
parallel/mesh.py), what it did (tally, per-stage aggregates, metrics
dump) and what the XLA compile cache contributed (hit/miss counts —
the visibility PAPERS.md's compiler-first inference work argues is a
prerequisite for any principled perf claim). Written via atomic replace
(telemetry/jsonl.py) so a preempted exit never leaves a torn document.
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
import time
from typing import Any, Dict, Optional

MANIFEST_SCHEMA_VERSION = "vft.run_manifest/1"
MANIFEST_FILENAME = "_run.json"


def _git_describe(cwd: Optional[str] = None) -> Dict[str, Any]:
    """Best-effort commit + dirty flag; a worker outside a checkout (pip
    install, docker) reports ``unknown`` rather than failing the run."""
    try:
        root = cwd or os.path.dirname(os.path.abspath(__file__))
        rev = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=root, capture_output=True,
            text=True, timeout=5)
        if rev.returncode != 0:
            return {"commit": "unknown"}
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], cwd=root, capture_output=True,
            text=True, timeout=5)
        return {"commit": rev.stdout.strip(),
                "dirty": bool(dirty.stdout.strip())
                if dirty.returncode == 0 else None}
    except Exception:
        return {"commit": "unknown"}


def _versions() -> Dict[str, str]:
    out = {"python": sys.version.split()[0]}
    from .. import __version__
    out["video_features_tpu"] = __version__
    for mod in ("jax", "jaxlib", "flax", "numpy", "cv2", "yaml"):
        try:
            m = __import__(mod)
            out[mod] = str(getattr(m, "__version__", "?"))
        except Exception:
            out[mod] = "absent"
    return out


def _topology() -> Dict[str, Any]:
    """Device/mesh topology via parallel/mesh.py; defensive — a manifest
    must still be written when the backend is torn down or absent."""
    try:
        from ..parallel.mesh import mesh_topology
        return mesh_topology()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def build_manifest(*,
                   run_config: Optional[dict] = None,
                   feature_type: Optional[str] = None,
                   host_id: Optional[str] = None,
                   run_id: Optional[str] = None,
                   started_time: Optional[float] = None,
                   wall_s: Optional[float] = None,
                   tally: Optional[Dict[str, int]] = None,
                   failure_tallies: Optional[Dict[str, int]] = None,
                   stage_totals: Optional[Dict[str, Any]] = None,
                   metrics_dump: Optional[dict] = None,
                   compile_cache: Optional[Dict[str, int]] = None,
                   health: Optional[Dict[str, Dict[str, int]]] = None,
                   roofline: Optional[dict] = None,
                   ) -> dict:
    done = (tally or {}).get("done", 0)
    return {
        "schema": MANIFEST_SCHEMA_VERSION,
        "feature_type": feature_type,
        "host": socket.gethostname(),
        "host_id": host_id,
        # matches the run_id in this run's heartbeats; report tools use it
        # to ignore stale heartbeat files from a prior run of the same dir
        "run_id": run_id,
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "started_time": started_time,
        "finished_time": round(time.time(), 3),
        "wall_s": None if wall_s is None else round(float(wall_s), 3),
        "videos_per_s": (round(done / wall_s, 4)
                         if wall_s and done else None),
        "tally": dict(tally or {}),
        "failure_tallies": dict(failure_tallies or {}),
        "stage_totals": dict(stage_totals or {}),
        "compile_cache": dict(compile_cache or {}),
        # output-health roll-up (telemetry/health.py): per-family digest
        # record + NaN/Inf totals; {} when health=false (nothing observed)
        "health": dict(health or {}),
        # roofline accounting (telemetry/roofline.py): the run's final
        # per-family MFU/verdict document; {} when roofline=false
        "roofline": dict(roofline or {}),
        "config": dict(run_config or {}),
        "versions": _versions(),
        "git": _git_describe(),
        "topology": _topology(),
        "metrics": metrics_dump or {"series": []},
    }
