"""Structured telemetry: metrics, per-video spans, manifest, heartbeats.

This package subsumes and extends the interactive stage timer
(utils/profiling.py) into the observability layer the ROADMAP's
production fleet needs — operators answer "which worker is slow, which
video stalled, is the chip or the host the bottleneck, and what did
last night's run actually do" from *artifacts*, not a live terminal:

  ===========================  ============================================
  ``_telemetry.jsonl``         one span record per video (telemetry/spans.py,
                               schema in ``video_span.schema.json``)
  ``_run.json``                run manifest at exit (telemetry/manifest.py)
  ``_heartbeat_{host_id}.json``  periodic per-worker liveness
                               (telemetry/heartbeat.py)
  ``_health.jsonl``            per-(video, family, key) feature digests
                               (telemetry/health.py, ``health=true``;
                               schema in ``feature_health.schema.json``)
  metrics registry             counters/gauges/fixed-bucket histograms
                               (telemetry/metrics.py), dumped into the
                               manifest + Prometheus export via
                               ``scripts/telemetry_report.py``
  ===========================  ============================================

Enabled by ``telemetry=true`` (+ ``metrics_interval_s=``) on the CLI;
cli.py owns the :class:`~.recorder.TelemetryRecorder` lifecycle. The
instrumentation points in utils/sinks.py, utils/faults.py, utils/io.py
and extractors/base.py call the module-level helpers below, which cost
one global (or thread-local) read when telemetry is off — the same
permanently-in-place, near-zero-disabled-overhead discipline as
``profiler.stage``.
"""
from __future__ import annotations

from typing import Any, Optional

from .spans import (NOOP_SPAN, NoopSpan, SPAN_FIELDS, STATUSES,  # noqa: F401
                    VideoSpan, current_span, use_span)
from .context import current_request_id, use_request  # noqa: F401
from .metrics import MetricsRegistry, prometheus_text  # noqa: F401

#: the active run's TelemetryRecorder, or None (telemetry disabled)
_active = None


def _set_active(recorder) -> None:
    global _active
    _active = recorder


def active():
    """The active :class:`~.recorder.TelemetryRecorder`, if any."""
    return _active


# -- cheap instrumentation helpers (no-ops when telemetry is off) -----------

def inc(name: str, n: float = 1.0, **labels: Any) -> None:
    """Increment a counter on the active recorder's registry."""
    r = _active
    if r is not None:
        r.registry.counter(name, **labels).inc(n)


def observe(name: str, value: float, buckets=None, **labels: Any) -> None:
    """Observe into a histogram on the active recorder's registry."""
    r = _active
    if r is not None:
        r.registry.histogram(name, buckets=buckets, **labels).observe(value)


def gauge_set(name: str, value: float, **labels: Any) -> None:
    """Set a gauge on the active recorder's registry — the fan-out
    queue-depth sampling path (parallel/fanout.py)."""
    r = _active
    if r is not None:
        r.registry.gauge(name, **labels).set(value)


def annotate(**kw: Any) -> None:
    """Set attributes on this thread's current video span, if any."""
    s = current_span()
    if s is not None:
        s.annotate(**kw)


def event(kind: str, **kw: Any) -> None:
    """Append a timeline event to this thread's current video span."""
    s = current_span()
    if s is not None:
        s.event(kind, **kw)
