"""Request-scoped correlation: one id threads a request through every
telemetry channel it touches.

The serving arc (serve.py spool, parallel/queue.py fleet leases) made
the telemetry pillars *per-host, per-run* — a client's request fans out
into span records, health digests, trace spans, failure-journal entries
and a ``done/`` response, possibly on different hosts, with nothing
tying them back to the request. This module is that tie: serve.py
installs the request id thread-locally around a request's videos
(:func:`use_request`), and every emitter that writes a per-video
artifact reads it back with :func:`current_request_id`:

  ==============================  =====================================
  ``_telemetry.jsonl`` span       ``request_id`` field (spans.py;
                                  ``video_span.schema.json``)
  ``_health.jsonl`` digest        ``request_id`` field (health.py;
                                  ``feature_health.schema.json``)
  ``_failures.jsonl`` record      ``request_id`` field (utils/faults.py,
                                  only when a request is in scope)
  ``_trace.json`` span            ``request`` arg on ``video_attempt``
                                  (utils/sinks.py) and the
                                  ``serve.request`` umbrella (serve.py)
  fleet-queue lease               ``request_id`` stamp on the claim
                                  record (parallel/queue.py)
  ``done/{id}.json`` response     the id IS the filename (serve.py)
  ==============================  =====================================

so ``grep -r <request_id>`` over an output root (or
``vft-fleet --request <id>``) retrieves every artifact one request
produced on any host.

Outside serve mode nothing installs a request, :func:`current_request_id`
returns None, and the correlated fields serialize as null/absent —
batch-run artifacts are unchanged except for the one nullable field the
schemas declare. The read is a single thread-local ``getattr``, the same
cost class as :func:`~.spans.current_span`.

Propagation is thread-local on purpose: one request's videos run
sequentially on the serve worker thread that claimed it (serve.py
``_process``), and decode-ahead producer threads already re-install the
consumer's span (``use_span``) — stage observations from unpropagated
threads were never attributed per-video, and the same holds per-request.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Iterator, Optional

_tls = threading.local()


def current_request_id() -> Optional[str]:
    """The request id installed on THIS thread, if any (one getattr)."""
    return getattr(_tls, "request_id", None)


def tenant_of(request_id: Optional[str]) -> Optional[str]:
    """The tenant component of a gateway-minted request id.

    The gateway (gateway.py) mints ids as ``{tenant}-{rid}`` — tenant
    names are ``[a-z0-9_]+`` (dash-free, enforced at tenant-table load)
    and the random suffix is dash-free hex, so the first ``-`` splits
    unambiguously. Spool-direct clients use plain ``uuid4().hex`` ids
    with no dash: those (and None) return None — the single-implicit-
    tenant world keeps working untouched."""
    if not request_id:
        return None
    head, sep, rest = str(request_id).partition("-")
    return head if sep and head and rest else None


def current_tenant() -> Optional[str]:
    """Tenant of the request installed on THIS thread, if any — how the
    feature cache's ``cache_scope=tenant`` keys entries per tenant
    without any plumbing through the extractor stack."""
    return tenant_of(current_request_id())


@contextmanager
def use_request(request_id: Optional[str]) -> Iterator[None]:
    """Install ``request_id`` thread-locally for a block — serve.py
    wraps each claimed request's video loop in this, so every per-video
    emitter below it correlates without new plumbing through the
    extractor stack."""
    prev = getattr(_tls, "request_id", None)
    _tls.request_id = None if request_id is None else str(request_id)
    try:
        yield
    finally:
        _tls.request_id = prev
