"""Durable, downsampled retention of per-host heartbeat series.

Heartbeats are *overwritten in place* every ``metrics_interval_s`` —
perfect for "is it alive now", useless five minutes after an incident:
by the time an operator opens the dir, the ticks that explained the SLO
burn are gone, and every windowed signal (burn rates, capacity slopes)
has to be reconstructed from whatever one process happened to hold in
memory. This module is the retention half of the alerting plane
(telemetry/alerts.py): each heartbeat tick also appends a *compact
sample* to ``{output_path}/_history_{host_id}.jsonl``, so

  - multi-window SLO burn rates are deltas between real samples, not
    guesses (``window_delta``);
  - the :class:`~..fleet_report.CapacityPlanner` slope inputs survive
    ``vft-fleet`` restarts (it seeds ``_prev`` from here);
  - MFU-regression alerts compare a family against ITS OWN history.

**Tiered downsampling** keeps a week of 2-second ticks bounded: recent
samples are kept at full resolution, older ones are thinned to one per
widening period, and samples past the last tier are dropped — see
:data:`TIERS`. Compaction rewrites the file atomically every
:data:`COMPACT_EVERY` appends; history files are single-writer (the
host_id is in the filename, the same discipline as heartbeats), so the
rewrite cannot race another producer. Readers (`read_history`) get the
usual jsonl torn-tail tolerance.

Samples are a pure function of the heartbeat the recorder just built
(:func:`sample_from_heartbeat`), so the retained series is exactly what
a live observer would have seen — no second measurement path to drift.
"""
from __future__ import annotations

import json
import os
import re
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from . import jsonl

HISTORY_PREFIX = "_history_"
HISTORY_GLOB = HISTORY_PREFIX + "*.jsonl"

SAMPLE_SCHEMA = "vft.history_sample/1"

#: tiered retention: ``(max_age_s, keep_one_per_s)`` — samples younger
#: than the first bound keep full resolution (period 0); each older tier
#: thins to one sample per period; anything past the last bound is
#: dropped. A 2s-tick host retains ~300 + 120 + 288 + 336 ≈ 1k samples
#: for a full week instead of ~300k.
TIERS: Tuple[Tuple[float, float], ...] = (
    (600.0, 0.0),         # last 10 min: every tick
    (3600.0, 30.0),       # last hour: one per 30 s
    (86400.0, 300.0),     # last day: one per 5 min
    (7 * 86400.0, 1800.0),  # last week: one per 30 min
)

#: appends between compaction passes (amortizes the atomic rewrite)
COMPACT_EVERY = 256


def history_filename(host_id: str) -> str:
    """``_history_{host_id}.jsonl``, filesystem-sanitized like the
    heartbeat filename (host ids embed hostnames and pids)."""
    safe = re.sub(r"[^A-Za-z0-9._-]+", "-", str(host_id))
    return f"{HISTORY_PREFIX}{safe}.jsonl"


# -- sampling ----------------------------------------------------------------

def _num(v, default=0.0) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def _attainment(requests: int, violations: int) -> Optional[float]:
    if not requests:
        return None
    return round(100.0 * (requests - violations) / requests, 2)


def sample_from_heartbeat(hb: dict,
                          nonfinite_total: Optional[int] = None) -> dict:
    """Compact, JSON-safe sample off one heartbeat dict: cumulative
    counters the alert windows diff (requests/violations, cache and
    compile-cache tallies, fleet reclaim/quarantine counts, videos by
    status) plus instantaneous gauges (queue depths, MFU per family).
    ``nonfinite_total`` comes from the recorder's health roll-up — the
    heartbeat itself doesn't carry it."""
    sample: Dict[str, object] = {
        "schema": SAMPLE_SCHEMA,
        "time": _num(hb.get("time"), time.time()),
        "host_id": hb.get("host_id"),
        "run_id": hb.get("run_id"),
        "uptime_s": _num(hb.get("uptime_s")),
        "final": bool(hb.get("final")),
        # stable keys materialized at 0: a counter that first appears
        # mid-run would otherwise have no baseline sample, and the spike
        # windows would read "no data" instead of "it was zero"
        "videos": {k: int((hb.get("videos") or {}).get(k) or 0)
                   for k in ("done", "skipped", "error", "quarantined")},
        "videos_done": int(hb.get("videos_done") or 0),
        "videos_per_s": _num(hb.get("videos_per_s")),
    }
    if nonfinite_total is not None:
        sample["nonfinite_total"] = int(nonfinite_total)
    ca = hb.get("cache") or {}
    if any((ca.get(k) or {}) for k in ("hits", "misses", "bypasses")):
        sample["cache"] = {
            "hits": sum(int(v) for v in (ca.get("hits") or {}).values()),
            "misses": sum(int(v) for v in (ca.get("misses") or {}).values()),
            "bypasses": sum(int(v)
                            for v in (ca.get("bypasses") or {}).values()),
        }
    cc = hb.get("compile_cache") or {}
    if cc:
        sample["compile_cache"] = {"hits": int(cc.get("hits") or 0),
                                   "misses": int(cc.get("misses") or 0)}
    fl = hb.get("fleet")
    if isinstance(fl, dict):
        q = fl.get("queue") or {}
        sample["fleet"] = {
            "active_claims": int(fl.get("active_claims") or 0),
            "stolen": int(fl.get("stolen") or 0),
            "reclaimed": int(fl.get("reclaimed") or 0),
            "quarantined": int(fl.get("quarantined") or 0),
            "idle_wait_s_total": _num(fl.get("idle_wait_s_total")),
            "queue": {k: int(q.get(k) or 0)
                      for k in ("pending", "claimed", "done",
                                "quarantined")},
        }
    serve = hb.get("serve")
    if isinstance(serve, dict):
        slo = serve.get("slo") or {}
        sample["slo"] = {
            "slo_s": slo.get("slo_s"),
            "requests": int(slo.get("requests") or 0),
            "violations": int(slo.get("violations") or 0),
        }
        sample["serve_pending"] = int(serve.get("pending") or 0)
        tens = serve.get("tenants")
        if isinstance(tens, dict) and tens:
            # per-tenant cumulative counters: what the tenant-scoped
            # SLO burn windows diff (telemetry/alerts.py). Tenant names
            # are [a-z0-9_]+ (gateway.py), so the dotted-path readers
            # (`_field`) can address them safely
            # cumulative attainment rides along so scenario curves
            # (loadgen.py) can be rebuilt from retained history alone
            # after the run — per-tenant was heartbeat-only before
            sample["tenants"] = {
                str(t): {"requests": int(v.get("requests") or 0),
                         "violations": int(v.get("violations") or 0),
                         "attainment_pct": _attainment(
                             int(v.get("requests") or 0),
                             int(v.get("violations") or 0))}
                for t, v in tens.items()}
    rf = hb.get("roofline") or {}
    fams = rf.get("families") if isinstance(rf, dict) else None
    if fams:
        sample["mfu"] = {fam: f.get("mfu") for fam, f in fams.items()
                         if isinstance(f, dict)}
    gc = hb.get("gc")
    if isinstance(gc, dict):
        # storage accounting (gc.py GcMonitor): the disk_pressure rule
        # reads used/quota levels and diffs used_bytes across windows to
        # project time-to-full
        sample["gc"] = {
            "used_bytes": int(gc.get("used_bytes") or 0),
            "quota_bytes": (int(gc["quota_bytes"])
                            if gc.get("quota_bytes") else None),
        }
    return sample


# -- tiered downsampling -----------------------------------------------------

def downsample(samples: Sequence[dict],
               now: Optional[float] = None, *,
               tiers: Sequence[Tuple[float, float]] = TIERS) -> List[dict]:
    """Apply ``tiers`` (default :data:`TIERS`) to a time-sorted sample
    list: within each tier, keep the LAST sample of every
    ``period``-wide bucket (the freshest state of that interval —
    windowed deltas read end-of-bucket counters); drop samples older
    than the final tier. Pure function, so tests drive it with a fake
    clock — and scripts/bench_history.py reuses it with bench-cadence
    tiers instead of copying the algorithm."""
    now = time.time() if now is None else float(now)
    kept: List[dict] = []
    buckets_seen: Dict[Tuple[int, int], int] = {}
    ordered = sorted(samples, key=lambda s: _num(s.get("time")))
    # walk newest -> oldest so "keep the last per bucket" is "keep the
    # first encountered", then restore chronological order at the end
    for s in reversed(ordered):
        t = _num(s.get("time"))
        age = now - t
        tier = None
        for i, (max_age, period) in enumerate(tiers):
            if age <= max_age:
                tier = (i, period)
                break
        if tier is None:
            continue  # past the last tier: dropped
        i, period = tier
        if period <= 0:
            kept.append(s)
            continue
        bucket = (i, int(t // period))
        if bucket in buckets_seen:
            continue
        buckets_seen[bucket] = 1
        kept.append(s)
    kept.reverse()
    return kept


# -- the writer --------------------------------------------------------------

class HistoryWriter:
    """Single-writer append + periodic compaction for one host's series.

    Attach it to a recorder (:meth:`attach`) and every heartbeat tick
    lands one sample; or drive :meth:`observe` directly with samples
    (tests, serve loops)."""

    def __init__(self, output_path: str, host_id: str,
                 clock=time.time) -> None:
        self.path = os.path.join(str(output_path),
                                 history_filename(host_id))
        self.host_id = str(host_id)
        self.clock = clock
        self._appends_since_compact = 0
        self._recorder = None
        # degradation latch (ENOSPC discipline): one failed append or
        # compaction disables the retention pillar for the run — the
        # alert windows go quiet, the extraction does not die
        self._disabled = False

    def observe(self, sample: dict) -> None:
        if self._disabled:
            return
        try:
            jsonl.append_jsonl(self.path, sample)
            self._appends_since_compact += 1
            if self._appends_since_compact >= COMPACT_EVERY:
                self.compact()
        except OSError as e:
            self._disabled = True
            from . import inc
            inc("vft_telemetry_write_failures_total", pillar="history")
            print(f"telemetry: failed to append {self.path} "
                  f"({type(e).__name__}: {e}) — history retention "
                  "disabled for this run")

    def compact(self, now: Optional[float] = None) -> int:
        """Rewrite the file through :func:`downsample` (atomic temp +
        replace — the heartbeat's own discipline). Returns the retained
        sample count. Safe: this host is the file's only writer."""
        now = self.clock() if now is None else now
        samples = list(jsonl.read_jsonl(self.path))
        kept = downsample(samples, now=now)
        tmp = self.path + ".compact.tmp"
        try:
            # vft-lint: disable=VFT004 — temp+fsync+os.replace in place (line-oriented rewrite; jsonl.py appends records, it does not rewrite files)
            with open(tmp, "w", encoding="utf-8") as f:
                for s in kept:
                    f.write(json.dumps(s, sort_keys=True) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._appends_since_compact = 0
        return len(kept)

    # -- recorder hook ------------------------------------------------------
    def attach(self, recorder) -> "HistoryWriter":
        """Register on the recorder's tick hooks: every heartbeat write
        (including the first and the final one) appends one sample."""
        self._recorder = recorder
        recorder.tick_hooks.append(self._on_tick)
        return self

    def _on_tick(self, hb: dict) -> None:
        nonfinite = None
        r = self._recorder
        if r is not None:
            try:
                health = r.health_summary()
                nonfinite = sum(int(h.get("nan", 0)) + int(h.get("inf", 0))
                                for h in health.values())
            except Exception:
                nonfinite = None
        self.observe(sample_from_heartbeat(hb, nonfinite_total=nonfinite))


# -- readers -----------------------------------------------------------------

def read_history(root: str) -> Dict[str, List[dict]]:
    """Every host's retained series under ``root`` (recursively, like
    heartbeat collection): ``{host_id: [samples sorted by time]}``.
    The host id is read from the records themselves (filename sanitizing
    is lossy); files whose records carry none key by filename."""
    out: Dict[str, List[dict]] = {}
    for p in sorted(Path(str(root)).rglob(HISTORY_GLOB)):
        if "_incidents" in p.parts:
            continue  # bundle tails are frozen evidence, not live series
        fallback = p.name[len(HISTORY_PREFIX):-len(".jsonl")]
        for rec in jsonl.read_jsonl(p):
            if rec.get("schema") != SAMPLE_SCHEMA:
                continue
            host = str(rec.get("host_id") or fallback)
            out.setdefault(host, []).append(rec)
    for host in out:
        out[host].sort(key=lambda s: _num(s.get("time")))
    return out


def _field(sample: dict, path: str):
    cur: object = sample
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def latest(samples: Sequence[dict], path: str):
    """The newest sample's value at dotted ``path`` (None when the
    series is empty or the field is absent from the newest sample)."""
    if not samples:
        return None
    return _field(samples[-1], path)


def window_delta(samples: Sequence[dict], path: str, now: float,
                 window_s: float, allow_negative: bool = False
                 ) -> Optional[Tuple[float, float]]:
    """``(value_delta, span_s)`` of the value at dotted ``path`` over
    roughly the last ``window_s`` seconds: newest sample minus the
    newest sample at least ``window_s`` old. When the series is younger
    than the window the OLDEST sample is the baseline (a partial window
    — ``span_s`` tells the caller how partial), which is what makes
    short runs alertable at all. None when fewer than two samples carry
    the field — or, for cumulative counters (``allow_negative=False``,
    the default), when the counter reset (delta < 0: a new run reusing
    the dir — a window across runs is meaningless). Gauges that
    legitimately shrink (queue depth) pass ``allow_negative=True``."""
    series = [(_num(s.get("time")), _field(s, path)) for s in samples]
    series = [(t, _num(v)) for t, v in series if v is not None]
    if len(series) < 2:
        return None
    t_new, v_new = series[-1]
    baseline = series[0]
    cutoff = float(now) - float(window_s)
    for t, v in series:
        if t <= cutoff:
            baseline = (t, v)
        else:
            break
    t_old, v_old = baseline
    if t_new <= t_old:
        return None
    delta = v_new - v_old
    if delta < 0 and not allow_negative:
        return None
    return delta, t_new - t_old


def window_rate(samples: Sequence[dict], num_path: str, den_path: str,
                now: float, window_s: float
                ) -> Optional[Tuple[float, float, float]]:
    """``(numerator_delta, denominator_delta, ratio)`` of two cumulative
    counters over one shared window — the burn-rate primitive
    (violations over requests). None when either counter is unreadable
    or nothing happened in the window (denominator delta == 0)."""
    num = window_delta(samples, num_path, now, window_s)
    den = window_delta(samples, den_path, now, window_s)
    if num is None or den is None or den[0] <= 0:
        return None
    return num[0], den[0], num[0] / den[0]
