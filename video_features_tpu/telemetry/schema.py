"""Checked-in JSON Schema for telemetry records + a dependency-free
validator.

TPU workers must not grow a ``jsonschema`` dependency for a validation
path that only tests and the CI gate exercise, so :func:`validate`
implements exactly the Draft-7 subset the span schema uses: ``type``
(including union lists and ``null``), ``enum``, ``required``,
``properties``, ``additionalProperties`` (bool or schema) and ``items``.
Unsupported keywords raise — silently ignoring a constraint would make
the gate vacuous.
"""
from __future__ import annotations

import json
import os
from typing import Any, List

SPAN_SCHEMA_PATH = os.path.join(os.path.dirname(__file__),
                                "video_span.schema.json")

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "integer": int,
    "boolean": bool,
    "null": type(None),
}

_HANDLED = {"$schema", "title", "description", "type", "enum", "required",
            "properties", "additionalProperties", "items"}


def load_span_schema() -> dict:
    with open(SPAN_SCHEMA_PATH, encoding="utf-8") as f:
        return json.load(f)


def _type_ok(value: Any, t: str) -> bool:
    if t == "number":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    py = _TYPES[t]
    if py is int:
        return isinstance(value, int) and not isinstance(value, bool)
    return isinstance(value, py)


def validate(value: Any, schema: dict, path: str = "$") -> List[str]:
    """Return a list of violation strings ('' path syntax: ``$.stages.s``);
    empty list == valid."""
    errs: List[str] = []
    unknown = set(schema) - _HANDLED
    if unknown:
        raise NotImplementedError(
            f"schema at {path} uses unsupported keywords {sorted(unknown)}; "
            "extend telemetry/schema.py before using them")
    if "enum" in schema:
        if value not in schema["enum"]:
            errs.append(f"{path}: {value!r} not in enum {schema['enum']}")
        return errs
    t = schema.get("type")
    if t is not None:
        types = t if isinstance(t, list) else [t]
        if not any(_type_ok(value, tt) for tt in types):
            errs.append(f"{path}: {type(value).__name__} is not {t}")
            return errs
    if isinstance(value, dict):
        props = schema.get("properties", {})
        for req in schema.get("required", []):
            if req not in value:
                errs.append(f"{path}: missing required key {req!r}")
        extra = schema.get("additionalProperties", True)
        for k, v in value.items():
            if k in props:
                errs.extend(validate(v, props[k], f"{path}.{k}"))
            elif extra is False:
                errs.append(f"{path}: unexpected key {k!r}")
            elif isinstance(extra, dict):
                errs.extend(validate(v, extra, f"{path}.{k}"))
    if isinstance(value, list) and "items" in schema:
        for i, v in enumerate(value):
            errs.extend(validate(v, schema["items"], f"{path}[{i}]"))
    return errs


def validate_span(rec: dict) -> List[str]:
    return validate(rec, load_span_schema())
