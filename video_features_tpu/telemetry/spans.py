"""Per-video lifecycle spans: one JSONL record per video attempt-set.

A :class:`VideoSpan` covers everything that happens to one video under
``safe_extract`` (utils/sinks.py): every retry, decode-ladder demotion,
stage timing and the terminal status, flattened into ONE record appended
to ``{output_path}/_telemetry.jsonl``. The record answers post-hoc what
tqdm could only show live: which video stalled, how many attempts it
burned, whether decode or forward dominated, and why it failed.

Propagation is thread-local (:func:`current_span` /
:func:`use_span`): ``safe_extract`` runs the attempt with the span
installed on its thread, and decode-ahead threads (utils/io.py
``Prefetcher``) re-install the span they captured at construction, so
stage timings from the producer thread still attribute to the right
video. Stage observations that happen on unpropagated threads (e.g.
inside a ``ProcessVideoSource`` child) are not attributed per-video but
still land in the global histograms (telemetry/metrics.py).

The record shape is frozen by ``video_span.schema.json`` (same
directory); :data:`SPAN_FIELDS` is the single source of truth for the
emitter and ``scripts/check_telemetry_schema.py`` fails CI when the two
drift.
"""
from __future__ import annotations

import os
import socket
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

#: schema identifier stamped into every record; bump on breaking change
SCHEMA_VERSION = "vft.video_span/1"

#: terminal statuses, mirroring safe_extract's return values
STATUSES = ("done", "skipped", "error", "quarantined")

#: cap on per-span timeline events: the first N are kept verbatim, the
#: overflow is counted and reported as one final ``events_dropped``
#: record. A pathological retry loop (or a future instrumentation bug)
#: must never grow a span's in-memory record without bound before it
#: serializes — spans are per-video observations, not logs.
MAX_SPAN_EVENTS = 256

#: exactly the top-level keys of every emitted record, in emit order —
#: scripts/check_telemetry_schema.py asserts these equal the JSON
#: Schema's properties, and tests validate emitted records against both
SPAN_FIELDS = (
    "schema", "video", "status", "feature_type", "host", "host_id", "pid",
    "request_id", "start_time", "wall_s", "attempts", "category", "error",
    "decode_mode", "decode_shared_ms", "ladder_steps", "stages",
    "video_fps", "video_frames", "events",
)

_tls = threading.local()


def current_span() -> Optional["VideoSpan"]:
    """The span installed on THIS thread, if any (cheap: one getattr)."""
    return getattr(_tls, "span", None)


@contextmanager
def use_span(span: Optional["VideoSpan"]) -> Iterator[None]:
    """Install ``span`` thread-locally for a block — how decode-ahead
    producer threads inherit the consumer's per-video attribution."""
    prev = getattr(_tls, "span", None)
    _tls.span = span
    try:
        yield
    finally:
        _tls.span = prev


class VideoSpan:
    """Accumulates one video's lifecycle; emits on ``__exit__``.

    Safe for concurrent stage observations (decode producer thread +
    consumer thread); annotations/events are expected from the owning
    thread but are lock-guarded anyway — a span must never corrupt
    under misuse, only lose precision.
    """

    def __init__(self, video: str, recorder=None,
                 feature_type: Optional[str] = None,
                 host_id: Optional[str] = None) -> None:
        from .context import current_request_id
        self.video = str(video)
        self.recorder = recorder
        self.feature_type = feature_type
        self.host_id = host_id
        # request-scoped correlation (telemetry/context.py): spans are
        # minted on the serve worker thread that owns the request, so the
        # id is captured here once; None outside serve mode
        self.request_id = current_request_id()
        self.record: Optional[dict] = None  # set at __exit__
        self._lock = threading.Lock()
        self._attrs: Dict[str, Any] = {}
        self._stages: Dict[str, List[float]] = {}  # name -> [seconds, calls]
        self._events: List[dict] = []
        self._events_dropped = 0
        self._ladder: List[str] = []
        self._t0 = time.perf_counter()
        self._start_time = time.time()
        self._prev = None

    # -- instrumentation points (called from sinks/faults/io/base) ----------
    def observe_stage(self, name: str, dt: float) -> None:
        with self._lock:
            s = self._stages.get(name)
            if s is None:
                self._stages[name] = [dt, 1]
            else:
                s[0] += dt
                s[1] += 1

    def annotate(self, **kw: Any) -> None:
        """Set/overwrite top-level record attributes (status, attempts,
        category, error, decode_mode, video_fps, video_frames...).
        Unknown keys are dropped at build time, never emitted — the
        schema is closed."""
        with self._lock:
            self._attrs.update(kw)

    def event(self, kind: str, **kw: Any) -> None:
        """Append a timeline event (retry, ladder, quarantine, source...)
        stamped with seconds-since-span-start. Capped at
        :data:`MAX_SPAN_EVENTS` (first N kept, overflow counted) so a
        runaway retry loop cannot grow the record without bound."""
        rec = {"kind": str(kind),
               "t": round(time.perf_counter() - self._t0, 4)}
        rec.update(kw)
        with self._lock:
            if len(self._events) < MAX_SPAN_EVENTS:
                self._events.append(rec)
            else:
                self._events_dropped += 1
            # ladder_steps stays complete past the cap: it is its own
            # bounded field (one entry per demotion, ladder depth <= 2)
            if kind == "ladder":
                to = kw.get("to")
                if to is not None:
                    self._ladder.append(str(to))

    # -- lifecycle ----------------------------------------------------------
    def __enter__(self) -> "VideoSpan":
        self._prev = getattr(_tls, "span", None)
        _tls.span = self
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        _tls.span = self._prev
        wall = time.perf_counter() - self._t0
        with self._lock:
            attrs = dict(self._attrs)
            stages = {k: {"s": round(v[0], 6), "calls": int(v[1])}
                      for k, v in self._stages.items()}
            events = list(self._events)
            dropped = self._events_dropped
            ladder = list(self._ladder)
        if dropped:
            events.append({"kind": "events_dropped", "count": int(dropped),
                           "t": round(wall, 4)})
        status = attrs.get("status")
        if status not in STATUSES:
            # an exception propagated past safe_extract (KeyboardInterrupt,
            # SystemExit) or the caller forgot to annotate: still emit a
            # well-formed record
            status = "error"
        err = attrs.get("error")
        self.record = {
            "schema": SCHEMA_VERSION,
            "video": self.video,
            "status": status,
            "feature_type": self.feature_type,
            "host": socket.gethostname(),
            "host_id": self.host_id,
            "pid": os.getpid(),
            "request_id": self.request_id,
            "start_time": round(self._start_time, 3),
            "wall_s": round(wall, 6),
            "attempts": int(attrs.get("attempts", 1)),
            "category": attrs.get("category"),
            "error": None if err is None else str(err)[:1000],
            "decode_mode": attrs.get("decode_mode"),
            # multi-family shared-decode attribution: ms of the video's
            # single decode pass that had run when this family's stream
            # completed (parallel/fanout.py); null for private decodes
            "decode_shared_ms": _maybe_float(attrs.get("decode_shared_ms")),
            "ladder_steps": ladder,
            "stages": stages,
            "video_fps": _maybe_float(attrs.get("video_fps")),
            "video_frames": _maybe_int(attrs.get("video_frames")),
            "events": events,
        }
        if self.recorder is not None:
            try:
                self.recorder.emit_span(self.record)
            except Exception as e:
                # a full disk / permission flap on the telemetry channel
                # must never fail the video it observed
                print(f"telemetry: failed to record span for {self.video}: "
                      f"{type(e).__name__}: {e}")


def _maybe_float(v: Any) -> Optional[float]:
    try:
        return None if v is None else float(v)
    except (TypeError, ValueError):
        return None


def _maybe_int(v: Any) -> Optional[int]:
    try:
        return None if v is None else int(v)
    except (TypeError, ValueError):
        return None


class NoopSpan:
    """The ``telemetry=false`` hot path: every method is a constant-time
    no-op and ``with`` never touches thread-local state. A single shared
    instance is safe — there is nothing to share."""

    __slots__ = ()

    def __enter__(self) -> "NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        return None

    def observe_stage(self, name: str, dt: float) -> None:
        pass

    def annotate(self, **kw: Any) -> None:
        pass

    def event(self, kind: str, **kw: Any) -> None:
        pass


NOOP_SPAN = NoopSpan()
