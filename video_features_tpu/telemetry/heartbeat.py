"""Multi-host heartbeats: periodic liveness files in the shared output dir.

Each worker writes ``{output_path}/_heartbeat_{host_id}.json`` every
``metrics_interval_s`` seconds (atomic replace, telemetry/jsonl.py), so
a coordinator — or an operator running ``scripts/telemetry_report.py``
— can tell a slow host from a dead one without SSH: a heartbeat older
than ~3 intervals means the worker stalled or died, and its ``last_video``
names the suspect input. This is the observability half of the
multi-host story whose work-partitioning half is
``parallel/mesh.py:local_shard_of_list`` — hosts never talk to each
other, they only co-own an output directory.

The writer thread is a daemon with an injectable clock/interval so tests
never sleep; ticks call back into the recorder, which owns the file
contents (telemetry/recorder.py ``build_heartbeat``).
"""
from __future__ import annotations

import re
import threading
from typing import Callable, Optional

HEARTBEAT_PREFIX = "_heartbeat_"
HEARTBEAT_GLOB = HEARTBEAT_PREFIX + "*.json"

#: a heartbeat older than this many intervals marks the host STALLED
STALL_INTERVALS = 3.0


def heartbeat_filename(host_id: str) -> str:
    """``_heartbeat_{host_id}.json`` with the id sanitized for the
    filesystem (host ids embed hostnames)."""
    safe = re.sub(r"[^A-Za-z0-9._-]+", "-", str(host_id))
    return f"{HEARTBEAT_PREFIX}{safe}.json"


class HeartbeatThread:
    """Fires ``tick()`` every ``interval_s`` until :meth:`stop`.

    ``Event.wait(interval)`` (not ``sleep``) so stop() interrupts a wait
    immediately — worker shutdown must not dangle for up to a full
    metrics interval.
    """

    def __init__(self, tick: Callable[[], None], interval_s: float) -> None:
        if float(interval_s) <= 0:
            raise ValueError(
                f"metrics_interval_s={interval_s}: need > 0")
        self._tick = tick
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="vft-heartbeat", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._tick()
            except Exception:
                # liveness reporting must never kill (or be killed by)
                # the extraction it observes; the next tick retries
                pass

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            self._thread = None
