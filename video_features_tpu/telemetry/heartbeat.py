"""Multi-host heartbeats: periodic liveness files in the shared output dir.

Each worker writes ``{output_path}/_heartbeat_{host_id}.json`` every
``metrics_interval_s`` seconds (atomic replace, telemetry/jsonl.py), so
a coordinator — or an operator running ``scripts/telemetry_report.py``
— can tell a slow host from a dead one without SSH: a heartbeat older
than ~3 intervals means the worker stalled or died, and its ``last_video``
names the suspect input. This is the observability half of the
multi-host story whose work-partitioning half is
``parallel/mesh.py:local_shard_of_list`` — hosts never talk to each
other, they only co-own an output directory.

The writer thread is a daemon with an injectable clock/interval so tests
never sleep; ticks call back into the recorder, which owns the file
contents (telemetry/recorder.py ``build_heartbeat``).
"""
from __future__ import annotations

import re
import threading
from typing import Callable, Optional

HEARTBEAT_PREFIX = "_heartbeat_"
HEARTBEAT_GLOB = HEARTBEAT_PREFIX + "*.json"

#: a heartbeat older than this many intervals marks the host STALLED
STALL_INTERVALS = 3.0


def heartbeat_filename(host_id: str) -> str:
    """``_heartbeat_{host_id}.json`` with the id sanitized for the
    filesystem (host ids embed hostnames)."""
    safe = re.sub(r"[^A-Za-z0-9._-]+", "-", str(host_id))
    return f"{HEARTBEAT_PREFIX}{safe}.json"


def matches_run(heartbeat: dict, run_id: Optional[str],
                started_time: Optional[float] = None) -> bool:
    """False iff this heartbeat demonstrably belongs to a PRIOR run than
    ``run_id`` (the manifest's). Output dirs are reused across runs and
    a worker that died without a final heartbeat leaves its file behind;
    report tools must not count that stale file as a live (or stalled)
    worker of the current run.

    Each host mints its own run_id (hosts never talk, they only co-own
    the output dir), so a multi-host fleet legitimately shows N distinct
    run_ids — a mismatched id only marks staleness when the heartbeat
    also PREDATES the manifest's ``started_time`` (a fleet sibling keeps
    refreshing its file, so its timestamp stays current). Either side
    missing a run_id (pre-run_id artifacts) matches: an unprovable
    mismatch stays visible rather than silently dropped."""
    hb_run = heartbeat.get("run_id")
    if run_id is None or hb_run is None or str(hb_run) == str(run_id):
        return True
    if started_time is None:
        return False
    hb_time = heartbeat.get("time")
    try:
        return hb_time is not None and float(hb_time) >= float(started_time)
    except (TypeError, ValueError):
        return False


class HeartbeatThread:
    """Fires ``tick()`` every ``interval_s`` until :meth:`stop`.

    ``Event.wait(interval)`` (not ``sleep``) so stop() interrupts a wait
    immediately — worker shutdown must not dangle for up to a full
    metrics interval.

    Tick failures are **counted, never swallowed silently**: a
    persistently-failing tick stops refreshing the heartbeat file, which
    to the fleet is indistinguishable from a dead host — its leases get
    stolen mid-work (parallel/queue.py's steal predicate is exactly this
    staleness). The accounting (:attr:`tick_errors_total`,
    :attr:`consecutive_errors`, :attr:`last_tick_error`) is exported as
    ``vft_heartbeat_tick_errors_total`` and surfaced inside the next
    *successful* heartbeat (telemetry/recorder.py ``build_heartbeat``),
    so an operator reading the file sees "this host is alive but its
    liveness channel was failing" instead of nothing at all.
    """

    def __init__(self, tick: Callable[[], None], interval_s: float) -> None:
        if float(interval_s) <= 0:
            raise ValueError(
                f"metrics_interval_s={interval_s}: need > 0")
        self._tick = tick
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.tick_errors_total = 0
        self.consecutive_errors = 0
        self.last_tick_error: Optional[str] = None
        self.frozen_ticks = 0

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="vft-heartbeat", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        from ..utils import inject
        while not self._stop.wait(self.interval_s):
            try:
                # chaos hook (utils/inject.py `heartbeat.tick`): `freeze`
                # silently skips ticks — the host looks dead while its
                # work continues (the lease-steal-of-a-live-host case);
                # raise-kind faults exercise the error accounting below
                fault = inject.fire("heartbeat.tick")
                if fault is not None and fault.kind == "freeze":
                    self.frozen_ticks += 1
                    continue
                self._tick()
                self.consecutive_errors = 0
            except Exception as e:
                # liveness reporting must never kill (or be killed by)
                # the extraction it observes — but a failing tick is
                # itself a liveness event: count it, export it, and keep
                # the last error for the next successful heartbeat
                self.tick_errors_total += 1
                self.consecutive_errors += 1
                self.last_tick_error = f"{type(e).__name__}: {e}"
                try:
                    from .. import telemetry
                    telemetry.inc("vft_heartbeat_tick_errors_total")
                except Exception:
                    pass
                if self.consecutive_errors == 1 or \
                        self.consecutive_errors % 10 == 0:
                    print(f"heartbeat: tick failed ({self.last_tick_error}); "
                          f"{self.consecutive_errors} consecutive failure(s)"
                          " — this host will look STALLED to the fleet if "
                          "they persist")

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            self._thread = None
