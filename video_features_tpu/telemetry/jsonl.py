"""Crash-safe JSON file primitives shared by every telemetry artifact.

Two disciplines, both inherited from the fault journal (utils/faults.py,
PR 1) and now factored here so `_telemetry.jsonl`, `_failures.jsonl`,
`_run.json` and the heartbeat files all behave identically under
preemption:

  - **atomic append** (:func:`append_jsonl`): one ``os.write`` on an
    ``O_APPEND`` fd per record, with torn-tail healing — a worker
    SIGKILLed mid-write leaves a line with no newline, and the next
    append prepends one so only the already-torn record is sacrificed.
    Concurrent shard workers sharing the output dir never interleave
    partial lines (records stay well under PIPE_BUF).
  - **atomic replace** (:func:`write_json_atomic`): temp file in the
    same directory + flush + fsync + ``os.replace``, the same contract
    as feature files (utils/sinks.py) — a reader can never observe a
    half-written manifest or heartbeat.

Readers (:func:`read_jsonl`) skip corrupt lines instead of failing:
telemetry is an observation channel, never a lock.
"""
from __future__ import annotations

import json
import os
import tempfile
from typing import Iterator, Union

PathLike = Union[str, os.PathLike]


def append_jsonl(path: PathLike, rec: dict) -> None:
    """Append one record as a single atomic ``os.write``, healing a torn
    tail left by a previously killed writer."""
    path = str(path)
    line = (json.dumps(rec, sort_keys=True) + "\n").encode()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        try:
            if os.fstat(fd).st_size > 0:
                with open(path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) != b"\n":
                        line = b"\n" + line
        except OSError:
            pass
        os.write(fd, line)
    finally:
        os.close(fd)


def read_jsonl(path: PathLike) -> Iterator[dict]:
    """Yield every parseable dict record; corrupt lines (torn appends from
    a killed worker) are skipped, never fatal. A missing file yields
    nothing."""
    try:
        f = open(str(path), encoding="utf-8", errors="replace")
    except OSError:
        return
    with f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(rec, dict):
                yield rec


def write_json_atomic(path: PathLike, obj: dict, indent: int = 2) -> None:
    """Write ``obj`` as JSON via temp-file + fsync + ``os.replace`` so a
    reader (or a resumed worker) can never see a partial document."""
    path = str(path)
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(obj, f, indent=indent, sort_keys=True, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
