"""Alerting & flight recorder: the plane that *watches* the telemetry.

Every prior observability pillar records; none evaluates. An operator
had to be staring at ``vft-fleet --watch`` at the right second to catch
an SLO burn, a stalled host or a non-finite-feature spike — and by the
time they investigated, the heartbeats that explained the incident had
been overwritten. This module closes the loop with the standard
production triad:

  **evaluate** — a declarative rule engine (:data:`BUILTIN_RULES`) runs
  on the heartbeat/aggregate cadence over artifacts alone: heartbeat
  states, ``_queue`` dir ground truth, and the retained history series
  (telemetry/history.py) that windowed signals (multi-window SLO
  burn rates, spike deltas, MFU-vs-own-history) diff against.

  **alert** — each (rule, scope) is a pending -> firing -> resolved
  state machine with dedup: transitions append to
  ``{root}/_alerts.jsonl`` under the checked-in ``alert.schema.json``;
  steady states emit nothing. The journal IS the engine's state — any
  evaluator (the in-process recorder hook, ``vft-alert`` one-shot from
  cron, ``vft-alert --watch`` next to ``vft-fleet --watch``) reconstructs
  open episodes from the last record per (rule, scope), so a cron-able
  one-shot resolves an alert a long-dead run fired. Firing/pending
  alerts render in ``vft-top``/``vft-fleet`` and export as
  Prometheus ``ALERTS``-style gauges.

  **capture** — the flight recorder: the moment a rule FIRES, an
  incident bundle lands under ``{root}/_incidents/{alert_id}/`` — the
  current heartbeats, tails of every failure/span/health/history
  journal, a stitched cross-host trace window, the ``_queue`` counts
  and the roofline summary — with a ``manifest.json`` hashing every
  captured artifact. Postmortems start from a self-contained black box
  instead of racing artifact turnover.

Enabled by ``alerts=true`` (+ ``history=true`` for windowed rules) on
any telemetry run; ``alerts=false`` leaves the artifact footprint
byte-identical to the pre-alerting layout. See docs/observability.md
"Alerting & incident bundles".
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import time
import uuid
from dataclasses import dataclass, replace
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from . import history, jsonl

ALERTS_FILENAME = "_alerts.jsonl"
INCIDENTS_DIRNAME = "_incidents"

SCHEMA_VERSION = "vft.alert/1"
INCIDENT_SCHEMA = "vft.incident/1"
ALERT_SCHEMA_PATH = os.path.join(os.path.dirname(__file__),
                                 "alert.schema.json")

#: every key an alert record carries — scripts/check_alerts_schema.py
#: pins alert.schema.json to exactly this list
ALERT_FIELDS = ("schema", "alert_id", "rule", "severity", "state", "scope",
                "summary", "value", "threshold", "since", "time", "run_id",
                "incident")

STATES = ("pending", "firing", "resolved")
SEVERITIES = ("page", "ticket")


def load_alert_schema() -> dict:
    with open(ALERT_SCHEMA_PATH, encoding="utf-8") as f:
        return json.load(f)


def validate_alert(rec: dict) -> List[str]:
    from .schema import validate
    return validate(rec, load_alert_schema())


# -- configuration ------------------------------------------------------------

@dataclass(frozen=True)
class AlertConfig:
    """Rule thresholds and window widths. Defaults target the serve
    SLO discipline (95% attainment, Google-SRE-style multi-window burn)
    and the fleet's own knobs (``fleet_max_reclaims=3``); every field
    is overridable from ``vft-alert`` flags or engine construction."""

    #: SLO attainment objective (%); error budget = 1 - target/100
    slo_target_pct: float = 95.0
    #: burn-rate trip point: 1.0 = consuming budget exactly as fast as
    #: the objective allows; > 1 exhausts it early
    burn_threshold: float = 1.0
    #: the short (fast-burn) and long (sustained-burn) windows — BOTH
    #: must exceed burn_threshold, so a single slow request can't page
    #: but a sustained burn still fires within short_window_s
    short_window_s: float = 300.0
    long_window_s: float = 3600.0
    #: requests required inside the short window before burn is judged
    min_requests: int = 1
    #: shared window for spike/growth/collapse rules
    spike_window_s: float = 600.0
    #: queue-depth trip point, per live host (the CapacityPlanner's own)
    up_pending_per_host: float = 2.0
    #: windowed lease reclaims before alerting (= fleet_max_reclaims)
    reclaim_spike: int = 3
    #: windowed quarantines before alerting (any is pathological)
    quarantine_spike: int = 1
    #: windowed terminal failures (error + quarantined videos)
    failure_spike: int = 1
    #: cache collapse: windowed hit rate below collapse_factor x the
    #: cumulative rate, with at least min_lookups in the window and a
    #: cumulative rate worth defending
    cache_min_lookups: int = 20
    compile_min_lookups: int = 4
    collapse_factor: float = 0.5
    min_baseline_rate: float = 0.25
    #: MFU regression vs the family's OWN history: current below
    #: mfu_regression_frac x median of >= mfu_min_history prior samples
    mfu_regression_frac: float = 0.7
    mfu_min_history: int = 3
    #: disk pressure (gc.py usage samples): fire at this fraction of the
    #: gc_quota_gb level, or when the windowed growth rate projects the
    #: quota full within the horizon
    disk_pressure_frac: float = 0.9
    disk_horizon_s: float = 3600.0


# -- rules --------------------------------------------------------------------

@dataclass(frozen=True)
class AlertRule:
    """One declarative rule: ``evaluate(obs, cfg)`` returns the scopes
    currently violating it. ``for_s`` is the pending dwell before a
    violation fires (0 = the condition's own windows are the damping);
    ``clear_for_s`` is honored by long-running engines only — a
    journal-reconstructed one-shot resolves immediately."""
    name: str
    severity: str
    description: str
    evaluate: Callable[[dict, AlertConfig], List[dict]]
    for_s: float = 0.0
    clear_for_s: float = 0.0


def _finding(scope: str, summary: str, value=None,
             threshold=None) -> dict:
    return {"scope": str(scope), "summary": str(summary),
            "value": (round(float(value), 4) if value is not None
                      else None),
            "threshold": (round(float(threshold), 4)
                          if threshold is not None else None)}


def _rule_slo_burn(obs: dict, cfg: AlertConfig) -> List[dict]:
    """Multi-window SLO burn: the windowed violation rate of the serve
    latency objective (``serve_slo_s``, measured on the queue-wait +
    service histograms) divided by the error budget. Fires only when
    BOTH the short and the long window burn >= threshold — fast enough
    to catch a real burn inside short_window_s, damped enough that one
    slow request against a quiet hour stays silent.

    With per-tenant tallies retained (the gateway arc — heartbeat
    ``serve.tenants``, sampled by telemetry/history.py), the same
    two-window test additionally runs per tenant, scoped
    ``{host}/tenant={name}`` — one noisy tenant burning ITS budget
    pages as that tenant, not as the host."""
    out: List[dict] = []
    now = obs["time"]
    budget = max(1e-6, 1.0 - cfg.slo_target_pct / 100.0)

    def burn(samples, num_path: str, den_path: str):
        short = history.window_rate(samples, num_path, den_path, now,
                                    cfg.short_window_s)
        if short is None or short[1] < cfg.min_requests:
            return None
        long_ = history.window_rate(samples, num_path, den_path, now,
                                    cfg.long_window_s) or short
        burn_s, burn_l = short[2] / budget, long_[2] / budget
        if burn_s >= cfg.burn_threshold and burn_l >= cfg.burn_threshold:
            return short, burn_s, burn_l
        return None

    for host, samples in sorted(obs["history"].items()):
        hit = burn(samples, "slo.violations", "slo.requests")
        if hit is not None:
            short, burn_s, burn_l = hit
            out.append(_finding(
                host,
                f"SLO burn rate {burn_s:.2f}x budget over "
                f"{cfg.short_window_s:.0f}s ({int(short[0])}/"
                f"{int(short[1])} requests violating; long window "
                f"{burn_l:.2f}x)",
                value=burn_s, threshold=cfg.burn_threshold))
        tenants = (samples[-1].get("tenants") or {}) if samples else {}
        for t in sorted(tenants):
            hit = burn(samples, f"tenants.{t}.violations",
                       f"tenants.{t}.requests")
            if hit is not None:
                short, burn_s, burn_l = hit
                out.append(_finding(
                    f"{host}/tenant={t}",
                    f"tenant {t}: SLO burn rate {burn_s:.2f}x budget "
                    f"over {cfg.short_window_s:.0f}s ({int(short[0])}/"
                    f"{int(short[1])} requests violating; long window "
                    f"{burn_l:.2f}x)",
                    value=burn_s, threshold=cfg.burn_threshold))
    return out


def _rule_host_stalled(obs: dict, cfg: AlertConfig) -> List[dict]:
    """A host whose heartbeat is silent past the stall window. When
    claim tracking exists (a fleet ``_queue`` or serve spool), the
    alert scopes to *stalled while holding leases* — it resolves the
    moment siblings reclaim them (the fleet healed around the corpse),
    which is also how a SIGKILLed host's alert ever resolves. A plain
    batch host (no claim dirs) alerts on staleness alone and resolves
    when its heartbeat refreshes or goes final."""
    out: List[dict] = []
    claims = obs.get("claims") or {}
    tracked = obs.get("claims_tracked", False)
    for e in obs["hosts"]:
        hb = e.get("hb")
        if hb is None or e.get("prior_run") or e["state"] != "STALLED":
            continue
        host = str(hb.get("host_id"))
        held = claims.get(_safe_scope(host))
        if tracked and not held:
            continue  # leases reclaimed (or never held): fleet healed
        age = e.get("age_s")
        summary = (f"heartbeat silent for {age:.0f}s"
                   if age is not None else "heartbeat silent")
        if held:
            summary += f" while holding {held} claim(s)"
        out.append(_finding(host, summary, value=age))
    return out


def _rule_queue_growth(obs: dict, cfg: AlertConfig) -> List[dict]:
    """Backlog growing faster than the fleet drains it: pending depth
    at or past the per-host trip point AND (when history exists) not
    shrinking over the window."""
    q = obs.get("queue")
    if not isinstance(q, dict):
        return []
    pending = int(q.get("pending") or 0)
    live = max(1, int(obs.get("n_live") or 0))
    per_host = pending / live
    if per_host < cfg.up_pending_per_host:
        return []
    now = obs["time"]
    growth = None
    for samples in obs["history"].values():
        d = history.window_delta(samples, "fleet.queue.pending", now,
                                 cfg.spike_window_s,
                                 allow_negative=True)  # depth is a gauge
        if d is not None:
            growth = max(growth, d[0]) if growth is not None else d[0]
    if growth is not None and growth <= 0:
        return []  # deep but draining: capacity is catching up
    return [_finding(
        "fleet",
        f"queue depth {pending} ({per_host:.1f}/host over "
        f"{live} live host(s))"
        + (f", +{growth:.0f} in {cfg.spike_window_s:.0f}s"
           if growth is not None else ""),
        value=per_host, threshold=cfg.up_pending_per_host)]


def _spike(obs: dict, cfg: AlertConfig, path: str, threshold: int,
           label: str) -> List[dict]:
    out: List[dict] = []
    now = obs["time"]
    for host, samples in sorted(obs["history"].items()):
        d = history.window_delta(samples, path, now, cfg.spike_window_s)
        if d is not None and d[0] >= threshold:
            out.append(_finding(
                host, f"{int(d[0])} {label} in the last {d[1]:.0f}s",
                value=d[0], threshold=threshold))
    return out


def _rule_reclaim_spike(obs: dict, cfg: AlertConfig) -> List[dict]:
    return _spike(obs, cfg, "fleet.reclaimed", cfg.reclaim_spike,
                  "lease reclaim(s)")


def _rule_quarantine_spike(obs: dict, cfg: AlertConfig) -> List[dict]:
    return _spike(obs, cfg, "fleet.queue.quarantined",
                  cfg.quarantine_spike, "queue quarantine(s)")


def _rule_nonfinite(obs: dict, cfg: AlertConfig) -> List[dict]:
    """Any windowed increase of non-finite feature values pages: the
    health gate quarantines them instead of writing (telemetry/
    health.py), so an increase means the model itself is emitting
    NaN/Inf — never acceptable at any rate."""
    return [replace_summary(f, f"non-finite feature values: {f['summary']}")
            for f in _spike(obs, cfg, "nonfinite_total", 1,
                            "new NaN/Inf value(s)")]


def replace_summary(finding: dict, summary: str) -> dict:
    finding = dict(finding)
    finding["summary"] = summary
    return finding


def _collapse(obs: dict, cfg: AlertConfig, hits_path: str,
              misses_path: str, min_lookups: int,
              label: str) -> List[dict]:
    """Hit-rate collapse: the windowed rate fell below
    ``collapse_factor`` x the cumulative rate the run had earned — a
    warm store going cold mid-run (rotting entries, a fingerprint
    bump, an eviction storm), not a store that was never warm."""
    out: List[dict] = []
    now = obs["time"]
    for host, samples in sorted(obs["history"].items()):
        hits = history.window_delta(samples, hits_path, now,
                                    cfg.spike_window_s)
        misses = history.window_delta(samples, misses_path, now,
                                      cfg.spike_window_s)
        if hits is None or misses is None:
            continue
        lookups = hits[0] + misses[0]
        if lookups < min_lookups:
            continue
        rate = hits[0] / lookups
        total_h = history.latest(samples, hits_path) or 0
        total_m = history.latest(samples, misses_path) or 0
        total = total_h + total_m
        baseline = total_h / total if total else 0.0
        if baseline < cfg.min_baseline_rate:
            continue  # never warm: nothing collapsed
        if rate < cfg.collapse_factor * baseline:
            out.append(_finding(
                host,
                f"{label} hit rate collapsed to {rate:.0%} over the "
                f"last {int(lookups)} lookup(s) (run baseline "
                f"{baseline:.0%})",
                value=rate, threshold=cfg.collapse_factor * baseline))
    return out


def _rule_cache_collapse(obs: dict, cfg: AlertConfig) -> List[dict]:
    return _collapse(obs, cfg, "cache.hits", "cache.misses",
                     cfg.cache_min_lookups, "feature-cache")


def _rule_compile_cache_collapse(obs: dict, cfg: AlertConfig
                                 ) -> List[dict]:
    return _collapse(obs, cfg, "compile_cache.hits",
                     "compile_cache.misses", cfg.compile_min_lookups,
                     "compile-cache")


def _rule_mfu_regression(obs: dict, cfg: AlertConfig) -> List[dict]:
    """A family's MFU falling below ``mfu_regression_frac`` x the median
    of ITS OWN retained history on the same host — the continuous
    version of the roofline verdict (telemetry/roofline.py): the chip
    didn't change, so a sustained drop means the feed did."""
    out: List[dict] = []
    for host, samples in sorted(obs["history"].items()):
        by_fam: Dict[str, List[float]] = {}
        for s in samples:
            for fam, mfu in (s.get("mfu") or {}).items():
                if mfu is not None:
                    by_fam.setdefault(str(fam), []).append(float(mfu))
        for fam, series in sorted(by_fam.items()):
            if len(series) < cfg.mfu_min_history + 1:
                continue
            current, prior = series[-1], sorted(series[:-1])
            median = prior[len(prior) // 2]
            if median > 0 and current < cfg.mfu_regression_frac * median:
                out.append(_finding(
                    f"{host}/{fam}",
                    f"MFU {100 * current:.1f}% is below "
                    f"{cfg.mfu_regression_frac:.0%} of this host's own "
                    f"median {100 * median:.1f}% "
                    f"({len(prior)} retained samples)",
                    value=current,
                    threshold=cfg.mfu_regression_frac * median))
    return out


def _rule_failure_spike(obs: dict, cfg: AlertConfig) -> List[dict]:
    """Windowed terminal failures (error + quarantined videos) — the
    catch-all that turns a chaos-injected fault or a poison input burst
    into a visible incident with its journal tail already bundled."""
    out: List[dict] = []
    now = obs["time"]
    for host, samples in sorted(obs["history"].items()):
        total = 0.0
        span = 0.0
        seen = False
        for path in ("videos.error", "videos.quarantined"):
            d = history.window_delta(samples, path, now,
                                     cfg.spike_window_s)
            if d is not None:
                seen = True
                total += d[0]
                span = max(span, d[1])
        if seen and total >= cfg.failure_spike:
            out.append(_finding(
                host,
                f"{int(total)} terminal failure(s) in the last "
                f"{span:.0f}s (journal tail in the incident bundle)",
                value=total, threshold=cfg.failure_spike))
    return out


def _rule_disk_pressure(obs: dict, cfg: AlertConfig) -> List[dict]:
    """Burn-rate alarm on the storage accounting (gc.py GcMonitor
    samples — heartbeat ``gc`` section, retained by history): fires at
    ``disk_pressure_frac`` of the quota level, or earlier when the
    windowed growth rate projects the quota full inside
    ``disk_horizon_s`` — a full disk is a fleet-wide FATAL (ENOSPC,
    utils/faults.py), so the page has to land while vft-gc can still
    win the race."""
    out: List[dict] = []
    now = obs["time"]
    for host, samples in sorted(obs["history"].items()):
        used = history.latest(samples, "gc.used_bytes")
        quota = history.latest(samples, "gc.quota_bytes")
        if not used or not quota:
            continue  # accounting off, or no quota configured
        used_f, quota_f = float(used), float(quota)
        if used_f >= cfg.disk_pressure_frac * quota_f:
            out.append(_finding(
                host,
                f"disk usage {used_f / 1e9:.2f}GB at "
                f"{100.0 * used_f / quota_f:.0f}% of the "
                f"{quota_f / 1e9:.2f}GB quota",
                value=used_f / quota_f,
                threshold=cfg.disk_pressure_frac))
            continue
        grow = history.window_delta(samples, "gc.used_bytes", now,
                                    cfg.spike_window_s,
                                    allow_negative=True)
        if grow is None or grow[0] <= 0 or grow[1] <= 0:
            continue  # flat or shrinking (GC winning): no projection
        rate = grow[0] / grow[1]  # bytes/s
        ttf = (quota_f - used_f) / rate
        if ttf < cfg.disk_horizon_s:
            out.append(_finding(
                host,
                f"disk filling at {rate / 1e6:.2f}MB/s — quota "
                f"{quota_f / 1e9:.2f}GB projected full in "
                f"{ttf:.0f}s (< {cfg.disk_horizon_s:.0f}s horizon)",
                value=ttf, threshold=cfg.disk_horizon_s))
    return out


def _rule_parity_drift(obs: dict, cfg: AlertConfig) -> List[dict]:
    """Per-seam numerics drift off the certify verdict artifacts
    (telemetry/parity.py ``_parity_verdict.json``, collected by
    ``observe_root``): one finding per out-of-band seam, scoped
    ``{host}/family={f}/seam={s}`` so the page names WHERE the numerics
    went, not just that they did. The episode clears when a re-certify
    PASS overwrites the verdict — the artifact is the state."""
    from . import parity
    out: List[dict] = []
    for doc in obs.get("parity") or []:
        fam = str(doc.get("family") or "?")
        host = str(doc.get("host") or "?")
        seams = doc.get("seams") or {}
        for seam in parity.SEAMS:
            m = seams.get(seam)
            if not isinstance(m, dict) or m.get("ok", True):
                continue
            note = m.get("note")
            out.append(_finding(
                f"{host}/family={fam}/seam={seam}",
                (f"parity drift at the {seam} seam"
                 + (f" ({note})" if note else
                    f": max_abs={m.get('max_abs')} vs band "
                    f"{m.get('tol_max_abs')}, cos={m.get('cos')} vs floor "
                    f"{m.get('tol_cos')}")
                 + (f" — flip {doc.get('flip')}" if doc.get("flip")
                    else "")),
                value=m.get("max_abs"), threshold=m.get("tol_max_abs")))
    return out


BUILTIN_RULES: Tuple[AlertRule, ...] = (
    AlertRule("slo_burn_rate", "page",
              "multi-window serve SLO burn over the error budget",
              _rule_slo_burn),
    AlertRule("host_stalled", "page",
              "heartbeat silent past the stall window (while holding "
              "leases, where claim tracking exists)",
              _rule_host_stalled),
    AlertRule("nonfinite_features", "page",
              "NaN/Inf feature values increasing",
              _rule_nonfinite),
    AlertRule("quarantine_spike", "page",
              "fleet-queue items quarantined as pathological",
              _rule_quarantine_spike),
    AlertRule("queue_depth_growth", "ticket",
              "backlog at/past the per-host trip point and not draining",
              _rule_queue_growth),
    AlertRule("reclaim_spike", "ticket",
              "lease reclaims spiking (hosts dying mid-work)",
              _rule_reclaim_spike),
    AlertRule("failure_spike", "ticket",
              "terminal video failures in the window",
              _rule_failure_spike),
    AlertRule("cache_hit_collapse", "ticket",
              "feature-cache hit rate collapsed vs the run baseline",
              _rule_cache_collapse),
    AlertRule("compile_cache_collapse", "ticket",
              "compile-cache hit rate collapsed vs the run baseline",
              _rule_compile_cache_collapse),
    AlertRule("mfu_regression", "ticket",
              "family MFU below its own retained history",
              _rule_mfu_regression),
    AlertRule("disk_pressure", "page",
              "storage usage at the quota level, or growth projecting "
              "it full within the horizon",
              _rule_disk_pressure),
    AlertRule("parity_drift", "page",
              "certified per-seam numerics error outside its tolerance "
              "band",
              _rule_parity_drift),
)


# -- observation --------------------------------------------------------------

def _safe_scope(s: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]+", "-", str(s))


def _claims_by_host(root: str) -> Tuple[Dict[str, int], bool]:
    """Per-host outstanding claim counts off the ground-truth dirs: the
    fleet queue's ``_queue/claimed/{host}/`` and the serve spool's
    ``claimed/{host}/``. Returns ``({safe_host: count}, tracked)`` —
    ``tracked`` False when neither structure exists (plain batch run)."""
    counts: Dict[str, int] = {}
    tracked = False
    for claimed in (os.path.join(str(root), "_queue", "claimed"),
                    os.path.join(str(root), "claimed")):
        if not os.path.isdir(claimed):
            continue
        tracked = True
        try:
            hosts = os.listdir(claimed)
        except OSError:
            continue
        for h in hosts:
            d = os.path.join(claimed, h)
            if not os.path.isdir(d):
                continue
            try:
                n = sum(1 for x in os.listdir(d) if x.endswith(".json"))
            except OSError:
                n = 0
            counts[h] = counts.get(h, 0) + n
    return counts, tracked


def observe_root(root: str, now: Optional[float] = None) -> dict:
    """Everything the rules read, gathered from artifacts alone (no
    live process — works on a dead fleet): heartbeat states, queue
    counts, per-host claim ground truth, retained history. Deliberately
    lighter than ``fleet_report.aggregate`` (no span/roofline sweeps):
    this runs on every heartbeat tick of every alerting host."""
    from ..fleet_report import _queue_counts, collect_heartbeats
    from . import parity
    now = time.time() if now is None else float(now)
    entries = collect_heartbeats(str(root), now=now)
    claims, tracked = _claims_by_host(root)
    return {
        "root": str(root),
        "time": now,
        "hosts": entries,
        "n_live": sum(1 for e in entries
                      if e.get("hb") is not None
                      and not e.get("prior_run")
                      and e["state"] == "live"),
        "queue": _queue_counts(str(root), entries),
        "claims": claims,
        "claims_tracked": tracked,
        "history": history.read_history(str(root)),
        # certify verdict artifacts (telemetry/parity.py): the
        # parity_drift rule reads per-seam ok flags off these
        "parity": parity.collect_verdicts(str(root)),
    }


# -- journal state ------------------------------------------------------------

def load_states(root: str) -> Dict[Tuple[str, str], dict]:
    """Open/closed episodes reconstructed from ``_alerts.jsonl``: the
    last record per (rule, scope) wins — the journal IS the state, so
    any evaluator (in-process hook, cron one-shot, watcher) continues
    where the previous one stopped."""
    out: Dict[Tuple[str, str], dict] = {}
    for rec in jsonl.read_jsonl(os.path.join(str(root), ALERTS_FILENAME)):
        if rec.get("schema") != SCHEMA_VERSION:
            continue
        out[(str(rec.get("rule")), str(rec.get("scope")))] = rec
    return out


def current_alerts(root: str, started_time: Optional[float] = None
                   ) -> List[dict]:
    """Every episode currently pending or firing — the render/gate/prom
    input. ``started_time`` (the manifest's) excludes records a PRIOR
    run of the same directory left open: an alert whose last transition
    predates this run's start is that run's business, not ours."""
    out = []
    for rec in load_states(str(root)).values():
        if rec.get("state") not in ("pending", "firing"):
            continue
        if started_time is not None and \
                float(rec.get("time", 0)) < float(started_time):
            continue
        out.append(rec)
    return sorted(out, key=lambda r: (r.get("state") != "firing",
                                      str(r.get("rule")),
                                      str(r.get("scope"))))


# -- the engine ---------------------------------------------------------------

class AlertEngine:
    """Evaluate rules against a root, append transitions, capture
    incident bundles. Stateless across processes by design (the journal
    reconstructs episodes); ``clear_for_s`` dwell is the only in-memory
    refinement, used by long-running engines."""

    def __init__(self, root: str, *, rules=BUILTIN_RULES,
                 cfg: Optional[AlertConfig] = None,
                 run_id: Optional[str] = None,
                 capture_incidents: bool = True,
                 clock=time.time) -> None:
        self.root = str(root)
        self.rules = tuple(rules)
        self.cfg = cfg or AlertConfig()
        self.run_id = run_id
        self.capture_incidents = capture_incidents
        self.clock = clock
        self.alerts_path = os.path.join(self.root, ALERTS_FILENAME)
        self._ok_since: Dict[Tuple[str, str], float] = {}
        self._last_summary: Dict[str, object] = {
            "firing": 0, "pending": 0, "names": []}
        self._recorder = None
        self.eval_errors = 0

    # -- one evaluation pass ------------------------------------------------
    def evaluate(self, obs: Optional[dict] = None,
                 now: Optional[float] = None) -> List[dict]:
        """Run every rule once; returns the records emitted (state
        transitions only — a steadily-firing alert emits nothing)."""
        now = self.clock() if now is None else float(now)
        if obs is None:
            obs = observe_root(self.root, now=now)
        states = load_states(self.root)
        emitted: List[dict] = []
        found: Dict[Tuple[str, str], Tuple[AlertRule, dict]] = {}
        for rule in self.rules:
            try:
                findings = rule.evaluate(obs, self.cfg)
            except Exception as e:
                self.eval_errors += 1
                print(f"alerts: rule {rule.name} failed: "
                      f"{type(e).__name__}: {e}")
                continue
            for f in findings:
                found[(rule.name, f["scope"])] = (rule, f)

        for key, (rule, f) in sorted(found.items()):
            st = states.get(key)
            open_ep = st is not None and st.get("state") in ("pending",
                                                             "firing")
            self._ok_since.pop(key, None)
            if not open_ep:
                alert_id = self._mint(rule.name, f["scope"])
                if rule.for_s > 0:
                    emitted.append(self._emit(
                        rule, f, "pending", alert_id, since=now, now=now))
                else:
                    emitted.append(self._fire(rule, f, alert_id,
                                              since=now, now=now, obs=obs))
            elif st.get("state") == "pending":
                since = float(st.get("since", now))
                if now - since >= rule.for_s:
                    emitted.append(self._fire(
                        rule, f, str(st.get("alert_id")), since=since,
                        now=now, obs=obs))
                # else: still pending — dedup, no record

        rules_by_name = {r.name: r for r in self.rules}
        for key, st in sorted(states.items()):
            if key in found or st.get("state") not in ("pending", "firing"):
                continue
            rule = rules_by_name.get(key[0])
            clear_for = rule.clear_for_s if rule is not None else 0.0
            if st.get("state") == "firing" and clear_for > 0:
                ok0 = self._ok_since.setdefault(key, now)
                if now - ok0 < clear_for:
                    continue  # condition clear but not yet for long enough
            self._ok_since.pop(key, None)
            rec = dict(st)
            rec.update(state="resolved", time=round(now, 3),
                       run_id=self.run_id)
            rec = {k: rec.get(k) for k in ALERT_FIELDS}
            rec["schema"] = SCHEMA_VERSION
            jsonl.append_jsonl(self.alerts_path, rec)
            emitted.append(rec)

        active = current_alerts(self.root)
        self._last_summary = {
            "firing": sum(1 for a in active if a["state"] == "firing"),
            "pending": sum(1 for a in active if a["state"] == "pending"),
            "names": [f"{a['rule']}:{a['scope']}" for a in active[:8]],
        }
        return emitted

    def _mint(self, rule: str, scope: str) -> str:
        return (f"{_safe_scope(rule)}-{_safe_scope(scope)}-"
                f"{uuid.uuid4().hex[:8]}")

    def _record(self, rule: AlertRule, f: dict, state: str, alert_id: str,
                since: float, now: float,
                incident: Optional[str] = None) -> dict:
        return {
            "schema": SCHEMA_VERSION,
            "alert_id": alert_id,
            "rule": rule.name,
            "severity": rule.severity,
            "state": state,
            "scope": f["scope"],
            "summary": f["summary"],
            "value": f.get("value"),
            "threshold": f.get("threshold"),
            "since": round(since, 3),
            "time": round(now, 3),
            "run_id": self.run_id,
            "incident": incident,
        }

    def _emit(self, rule: AlertRule, f: dict, state: str, alert_id: str,
              since: float, now: float,
              incident: Optional[str] = None) -> dict:
        rec = self._record(rule, f, state, alert_id, since, now, incident)
        jsonl.append_jsonl(self.alerts_path, rec)
        return rec

    def _fire(self, rule: AlertRule, f: dict, alert_id: str,
              since: float, now: float, obs: dict) -> dict:
        incident = None
        if self.capture_incidents:
            rec = self._record(rule, f, "firing", alert_id, since, now)
            incident = capture_incident(self.root, rec, now=now)
        return self._emit(rule, f, "firing", alert_id, since, now,
                          incident=incident)

    # -- recorder hook ------------------------------------------------------
    def attach(self, recorder) -> "AlertEngine":
        """Evaluate on every heartbeat tick and publish the episode
        summary as the heartbeat ``alerts`` section (one tick behind the
        evaluation it summarizes — sections render before hooks run)."""
        self._recorder = recorder
        recorder.tick_hooks.append(self._on_tick)
        recorder.extra_sections["alerts"] = self.heartbeat_section
        return self

    def _on_tick(self, hb: dict) -> None:
        try:
            self.evaluate()
        except Exception as e:
            # alerting must never become the outage: count and carry on
            self.eval_errors += 1
            if self.eval_errors <= 1:
                print(f"alerts: evaluation failed: "
                      f"{type(e).__name__}: {e}")

    def heartbeat_section(self) -> dict:
        return dict(self._last_summary, eval_errors=self.eval_errors)


# -- the flight recorder ------------------------------------------------------

#: trace events captured around an incident (seconds before firing)
INCIDENT_TRACE_WINDOW_S = 300.0
#: jsonl tail length per captured journal
INCIDENT_TAIL_LINES = 200

#: journals tailed into every bundle
_TAIL_NAMES = ("_failures.jsonl", "_telemetry.jsonl", "_health.jsonl",
               ALERTS_FILENAME)


def _sha256(path: str) -> Tuple[int, str]:
    h = hashlib.sha256()
    n = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
            n += len(chunk)
    return n, h.hexdigest()


def _bundle_name(root: str, p: Path) -> str:
    rel = os.path.relpath(str(p), str(root))
    return _safe_scope(rel)


def capture_incident(root: str, record: dict,
                     now: Optional[float] = None,
                     tail_lines: int = INCIDENT_TAIL_LINES
                     ) -> Optional[str]:
    """Write the black box for one firing alert:
    ``{root}/_incidents/{alert_id}/`` holding the current heartbeats,
    the tail of every journal (failures/spans/health/alerts/history),
    a stitched cross-host trace window, the ``_queue`` counts and the
    roofline roll-up — plus ``manifest.json`` listing every captured
    artifact with its size and sha256 (written LAST: a manifest's
    presence marks the bundle complete). Returns the bundle path
    relative to ``root``, or None — capture failure degrades to an
    alert without a bundle, never to a failed evaluation."""
    try:
        now = time.time() if now is None else float(now)
        root = str(root)
        alert_id = _safe_scope(record.get("alert_id") or "alert")
        rel_bundle = os.path.join(INCIDENTS_DIRNAME, alert_id)
        bundle = os.path.join(root, rel_bundle)
        os.makedirs(bundle, exist_ok=True)
        artifacts: List[dict] = []
        root_p = Path(root)

        def _add(rel: str) -> None:
            full = os.path.join(bundle, rel)
            size, sha = _sha256(full)
            artifacts.append({"path": rel, "bytes": size, "sha256": sha})

        def _write(rel: str, text: str) -> None:
            full = os.path.join(bundle, rel)
            os.makedirs(os.path.dirname(full) or bundle, exist_ok=True)
            # vft-lint: disable=VFT004 — bundle integrity is manifest-hash-based: manifest.json is written LAST over the recorded sha256s, so a torn artifact fails verify_incident instead of being trusted
            with open(full, "w", encoding="utf-8") as f:
                f.write(text)
            _add(rel)

        _write("alert.json", json.dumps(record, indent=2, sort_keys=True))

        # the heartbeats as they were at firing time — exactly the files
        # the next tick would have overwritten. Captured names are
        # prefixed so no collector glob (HEARTBEAT_GLOB etc.) can ever
        # re-ingest a frozen snapshot as a live artifact — a bundle
        # must be inert evidence, not a ghost host.
        from .heartbeat import HEARTBEAT_GLOB
        for p in sorted(root_p.rglob(HEARTBEAT_GLOB)):
            if INCIDENTS_DIRNAME in p.parts:
                continue
            try:
                _write(os.path.join("heartbeats",
                                    "hb-" + _bundle_name(root, p)),
                       p.read_text(encoding="utf-8", errors="replace"))
            except OSError:
                continue

        # journal tails: enough context to see the minutes before the
        # incident without copying gigabytes of history
        names = list(_TAIL_NAMES)
        for p in sorted(root_p.rglob(history.HISTORY_GLOB)):
            if INCIDENTS_DIRNAME not in p.parts:
                names.append(os.path.relpath(str(p), root))
        seen_tails = set()
        for name in names:
            for p in sorted(root_p.rglob(os.path.basename(name))):
                if INCIDENTS_DIRNAME in p.parts or str(p) in seen_tails:
                    continue
                seen_tails.add(str(p))
                try:
                    lines = p.read_text(encoding="utf-8",
                                        errors="replace").splitlines(True)
                except OSError:
                    continue
                # ".tail" suffix: span/health/history collectors glob on
                # *.jsonl and must never double-count bundle copies
                _write(os.path.join("tails",
                                    _bundle_name(root, p) + ".tail"),
                       "".join(lines[-tail_lines:]))

        # stitched cross-host trace, clipped to the incident window
        try:
            from ..fleet_report import find_trace_files, stitch_traces
            docs = []
            for p in find_trace_files(root):
                if INCIDENTS_DIRNAME in p.parts:
                    continue
                try:
                    with open(p, encoding="utf-8") as f:
                        doc = json.load(f)
                except (OSError, ValueError):
                    continue
                if isinstance(doc.get("traceEvents"), list):
                    docs.append((_bundle_name(root, p), doc))
            if docs:
                merged = stitch_traces(docs)
                anchor = (merged.get("otherData") or {}).get("anchor_unix")
                if isinstance(anchor, (int, float)):
                    lo = (now - INCIDENT_TRACE_WINDOW_S - anchor) * 1e6
                    merged["traceEvents"] = [
                        ev for ev in merged["traceEvents"]
                        if not isinstance(ev.get("ts"), (int, float))
                        or ev["ts"] >= lo]
                    merged["otherData"]["incident_window_s"] = \
                        INCIDENT_TRACE_WINDOW_S
                _write("trace_window.json", json.dumps(merged))
        except Exception:
            pass

        # queue ground truth + per-host claims at firing time
        claims, tracked = _claims_by_host(root)
        if tracked or os.path.isdir(os.path.join(root, "_queue")):
            from ..fleet_report import _queue_counts
            _write("queue.json", json.dumps(
                {"counts": _queue_counts(root, []),
                 "claims_by_host": claims}, indent=2, sort_keys=True))

        # roofline roll-up, when any host ran with roofline=true
        try:
            from .roofline import aggregate_rooflines
            rf = aggregate_rooflines(root)
            if rf:
                _write("roofline.json", json.dumps(rf, indent=2,
                                                   sort_keys=True))
        except Exception:
            pass

        jsonl.write_json_atomic(os.path.join(bundle, "manifest.json"), {
            "schema": INCIDENT_SCHEMA,
            "alert_id": record.get("alert_id"),
            "rule": record.get("rule"),
            "scope": record.get("scope"),
            "time": round(now, 3),
            "root": root,
            "artifacts": sorted(artifacts, key=lambda a: a["path"]),
        })
        return rel_bundle
    except Exception as e:
        print(f"alerts: incident capture failed: {type(e).__name__}: {e}")
        return None


def verify_incident(bundle: str) -> List[str]:
    """Re-hash every artifact the manifest lists; returns violations
    (missing manifest / missing file / size or sha mismatch). The
    auditor-style completeness check tests and the CI gate share."""
    errs: List[str] = []
    man_path = os.path.join(str(bundle), "manifest.json")
    try:
        with open(man_path, encoding="utf-8") as f:
            man = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable manifest {man_path}: {type(e).__name__}: {e}"]
    if man.get("schema") != INCIDENT_SCHEMA:
        errs.append(f"manifest schema {man.get('schema')!r} != "
                    f"{INCIDENT_SCHEMA!r}")
    arts = man.get("artifacts") or []
    if not arts:
        errs.append("manifest lists no artifacts")
    for a in arts:
        full = os.path.join(str(bundle), str(a.get("path")))
        if not os.path.isfile(full):
            errs.append(f"missing artifact {a.get('path')}")
            continue
        size, sha = _sha256(full)
        if size != a.get("bytes") or sha != a.get("sha256"):
            errs.append(f"artifact {a.get('path')}: bytes/sha mismatch "
                        "vs manifest")
    return errs


# -- rendering / prom ---------------------------------------------------------

def render_alerts(active: List[dict]) -> List[str]:
    """The ``== alerts ==`` block ``vft-top``/``vft-fleet`` share."""
    if not active:
        return []
    firing = sum(1 for a in active if a["state"] == "firing")
    pending = len(active) - firing
    lines = [f"== alerts ==  {firing} firing / {pending} pending"]
    for a in active:
        line = (f"  [{a['severity'].upper():<6}] {a['state'].upper():<7} "
                f"{a['rule']}({a['scope']}): {a['summary']}")
        if a.get("incident"):
            line += f"  [bundle: {a['incident']}]"
        lines.append(line)
    return lines


def alerts_prom_series(active: List[dict]) -> List[dict]:
    """Prometheus ``ALERTS``-style gauges (the exact shape an
    Alertmanager-fed rule evaluator exports): one ``ALERTS{alertname,
    severity, alertstate, scope} 1`` per live episode, for the
    telemetry/metrics.py dump format."""
    return [{"name": "ALERTS", "kind": "gauge",
             "labels": {"alertname": str(a["rule"]),
                        "alertstate": str(a["state"]),
                        "severity": str(a["severity"]),
                        "scope": str(a["scope"])},
             "value": 1.0} for a in active]


# -- CLI ----------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    """``vft-alert``: evaluate the rules against a shared root —
    one-shot (CI/cron-able) or continuously next to
    ``vft-fleet --watch``."""
    import argparse
    import sys
    ap = argparse.ArgumentParser(
        description="evaluate alert rules over a fleet root's artifacts "
                    "and maintain _alerts.jsonl + incident bundles")
    ap.add_argument("root", help="the fleet's shared output root (or a "
                                 "vft-serve spool dir)")
    ap.add_argument("--watch", action="store_true",
                    help="evaluate continuously until interrupted")
    ap.add_argument("--every", type=float, default=5.0,
                    help="--watch evaluation period in seconds (default 5)")
    ap.add_argument("--iterations", type=int, default=0,
                    help="--watch passes before exiting (0 = forever)")
    ap.add_argument("--window", type=float, default=None,
                    help="short/spike window override in seconds")
    ap.add_argument("--long-window", type=float, default=None,
                    help="long burn window override in seconds")
    ap.add_argument("--slo-target", type=float, default=None,
                    help="SLO attainment target %% (default 95)")
    ap.add_argument("--no-incidents", action="store_true",
                    help="evaluate and journal only; skip bundle capture")
    ap.add_argument("--prom", metavar="FILE", default=None,
                    help="write ALERTS-style gauges as a Prometheus "
                         "textfile")
    ap.add_argument("--fail-on-firing", action="store_true",
                    help="exit 1 while any alert is firing (the cron/CI "
                         "gate)")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.root):
        print(f"error: {args.root} is not a directory", file=sys.stderr)
        return 2
    overrides = {}
    if args.window is not None:
        overrides.update(short_window_s=args.window,
                         spike_window_s=args.window)
    if args.long_window is not None:
        overrides["long_window_s"] = args.long_window
    if args.slo_target is not None:
        overrides["slo_target_pct"] = args.slo_target
    cfg = replace(AlertConfig(), **overrides) if overrides \
        else AlertConfig()
    engine = AlertEngine(args.root, cfg=cfg,
                         capture_incidents=not args.no_incidents)
    passes = 0
    active: List[dict] = []
    while True:
        emitted = engine.evaluate()
        active = current_alerts(args.root)
        for rec in emitted:
            print(f"-> {rec['state'].upper():<8} [{rec['severity']}] "
                  f"{rec['rule']}({rec['scope']}): {rec['summary']}"
                  + (f"  [bundle: {rec['incident']}]"
                     if rec.get("incident") else ""))
        lines = render_alerts(active)
        print("\n".join(lines) if lines
              else f"alerts: none active under {args.root}")
        passes += 1
        if not args.watch or (args.iterations
                              and passes >= args.iterations):
            break
        try:
            time.sleep(max(0.05, args.every))
        except KeyboardInterrupt:
            break
    if args.prom:
        from .metrics import prometheus_text
        from ..utils.sinks import _write_bytes_atomic
        dump = {"series": alerts_prom_series(active)}
        # textfile-collector convention: rename into place so a
        # mid-write scrape never parses half an ALERTS series
        _write_bytes_atomic(args.prom, prometheus_text(dump).encode("utf-8"))
        print(f"prometheus textfile: {args.prom} "
              f"({len(dump['series'])} series)")
    if args.fail_on_firing and any(a["state"] == "firing"
                                   for a in active):
        firing = [a for a in active if a["state"] == "firing"]
        print("fail-on-firing: "
              + ", ".join(f"{a['rule']}({a['scope']})" for a in firing),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
