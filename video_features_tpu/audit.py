"""vft-audit: the run-invariant auditor — prove the durability contracts.

Reads a finished *or killed* output directory and asserts, as one
PASS/FAIL verdict, the cross-subsystem invariants that PRs 1-8's
durability machinery promises to hold under ANY interleaving of crashes
(see docs/chaos.md for the full list with rationale):

  1. **no ``.tmp`` litter** — every writer in the tree uses
     temp+fsync+rename with unlink-on-failure (utils/sinks.py,
     telemetry/jsonl.py, serve.py); a ``.tmp`` file on disk means a
     writer leaked its scratch.
  2. **at most one torn record per jsonl file, and only at the tail** —
     O_APPEND single-write records (telemetry/jsonl.py) can tear only
     the last line (a SIGKILL mid-write); a corrupt line mid-file means
     interleaved or non-atomic appends.
  3. **done markers => artifacts** — every fleet-queue ``done/`` marker
     with status done/skipped has loadable artifacts for its video;
     status error has a failure-journal record explaining it.
  4. **no orphaned claims for finalized hosts** — a host that wrote a
     *final* heartbeat exited gracefully and must have released or
     completed every claim (cli.py ``release_all``); claims whose owner
     is merely stale/missing are *recoverable* (lease steal) and only
     noted.
  5. **nothing stranded** — with ``--expect-complete`` (a drained run),
     ``pending/``/``claimed/`` must be empty; ``.staging/`` entries
     whose item has no done marker are violations once no live host
     remains to sweep them.
  6. **every quarantined item has a POISON journal record** — the queue
     quarantine (parallel/queue.py) and the journal (utils/faults.py)
     must agree, or ``retry_failed=true`` cannot lift it.
  7. **health digests re-verify** — each ``_health.jsonl`` record's
     quantization-tolerant signature (telemetry/health.py) is recomputed
     from the artifact on disk; a mismatch means the bytes rotted or a
     non-atomic writer tore them. A record with NaN/Inf counts must have
     NO artifact (the health gate refuses those writes).
  8. **artifact spans re-verify** — every ``artifact`` span event
     (utils/sinks.py records bytes+sha256 of exactly what was renamed
     into place) must match the file on disk, byte for byte.
  9. **cache entries re-verify** — with ``--cache-dir`` (or a manifest
     that names one), every store entry must load, carry the current
     schema, and match its stored per-tensor signatures
     (verify-before-trust, cache.py).
 10. **gateway/spool lifecycle reconciles** — for every serve spool
     under the root: ``expired/`` records carry status
     ``deadline_exceeded`` and are mutually exclusive with ``done/``
     responses; a request expired at claim time (``processed=0``)
     produced ZERO video spans (the wasted-work guard, serve.py); every
     ``inbox/`` upload is named by a gateway journal record; requests
     the gateway rejected/shed at the door never reached the spool; and
     per-tenant accepted counts in the gateway journals reconcile with
     the spool's terminal (done/expired) markers (gateway.py).
 11. **GC deletions reconcile with their journal** — every ``vft-gc``
     deletion is journaled to ``_gc_{host}.jsonl`` BEFORE the unlink
     (gc.py): a journaled path still present is a *note* (the GC died
     in the crash window; the next run converges), but a journaled
     spool/inbox deletion whose request is claimable again or whose
     blob a live request references is a violation — the safety rules
     promise GC never deletes what the fleet can still reach.

Violations are states the machinery PROMISES cannot happen no matter
where a worker died; notes are recoverable in-flight states a killed
run legitimately leaves behind. Exit 0 on PASS (no violations), 1 on
FAIL — tests/test_chaos.py's seeded matrix and the
``scripts/check_inject_smoke.py`` CI gate both end every injected run
with this verdict.
"""
from __future__ import annotations

import argparse
import json
import os
import pickle
import sys
from pathlib import Path
from typing import Dict, List, Optional, Tuple

ARTIFACT_EXTS = (".npy", ".pkl")


class Audit:
    """One audit pass over an output root; collects violations/notes."""

    def __init__(self, root: str, *, cache_dir: Optional[str] = None,
                 expect_complete: bool = False) -> None:
        self.root = Path(root)
        self.cache_dir = cache_dir
        self.expect_complete = bool(expect_complete)
        self.violations: List[str] = []
        self.notes: List[str] = []
        self.stats: Dict[str, int] = {}
        self._journal_files: List[Path] = []

    def violation(self, msg: str) -> None:
        self.violations.append(msg)

    def note(self, msg: str) -> None:
        self.notes.append(msg)

    # -- helpers ------------------------------------------------------------
    def _rel(self, p: Path) -> str:
        try:
            return str(p.relative_to(self.root))
        except ValueError:
            return str(p)

    def _load_artifact(self, path: Path):
        import numpy as np
        if path.suffix == ".npy":
            return np.load(path, allow_pickle=False)
        with open(path, "rb") as f:
            return pickle.load(f)

    def _journal_records(self) -> Dict[str, dict]:
        """Latest failure-journal record per video, across every
        ``_failures.jsonl`` under the root (multi-family runs keep one
        per family dir; last record wins within a file, any file's
        POISON counts for invariant 6)."""
        from .telemetry.jsonl import read_jsonl
        out: Dict[str, dict] = {}
        self._journal_files = sorted(self.root.rglob("_failures.jsonl"))
        for path in self._journal_files:
            for rec in read_jsonl(path):
                v = rec.get("video")
                if v is not None:
                    # POISON/terminal records win over later RESOLVED only
                    # for invariant 6's purposes? No: mirror FailureJournal
                    # (last record wins); RESOLVED lifting is legitimate
                    out[str(v)] = rec
        return out

    # -- invariant 1: no .tmp litter ----------------------------------------
    def check_tmp_litter(self) -> None:
        tmps = sorted(self.root.rglob("*.tmp"))
        self.stats["tmp_files"] = len(tmps)
        for p in tmps:
            self.violation(
                f"tmp litter: {self._rel(p)} — a temp+rename writer leaked "
                "its scratch file (missing unlink-on-failure)")

    # -- invariant 2: jsonl torn tails only ---------------------------------
    def check_jsonl(self) -> None:
        files = sorted(self.root.rglob("*.jsonl"))
        self.stats["jsonl_files"] = len(files)
        for path in files:
            try:
                raw_lines = path.read_bytes().split(b"\n")
            except OSError as e:
                self.violation(f"{self._rel(path)}: unreadable ({e})")
                continue
            # a trailing newline yields one empty final element; drop it
            if raw_lines and raw_lines[-1] == b"":
                raw_lines.pop()
            bad = []
            for i, raw in enumerate(raw_lines):
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    json.loads(raw.decode("utf-8", errors="replace"))
                except ValueError:
                    bad.append(i)
            for i in bad:
                if i == len(raw_lines) - 1:
                    self.note(f"{self._rel(path)}: torn trailing record "
                              "(healable: the next append repairs it)")
                else:
                    self.violation(
                        f"{self._rel(path)}: corrupt record at line {i + 1} "
                        f"of {len(raw_lines)} — mid-file tears cannot happen "
                        "under single-write O_APPEND records")

    # -- invariants 3-6: fleet queue state ----------------------------------
    def _read_json(self, path: Path) -> Optional[dict]:
        try:
            with open(path, encoding="utf-8") as f:
                rec = json.load(f)
            return rec if isinstance(rec, dict) else None
        except (OSError, ValueError):
            return None

    def _heartbeats(self, out_root: Path) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for p in sorted(out_root.glob("_heartbeat_*.json")):
            hb = self._read_json(p)
            if hb is not None:
                out[str(hb.get("host_id") or p.stem)] = hb
        return out

    def check_queue(self, journal: Dict[str, dict]) -> None:
        from .telemetry.heartbeat import heartbeat_filename
        queues = sorted(p for p in self.root.rglob("_queue")
                        if p.is_dir())
        self.stats["queues"] = len(queues)
        for q in queues:
            out_root = q.parent
            hbs = self._heartbeats(out_root)
            all_final = bool(hbs) and all(hb.get("final") for hb in
                                          hbs.values())
            done: Dict[str, dict] = {}
            for p in sorted((q / "done").glob("*.json")):
                rec = self._read_json(p)
                if rec is None:
                    self.violation(f"{self._rel(p)}: unparseable done "
                                   "marker — done markers are single-write "
                                   "O_EXCL files and cannot tear")
                    continue
                done[p.stem] = rec
            self.stats["done_markers"] = \
                self.stats.get("done_markers", 0) + len(done)

            # 3: done/skipped => artifacts; error => journal record
            for iid, rec in done.items():
                video = rec.get("video")
                status = rec.get("status")
                stem = Path(str(video)).stem if video else iid
                arts = [p for ext in ARTIFACT_EXTS
                        for p in out_root.rglob(f"{stem}_*{ext}")]
                if status in ("done", "skipped"):
                    if not arts:
                        self.violation(
                            f"done marker {iid} (status={status}) has no "
                            f"artifact for {stem!r} under "
                            f"{self._rel(out_root)} — completion published "
                            "before the sink's atomic rename landed")
                        continue
                    for a in arts:
                        try:
                            self._load_artifact(a)
                        except Exception as e:
                            self.violation(
                                f"done marker {iid}: artifact "
                                f"{self._rel(a)} does not load "
                                f"({type(e).__name__}: {e}) — atomic sinks "
                                "cannot leave torn outputs")
                elif status == "error":
                    if journal and str(video) not in journal:
                        self.violation(
                            f"done marker {iid} reports status=error but "
                            f"no failure journal records {video!r} — every "
                            "terminal failure must be journaled")
                    if not self._journal_files:
                        self.note(f"done marker {iid} status=error with no "
                                  "journal present (print sink?)")

            # 4: claims vs owner heartbeats
            claimed_root = q / "claimed"
            if claimed_root.is_dir():
                for host_dir in sorted(claimed_root.iterdir()):
                    if not host_dir.is_dir():
                        continue
                    claims = sorted(host_dir.glob("*.json"))
                    if not claims:
                        continue
                    hb = self._read_json(
                        out_root / heartbeat_filename(host_dir.name))
                    owner = host_dir.name
                    if hb is not None and hb.get("final"):
                        for c in claims:
                            self.violation(
                                f"orphaned claim {self._rel(c)}: owner "
                                f"{owner} wrote a FINAL heartbeat — a "
                                "graceful exit must release or complete "
                                "every claim (cli.py release_all)")
                    elif self.expect_complete:
                        for c in claims:
                            self.violation(
                                f"leftover claim {self._rel(c)} after a "
                                f"supposedly drained run (owner {owner})")
                    else:
                        self.note(f"{len(claims)} in-flight claim(s) held "
                                  f"by {owner} (owner not finalized — "
                                  "recoverable by lease steal)")
                    for c in claims:
                        if c.stem in done:
                            self.note(f"claim {self._rel(c)} duplicates a "
                                      "done marker (recoverable: claimants "
                                      "discard against done)")

            # 5: pending / staging strandedness
            pending = sorted((q / "pending").glob("*.json"))
            if pending and self.expect_complete:
                for p in pending:
                    self.violation(f"pending item {self._rel(p)} after a "
                                   "supposedly drained run")
            for p in pending:
                if p.stem in done:
                    self.note(f"pending item {self._rel(p)} duplicates a "
                              "done marker (recoverable: discarded at "
                              "next claim)")
            staging = sorted((q / ".staging").glob("*.json"))
            for p in staging:
                rec = self._read_json(p) or {}
                iid = str(rec.get("id") or "")
                if iid and iid in done:
                    self.note(f"staging leftover {self._rel(p)} for a done "
                              "item (dead weight; swept later)")
                elif all_final or self.expect_complete:
                    self.violation(
                        f"item stranded in staging: {self._rel(p)} has no "
                        "done marker and no live host remains to sweep it "
                        "back to pending — the work is lost")
                else:
                    self.note(f"staging in-flight: {self._rel(p)} "
                              "(recoverable by the orphan sweep)")

            # 6: quarantined => POISON journal record
            for p in sorted((q / "quarantined").glob("*.json")):
                rec = self._read_json(p)
                video = (rec or {}).get("video")
                jrec = journal.get(str(video)) if video else None
                if jrec is None or jrec.get("category") != "POISON":
                    self.violation(
                        f"quarantined item {self._rel(p)} "
                        f"(video={video!r}) has no POISON record in any "
                        "failure journal — retry_failed=true could never "
                        "lift it and restarted workers would re-dispatch")

    # -- invariant 10: gateway/spool lifecycle reconciles --------------------
    def _span_request_counts(self) -> Dict[str, int]:
        """``{request_id: span_count}`` over every span file under the
        root — the evidence for 'expired at claim = zero work'."""
        from .telemetry.jsonl import read_jsonl
        out: Dict[str, int] = {}
        for spath in sorted(self.root.rglob("_telemetry.jsonl")):
            for rec in read_jsonl(spath):
                rid = rec.get("request_id")
                if rid:
                    out[str(rid)] = out.get(str(rid), 0) + 1
        return out

    def check_spools(self) -> None:
        from .telemetry.jsonl import read_jsonl
        spools = sorted({p.parent for p in self.root.rglob("requests")
                         if p.is_dir() and (p.parent / "done").is_dir()
                         and (p.parent / "claimed").is_dir()})
        if not spools:
            return
        self.stats["spools"] = len(spools)
        span_counts: Optional[Dict[str, int]] = None  # computed lazily
        for spool in spools:
            done_ids = {p.stem for p in (spool / "done").glob("*.json")}
            expired_dir = spool / "expired"
            expired_files = (sorted(expired_dir.glob("*.json"))
                             if expired_dir.is_dir() else [])
            self.stats["expired_records"] = \
                self.stats.get("expired_records", 0) + len(expired_files)
            for p in expired_files:
                rec = self._read_json(p)
                rid = p.stem
                if rec is None or rec.get("status") != "deadline_exceeded":
                    self.violation(
                        f"{self._rel(p)}: expired record must carry "
                        f"status=deadline_exceeded "
                        f"(got {(rec or {}).get('status')!r})")
                    continue
                if rid in done_ids:
                    self.violation(
                        f"request {rid}: BOTH a done/ response and an "
                        "expired/ record exist — deadline_exceeded and "
                        "completion are mutually exclusive terminal "
                        "states (serve.py)")
                if int(rec.get("processed") or 0) == 0:
                    if span_counts is None:
                        span_counts = self._span_request_counts()
                    if span_counts.get(rid):
                        self.violation(
                            f"request {rid}: expired at claim "
                            f"(processed=0) yet produced "
                            f"{span_counts[rid]} video span(s) — the "
                            "wasted-work guard must cancel BEFORE any "
                            "decode/device time burns")

            journals = sorted(spool.glob("_gateway_*.jsonl"))
            if not journals:
                continue
            self.stats["gateway_journals"] = \
                self.stats.get("gateway_journals", 0) + len(journals)
            events = [rec for j in journals for rec in read_jsonl(j)]

            # no orphaned uploads: every inbox file entered through the
            # journaled (content-addressed, atomic) upload path
            journaled = {os.path.basename(str(rec.get("path")))
                         for rec in events
                         if rec.get("event") == "upload" and rec.get("path")}
            inbox = spool / "inbox"
            if inbox.is_dir():
                files = [p for p in sorted(inbox.iterdir()) if p.is_file()]
                self.stats["inbox_files"] = \
                    self.stats.get("inbox_files", 0) + len(files)
                for p in files:
                    if p.name not in journaled:
                        self.violation(
                            f"orphaned upload {self._rel(p)}: no gateway "
                            "journal record names it — every inbox file "
                            "must arrive through the journaled upload "
                            "path (gateway.py store_upload)")

            accepted: Dict[str, str] = {}
            refused: List[str] = []
            for rec in events:
                ev, rid = rec.get("event"), rec.get("id")
                if not rid:
                    continue
                if ev == "accepted":
                    accepted[str(rid)] = str(rec.get("tenant"))
                elif ev in ("rejected", "shed"):
                    refused.append(str(rid))
            expired_ids = {p.stem for p in expired_files}
            for rid in sorted(refused):
                if rid in done_ids or rid in expired_ids or \
                        (spool / "requests" / f"{rid}.json").exists():
                    self.violation(
                        f"request {rid} was refused (429/503) at the "
                        "gateway door yet reached the spool — a refused "
                        "request must produce no work")

            # per-tenant reconcile: accepted == terminal, rid by rid
            per_tenant: Dict[str, Dict[str, int]] = {}
            for rid, tenant in sorted(accepted.items()):
                t = per_tenant.setdefault(tenant,
                                          {"accepted": 0, "terminal": 0})
                t["accepted"] += 1
                if rid in done_ids or rid in expired_ids:
                    t["terminal"] += 1
                elif self.expect_complete:
                    self.violation(
                        f"gateway-accepted request {rid} (tenant "
                        f"{tenant}) has no terminal record — every 202 "
                        "must resolve to a done/ response or an "
                        "expired/ record by drain time")
                else:
                    self.note(f"gateway-accepted request {rid} still "
                              "open (in flight — resolves by response "
                              "or deadline)")
            if self.expect_complete:
                for tenant, t in sorted(per_tenant.items()):
                    if t["accepted"] != t["terminal"]:
                        self.violation(
                            f"tenant {tenant}: {t['accepted']} accepted "
                            f"vs {t['terminal']} terminal — per-tenant "
                            "journal counts must reconcile with the "
                            "spool's done/expired markers")

    # -- invariant 7: health digests re-verify -------------------------------
    def check_health(self) -> None:
        import numpy as np
        from .telemetry.health import HEALTH_FILENAME, content_signature
        from .telemetry.jsonl import read_jsonl
        n_checked = 0
        for hpath in sorted(self.root.rglob(HEALTH_FILENAME)):
            fam_dir = hpath.parent
            latest: Dict[Tuple[str, str], dict] = {}
            for rec in read_jsonl(hpath):
                latest[(str(rec.get("video")), str(rec.get("key")))] = rec
            for (video, key), rec in sorted(latest.items()):
                stem = Path(video).stem
                art = None
                for ext in ARTIFACT_EXTS:
                    cand = fam_dir / f"{stem}_{key}{ext}"
                    if cand.exists():
                        art = cand
                        break
                nonfinite = int(rec.get("nan") or 0) + int(rec.get("inf")
                                                           or 0)
                if art is None:
                    if nonfinite == 0:
                        self.note(f"health digest for ({stem}, {key}) has "
                                  f"no artifact in {self._rel(fam_dir)} "
                                  "(print sink, or killed pre-write — "
                                  "digests are taken before the sink)")
                    continue
                if nonfinite:
                    self.violation(
                        f"{self._rel(art)}: health recorded {rec.get('nan')}"
                        f" NaN / {rec.get('inf')} Inf for this tensor, yet "
                        "an artifact exists — the non-finite gate must "
                        "refuse the write (telemetry/health.py)")
                    continue
                try:
                    value = self._load_artifact(art)
                except Exception as e:
                    self.violation(f"{self._rel(art)}: does not load "
                                   f"({type(e).__name__}: {e})")
                    continue
                got = content_signature(np.asarray(value))
                if got != rec.get("sig"):
                    self.violation(
                        f"{self._rel(art)}: content signature mismatch vs "
                        "its _health.jsonl record — the bytes on disk are "
                        "not the bytes that were digested (rot, tamper, "
                        "or a non-atomic writer)")
                n_checked += 1
        self.stats["health_verified"] = n_checked

    # -- invariant 8: artifact span shas re-verify ---------------------------
    def check_artifact_spans(self) -> None:
        import hashlib
        from .telemetry.jsonl import read_jsonl
        latest: Dict[str, dict] = {}
        for spath in sorted(self.root.rglob("_telemetry.jsonl")):
            for rec in read_jsonl(spath):
                for ev in rec.get("events") or []:
                    if ev.get("kind") == "artifact" and ev.get("file"):
                        latest[str(ev["file"])] = ev
        n_checked = 0
        for fname, ev in sorted(latest.items()):
            matches = sorted(self.root.rglob(fname))
            if not matches:
                self.violation(
                    f"artifact {fname} recorded in a span (bytes="
                    f"{ev.get('bytes')}) but absent on disk — spans emit "
                    "after the atomic rename, so the file must exist")
                continue
            for path in matches:
                try:
                    data = path.read_bytes()
                except OSError as e:
                    self.violation(f"{self._rel(path)}: unreadable ({e})")
                    continue
                if ev.get("bytes") is not None and \
                        len(data) != int(ev["bytes"]):
                    self.violation(
                        f"{self._rel(path)}: {len(data)} bytes on disk vs "
                        f"{ev['bytes']} recorded — truncated or replaced "
                        "by a non-identical writer")
                    continue
                if ev.get("sha256") and \
                        hashlib.sha256(data).hexdigest() != ev["sha256"]:
                    self.violation(
                        f"{self._rel(path)}: sha256 differs from the span "
                        "record of what was renamed into place")
                n_checked += 1
        self.stats["artifact_spans_verified"] = n_checked

    # -- invariant 9: cache entries re-verify --------------------------------
    def _discover_cache_dir(self) -> Optional[str]:
        if self.cache_dir:
            return self.cache_dir
        for mpath in sorted(self.root.rglob("_run.json")):
            m = self._read_json(mpath) or {}
            cfgs: List[dict] = []
            rc = m.get("run_config") or {}
            cfgs.append(rc)
            cfgs.extend((rc.get("families") or {}).values())
            for cfg in cfgs:
                if isinstance(cfg, dict) and cfg.get("cache") and \
                        cfg.get("cache_dir"):
                    return str(cfg["cache_dir"])
        return None

    def check_cache(self) -> None:
        import numpy as np
        from .cache import SCHEMA_VERSION
        from .telemetry.health import content_signature
        root = self._discover_cache_dir()
        if root is None:
            return
        if not os.path.isdir(root):
            self.note(f"cache dir {root} does not exist (nothing stored)")
            return
        n_checked = 0
        for path in sorted(Path(root).rglob("*.pkl")):
            try:
                with open(path, "rb") as f:
                    entry = pickle.load(f)
                if entry.get("schema") != SCHEMA_VERSION:
                    raise ValueError(f"schema {entry.get('schema')!r}")
                for k, arr in entry["feats"].items():
                    if content_signature(np.asarray(arr)) != \
                            entry["sigs"].get(k):
                        raise ValueError(f"signature mismatch for {k!r}")
            except Exception as e:
                self.violation(
                    f"cache entry {path} fails re-verification "
                    f"({type(e).__name__}: {e}) — atomic entry writes + "
                    "verify-before-trust promise this never persists")
                continue
            n_checked += 1
        self.stats["cache_entries_verified"] = n_checked

    def check_gc(self) -> None:
        """Invariant 11: every ``_gc_*.jsonl`` evict record either
        completed (path gone and, for spool/inbox, still safe to be
        gone) or is a recoverable journal-then-die remnant (note)."""
        from .gc import GC_JOURNAL_GLOB, _claimable_rids, \
            _referenced_inbox_blobs
        from .telemetry.jsonl import read_jsonl
        journals = sorted(self.root.glob(GC_JOURNAL_GLOB))
        if not journals:
            return
        live_rids = _claimable_rids(str(self.root))
        live_blobs = _referenced_inbox_blobs(str(self.root))
        n_records = n_pending = 0
        for jp in journals:
            for rec in read_jsonl(jp):
                if rec.get("event") != "evict":
                    continue
                n_records += 1
                path = rec.get("path") or ""
                plane = rec.get("plane")
                base = os.path.basename(path)
                if os.path.exists(path):
                    n_pending += 1
                    continue  # journaled-but-present: noted in bulk below
                # deleted: the safety rule must still hold NOW
                if plane == "spool" and base.endswith(".json") and \
                        base[:-len(".json")] in live_rids:
                    self.violation(
                        f"gc journal {jp.name} deleted spool response "
                        f"{base} whose request is claimable — the "
                        "claimable-rid rule (gc.py plan_spool) promises "
                        "this never happens")
                elif plane == "inbox" and base in live_blobs:
                    self.violation(
                        f"gc journal {jp.name} deleted inbox blob {base} "
                        "still referenced by a live request — the "
                        "reference rule (gc.py plan_inbox) promises this "
                        "never happens")
        if n_pending:
            self.note(
                f"{n_pending} gc-journaled deletion(s) not yet on disk "
                "— the GC died between journal and unlink; the next "
                "vft-gc run re-plans and completes them (recoverable)")
        self.stats["gc_journal_records"] = n_records

    def check_scenarios(self) -> None:
        """Invariant 12: every ``_scenario.json`` drill verdict
        (loadgen.py) is internally consistent and consistent with the
        loadgen journal it names — the offered count must equal the
        journal's request events (the artifact may not claim traffic the
        deterministic record doesn't show), per-tenant tallies must sum
        to the headline numbers, and a PASS verdict may not sit on top
        of a recorded audit failure."""
        from .loadgen import SCENARIO_SCHEMA
        from .telemetry.jsonl import read_jsonl
        n = 0
        for sp in sorted(self.root.rglob("_scenario.json")):
            doc = self._read_json(sp)
            if doc is None:
                self.violation(f"{self._rel(sp)}: unreadable")
                continue
            n += 1
            if doc.get("schema") != SCENARIO_SCHEMA:
                self.violation(
                    f"{self._rel(sp)}: schema {doc.get('schema')!r} != "
                    f"{SCENARIO_SCHEMA!r}")
                continue
            tens = doc.get("tenants") or {}
            for k in ("offered", "admitted", "completed", "expired",
                      "rejected", "shed", "errors"):
                want = sum(int(tb.get(k) or 0) for tb in tens.values())
                if int(doc.get(k) or 0) != want:
                    self.violation(
                        f"{self._rel(sp)}: headline {k}="
                        f"{doc.get(k)} != per-tenant sum {want}")
            parts = sum(int(doc.get(k) or 0)
                        for k in ("admitted", "rejected", "shed",
                                  "errors"))
            if parts != int(doc.get("offered") or 0):
                self.violation(
                    f"{self._rel(sp)}: admitted+rejected+shed+errors="
                    f"{parts} != offered={doc.get('offered')} — every "
                    "offered request has exactly one door outcome")
            jp = sp.parent / str(doc.get("journal") or "")
            if doc.get("journal") and jp.is_file():
                reqs = sum(1 for rec in read_jsonl(jp)
                           if rec.get("event") == "request")
                if reqs != int(doc.get("offered") or 0):
                    self.violation(
                        f"{self._rel(sp)}: offered={doc.get('offered')} "
                        f"but the loadgen journal {jp.name} records "
                        f"{reqs} request event(s)")
            elif doc.get("journal"):
                self.note(f"{self._rel(sp)}: journal "
                          f"{doc.get('journal')} not found beside the "
                          "artifact — offered count unverifiable")
            if doc.get("verdict") == "PASS" and \
                    not (doc.get("audit") or {}).get("pass"):
                self.violation(
                    f"{self._rel(sp)}: verdict PASS over a recorded "
                    "audit failure — the drill gate requires both")
        if n:
            self.stats["scenario_artifacts"] = n

    # -- driver --------------------------------------------------------------
    def run(self) -> bool:
        if not self.root.is_dir():
            self.violation(f"{self.root}: not a directory")
            return False
        journal = self._journal_records()
        self.stats["journal_records"] = len(journal)
        self.check_tmp_litter()
        self.check_jsonl()
        self.check_queue(journal)
        self.check_spools()
        self.check_health()
        self.check_artifact_spans()
        self.check_cache()
        self.check_gc()
        self.check_scenarios()
        return not self.violations


def audit_run(root: str, *, cache_dir: Optional[str] = None,
              expect_complete: bool = False
              ) -> Tuple[bool, List[str], List[str]]:
    """Library entry point (tests/test_chaos.py): returns
    ``(ok, violations, notes)``."""
    a = Audit(root, cache_dir=cache_dir, expect_complete=expect_complete)
    ok = a.run()
    return ok, a.violations, a.notes


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="vft-audit",
        description="Audit a (finished or killed) extraction output "
                    "directory against the cross-subsystem durability "
                    "invariants (docs/chaos.md).")
    ap.add_argument("root", help="output directory to audit (the CLI's "
                                 "output_path, or the root above it for "
                                 "multi-family runs)")
    ap.add_argument("--cache-dir", default=None,
                    help="feature-cache root to re-verify (default: "
                         "discovered from _run.json manifests)")
    ap.add_argument("--expect-complete", action="store_true",
                    help="the run claims to have drained: leftover "
                         "pending/claimed queue entries become violations")
    args = ap.parse_args(argv)
    a = Audit(args.root, cache_dir=args.cache_dir,
              expect_complete=args.expect_complete)
    ok = a.run()
    print(f"vft-audit: {args.root}")
    stat_line = ", ".join(f"{k}={v}" for k, v in sorted(a.stats.items()))
    if stat_line:
        print(f"  checked: {stat_line}")
    for v in a.violations:
        print(f"  VIOLATION: {v}")
    for n in a.notes:
        print(f"  note: {n}")
    print(f"AUDIT: {'PASS' if ok else 'FAIL'} "
          f"({len(a.violations)} violation(s), {len(a.notes)} note(s))")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
