"""vft-loadgen: a seeded, replayable traffic-scenario observatory.

Every SLO number the stack publishes comes from quiet-box benches until
something *generates* realistic traffic. This module turns checked-in
scenario specs (``scenarios/*.yml``) into deterministic, seeded request
trains driven through the real ``vft-gateway`` HTTP front door, and
turns each run into a **recorded drill**: the offered traffic is
journaled (``_loadgen_{host}.jsonl``, schema ``vft.loadgen_event/1``),
and at exit the journal is joined against the gateway admission journal,
the spool ``done/``/``expired/`` terminals, retained history
(telemetry/history.py) and the alert journal to publish a per-scenario
verdict artifact ``_scenario.json`` — offered vs admitted vs completed
per tenant, p50/p95/p99 wait+service, the **SLO attainment curve over
the scenario timeline**, shed/429/expired accounting, and a PASS/FAIL
verdict gated on ``vft-audit`` plus the scenario's declared objectives.

Determinism contract (pinned by tests/test_loadgen.py):

  * the offered-traffic journal is a pure function of (spec, seed) —
    same YAML + same seed ⇒ **bit-identical** journal lines: ids,
    virtual-clock timestamps, content keys, deadline spreads;
  * every random draw comes from a *named per-scenario stream*
    (``random.Random(f"{seed}:{scenario}:{stream}")``), so composing a
    second scenario onto the same timeline never perturbs the first
    one's events — scenario A's journal lines are identical whether A
    runs alone or alongside B;
  * run-dependent facts (HTTP status codes, measured waits) are NEVER
    written to the journal — they live in the gateway journal and the
    spool terminals, which is exactly what the exit join reads.

Clocks: scenarios are authored in *virtual seconds*. ``clock: virtual``
compresses wall time by ``speedup`` (CI runs a 60-virtual-second burst
drill in ~2 wall seconds); ``clock: wall`` is ``speedup = 1`` for real
drills. The scaling contract — arrival gaps and request ``timeout_s``
divide by ``speedup`` on the wire, measured wall durations multiply
back — is mirrored by :func:`write_tenant_table`, which emits the
gateway ``tenants.yml`` with ``rate_rps`` scaled the same way so the
wall-clock token buckets apply the *virtual* quota.

Chaos composes: a scenario's ``inject:`` key is the existing plan DSL
(utils/inject.py), armed for the run when gateway/serve share the
process (tests, the smoke gate, bench); cross-process drills arm the
server with ``VFT_INJECT`` instead (docs/scenarios.md).
"""
from __future__ import annotations

import argparse
import hashlib
import json
import math
import os
import re
import socket
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from . import serve
from .telemetry import jsonl
from .telemetry.metrics import MetricsRegistry

JOURNAL_PREFIX = "_loadgen_"
SCENARIO_FILENAME = "_scenario.json"

SCHEMA_VERSION = "vft.loadgen_event/1"
SCENARIO_SCHEMA = "vft.scenario/1"

#: journal event vocabulary (schema enum; vft-lint VFT006 pins it)
EVENTS = ("begin", "request", "end")

#: verdict vocabulary (scenario schema enum)
VERDICTS = ("PASS", "FAIL")

#: every key a ``vft.loadgen_event/1`` journal record may carry —
#: vft-lint VFT006 holds this tuple and
#: telemetry/loadgen_event.schema.json in lockstep
LOADGEN_FIELDS = ("schema", "scenario", "seed", "seq", "t", "event", "id",
                  "tenant", "klass", "videos", "timeout_s", "slow_bps",
                  "spec_sha", "offered")

#: top-level keys of the ``_scenario.json`` verdict artifact — lockstep
#: with telemetry/scenario.schema.json (VFT006)
SCENARIO_FIELDS = ("schema", "time", "scenario", "scenarios", "clock",
                   "speedup", "duration_s", "slo_s", "host_id", "journal",
                   "offered", "admitted", "completed", "expired",
                   "rejected", "shed", "errors", "tenants", "latency",
                   "curve", "history", "alerts", "audit", "objectives",
                   "verdict")

ARRIVAL_PROCESSES = ("constant", "diurnal", "burst")

#: objective keys a scenario may declare (besides the optional
#: ``tenant`` scope); unknown keys fail at load, not at verdict time
OBJECTIVE_KEYS = ("min_attainment_pct", "min_admitted_pct",
                  "max_shed_pct", "max_rejected_pct", "min_rejected",
                  "min_expired", "max_expired_pct", "min_completed")


def journal_filename(host_id: str) -> str:
    """``_loadgen_{host_id}.jsonl``, sanitized like the heartbeat and
    history filenames (host ids embed hostnames and pids)."""
    safe = re.sub(r"[^A-Za-z0-9._-]+", "-", str(host_id))
    return f"{JOURNAL_PREFIX}{safe}.jsonl"


# -- scenario specs ----------------------------------------------------------

def _bad(path: str, msg: str) -> ValueError:
    return ValueError(f"{path}: {msg}")


def load_scenario(path: str) -> Dict[str, Any]:
    """Parse + validate one scenario YAML into a normalized spec dict.

    Raises ``ValueError`` naming the file and the offending key, so a
    typo'd scenario fails at launch — the same discipline as the
    gateway tenant table and the inject plan DSL."""
    import yaml
    with open(path, encoding="utf-8") as f:
        raw = yaml.safe_load(f) or {}
    if not isinstance(raw, dict):
        raise _bad(path, "scenario spec must be a mapping")
    name = raw.get("scenario")
    if not isinstance(name, str) or not re.fullmatch(r"[a-z0-9_]+", name):
        raise _bad(path, "needs scenario: <name> matching [a-z0-9_]+")
    if not isinstance(raw.get("seed"), int):
        raise _bad(path, "needs an integer seed: (the replay contract)")
    spec: Dict[str, Any] = {
        "scenario": name,
        "seed": int(raw["seed"]),
        "duration_s": float(raw.get("duration_s") or 30.0),
        "clock": str(raw.get("clock") or "virtual"),
        "speedup": float(raw.get("speedup") or 0.0) or None,
        "slo_s": (float(raw["slo_s"]) if raw.get("slo_s") is not None
                  else None),
        "curve_windows": int(raw.get("curve_windows") or 10),
        "retry_max": int(raw.get("retry_max") or 0),
        "inject": raw.get("inject"),
    }
    if spec["duration_s"] <= 0:
        raise _bad(path, "duration_s must be > 0")
    if spec["clock"] not in ("virtual", "wall"):
        raise _bad(path, "clock must be 'virtual' or 'wall'")
    if spec["clock"] == "wall":
        spec["speedup"] = 1.0
    if spec["speedup"] is not None and spec["speedup"] < 1.0:
        raise _bad(path, "speedup must be >= 1")
    if spec["curve_windows"] < 1:
        raise _bad(path, "curve_windows must be >= 1")
    if spec["inject"] is not None:
        from .utils import inject
        inject.parse_plan(str(spec["inject"]))  # validate at load

    arr = raw.get("arrivals") or {}
    proc = str(arr.get("process") or "constant")
    if proc not in ARRIVAL_PROCESSES:
        raise _bad(path, f"arrivals.process must be one of "
                         f"{'/'.join(ARRIVAL_PROCESSES)}")
    rate = float(arr.get("rate_rps") or 1.0)
    if rate <= 0:
        raise _bad(path, "arrivals.rate_rps must be > 0")
    spec["arrivals"] = {"process": proc, "rate_rps": rate}
    if proc == "diurnal":
        d = arr.get("diurnal") or {}
        period = float(d.get("period_s") or spec["duration_s"])
        depth = float(d.get("depth") if d.get("depth") is not None
                      else 0.6)
        if period <= 0 or not (0.0 <= depth < 1.0):
            raise _bad(path, "diurnal needs period_s > 0 and "
                             "0 <= depth < 1")
        spec["arrivals"]["diurnal"] = {"period_s": period, "depth": depth}
    if proc == "burst":
        b = arr.get("burst") or {}
        burst = {"period_s": float(b.get("period_s") or 20.0),
                 "length_s": float(b.get("length_s") or 5.0),
                 "rate_rps": float(b.get("rate_rps") or rate * 10)}
        if burst["period_s"] <= 0 or burst["length_s"] <= 0 \
                or burst["length_s"] > burst["period_s"] \
                or burst["rate_rps"] < 0:
            raise _bad(path, "burst needs 0 < length_s <= period_s and "
                             "rate_rps >= 0")
        spec["arrivals"]["burst"] = burst

    co = raw.get("corpus") or {}
    spec["corpus"] = {"n_items": int(co.get("n_items") or 8),
                      "zipf_s": float(co.get("zipf_s") or 0.0),
                      "videos_per_request": int(
                          co.get("videos_per_request") or 1),
                      "upload": bool(co.get("upload") or False)}
    if spec["corpus"]["n_items"] < 1 or spec["corpus"]["zipf_s"] < 0 \
            or spec["corpus"]["videos_per_request"] < 1:
        raise _bad(path, "corpus needs n_items >= 1, zipf_s >= 0, "
                         "videos_per_request >= 1")

    tens = raw.get("tenants")
    if not isinstance(tens, dict) or not tens:
        raise _bad(path, "needs at least one tenant under tenants:")
    spec["tenants"] = {}
    for tname, t in tens.items():
        if not re.fullmatch(r"[a-z0-9_]+", str(tname)):
            raise _bad(path, f"tenant {tname!r} must match [a-z0-9_]+ "
                             "(gateway id-prefix contract)")
        t = t or {}
        if not isinstance(t.get("key"), str):
            raise _bad(path, f"tenant {tname!r} needs a string 'key'")
        tt = {"key": t["key"],
              "share": float(t.get("share") or 1.0),
              "priority": str(t.get("priority") or "normal"),
              "rate_rps": float(t.get("rate_rps") or 50.0),
              "burst": float(t.get("burst") or 100.0),
              "max_inflight": int(t.get("max_inflight") or 64),
              "slow_bps": (float(t["slow_bps"])
                           if t.get("slow_bps") else None)}
        if tt["share"] <= 0:
            raise _bad(path, f"tenant {tname!r}: share must be > 0")
        if tt["priority"] not in ("high", "normal", "low"):
            raise _bad(path, f"tenant {tname!r}: priority must be "
                             "high/normal/low")
        to = t.get("timeout_s")
        if to is None:
            tt["timeout_s"] = None
        elif isinstance(to, (int, float)):
            tt["timeout_s"] = (float(to), float(to))
        elif isinstance(to, (list, tuple)) and len(to) == 2 \
                and float(to[0]) <= float(to[1]) and float(to[0]) > 0:
            tt["timeout_s"] = (float(to[0]), float(to[1]))
        else:
            raise _bad(path, f"tenant {tname!r}: timeout_s must be a "
                             "positive number or [lo, hi]")
        spec["tenants"][str(tname)] = tt

    spec["objectives"] = []
    for i, obj in enumerate(raw.get("objectives") or []):
        if not isinstance(obj, dict) or not obj:
            raise _bad(path, f"objectives[{i}] must be a mapping")
        unknown = set(obj) - set(OBJECTIVE_KEYS) - {"tenant"}
        if unknown:
            raise _bad(path, f"objectives[{i}]: unknown key(s) "
                             f"{sorted(unknown)}; pick from "
                             f"{OBJECTIVE_KEYS}")
        if obj.get("tenant") is not None \
                and str(obj["tenant"]) not in spec["tenants"]:
            raise _bad(path, f"objectives[{i}]: unknown tenant "
                             f"{obj['tenant']!r}")
        if not set(obj) - {"tenant"}:
            raise _bad(path, f"objectives[{i}] declares no threshold")
        spec["objectives"].append(dict(obj))

    # identity of the spec AS PARSED — replay proof ties the journal to
    # the exact scenario, not just its filename
    spec["spec_sha"] = hashlib.sha256(json.dumps(
        {k: v for k, v in sorted(spec.items()) if k != "spec_sha"},
        sort_keys=True, default=list).encode()).hexdigest()[:16]
    return spec


def write_tenant_table(specs: List[Dict[str, Any]], path: str,
                       speedup: float) -> None:
    """Emit the gateway ``tenants.yml`` for a drill: the scenario's
    *virtual* per-tenant quotas with ``rate_rps`` multiplied by
    ``speedup``, so the gateway's wall-clock token buckets enforce the
    virtual contract under time compression. ``burst`` and
    ``max_inflight`` are counts, not rates — they pass through."""
    from .utils.sinks import _write_bytes_atomic
    merged: Dict[str, Dict[str, Any]] = {}
    for spec in specs:
        for name, t in spec["tenants"].items():
            prev = merged.get(name)
            if prev is not None and prev["key"] != t["key"]:
                raise ValueError(
                    f"composed scenarios disagree on tenant {name!r} key")
            merged[name] = t
    lines = ["tenants:"]
    for name in sorted(merged):
        t = merged[name]
        lines += [f"  {name}:",
                  f"    key: {t['key']}",
                  f"    rate_rps: {t['rate_rps'] * speedup:g}",
                  f"    burst: {t['burst']:g}",
                  f"    max_inflight: {t['max_inflight']}",
                  f"    priority: {t['priority']}"]
    _write_bytes_atomic(path, ("\n".join(lines) + "\n").encode())


# -- deterministic traffic model ---------------------------------------------

def _stream(spec: Dict[str, Any], name: str):
    """A named, scenario-scoped RNG stream. Seeding with the string
    ``"{seed}:{scenario}:{name}"`` (hashed stably by ``random.Random``)
    makes every stream independent: adding a stream — or composing a
    second scenario — never perturbs another stream's draws."""
    import random
    return random.Random(f"{spec['seed']}:{spec['scenario']}:{name}")


def _rate_at(spec: Dict[str, Any], t: float) -> float:
    arr = spec["arrivals"]
    rate = arr["rate_rps"]
    if arr["process"] == "diurnal":
        d = arr["diurnal"]
        # trough = rate*(1-depth) at t=0, peak = rate at period/2
        phase = 0.5 + 0.5 * math.cos(2 * math.pi * t / d["period_s"])
        return rate * (1.0 - d["depth"] * phase)
    if arr["process"] == "burst":
        b = arr["burst"]
        if (t % b["period_s"]) < b["length_s"]:
            return rate + b["rate_rps"]
    return rate


def _max_rate(spec: Dict[str, Any]) -> float:
    arr = spec["arrivals"]
    if arr["process"] == "burst":
        return arr["rate_rps"] + arr["burst"]["rate_rps"]
    return arr["rate_rps"]


def _zipf_cdf(n_items: int, s: float) -> List[float]:
    w = [1.0 / (r ** s) for r in range(1, n_items + 1)]
    total = sum(w)
    cdf, acc = [], 0.0
    for x in w:
        acc += x / total
        cdf.append(acc)
    return cdf


def content_key(spec: Dict[str, Any], rank: int) -> str:
    """Scenario-scoped corpus item name (rank 0 is the hottest item
    under Zipf skew) — scoping by scenario keeps composed journals
    bit-identical to solo runs."""
    return f"{spec['scenario']}-item{rank:04d}"


def offered_events(spec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The whole offered-traffic schedule for one scenario, in virtual
    time — a pure function of the spec. Arrival times come from
    thinning a Poisson process at the peak rate (so constant, diurnal
    and burst trains share one generator); tenant mix, content
    popularity and deadline spreads each draw from their own stream."""
    arr, ten = _stream(spec, "arrivals"), _stream(spec, "tenants")
    con, dl = _stream(spec, "content"), _stream(spec, "deadlines")
    lam_max = _max_rate(spec)
    duration = spec["duration_s"]
    cdf = _zipf_cdf(spec["corpus"]["n_items"], spec["corpus"]["zipf_s"])
    tnames = list(spec["tenants"])
    shares = [spec["tenants"][t]["share"] for t in tnames]
    total_share = sum(shares)

    def draw_tenant() -> str:
        u, acc = ten.random() * total_share, 0.0
        for tn, sh in zip(tnames, shares):
            acc += sh
            if u <= acc:
                return tn
        return tnames[-1]

    def draw_item() -> str:
        u = con.random()
        for rank, c in enumerate(cdf):
            if u <= c:
                return content_key(spec, rank)
        return content_key(spec, len(cdf) - 1)

    base = {"schema": SCHEMA_VERSION, "scenario": spec["scenario"],
            "seed": spec["seed"]}
    events: List[Dict[str, Any]] = [
        {**base, "seq": 0, "t": 0.0, "event": "begin",
         "spec_sha": spec["spec_sha"]}]
    t, seq = 0.0, 0
    while True:
        t += arr.expovariate(lam_max)
        if t >= duration:
            break
        if arr.random() > _rate_at(spec, t) / lam_max:
            continue  # thinned: the instantaneous rate is below peak
        seq += 1
        tname = draw_tenant()
        tspec = spec["tenants"][tname]
        videos = [draw_item()
                  for _ in range(spec["corpus"]["videos_per_request"])]
        lo_hi = tspec["timeout_s"]
        timeout = (round(dl.uniform(*lo_hi), 3)
                   if lo_hi is not None else None)
        events.append({**base, "seq": seq, "t": round(t, 6),
                       "event": "request",
                       "id": f"{spec['scenario']}-{seq:05d}",
                       "tenant": tname, "klass": tspec["priority"],
                       "videos": videos, "timeout_s": timeout,
                       "slow_bps": tspec["slow_bps"]})
    events.append({**base, "seq": seq + 1, "t": duration, "event": "end",
                   "offered": seq})
    return events


def synthesize_corpus(corpus_dir: str, specs: List[Dict[str, Any]],
                      sample: Optional[str] = None) -> Dict[str, str]:
    """Materialize every scenario's content items as distinct files so
    the Zipf popularity skew reaches the content-addressed planes
    (gateway inbox dedup, feature cache) the way production traffic
    would. With a ``sample`` video its bytes seed every item (a unique
    suffix after the container payload keeps items distinct while still
    decodable); without one the items are tiny synthetic stubs — enough
    for stub-served drills and admission-plane tests."""
    from .utils.sinks import _write_bytes_atomic
    os.makedirs(corpus_dir, exist_ok=True)
    base = b""
    if sample:
        with open(sample, "rb") as f:
            base = f.read()
    out: Dict[str, str] = {}
    for spec in specs:
        for rank in range(spec["corpus"]["n_items"]):
            key = content_key(spec, rank)
            path = os.path.join(corpus_dir, f"{key}.mp4")
            if key not in out:
                data = base + b"\x00vft-corpus:" + key.encode() \
                    if base else b"vft-synth-corpus:" + key.encode()
                if not os.path.exists(path):
                    _write_bytes_atomic(path, data)
                out[key] = path
    return out


# -- the drill runner --------------------------------------------------------

def _pctl(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile; None on empty."""
    if not values:
        return None
    vs = sorted(values)
    idx = max(0, min(len(vs) - 1, math.ceil(q / 100.0 * len(vs)) - 1))
    return round(vs[idx], 4)


class DrillRunner:
    """One recorded drill: issue the offered schedule of one or more
    composed scenarios against a live gateway, then join every journal
    the stack already keeps into the ``_scenario.json`` verdict."""

    def __init__(self, specs: List[Dict[str, Any]], spool_dir: str,
                 base_url: str, *, corpus: Dict[str, str],
                 out_root: Optional[str] = None,
                 speedup: Optional[float] = None,
                 host_id: Optional[str] = None,
                 audit_root: Optional[str] = None,
                 cache_dir: Optional[str] = None,
                 drain_timeout_s: float = 60.0,
                 http_timeout_s: float = 30.0) -> None:
        if not specs:
            raise ValueError("need at least one scenario spec")
        self.specs = list(specs)
        self.spool_dir = str(spool_dir)
        self.base_url = base_url.rstrip("/")
        self.corpus = dict(corpus)
        self.out_root = str(out_root or spool_dir)
        self.speedup = float(
            speedup if speedup is not None
            else next((s["speedup"] for s in specs if s["speedup"]),
                      20.0 if specs[0]["clock"] == "virtual" else 1.0))
        self.host_id = host_id or f"lg-{socket.gethostname()}-{os.getpid()}"
        self.audit_root = str(audit_root or os.path.dirname(
            os.path.abspath(self.spool_dir)))
        self.cache_dir = cache_dir
        self.drain_timeout_s = float(drain_timeout_s)
        self.http_timeout_s = float(http_timeout_s)
        self.journal_path = os.path.join(self.out_root,
                                         journal_filename(self.host_id))
        self.registry = MetricsRegistry()
        #: loadgen id -> outcome {code, gw_id, tenant, scenario, t,
        #: timeout_s, attempts, error}
        self.outcomes: Dict[str, Dict[str, Any]] = {}
        self._api_key = {t: spec["tenants"][t]["key"]
                         for spec in specs for t in spec["tenants"]}
        self._uploaded: Dict[str, str] = {}

    # -- HTTP ----------------------------------------------------------------
    def _call(self, method: str, path: str, data: Optional[bytes],
              key: Optional[str]) -> Tuple[int, dict, Dict[str, str]]:
        req = urllib.request.Request(self.base_url + path, data=data,
                                     method=method)
        if key:
            req.add_header("X-API-Key", key)
        try:
            with urllib.request.urlopen(
                    req, timeout=self.http_timeout_s) as r:
                return r.status, json.loads(r.read()), dict(r.headers)
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read())
            except ValueError:
                body = {}
            return e.code, body, dict(e.headers)

    def _slow_upload(self, data: bytes, name: str, key: str,
                     bps: float) -> Tuple[int, dict]:
        """A deliberately slow client: stream the body in small chunks
        paced to ``bps`` so the gateway's body read (its ``gateway.read``
        inject site) sees a trickling upload, not one recv."""
        import http.client
        from urllib.parse import urlparse
        u = urlparse(self.base_url)
        conn = http.client.HTTPConnection(u.hostname, u.port,
                                          timeout=self.http_timeout_s)
        try:
            conn.putrequest("POST", f"/v1/upload?name={name}")
            conn.putheader("X-API-Key", key)
            conn.putheader("Content-Length", str(len(data)))
            conn.endheaders()
            chunk = max(1, int(bps / 10))  # ~10 sends per second
            for i in range(0, len(data), chunk):
                conn.send(data[i:i + chunk])
                if i + chunk < len(data):
                    time.sleep(chunk / bps)
            r = conn.getresponse()
            return r.status, json.loads(r.read())
        finally:
            conn.close()

    def _ensure_ingested(self, ev: Dict[str, Any],
                         spec: Dict[str, Any]) -> List[str]:
        """Resolve the event's content keys to server-side paths —
        either the shared-filesystem corpus paths, or (``corpus.upload``
        scenarios) the content-addressed inbox paths after pushing the
        bytes through the real upload door, throttled for slow-client
        tenants. Re-uploading a hot item on every request is the point:
        the gateway answers with a dedup hit instead of duplicate
        bytes on disk."""
        if not spec["corpus"]["upload"]:
            return [self.corpus[k] for k in ev["videos"]]
        key = self._api_key[ev["tenant"]]
        paths = []
        for ck in ev["videos"]:
            with open(self.corpus[ck], "rb") as f:
                data = f.read()
            bps = ev.get("slow_bps")
            if bps:
                st, body = self._slow_upload(data, f"{ck}.mp4", key, bps)
            else:
                st, body, _ = self._call(
                    "POST", f"/v1/upload?name={ck}.mp4", data, key)
            if st in (200, 201) and body.get("path"):
                self._uploaded[ck] = body["path"]
            paths.append(self._uploaded.get(ck, self.corpus[ck]))
        return paths

    # -- the run -------------------------------------------------------------
    def run(self) -> Dict[str, Any]:
        from .utils import inject
        plans = [s["inject"] for s in self.specs if s.get("inject")]
        if len(plans) > 1:
            print("vft-loadgen: multiple inject plans; arming the first "
                  "only (one plan per process)", file=sys.stderr)
        # a fresh drill, a fresh record: drop both the journal and any
        # prior verdict (a stale _scenario.json would fail vft-audit's
        # artifact/journal consistency invariant against the new events)
        for stale in (self.journal_path,
                      os.path.join(self.out_root, SCENARIO_FILENAME)):
            try:
                os.unlink(stale)
            except OSError:
                pass
        os.makedirs(self.out_root, exist_ok=True)
        events = sorted(
            (ev for spec in self.specs for ev in offered_events(spec)),
            key=lambda e: (e["t"], e["scenario"], e["seq"]))
        inject.arm_for_run(plans[0] if plans else None)
        spec_of = {s["scenario"]: s for s in self.specs}
        t_start = time.monotonic()
        try:
            for ev in events:
                jsonl.append_jsonl(self.journal_path, ev)
                if ev["event"] != "request":
                    continue
                # pace the wall clock to the compressed virtual schedule
                lag = ev["t"] / self.speedup - (time.monotonic() - t_start)
                if lag > 0:
                    time.sleep(lag)
                self._issue(ev, spec_of[ev["scenario"]])
            self._drain()
        finally:
            inject.disarm()
        report = self.build_report()
        jsonl.write_json_atomic(
            os.path.join(self.out_root, SCENARIO_FILENAME), report)
        return report

    def _issue(self, ev: Dict[str, Any], spec: Dict[str, Any]) -> None:
        tenant = ev["tenant"]
        out: Dict[str, Any] = {"code": None, "gw_id": None,
                               "tenant": tenant,
                               "scenario": ev["scenario"], "t": ev["t"],
                               "timeout_s": ev["timeout_s"],
                               "attempts": 0, "error": None}
        self.outcomes[ev["id"]] = out
        self.registry.counter("vft_loadgen_offered_total",
                              tenant=tenant).inc()
        try:
            paths = self._ensure_ingested(ev, spec)
        except (OSError, ValueError, Exception) as e:  # noqa: BLE001 —
            # ingestion faults (incl. injected slow-client kills) must
            # surface as drill errors, never kill the drill
            out["error"] = f"upload: {type(e).__name__}: {e}"
            return
        body: Dict[str, Any] = {"video_paths": paths}
        if ev["timeout_s"] is not None:
            body["timeout_s"] = ev["timeout_s"] / self.speedup
        data = json.dumps(body).encode()
        key = self._api_key[tenant]
        for attempt in range(1 + spec["retry_max"]):
            out["attempts"] = attempt + 1
            try:
                st, resp, _ = self._call("POST", "/v1/extract", data, key)
            except (OSError, ValueError) as e:
                out["code"], out["error"] = 0, f"{type(e).__name__}: {e}"
                break
            out["code"] = st
            if st == 202:
                out["gw_id"] = resp.get("id")
                break
            if st == 429 and attempt < spec["retry_max"]:
                # an honest Retry-After converges; cap the wall sleep so
                # a lying one cannot stall the drill
                time.sleep(min(float(resp.get("retry_after_s") or 1.0),
                               5.0))
                continue
            break
        name = {202: "vft_loadgen_admitted_total",
                429: "vft_loadgen_rejected_total",
                503: "vft_loadgen_shed_total"}.get(out["code"])
        if name:
            self.registry.counter(name, tenant=tenant).inc()

    def _drain(self) -> None:
        """Wait (wall-bounded) until every admitted request reached a
        terminal record — ``done/`` or ``expired/``; the gateway sweep
        expires the edge-queued stragglers. An incomplete drain is not
        hidden: the missing terminals fail the audit gate."""
        pending = {o["gw_id"] for o in self.outcomes.values()
                   if o["gw_id"]}
        deadline = time.monotonic() + self.drain_timeout_s
        while pending and time.monotonic() < deadline:
            pending = {rid for rid in pending
                       if serve.read_terminal(self.spool_dir, rid) is None}
            if pending:
                time.sleep(0.05)

    # -- the exit join -------------------------------------------------------
    def build_report(self, now: Optional[float] = None) -> Dict[str, Any]:
        from .audit import audit_run
        from .telemetry.alerts import ALERTS_FILENAME
        from .telemetry.history import read_history
        duration = max(s["duration_s"] for s in self.specs)
        slo_s = next((s["slo_s"] for s in self.specs
                      if s["slo_s"] is not None), None)
        offered_by_sc: Dict[str, int] = {}
        for rec in jsonl.read_jsonl(self.journal_path):
            if rec.get("event") == "request":
                offered_by_sc[rec["scenario"]] = \
                    offered_by_sc.get(rec["scenario"], 0) + 1

        tenants: Dict[str, Dict[str, Any]] = {
            t: {"offered": 0, "admitted": 0, "completed": 0,
                "expired": 0, "rejected": 0, "shed": 0, "errors": 0,
                "violations": 0, "attainment_pct": None}
            for t in self._api_key}
        waits: List[float] = []
        services: List[float] = []
        n_windows = max(s["curve_windows"] for s in self.specs)
        win_w = duration / n_windows
        windows: List[Dict[str, Any]] = [
            {"t0": round(i * win_w, 3), "t1": round((i + 1) * win_w, 3),
             "tenants": {}} for i in range(n_windows)]

        def wslot(t: float) -> Dict[str, Any]:
            return windows[min(n_windows - 1, int(t / win_w))]["tenants"]

        for lg_id, out in self.outcomes.items():
            tb = tenants[out["tenant"]]
            wb = wslot(out["t"]).setdefault(
                out["tenant"], {"offered": 0, "admitted": 0,
                                "completed": 0, "violations": 0,
                                "attainment_pct": None})
            tb["offered"] += 1
            wb["offered"] += 1
            if out["error"] is not None or out["code"] == 0:
                tb["errors"] += 1
                continue
            if out["code"] == 429:
                tb["rejected"] += 1
                continue
            if out["code"] == 503:
                tb["shed"] += 1
                continue
            if out["code"] != 202:
                tb["errors"] += 1
                continue
            tb["admitted"] += 1
            wb["admitted"] += 1
            term = serve.read_terminal(self.spool_dir, out["gw_id"])
            if term is None:
                # never reached a terminal inside the drain window —
                # an audit-visible hole, counted as a violation here too
                tb["violations"] += 1
                wb["violations"] += 1
                continue
            if term.get("status") == "deadline_exceeded":
                tb["expired"] += 1
                tb["violations"] += 1
                wb["violations"] += 1
                self.registry.counter("vft_loadgen_expired_total",
                                      tenant=out["tenant"]).inc()
                continue
            tb["completed"] += 1
            wb["completed"] += 1
            self.registry.counter("vft_loadgen_completed_total",
                                  tenant=out["tenant"]).inc()
            # measured wall durations scale back into virtual seconds
            wait_v = float(term.get("wait_s") or 0.0) * self.speedup
            svc_v = float(term.get("latency_s") or 0.0) * self.speedup
            waits.append(wait_v)
            services.append(svc_v)
            if slo_s is not None and wait_v + svc_v > slo_s:
                tb["violations"] += 1
                wb["violations"] += 1

        for tb in tenants.values():
            answered = tb["admitted"]
            if answered:
                tb["attainment_pct"] = round(
                    100.0 * (answered - tb["violations"]) / answered, 2)
        for w in windows:
            for wb in w["tenants"].values():
                if wb["admitted"]:
                    wb["attainment_pct"] = round(
                        100.0 * (wb["admitted"] - wb["violations"])
                        / wb["admitted"], 2)

        history = None
        try:
            by_host = read_history(self.spool_dir)
        except Exception:
            by_host = {}
        samples = [s for host_samples in by_host.values()
                   for s in host_samples
                   if isinstance(s.get("tenants"), dict)]
        samples.sort(key=lambda s: float(s.get("time") or 0.0))
        if samples:
            series: Dict[str, List[Dict[str, Any]]] = {}
            for s in samples:
                for t, v in s["tenants"].items():
                    series.setdefault(t, []).append(
                        {"time": s.get("time"),
                         "attainment_pct": v.get("attainment_pct")})
            history = {"ticks": len(samples), "tenants": series}

        alerts = {"page": 0, "ticket": 0}
        for rec in jsonl.read_jsonl(
                os.path.join(self.spool_dir, ALERTS_FILENAME)):
            if rec.get("state") == "firing" \
                    and rec.get("severity") in alerts:
                alerts[rec.get("severity")] += 1

        try:
            ok, violations, _notes = audit_run(
                self.audit_root, cache_dir=self.cache_dir,
                expect_complete=True)
            audit = {"pass": bool(ok), "violations": len(violations)}
        except Exception as e:
            audit = {"pass": False, "violations": -1,
                     "error": f"{type(e).__name__}: {e}"}

        totals = {k: sum(tb[k] for tb in tenants.values())
                  for k in ("offered", "admitted", "completed", "expired",
                            "rejected", "shed", "errors")}
        objectives = []
        all_met = True
        for spec in self.specs:
            for obj in spec["objectives"]:
                actual, met = self._eval_objective(obj, tenants, totals)
                objectives.append({**obj, "scenario": spec["scenario"],
                                   "actual": actual, "met": met})
                all_met = all_met and met
        verdict = "PASS" if (audit["pass"] and all_met) else "FAIL"

        report = {
            "schema": SCENARIO_SCHEMA,
            "time": round(now if now is not None else time.time(), 3),
            "scenario": "+".join(s["scenario"] for s in self.specs),
            "scenarios": [{"name": s["scenario"], "seed": s["seed"],
                           "spec_sha": s["spec_sha"],
                           "offered": offered_by_sc.get(
                               s["scenario"], 0)}
                          for s in self.specs],
            "clock": self.specs[0]["clock"],
            "speedup": self.speedup,
            "duration_s": duration,
            "slo_s": slo_s,
            "host_id": self.host_id,
            "journal": os.path.basename(self.journal_path),
            **totals,
            "tenants": tenants,
            "latency": {"unit": "virtual_s",
                        "wait": {"p50": _pctl(waits, 50),
                                 "p95": _pctl(waits, 95),
                                 "p99": _pctl(waits, 99)},
                        "service": {"p50": _pctl(services, 50),
                                    "p95": _pctl(services, 95),
                                    "p99": _pctl(services, 99)}},
            "curve": windows,
            "history": history,
            "alerts": alerts,
            "audit": audit,
            "objectives": objectives,
            "verdict": verdict,
        }
        return report

    @staticmethod
    def _eval_objective(obj: Dict[str, Any],
                        tenants: Dict[str, Dict[str, Any]],
                        totals: Dict[str, int]
                        ) -> Tuple[Optional[float], bool]:
        scope = (tenants.get(str(obj["tenant"]))
                 if obj.get("tenant") is not None else totals)
        if scope is None:
            return None, False

        def pct(num_key: str) -> Optional[float]:
            off = scope.get("offered") or 0
            if not off:
                return None
            return round(100.0 * (scope.get(num_key) or 0) / off, 2)

        met = True
        actual: Optional[float] = None
        if "min_attainment_pct" in obj:
            actual = (tenants.get(str(obj.get("tenant")), {})
                      .get("attainment_pct")
                      if obj.get("tenant") is not None else None)
            if actual is None and obj.get("tenant") is None:
                # fleet-wide: admitted-weighted over every tenant
                adm = sum(tb["admitted"] for tb in tenants.values())
                vio = sum(tb["violations"] for tb in tenants.values())
                actual = (round(100.0 * (adm - vio) / adm, 2)
                          if adm else None)
            met = actual is not None \
                and actual >= float(obj["min_attainment_pct"])
        elif "min_admitted_pct" in obj:
            actual = pct("admitted")
            met = actual is not None \
                and actual >= float(obj["min_admitted_pct"])
        elif "max_shed_pct" in obj:
            actual = pct("shed")
            met = actual is not None \
                and actual <= float(obj["max_shed_pct"])
        elif "max_rejected_pct" in obj:
            actual = pct("rejected")
            met = actual is not None \
                and actual <= float(obj["max_rejected_pct"])
        elif "max_expired_pct" in obj:
            actual = pct("expired")
            met = actual is not None \
                and actual <= float(obj["max_expired_pct"])
        elif "min_rejected" in obj:
            actual = float(scope.get("rejected") or 0)
            met = actual >= float(obj["min_rejected"])
        elif "min_expired" in obj:
            actual = float(scope.get("expired") or 0)
            met = actual >= float(obj["min_expired"])
        elif "min_completed" in obj:
            actual = float(scope.get("completed") or 0)
            met = actual >= float(obj["min_completed"])
        else:
            met = False
        return actual, met


# -- CLI ---------------------------------------------------------------------

def loadgen_main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="vft-loadgen",
        description="Seeded, replayable traffic drills against the "
                    "vft-gateway front door; each run publishes a "
                    "_scenario.json verdict with the SLO attainment "
                    "curve (docs/scenarios.md)")
    ap.add_argument("scenarios", nargs="+",
                    help="scenario YAML path(s); several compose onto "
                         "one timeline with independent streams")
    ap.add_argument("--spool", required=True,
                    help="the gateway/serve spool dir (journals, "
                         "terminals and the verdict artifact land here)")
    ap.add_argument("--base-url", default=None,
                    help="gateway base URL, e.g. http://127.0.0.1:8080 "
                         "(required unless --dry-run/--emit-tenants)")
    ap.add_argument("--corpus", default=None,
                    help="corpus dir (default {spool}/loadgen_corpus)")
    ap.add_argument("--sample", default=None,
                    help="seed video whose bytes back the synthesized "
                         "corpus items")
    ap.add_argument("--speedup", type=float, default=None,
                    help="override the scenario clock compression")
    ap.add_argument("--out", default=None,
                    help="artifact dir (default: the spool)")
    ap.add_argument("--audit-root", default=None,
                    help="tree vft-audit verifies (default: the "
                         "spool's parent)")
    ap.add_argument("--cache-dir", default=None,
                    help="feature-cache dir for the audit gate")
    ap.add_argument("--host-id", default=None,
                    help="journal identity (default lg-{host}-{pid})")
    ap.add_argument("--drain-timeout-s", type=float, default=60.0,
                    help="wall bound on waiting for terminals at exit")
    ap.add_argument("--dry-run", action="store_true",
                    help="write the deterministic offered journal only "
                         "— no HTTP, no verdict")
    ap.add_argument("--emit-tenants", metavar="PATH", default=None,
                    help="write the speedup-scaled gateway tenants.yml "
                         "for these scenarios and exit")
    args = ap.parse_args(argv)

    try:
        specs = [load_scenario(p) for p in args.scenarios]
    except (OSError, ValueError) as e:
        print(f"vft-loadgen: {e}", file=sys.stderr)
        return 2
    names = [s["scenario"] for s in specs]
    if len(set(names)) != len(names):
        print("vft-loadgen: composed scenarios must have distinct "
              "names", file=sys.stderr)
        return 2
    speedup = float(
        args.speedup if args.speedup is not None
        else next((s["speedup"] for s in specs if s["speedup"]),
                  20.0 if specs[0]["clock"] == "virtual" else 1.0))

    if args.emit_tenants:
        write_tenant_table(specs, args.emit_tenants, speedup)
        print(f"vft-loadgen: wrote {args.emit_tenants} "
              f"(rate_rps x{speedup:g})")
        return 0

    os.makedirs(args.spool, exist_ok=True)
    if args.dry_run:
        host = args.host_id or f"lg-{socket.gethostname()}-{os.getpid()}"
        out_root = args.out or args.spool
        os.makedirs(out_root, exist_ok=True)
        jpath = os.path.join(out_root, journal_filename(host))
        try:
            os.unlink(jpath)
        except OSError:
            pass
        events = sorted(
            (ev for spec in specs for ev in offered_events(spec)),
            key=lambda e: (e["t"], e["scenario"], e["seq"]))
        for ev in events:
            jsonl.append_jsonl(jpath, ev)
        n = sum(1 for e in events if e["event"] == "request")
        print(f"vft-loadgen: dry run — {n} offered request(s) "
              f"journaled to {jpath}")
        return 0

    if not args.base_url:
        print("vft-loadgen: --base-url is required (or --dry-run / "
              "--emit-tenants)", file=sys.stderr)
        return 2
    corpus_dir = args.corpus or os.path.join(args.spool, "loadgen_corpus")
    corpus = synthesize_corpus(corpus_dir, specs, sample=args.sample)
    runner = DrillRunner(
        specs, args.spool, args.base_url, corpus=corpus,
        out_root=args.out, speedup=speedup, host_id=args.host_id,
        audit_root=args.audit_root, cache_dir=args.cache_dir,
        drain_timeout_s=args.drain_timeout_s)
    report = runner.run()
    t = report["tenants"]
    for name in sorted(t):
        tb = t[name]
        att = (f"{tb['attainment_pct']}%"
               if tb["attainment_pct"] is not None else "n/a")
        print(f"vft-loadgen: {name}: offered={tb['offered']} "
              f"admitted={tb['admitted']} completed={tb['completed']} "
              f"expired={tb['expired']} 429={tb['rejected']} "
              f"shed={tb['shed']} attainment={att}")
    print(f"vft-loadgen: {report['scenario']}: {report['verdict']} "
          f"(audit={'PASS' if report['audit']['pass'] else 'FAIL'}, "
          f"{sum(1 for o in report['objectives'] if o['met'])}/"
          f"{len(report['objectives'])} objective(s) met) -> "
          f"{os.path.join(runner.out_root, SCENARIO_FILENAME)}")
    return 0 if report["verdict"] == "PASS" else 1


def main() -> int:
    return loadgen_main(sys.argv[1:])


if __name__ == "__main__":
    raise SystemExit(main())
