"""feature_type -> extractor class dispatch (lazy imports).

Equivalent of the reference's if/elif ladder in main.py:21-38. Lazy importing
keeps startup fast and lets families with heavy optional deps fail only when
actually requested.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, List, Type


_DISPATCH = {
    "resnet": ("resnet", "ExtractResNet"),
    "r21d": ("r21d", "ExtractR21D"),
    "s3d": ("s3d", "ExtractS3D"),
    "i3d": ("i3d", "ExtractI3D"),
    "clip": ("clip", "ExtractCLIP"),
    "vggish": ("vggish", "ExtractVGGish"),
    "raft": ("raft", "ExtractRAFT"),
    "pwc": ("pwc", "ExtractPWC"),
}

#: families that consume the AUDIO track: in a multi-family run they
#: share one wav rip per video instead of subscribing to the FrameBus
AUDIO_FAMILIES = frozenset({"vggish"})


def parse_feature_types(feature_type: str) -> List[str]:
    """``'resnet,clip,s3d'`` -> ``['resnet', 'clip', 's3d']``.

    A single name passes through as a one-element list; every name must
    be registered and unique (duplicate families would race on the same
    output files)."""
    fams = [f.strip() for f in str(feature_type).split(",") if f.strip()]
    if not fams:
        raise NotImplementedError(f"Unknown feature_type: {feature_type!r}")
    seen = set()
    for f in fams:
        if f not in _DISPATCH:
            raise NotImplementedError(f"Unknown feature_type: {f!r}")
        if f in seen:
            raise ValueError(
                f"feature_type={feature_type!r}: family {f!r} is listed "
                "twice (its outputs would race on the same files)")
        seen.add(f)
    return fams


def get_extractor_cls(feature_type: str) -> Type:
    if feature_type not in _DISPATCH:
        raise NotImplementedError(f"Unknown feature_type: {feature_type}")
    module_name, cls_name = _DISPATCH[feature_type]
    import importlib
    full_module = f"{__package__}.extractors.{module_name}"
    try:
        module = importlib.import_module(full_module)
    except ModuleNotFoundError as e:
        if e.name != full_module:
            raise  # a real missing dependency, not an unimplemented family
        raise NotImplementedError(
            f"feature_type={feature_type!r} is registered but its extractor "
            "is not implemented yet") from e
    return getattr(module, cls_name)
