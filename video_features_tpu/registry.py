"""feature_type -> extractor class dispatch (lazy imports).

Equivalent of the reference's if/elif ladder in main.py:21-38. Lazy importing
keeps startup fast and lets families with heavy optional deps fail only when
actually requested.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Type


_DISPATCH = {
    "resnet": ("resnet", "ExtractResNet"),
    "r21d": ("r21d", "ExtractR21D"),
    "s3d": ("s3d", "ExtractS3D"),
    "i3d": ("i3d", "ExtractI3D"),
    "clip": ("clip", "ExtractCLIP"),
    "vggish": ("vggish", "ExtractVGGish"),
    "raft": ("raft", "ExtractRAFT"),
    "pwc": ("pwc", "ExtractPWC"),
}


def get_extractor_cls(feature_type: str) -> Type:
    if feature_type not in _DISPATCH:
        raise NotImplementedError(f"Unknown feature_type: {feature_type}")
    module_name, cls_name = _DISPATCH[feature_type]
    import importlib
    full_module = f"{__package__}.extractors.{module_name}"
    try:
        module = importlib.import_module(full_module)
    except ModuleNotFoundError as e:
        if e.name != full_module:
            raise  # a real missing dependency, not an unimplemented family
        raise NotImplementedError(
            f"feature_type={feature_type!r} is registered but its extractor "
            "is not implemented yet") from e
    return getattr(module, cls_name)
