"""``python -m video_features_tpu.lint`` — same entry as ``vft-lint``."""
from .engine import main

if __name__ == "__main__":
    raise SystemExit(main())
