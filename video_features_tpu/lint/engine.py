"""The `vft-lint` rule engine: parse once, prove every contract.

Design constraints, in order:

  1. **sub-10-seconds on the whole tree** — the pass must be cheap
     enough to run on every push *before* the test matrix. It therefore
     never imports the package under analysis (no jax, no numpy): every
     contract constant (``NON_SEMANTIC_KEYS``, ``SITES``, ``*_FIELDS``,
     the metric registry) is extracted from the AST with
     ``ast.literal_eval``, the family YAMLs via ``yaml.safe_load`` and
     the schema contracts via ``json.load``. Parsing ~25k LoC this way
     costs well under a second;
  2. **stable finding identity** — a finding's fingerprint is
     ``sha1(rule|path|message)``, deliberately excluding line numbers,
     so a baseline survives unrelated edits above the finding;
  3. **suppressions are part of the contract** — a
     ``# vft-lint: disable=VFT0xx — reason`` comment silences a rule on
     one line, and an *unreasoned* disable is itself reported (VFT000,
     warn tier): every exception must be self-documenting;
  4. **grandfathering, not amnesty** — ``--baseline`` +
     ``--fail-on-new`` lets a rule land before the tree is fully clean
     while still failing the build on any *new* violation.

Rules live in :mod:`video_features_tpu.lint.rules` and register
themselves through the :func:`rule` decorator with stable ``VFT0xx``
ids; the engine knows nothing about any individual contract.
"""
from __future__ import annotations

import argparse
import ast
import hashlib
import json
import os
import re
import sys
import time
from pathlib import Path
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

#: bump when the --json output shape changes (pinned by tests/test_lint.py)
JSON_SCHEMA = "vft.lint/1"

#: the default baseline file, repo-root-relative (CI passes it explicitly)
BASELINE_FILENAME = ".vft-lint-baseline.json"

#: tiers: errors fail the run, warnings never do
ERROR, WARN = "error", "warn"

_SUPPRESS_RE = re.compile(
    r"#\s*vft-lint:\s*disable=([A-Za-z0-9,_ ]+?)(?:\s*(?:[—\-:]+)\s*(.*))?$")


class Finding:
    """One rule violation, anchored to a file and line."""

    __slots__ = ("rule", "tier", "path", "line", "message")

    def __init__(self, rule: str, path: str, line: int, message: str,
                 tier: str = ERROR) -> None:
        self.rule = rule
        self.tier = tier
        self.path = path
        self.line = int(line)
        self.message = message

    @property
    def fingerprint(self) -> str:
        # line numbers excluded on purpose: a baseline must survive
        # unrelated edits that shift the finding down the file
        blob = f"{self.rule}|{self.path}|{self.message}"
        return hashlib.sha1(blob.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {"rule": self.rule, "tier": self.tier, "path": self.path,
                "line": self.line, "message": self.message,
                "fingerprint": self.fingerprint}

    def render(self) -> str:
        mark = "warning" if self.tier == WARN else "error"
        return f"{self.path}:{self.line}: {self.rule} [{mark}] {self.message}"


#: rule id -> (function, tier, title)
_RULES: Dict[str, Tuple[Callable[["LintContext"], List[Finding]], str, str]] \
    = {}


def rule(rule_id: str, title: str, tier: str = ERROR):
    """Register a rule. The function receives a :class:`LintContext` and
    returns findings; its id is stable forever (suppressions and
    baselines reference it)."""
    def deco(fn):
        if rule_id in _RULES:
            raise ValueError(f"duplicate lint rule id {rule_id}")
        _RULES[rule_id] = (fn, tier, title)
        fn.rule_id = rule_id
        fn.title = title
        return fn
    return deco


def registered_rules() -> Dict[str, Tuple[Callable, str, str]]:
    _load_rules()
    return dict(_RULES)


class ParsedModule:
    """One parsed source file: AST + raw lines + per-line suppressions."""

    def __init__(self, relpath: str, source: str) -> None:
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        #: line -> (set of rule ids or {'all'}, has_reason)
        self.suppressions: Dict[int, Tuple[set, bool]] = {}
        self._docstring_ids = self._collect_docstrings()
        self._scan_suppressions()

    def _collect_docstrings(self) -> set:
        ids = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Module, ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                body = getattr(node, "body", [])
                if body and isinstance(body[0], ast.Expr) and \
                        isinstance(body[0].value, ast.Constant) and \
                        isinstance(body[0].value.value, str):
                    ids.add(id(body[0].value))
        return ids

    def is_docstring(self, node: ast.AST) -> bool:
        return id(node) in self._docstring_ids

    def _scan_suppressions(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group(1).split(",")
                     if r.strip()}
            reason = (m.group(2) or "").strip()
            # a comment alone on its line suppresses the NEXT line (the
            # flagged statement is often too long to share a line with
            # its justification)
            target = i + 1 if line.lstrip().startswith("#") else i
            self.suppressions[target] = (rules, bool(reason))

    def suppressed(self, line: int, rule_id: str) -> bool:
        entry = self.suppressions.get(line)
        if not entry:
            return False
        rules, _ = entry
        return "ALL" in rules or rule_id.upper() in rules


class LintContext:
    """Everything the rules read: parsed sources, family YAMLs, schema
    JSONs and the chaos doc — loaded once, shared by every rule."""

    PACKAGE = "video_features_tpu"

    def __init__(self, repo_root: str) -> None:
        self.repo_root = Path(repo_root).resolve()
        self.pkg_root = self.repo_root / self.PACKAGE
        if not self.pkg_root.is_dir():
            raise FileNotFoundError(
                f"{self.pkg_root} not found — vft-lint must run from (or "
                f"be pointed at) the repository root")
        self.modules: Dict[str, ParsedModule] = {}
        self.parse_errors: List[Finding] = []
        self._const_cache: Dict[str, Dict[str, Any]] = {}
        self._load_sources()
        self.configs = self._load_configs()

    # -- loading -----------------------------------------------------------
    def _iter_source_files(self) -> Iterable[Path]:
        yield from sorted(self.pkg_root.rglob("*.py"))
        scripts = self.repo_root / "scripts"
        if scripts.is_dir():
            yield from sorted(scripts.glob("*.py"))

    def _load_sources(self) -> None:
        for path in self._iter_source_files():
            if "__pycache__" in path.parts:
                continue
            rel = str(path.relative_to(self.repo_root))
            try:
                self.modules[rel] = ParsedModule(rel, path.read_text())
            except (OSError, SyntaxError) as e:
                # a file the engine cannot parse is maximal drift for
                # every rule that would have read it: surface it instead
                # of silently analyzing a partial tree
                self.parse_errors.append(Finding(
                    "VFT000", rel, getattr(e, "lineno", 1) or 1,
                    f"unparseable source: {type(e).__name__}: {e}"))

    def _load_configs(self) -> Dict[str, Dict[str, Any]]:
        import yaml
        out: Dict[str, Dict[str, Any]] = {}
        cfg_dir = self.pkg_root / "configs"
        for p in sorted(cfg_dir.glob("*.yml")):
            try:
                out[p.stem] = dict(yaml.safe_load(p.read_text()) or {})
            except Exception as e:
                self.parse_errors.append(Finding(
                    "VFT000", str(p.relative_to(self.repo_root)), 1,
                    f"unparseable family YAML: {type(e).__name__}: {e}"))
        return out

    # -- shared readers ----------------------------------------------------
    def package_modules(self) -> Dict[str, ParsedModule]:
        prefix = self.PACKAGE + os.sep
        return {rel: m for rel, m in self.modules.items()
                if rel.startswith(prefix)}

    def module(self, relpath: str) -> Optional[ParsedModule]:
        return self.modules.get(relpath)

    _CONTAINER_CALLS = {"frozenset", "set", "tuple", "list", "dict"}

    def constants(self, relpath: str) -> Dict[str, Any]:
        """Module-level contract constants: plain literal assignments
        (``NAME = <literal>``) plus ``frozenset({...})``-style wrappers
        around one literal argument, ``ast.literal_eval``-ed. Anything
        non-literal is skipped — the contract constants the rules read
        are all plain literals by design."""
        if relpath in self._const_cache:
            return self._const_cache[relpath]
        out: Dict[str, Any] = {}
        mod = self.module(relpath)
        if mod is not None:
            for node in mod.tree.body:
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                value = node.value
                if isinstance(value, ast.Call) and \
                        isinstance(value.func, ast.Name) and \
                        value.func.id in self._CONTAINER_CALLS and \
                        len(value.args) == 1 and not value.keywords:
                    value = value.args[0]
                try:
                    out[node.targets[0].id] = ast.literal_eval(value)
                except (ValueError, SyntaxError):
                    continue
        self._const_cache[relpath] = out
        return out

    def load_json(self, relpath: str) -> Optional[dict]:
        p = self.repo_root / relpath
        try:
            return json.loads(p.read_text())
        except (OSError, ValueError):
            return None

    def read_text(self, relpath: str) -> Optional[str]:
        try:
            return (self.repo_root / relpath).read_text()
        except OSError:
            return None

    def line_of(self, relpath: str, needle: str, default: int = 1) -> int:
        """First line containing ``needle`` — anchors findings about a
        missing entry to the declaration it should be added to."""
        mod = self.module(relpath)
        if mod is None:
            return default
        for i, line in enumerate(mod.lines, start=1):
            if needle in line:
                return i
        return default


def _load_rules() -> None:
    # import for side effects: rules.py registers itself via @rule
    from . import rules  # noqa: F401


def run_lint(repo_root: str,
             rule_ids: Optional[Iterable[str]] = None
             ) -> Tuple[List[Finding], List[Finding], float]:
    """Run the pass. Returns ``(findings, suppressed, elapsed_s)`` —
    suppressed findings are returned separately so callers can audit
    what the disables are hiding."""
    _load_rules()
    t0 = time.monotonic()
    ctx = LintContext(repo_root)
    findings: List[Finding] = list(ctx.parse_errors)
    wanted = {r.upper() for r in rule_ids} if rule_ids else None
    for rid, (fn, tier, _title) in sorted(_RULES.items()):
        if wanted is not None and rid not in wanted:
            continue
        for f in fn(ctx):
            if tier == WARN:
                f.tier = WARN  # a warn-tier rule can never fail the build
            findings.append(f)
    # meta-rule VFT000: a disable comment without a reason defeats the
    # self-documenting-exceptions contract
    for rel, mod in ctx.modules.items():
        for line, (rules, has_reason) in sorted(mod.suppressions.items()):
            if not has_reason:
                findings.append(Finding(
                    "VFT000", rel, min(line, len(mod.lines) or 1),
                    f"suppression without a reason: disable="
                    f"{','.join(sorted(rules))} — append '— <why>' so the "
                    f"exception documents itself", tier=WARN))
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        mod = ctx.modules.get(f.path)
        if mod is not None and mod.suppressed(f.line, f.rule):
            suppressed.append(f)
        else:
            kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return kept, suppressed, time.monotonic() - t0


# -- baseline ----------------------------------------------------------------

def load_baseline(path: str) -> set:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "fingerprints" not in doc:
        raise ValueError(f"{path}: not a vft-lint baseline "
                         "(expected {{'fingerprints': [...]}})")
    return set(doc["fingerprints"])


def write_baseline(path: str, findings: List[Finding]) -> int:
    errors = [f for f in findings if f.tier == ERROR]
    doc = {"schema": JSON_SCHEMA, "kind": "baseline",
           "fingerprints": sorted({f.fingerprint for f in errors}),
           "entries": [f.to_dict() for f in errors]}
    # vft-lint: disable=VFT004 — a dev-tool artifact at the operator's chosen path, reviewed into git; not a fleet output
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return len(doc["fingerprints"])


# -- CLI ---------------------------------------------------------------------

def _find_repo_root(start: Optional[str]) -> str:
    if start:
        return start
    for cand in (Path.cwd(), *Path.cwd().parents):
        if (cand / LintContext.PACKAGE / "configs").is_dir():
            return str(cand)
    # installed-package fallback: the source checkout this file lives in
    here = Path(__file__).resolve()
    return str(here.parents[2])


def main(argv: Optional[List[str]] = None) -> int:
    _load_rules()
    ap = argparse.ArgumentParser(
        prog="vft-lint",
        description="Contract-aware static analysis: prove the repo's "
                    "cross-file invariants (cache keying, chaos sites, "
                    "schema lockstep, atomic writes, metric names) "
                    "without running anything.")
    ap.add_argument("root", nargs="?", default=None,
                    help="repository root (default: auto-detect upward "
                         "from the current directory)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (schema "
                         f"{JSON_SCHEMA!r}, pinned by tests)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON of grandfathered fingerprints")
    ap.add_argument("--fail-on-new", action="store_true",
                    help="with --baseline: exit 1 only on findings NOT in "
                         "the baseline")
    ap.add_argument("--write-baseline", metavar="PATH", default=None,
                    help="write the current error findings as a baseline "
                         "and exit 0")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, (_fn, tier, title) in sorted(_RULES.items()):
            print(f"{rid}  [{tier:5s}]  {title}")
        return 0

    root = _find_repo_root(args.root)
    rule_ids = [r for r in (args.rules or "").split(",") if r] or None
    try:
        findings, suppressed, elapsed = run_lint(root, rule_ids)
    except FileNotFoundError as e:
        print(f"vft-lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        n = write_baseline(args.write_baseline, findings)
        print(f"vft-lint: wrote {n} grandfathered finding(s) to "
              f"{args.write_baseline}")
        return 0

    baseline: set = set()
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"vft-lint: cannot load baseline: {e}", file=sys.stderr)
            return 2

    errors = [f for f in findings if f.tier == ERROR]
    warns = [f for f in findings if f.tier != ERROR]
    new_errors = [f for f in errors if f.fingerprint not in baseline]
    gating = new_errors if (args.baseline and args.fail_on_new) else errors

    if args.json:
        doc = {"schema": JSON_SCHEMA, "root": str(root),
               "elapsed_s": round(elapsed, 3),
               "counts": {"errors": len(errors), "warnings": len(warns),
                          "suppressed": len(suppressed),
                          "new_errors": len(new_errors),
                          "baselined": len(errors) - len(new_errors)},
               "findings": [dict(f.to_dict(),
                                 new=f.fingerprint not in baseline)
                            for f in findings]}
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 1 if gating else 0

    for f in findings:
        tag = ""
        if args.baseline and f.tier == ERROR and f.fingerprint in baseline:
            tag = " (baselined)"
        print(f.render() + tag)
    verdict = "FAIL" if gating else "PASS"
    extra = f", {len(errors) - len(new_errors)} baselined" if baseline else ""
    print(f"vft-lint: {verdict} — {len(errors)} error(s) "
          f"({len(new_errors)} new{extra}), {len(warns)} warning(s), "
          f"{len(suppressed)} suppressed, {len(_RULES)} rules "
          f"in {elapsed:.2f}s")
    return 1 if gating else 0


if __name__ == "__main__":
    raise SystemExit(main())
