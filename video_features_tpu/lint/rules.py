"""The repo-specific contracts, as mechanical rules (VFT001–VFT007).

Each rule encodes one cross-file invariant that previously lived only in
reviewers' heads (and in minutes-long CI smokes). They are pure
functions of the parsed tree: no imports of the analyzed package, no
execution. See ``docs/static_analysis.md`` for the operator-facing rule
table; the module docstrings of the *checked* files remain the
authority on why each contract exists.

Shared extraction heuristics (documented here because findings depend on
them):

  * a **config read** is ``X.get("k")``, ``X["k"]``, ``"k" in X`` or
    ``X.k`` where ``X`` is a name ``args``/``cli_args`` or any
    ``*.args`` attribute — the repo-wide naming convention for the
    sanity-checked config mapping. Dict/Config method names are never
    treated as keys;
  * a **validator** is any function named ``sanity_check*`` or
    ``validate_*``; the config keys it reads are the "validated" set;
  * contract constants (``NON_SEMANTIC_KEYS``, ``SITES``, ``*_FIELDS``,
    ``METRICS``...) are extracted from module-level literal assignments
    (including ``frozenset({...})``-style single-literal-arg calls).
"""
from __future__ import annotations

import ast
import re
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from .engine import ERROR, WARN, Finding, LintContext, ParsedModule, rule

# -- shared extraction -------------------------------------------------------

#: receivers whose string keys are config keys (the repo-wide convention)
_CFG_NAMES = ("args", "cli_args")

#: attribute names that are mapping API, never config keys
_MAPPING_ATTRS = {
    "get", "items", "keys", "values", "pop", "setdefault", "update",
    "copy", "clear", "to_yaml",
}

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


def _iter_scope(node: ast.AST) -> Iterable[ast.AST]:
    """Children of ``node`` without descending into nested defs (the
    nested def node itself IS yielded so callers can recurse)."""
    for child in ast.iter_child_nodes(node):
        yield child
        if not isinstance(child, _SCOPE_NODES + (ast.Lambda,)):
            yield from _iter_scope(child)


def _is_cfg_receiver(node: ast.AST, excluded: Set[str]) -> bool:
    if isinstance(node, ast.Name):
        return node.id in _CFG_NAMES and node.id not in excluded
    return isinstance(node, ast.Attribute) and node.attr == "args"


def config_key_reads(tree: ast.AST) -> List[Tuple[str, int]]:
    """``(key, line)`` pairs for every config read under ``tree``.

    Scope-aware: a name (re)bound in the enclosing scope from
    ``*.parse_args(...)`` (an argparse namespace) or from a ``.get(...)``
    (a sub-dict of some record) is NOT a config mapping there, however
    it is spelled — CLI tools conventionally call both ``args``."""
    reads: List[Tuple[str, int]] = []

    def _str_arg(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        return None

    def _rebound_non_config(scope: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for node in _iter_scope(scope):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, (ast.Call, ast.BoolOp)):
                value = node.value
                if isinstance(value, ast.BoolOp) and value.values:
                    value = value.values[0]
                fn = getattr(value, "func", None)
                if isinstance(fn, ast.Attribute) and \
                        fn.attr in ("parse_args", "get"):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id in _CFG_NAMES:
                            out.add(t.id)
        return out

    def _visit(scope: ast.AST, inherited: Set[str]) -> None:
        excluded = inherited | _rebound_non_config(scope)
        for node in _iter_scope(scope):
            if isinstance(node, _SCOPE_NODES):
                _visit(node, excluded)
                continue
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                         ast.Attribute):
                if node.func.attr in ("get", "setdefault", "pop") and \
                        _is_cfg_receiver(node.func.value, excluded) and \
                        node.args:
                    key = _str_arg(node.args[0])
                    if key:
                        reads.append((key, node.lineno))
            elif isinstance(node, ast.Subscript) and \
                    _is_cfg_receiver(node.value, excluded):
                key = _str_arg(node.slice)
                if key:
                    reads.append((key, node.lineno))
            elif isinstance(node, ast.Compare) and \
                    isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                    _is_cfg_receiver(node.comparators[0], excluded):
                key = _str_arg(node.left)
                if key:
                    reads.append((key, node.lineno))
            elif isinstance(node, ast.Attribute) and \
                    _is_cfg_receiver(node.value, excluded) and \
                    node.attr not in _MAPPING_ATTRS and \
                    not node.attr.startswith("_"):
                reads.append((node.attr, node.lineno))

    _visit(tree, set())
    return reads


def _is_validator(fn: ast.AST) -> bool:
    return isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
        fn.name.startswith("sanity_check") or fn.name.startswith("validate_"))


def validator_keys(ctx: LintContext) -> Dict[str, Tuple[str, int]]:
    """key -> (module, line) for every config key read inside a
    validator function anywhere in the package."""
    out: Dict[str, Tuple[str, int]] = {}
    for rel, mod in ctx.package_modules().items():
        for node in ast.walk(mod.tree):
            if _is_validator(node):
                for key, line in config_key_reads(node):
                    out.setdefault(key, (rel, line))
    return out


def validator_spans(mod: ParsedModule) -> List[Tuple[int, int]]:
    spans = []
    for node in ast.walk(mod.tree):
        if _is_validator(node):
            spans.append((node.lineno, node.end_lineno or node.lineno))
    return spans


def _yaml_key_sets(ctx: LintContext) -> Tuple[Set[str], Set[str]]:
    """(union, intersection) of the family YAML key sets."""
    sets = [set(d) for d in ctx.configs.values()]
    if not sets:
        return set(), set()
    return set.union(*sets), set.intersection(*sets)


_CACHE_PY = "video_features_tpu/cache.py"
_CONFIG_PY = "video_features_tpu/config.py"
_INJECT_PY = "video_features_tpu/utils/inject.py"
_NAMES_PY = "video_features_tpu/telemetry/names.py"


def _declared(ctx: LintContext, relpath: str, const: str) -> Set[str]:
    val = ctx.constants(relpath).get(const)
    if val is None:
        return set()
    return {str(v) for v in val}


# -- VFT001: cache-fingerprint classification --------------------------------

@rule("VFT001", "every config key is classified semantic or non-semantic "
                "for the cache fingerprint")
def check_cache_classification(ctx: LintContext) -> List[Finding]:
    """The recurring cache-poisoning hazard: ``cache.py`` drops
    ``NON_SEMANTIC_KEYS`` from the config fingerprint and keeps
    everything else. A new operational key that nobody classifies
    silently lands IN the fingerprint — identical features stop sharing
    entries (PRs 9, 11, 13 and 14 each had to remember this by hand).
    This rule makes the choice explicit: every key in any family YAML
    and every validator-read key must appear in exactly one of
    ``cache.NON_SEMANTIC_KEYS`` or ``cache.SEMANTIC_KEYS``."""
    findings: List[Finding] = []
    non_semantic = _declared(ctx, _CACHE_PY, "NON_SEMANTIC_KEYS")
    semantic = _declared(ctx, _CACHE_PY, "SEMANTIC_KEYS")
    if not non_semantic or not semantic:
        missing = [n for n, s in (("NON_SEMANTIC_KEYS", non_semantic),
                                  ("SEMANTIC_KEYS", semantic)) if not s]
        return [Finding("VFT001", _CACHE_PY, 1,
                        f"cannot extract {'/'.join(missing)} from cache.py "
                        "— the classification contract is gone")]
    removed = _declared(ctx, _CONFIG_PY, "REMOVED_KEYS")
    launch = _declared(ctx, _CONFIG_PY, "LAUNCH_KEYS")
    yaml_union, _ = _yaml_key_sets(ctx)
    universe = yaml_union | set(validator_keys(ctx))
    anchor = ctx.line_of(_CACHE_PY, "NON_SEMANTIC_KEYS = ")

    both = sorted(non_semantic & semantic)
    for key in both:
        findings.append(Finding(
            "VFT001", _CACHE_PY, anchor,
            f"config key '{key}' is in BOTH NON_SEMANTIC_KEYS and "
            f"SEMANTIC_KEYS — the fingerprint contract must pick one"))
    for key in sorted(universe - non_semantic - semantic - removed):
        findings.append(Finding(
            "VFT001", _CACHE_PY, anchor,
            f"config key '{key}' is unclassified: add it to "
            f"cache.NON_SEMANTIC_KEYS (operational — must NOT perturb the "
            f"cache fingerprint) or cache.SEMANTIC_KEYS (value-bearing — "
            f"must key the cache)"))
    # stale classifications: a key no code, YAML or declaration knows
    code_reads = set()
    for rel, mod in ctx.package_modules().items():
        for key, _line in config_key_reads(mod.tree):
            code_reads.add(key)
    known = universe | launch | removed | code_reads
    for key in sorted((non_semantic | semantic) - known):
        findings.append(Finding(
            "VFT001", _CACHE_PY, anchor,
            f"classified key '{key}' no longer exists anywhere (not in "
            f"any family YAML, validator, declared list or code read) — "
            f"delete the stale classification", tier=WARN))
    return findings


# -- VFT002: config keys <-> YAML defaults <-> validation --------------------

@rule("VFT002", "validated keys are declared in the family YAMLs; keys "
                "read in code are declared or validated")
def check_config_key_coverage(ctx: LintContext) -> List[Finding]:
    """Two halves of the config contract:

    (a) every key a validator reads must be carried by ALL family YAMLs,
        or be declared in ``config.OPTIONAL_KEYS`` (family-specific
        defaults), ``config.LAUNCH_KEYS`` (launch-time CLI keys that
        never ride a YAML) or ``config.REMOVED_KEYS`` (legacy, deleted
        at validation);
    (b) every config key read anywhere in the package must be *known*:
        present in at least one family YAML, read by a validator, or in
        the declared LAUNCH/REMOVED lists. An unknown read is a key a
        typo'd run would silently default — the class of bug
        sanity_check exists to prevent."""
    findings: List[Finding] = []
    optional = _declared(ctx, _CONFIG_PY, "OPTIONAL_KEYS")
    launch = _declared(ctx, _CONFIG_PY, "LAUNCH_KEYS")
    removed = _declared(ctx, _CONFIG_PY, "REMOVED_KEYS")
    if not optional or not launch:
        return [Finding("VFT002", _CONFIG_PY, 1,
                        "cannot extract OPTIONAL_KEYS/LAUNCH_KEYS from "
                        "config.py — the declared key lists are gone")]
    yaml_union, yaml_common = _yaml_key_sets(ctx)
    vkeys = validator_keys(ctx)

    for key, (rel, line) in sorted(vkeys.items()):
        if key in removed or key in launch:
            continue
        if key not in yaml_common and key not in optional:
            where = "no family YAML" if key not in yaml_union else \
                "only some family YAMLs"
            findings.append(Finding(
                "VFT002", rel, line,
                f"validated config key '{key}' appears in {where} — add "
                f"the default to every configs/*.yml, or declare it in "
                f"config.OPTIONAL_KEYS / LAUNCH_KEYS"))
    # stale declarations
    cfg_anchor = ctx.line_of(_CONFIG_PY, "OPTIONAL_KEYS = ")
    for key in sorted(optional - yaml_union):
        findings.append(Finding(
            "VFT002", _CONFIG_PY, cfg_anchor,
            f"OPTIONAL_KEYS entry '{key}' appears in no family YAML — "
            f"stale declaration", tier=WARN))

    known = yaml_union | set(vkeys) | launch | removed
    for rel, mod in sorted(ctx.package_modules().items()):
        spans = validator_spans(mod)
        for key, line in config_key_reads(mod.tree):
            if key in known:
                continue
            if any(lo <= line <= hi for lo, hi in spans):
                continue  # the validator read IS the declaration
            findings.append(Finding(
                "VFT002", rel, line,
                f"config key '{key}' is read here but declared nowhere: "
                f"not in any configs/*.yml, no validator reads it, and it "
                f"is not in config.LAUNCH_KEYS — a typo'd value would "
                f"silently default"))
    return findings


# -- VFT003: chaos sites -----------------------------------------------------

def _inject_call_sites(ctx: LintContext) -> List[Tuple[str, str, int]]:
    """(site, module, line) for every ``inject.fire("site")`` /
    ``*._inject.check("site")`` call in the package."""
    out: List[Tuple[str, str, int]] = []
    for rel, mod in ctx.package_modules().items():
        if rel == _INJECT_PY:
            continue  # the plan parser mentions sites generically
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("fire", "check")):
                continue
            recv = node.func.value
            recv_name = recv.id if isinstance(recv, ast.Name) else \
                recv.attr if isinstance(recv, ast.Attribute) else ""
            if "inject" not in recv_name:
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                out.append((node.args[0].value, rel, node.lineno))
    return out


@rule("VFT003", "chaos sites: fire() names registered, every site has a "
                "call site and a docs/chaos.md row")
def check_inject_sites(ctx: LintContext) -> List[Finding]:
    """The fault plane is only as deterministic as its registry: a
    ``fire()`` naming an unregistered site raises at runtime (or worse,
    a plan targeting it fails validation and the drill silently tests
    nothing), and a registered site with no call site is dead chaos
    coverage — the matrix claims to exercise a failure mode it cannot
    reach. The site table in ``docs/chaos.md`` is the operator contract
    and must list every site."""
    findings: List[Finding] = []
    sites = _declared(ctx, _INJECT_PY, "SITES")
    if not sites:
        return [Finding("VFT003", _INJECT_PY, 1,
                        "cannot extract SITES from utils/inject.py")]
    calls = _inject_call_sites(ctx)
    called = {s for s, _rel, _line in calls}
    for site, rel, line in calls:
        if site not in sites:
            findings.append(Finding(
                "VFT003", rel, line,
                f"inject site '{site}' is fired here but not registered "
                f"in inject.SITES — plans cannot target it and "
                f"sanity_check would reject them"))
    anchor = ctx.line_of(_INJECT_PY, "SITES = ")
    chaos_doc = ctx.read_text("docs/chaos.md") or ""
    documented = set()
    for line_text in chaos_doc.splitlines():
        if line_text.lstrip().startswith("|"):
            documented.update(re.findall(r"`([a-z_]+\.[a-z_]+)`", line_text))
    for site in sorted(sites):
        if site not in called:
            findings.append(Finding(
                "VFT003", _INJECT_PY, anchor,
                f"registered inject site '{site}' has no fire()/check() "
                f"call site — dead chaos coverage: the matrix claims a "
                f"failure mode nothing can reach"))
        if site not in documented:
            findings.append(Finding(
                "VFT003", _INJECT_PY, anchor,
                f"registered inject site '{site}' has no row in the "
                f"docs/chaos.md site table — the operator contract is "
                f"incomplete"))
    return findings


# -- VFT004: atomic-write discipline -----------------------------------------

#: modules that ARE the sanctioned write paths
_ATOMIC_MODULES = {"video_features_tpu/telemetry/jsonl.py"}
#: (module, function) pairs that are sanctioned
_ATOMIC_FUNCS = {("video_features_tpu/utils/sinks.py",
                  "_write_bytes_atomic")}

_WRITE_MODES = re.compile(r"[wax]")


def _open_mode(node: ast.Call) -> Optional[str]:
    args = node.args
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else \
        fn.id if isinstance(fn, ast.Name) else ""
    idx = 1
    if name == "open" and isinstance(fn, ast.Attribute) and \
            isinstance(fn.value, ast.Name) and fn.value.id == "os":
        return None  # os.open uses flags; covered via the fdopen wrapper
    if name not in ("open", "fdopen"):
        return None
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            return str(kw.value.value)
    if len(args) > idx and isinstance(args[idx], ast.Constant) \
            and isinstance(args[idx].value, str):
        return args[idx].value
    return "r" if name == "open" else None


class _WriteVisitor(ast.NodeVisitor):
    def __init__(self) -> None:
        self.findings: List[Tuple[int, str]] = []
        self._bytesio: List[Set[str]] = [set()]

    def visit_FunctionDef(self, node):  # noqa: N802
        self._bytesio.append(set())
        self.generic_visit(node)
        self._bytesio.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):  # noqa: N802
        value = node.value
        if isinstance(value, ast.Call):
            fn = value.func
            callee = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else ""
            if callee == "BytesIO":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self._bytesio[-1].add(t.id)
        self.generic_visit(node)

    def _first_arg_is_buffer(self, node: ast.Call) -> bool:
        if node.args and isinstance(node.args[0], ast.Name):
            return any(node.args[0].id in scope for scope in self._bytesio)
        return False

    def visit_Call(self, node):  # noqa: N802
        fn = node.func
        callee = fn.attr if isinstance(fn, ast.Attribute) else \
            fn.id if isinstance(fn, ast.Name) else ""
        mode = _open_mode(node)
        if mode is not None and _WRITE_MODES.search(mode) \
                and "+" not in mode:
            self.findings.append((
                node.lineno,
                f"raw write-mode open(..., {mode!r}): durable artifacts "
                f"must go through utils/sinks._write_bytes_atomic or "
                f"telemetry/jsonl.py (temp+fsync+rename), or carry a "
                f"reasoned suppression"))
        elif callee == "save" and isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id in ("np", "numpy") and \
                not self._first_arg_is_buffer(node):
            self.findings.append((
                node.lineno,
                "np.save to a path writes non-atomically: serialize to "
                "BytesIO and route through _write_bytes_atomic"))
        elif callee == "dump" and isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id in ("json", "pickle") and \
                len(node.args) > 1 and isinstance(node.args[1], ast.Call):
            self.findings.append((
                node.lineno,
                f"{fn.value.id}.dump into an inline open(): route the "
                f"bytes through _write_bytes_atomic instead"))
        self.generic_visit(node)


@rule("VFT004", "durable artifacts go through the atomic "
                "temp+fsync+rename path")
def check_atomic_writes(ctx: LintContext) -> List[Finding]:
    """PR 9 proved (with injected ENOSPC/torn/drop faults) that the
    temp+fsync+rename discipline is what keeps a preempted worker from
    leaving half-written artifacts that later readers trust. The
    discipline only holds if every new write site uses it. This rule
    flags raw write-mode opens, path-level ``np.save`` and inline-open
    ``json.dump``/``pickle.dump`` in the package; the sanctioned paths
    (``utils/sinks._write_bytes_atomic``, ``telemetry/jsonl.py``) are
    exempt, and deliberate exceptions (O_EXCL first-writer-wins
    protocol files, verify-then-promote downloads) carry reasoned
    suppressions."""
    findings: List[Finding] = []
    sanctioned_by_mod: Dict[str, Set[str]] = {}
    for mod_rel, func in _ATOMIC_FUNCS:
        sanctioned_by_mod.setdefault(mod_rel, set()).add(func)
    for rel, mod in sorted(ctx.package_modules().items()):
        if rel in _ATOMIC_MODULES:
            continue
        sanctioned = sanctioned_by_mod.get(rel, set())
        visitor = _WriteVisitor()
        for node in mod.tree.body:
            visitor.visit(node)
        for line, msg in visitor.findings:
            # drop findings inside sanctioned functions
            if sanctioned and _line_in_functions(mod, line, sanctioned):
                continue
            findings.append(Finding("VFT004", rel, line, msg))
    return findings


def _line_in_functions(mod: ParsedModule, line: int,
                       names: Set[str]) -> bool:
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                node.name in names and \
                node.lineno <= line <= (node.end_lineno or node.lineno):
            return True
    return False


# -- VFT005: metric-name registry --------------------------------------------

_METRIC_NAME = re.compile(r"^vft_[a-z0-9]+(_[a-z0-9]+)*$")
_METRIC_CALL_ATTRS = {"counter", "gauge", "histogram", "gauge_set", "inc",
                      "observe"}
_METRIC_CALL_NAMES = {"gauge_set", "inc", "observe", "g"}
_KIND_OF_CALL = {"counter": "counter", "inc": "counter",
                 "gauge": "gauge", "gauge_set": "gauge", "g": "gauge",
                 "histogram": "histogram", "observe": "histogram"}


def _metric_callee(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in _METRIC_CALL_ATTRS:
        return fn.attr
    if isinstance(fn, ast.Name) and fn.id in _METRIC_CALL_NAMES:
        return fn.id
    return None


def _fstring_pattern(node: ast.JoinedStr) -> Optional[str]:
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(re.escape(str(v.value)))
        else:
            parts.append(r"[a-z0-9_]+")
    pat = "".join(parts)
    first = node.values[0] if node.values else None
    if isinstance(first, ast.Constant) and \
            str(first.value).startswith("vft_"):
        return pat
    return None


@rule("VFT005", "every vft_* metric name resolves against the declared "
                "registry; counters end in _total")
def check_metric_names(ctx: LintContext) -> List[Finding]:
    """74 distinct series names flow from emitters through heartbeats to
    renderers and Prometheus exports — connected only by string
    equality. ``telemetry/names.py`` is the single declared registry;
    every literal (and every f-string a metric call builds) must resolve
    against it, so an emitter rename that forgets a renderer (or vice
    versa) fails the lint instead of silently exporting a dead series.
    Prometheus naming is enforced where it is load-bearing: counters
    end in ``_total``."""
    findings: List[Finding] = []
    names_mod = ctx.module(_NAMES_PY)
    if names_mod is None:
        return [Finding("VFT005", _NAMES_PY, 1,
                        "telemetry/names.py (the metric-name registry) "
                        "is missing")]
    registry = ctx.constants(_NAMES_PY).get("METRICS")
    if not isinstance(registry, dict) or not registry:
        return [Finding("VFT005", _NAMES_PY, 1,
                        "cannot extract METRICS dict from "
                        "telemetry/names.py")]
    anchor = ctx.line_of(_NAMES_PY, "METRICS = ")
    for name, kind in sorted(registry.items()):
        if not _METRIC_NAME.match(name):
            findings.append(Finding(
                "VFT005", _NAMES_PY, anchor,
                f"registry name '{name}' is not a valid vft_* metric "
                f"name"))
        if kind == "counter" and not name.endswith("_total"):
            findings.append(Finding(
                "VFT005", _NAMES_PY, anchor,
                f"counter '{name}' must end in _total (Prometheus "
                f"counter naming)"))
        if kind not in ("counter", "gauge", "histogram"):
            findings.append(Finding(
                "VFT005", _NAMES_PY, anchor,
                f"registry entry '{name}' has unknown kind {kind!r}"))

    used: Set[str] = set()
    for rel, mod in sorted(ctx.modules.items()):
        if rel == _NAMES_PY:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    not mod.is_docstring(node) and \
                    _METRIC_NAME.match(node.value):
                if node.value not in registry:
                    findings.append(Finding(
                        "VFT005", rel, node.lineno,
                        f"metric name '{node.value}' is not declared in "
                        f"telemetry/names.py METRICS — emitter/renderer "
                        f"drift, or a new series missing its "
                        f"registration"))
                else:
                    used.add(node.value)
            elif isinstance(node, ast.Call):
                callee = _metric_callee(node)
                if callee is None or not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.JoinedStr):
                    pat = _fstring_pattern(arg)
                    if pat is None:
                        continue
                    matches = [n for n in registry
                               if re.fullmatch(pat, n)]
                    if not matches:
                        findings.append(Finding(
                            "VFT005", rel, node.lineno,
                            f"dynamically-built metric name (pattern "
                            f"vft_…) matches no registry entry — declare "
                            f"each expansion in telemetry/names.py"))
                    used.update(matches)
                elif isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str) and \
                        arg.value in registry:
                    declared = registry[arg.value]
                    expect = _KIND_OF_CALL.get(callee)
                    if expect and declared != expect and callee != "g":
                        findings.append(Finding(
                            "VFT005", rel, node.lineno,
                            f"'{arg.value}' is declared a {declared} but "
                            f"used via .{callee}()"))
    for name in sorted(set(registry) - used):
        findings.append(Finding(
            "VFT005", _NAMES_PY, anchor,
            f"registry entry '{name}' is referenced nowhere in the "
            f"package or scripts — stale registration", tier=WARN))
    return findings


# -- VFT006: *_FIELDS <-> schema JSON lockstep -------------------------------

def _schema_checks(ctx: LintContext, label: str, schema: Optional[dict],
                   mod_rel: str, consts: Dict[str, Any],
                   fields_name: str, anchor: int,
                   enums: Dict[str, str]) -> List[Finding]:
    findings: List[Finding] = []
    fields = consts.get(fields_name)
    if schema is None or fields is None:
        findings.append(Finding(
            "VFT006", mod_rel, anchor,
            f"{label}: cannot load the schema JSON and/or extract "
            f"{fields_name} — the lockstep contract is unverifiable"))
        return findings
    props = set(schema.get("properties", {}))
    want = set(fields)
    for k in sorted(props - want):
        findings.append(Finding(
            "VFT006", mod_rel, anchor,
            f"{label}: schema-only property '{k}' (the emitter never "
            f"writes it) — {fields_name} and the schema JSON drifted"))
    for k in sorted(want - props):
        findings.append(Finding(
            "VFT006", mod_rel, anchor,
            f"{label}: emitter field '{k}' missing from the schema JSON "
            f"properties"))
    for k in sorted(set(schema.get("required", [])) - props):
        findings.append(Finding(
            "VFT006", mod_rel, anchor,
            f"{label}: required key '{k}' is not in properties"))
    if schema.get("additionalProperties", True) is not False:
        findings.append(Finding(
            "VFT006", mod_rel, anchor,
            f"{label}: schema must set additionalProperties: false (the "
            f"record contract is closed)"))
    tag = schema.get("properties", {}).get("schema", {}).get("enum")
    version = consts.get("SCHEMA_VERSION")
    if version is not None and tag != [version]:
        findings.append(Finding(
            "VFT006", mod_rel, anchor,
            f"{label}: schema tag enum {tag} != [{version!r}]"))
    for prop, const in enums.items():
        declared = consts.get(const)
        got = schema.get("properties", {}).get(prop, {}).get("enum")
        if declared is not None and got != list(declared):
            findings.append(Finding(
                "VFT006", mod_rel, anchor,
                f"{label}: '{prop}' enum {got} != {const} "
                f"{list(declared)}"))
    return findings


@rule("VFT006", "*_FIELDS tuples and the checked-in *.schema.json stay "
                "in lockstep")
def check_schema_lockstep(ctx: LintContext) -> List[Finding]:
    """Each telemetry record shape is declared twice on purpose — once
    in code (the emitter's ``*_FIELDS`` tuple) and once as the
    checked-in consumer contract (``*.schema.json``). This rule pins
    the two statically (properties equality, required ⊆ properties,
    closed records, version-tag and status enums), subsuming the static
    halves of the five ``scripts/check_*_schema.py`` CI gates — which
    keep only their dynamic smokes."""
    findings: List[Finding] = []
    tel = "video_features_tpu/telemetry/"

    def consts_of(rel: str) -> Tuple[Dict[str, Any], int]:
        return ctx.constants(rel), 1

    # spans <-> video_span.schema.json
    rel = tel + "spans.py"
    consts, _ = consts_of(rel)
    findings += _schema_checks(
        ctx, "video_span", ctx.load_json(tel + "video_span.schema.json"),
        rel, consts, "SPAN_FIELDS",
        ctx.line_of(rel, "SPAN_FIELDS = "), {"status": "STATUSES"})

    # health <-> feature_health.schema.json
    rel = tel + "health.py"
    consts, _ = consts_of(rel)
    findings += _schema_checks(
        ctx, "feature_health",
        ctx.load_json(tel + "feature_health.schema.json"),
        rel, consts, "HEALTH_FIELDS",
        ctx.line_of(rel, "HEALTH_FIELDS = "), {})

    # alerts <-> alert.schema.json
    rel = tel + "alerts.py"
    consts, _ = consts_of(rel)
    findings += _schema_checks(
        ctx, "alert", ctx.load_json(tel + "alert.schema.json"),
        rel, consts, "ALERT_FIELDS",
        ctx.line_of(rel, "ALERT_FIELDS = "),
        {"state": "STATES", "severity": "SEVERITIES"})

    # loadgen <-> loadgen_event.schema.json + scenario.schema.json
    # (two record shapes, one emitter module — the journal record and
    # the exit-join verdict artifact; the scenario schema's version tag
    # lives in SCENARIO_SCHEMA, not SCHEMA_VERSION, hence the override)
    rel = "video_features_tpu/loadgen.py"
    consts, _ = consts_of(rel)
    findings += _schema_checks(
        ctx, "loadgen_event",
        ctx.load_json(tel + "loadgen_event.schema.json"),
        rel, consts, "LOADGEN_FIELDS",
        ctx.line_of(rel, "LOADGEN_FIELDS = "), {"event": "EVENTS"})
    findings += _schema_checks(
        ctx, "scenario", ctx.load_json(tel + "scenario.schema.json"),
        rel, dict(consts, SCHEMA_VERSION=consts.get("SCENARIO_SCHEMA")),
        "SCENARIO_FIELDS", ctx.line_of(rel, "SCENARIO_FIELDS = "),
        {"verdict": "VERDICTS"})

    # parity <-> parity.schema.json + parity_verdict.schema.json
    # (two record shapes, one emitter module — the per-seam digest
    # journal and the certify verdict artifact; the verdict schema's
    # version tag lives in VERDICT_SCHEMA, not SCHEMA_VERSION)
    rel = tel + "parity.py"
    consts, _ = consts_of(rel)
    findings += _schema_checks(
        ctx, "parity", ctx.load_json(tel + "parity.schema.json"),
        rel, consts, "PARITY_FIELDS",
        ctx.line_of(rel, "PARITY_FIELDS = "), {"seam": "SEAMS"})
    findings += _schema_checks(
        ctx, "parity_verdict",
        ctx.load_json(tel + "parity_verdict.schema.json"),
        rel, dict(consts, SCHEMA_VERSION=consts.get("VERDICT_SCHEMA")),
        "VERDICT_FIELDS", ctx.line_of(rel, "VERDICT_FIELDS = "),
        {"verdict": "VERDICTS"})

    # roofline <-> roofline.schema.json (nested)
    rel = tel + "roofline.py"
    consts, _ = consts_of(rel)
    schema = ctx.load_json(tel + "roofline.schema.json")
    anchor = ctx.line_of(rel, "ROOFLINE_FIELDS = ")
    findings += _schema_checks(ctx, "roofline", schema, rel, consts,
                               "ROOFLINE_FIELDS", anchor, {})
    if schema is not None:
        dev = schema.get("properties", {}).get("device", {})
        findings += _schema_checks(ctx, "roofline.device", dev, rel,
                                   dict(consts, SCHEMA_VERSION=None),
                                   "DEVICE_FIELDS", anchor, {})
        fam = schema.get("properties", {}).get("families", {}) \
            .get("additionalProperties", {})
        findings += _schema_checks(ctx, "roofline.family", fam, rel,
                                   dict(consts, SCHEMA_VERSION=None),
                                   "FAMILY_FIELDS", anchor, {})
        card = fam.get("properties", {}).get("programs", {}) \
            .get("items", {})
        findings += _schema_checks(ctx, "roofline.card", card, rel,
                                   dict(consts, SCHEMA_VERSION=None),
                                   "CARD_FIELDS", anchor, {})
        verdicts = consts.get("VERDICTS")
        got = fam.get("properties", {}).get("verdict", {}).get("enum")
        if verdicts is not None and (
                got is None
                or [v for v in got if v is not None] != list(verdicts)):
            findings.append(Finding(
                "VFT006", rel, anchor,
                f"roofline verdict enum {got} != VERDICTS "
                f"{list(verdicts)} (+ null)"))
    return findings


# -- VFT007: unlocked mutation of module globals in threaded modules ---------

_THREADED_MODULES = (
    "video_features_tpu/serve.py",
    "video_features_tpu/gateway.py",
    "video_features_tpu/parallel/queue.py",
    "video_features_tpu/telemetry/heartbeat.py",
)
_MUTATORS = {"append", "add", "update", "pop", "popleft", "appendleft",
             "extend", "remove", "clear", "setdefault", "insert",
             "discard"}


def _mutable_globals(mod: ParsedModule) -> Set[str]:
    out: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            v = node.value
            if isinstance(v, (ast.List, ast.Dict, ast.Set)):
                out.add(node.targets[0].id)
            elif isinstance(v, ast.Call):
                fn = v.func
                callee = fn.attr if isinstance(fn, ast.Attribute) else \
                    fn.id if isinstance(fn, ast.Name) else ""
                if callee in ("list", "dict", "set", "deque",
                              "defaultdict", "OrderedDict"):
                    out.add(node.targets[0].id)
    return out


class _LockVisitor(ast.NodeVisitor):
    def __init__(self, globals_: Set[str]) -> None:
        self.globals = globals_
        self.findings: List[Tuple[int, str]] = []
        self._with_depth = 0
        self._declared_global: List[Set[str]] = []

    def _locked(self) -> bool:
        return self._with_depth > 0

    def visit_With(self, node):  # noqa: N802
        locked = any("lock" in ast.unparse(item.context_expr).lower()
                     for item in node.items)
        if locked:
            self._with_depth += 1
        self.generic_visit(node)
        if locked:
            self._with_depth -= 1

    def visit_FunctionDef(self, node):  # noqa: N802
        self._declared_global.append(set())
        self.generic_visit(node)
        self._declared_global.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Global(self, node):  # noqa: N802
        if self._declared_global:
            self._declared_global[-1].update(node.names)
        self.generic_visit(node)

    def _flag(self, line: int, name: str, how: str) -> None:
        if not self._locked():
            self.findings.append((
                line, f"module global '{name}' {how} outside a lock-guarded "
                      f"'with' block — this module runs threaded; guard the "
                      f"mutation or make the state thread-local"))

    def visit_Call(self, node):  # noqa: N802
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id in self.globals:
            self._flag(node.lineno, fn.value.id, f"mutated via .{fn.attr}()")
        self.generic_visit(node)

    def visit_Subscript(self, node):  # noqa: N802
        if isinstance(node.ctx, (ast.Store, ast.Del)) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in self.globals:
            self._flag(node.lineno, node.value.id, "item-assigned")
        self.generic_visit(node)

    def visit_Assign(self, node):  # noqa: N802
        if self._declared_global:
            for t in node.targets:
                if isinstance(t, ast.Name) and \
                        t.id in self._declared_global[-1] and \
                        t.id in self.globals:
                    self._flag(node.lineno, t.id, "rebound via 'global'")
        self.generic_visit(node)


@rule("VFT007", "module-global mutation in threaded modules happens "
                "under a lock", tier=WARN)
def check_threaded_globals(ctx: LintContext) -> List[Finding]:
    """serve, gateway, the fleet queue and the heartbeat flusher all run
    real threads. A module-level mutable global mutated outside a
    ``with <lock>:`` block is a data race waiting for load. Warn-tier:
    the heuristic cannot see a lock held by the caller, so it flags for
    human review rather than failing the build."""
    findings: List[Finding] = []
    for rel in _THREADED_MODULES:
        mod = ctx.module(rel)
        if mod is None:
            continue
        globals_ = _mutable_globals(mod)
        if not globals_:
            continue
        visitor = _LockVisitor(globals_)
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                visitor.visit(node)
        for line, msg in visitor.findings:
            findings.append(Finding("VFT007", rel, line, msg, tier=WARN))
    return findings
