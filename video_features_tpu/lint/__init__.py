"""`vft-lint`: contract-aware static analysis for this repository.

Fourteen PRs in, the system's correctness rests on cross-file contracts
that were maintained purely by convention: every new config key must be
classified against ``cache.py``'s fingerprint sets or it silently
poisons the content-addressed cache key; every ``inject.fire(site)``
must name a registered site with chaos-doc coverage; every ``*_FIELDS``
tuple must stay in lockstep with its checked-in ``*.schema.json``;
every durable artifact must go through the temp+fsync+rename path. The
runtime ``scripts/check_*.py`` smokes catch that drift minutes into CI
— *after* the code already shipped past review. This package proves the
mechanical halves of those contracts in seconds, at review time, with
no imports of the package under analysis (pure ``ast`` + YAML + JSON).

Entry points: the ``vft-lint`` console script, ``python main.py lint``,
or ``python -m video_features_tpu.lint``. See ``docs/static_analysis.md``
for the rule table and the suppression/baseline workflow.
"""
from .engine import main, run_lint  # noqa: F401
