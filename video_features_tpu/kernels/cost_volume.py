"""PWC-Net 81-channel cost volume as a Pallas TPU kernel.

Replaces the reference's raw-CUDA correlation kernel (reference
models/pwc/pwc_src/correlation.py:47-115: output channel ``(dy+4)*9+(dx+4)``
is the channel-mean of ``f1 * shift(f2, dy, dx)`` with 4 px zero padding).

TPU design (not a translation of the CUDA kernel's shared-memory layout):

  - channel-major tiles: inputs are transposed to (B, C, H, W) so the wide
    spatial W axis sits on the 128-lane dimension and the reduction over C
    runs across sublane groups — lane utilization is set by W, not by the
    (often small: 32..196) channel count;
  - the second feature map is kept in HBM and each program DMAs exactly its
    (C, TH+2r, W+2r) halo block into VMEM scratch once, then all 81
    displacement windows are strided reads of that scratch — f2 moves from
    HBM once per row-tile instead of 81 times;
  - the 81 multiply-reduce windows write one (TH, W) channel plane each,
    contiguous vector stores.

Grid: (B, H/TH). The XLA twin (81 shifted multiply-reduces, fused by XLA) is
kept for CPU and as a fallback; parity is tested in interpret mode.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def cost_volume_xla(f1: jnp.ndarray, f2: jnp.ndarray,
                    radius: int = 4) -> jnp.ndarray:
    """(B, H, W, C) x2 -> (B, H, W, (2r+1)^2), channel (dy+r)*(2r+1)+(dx+r)."""
    b, h, w, c = f1.shape
    f2p = jnp.pad(f2, ((0, 0), (radius, radius), (radius, radius), (0, 0)))
    out = []
    for dy in range(-radius, radius + 1):
        for dx in range(-radius, radius + 1):
            win = f2p[:, radius + dy:radius + dy + h,
                      radius + dx:radius + dx + w, :]
            out.append(jnp.mean(f1 * win, axis=-1))
    return jnp.stack(out, axis=-1)


def _kernel(f1_ref, f2p_ref, out_ref, scratch, sem, *, th: int, radius: int,
            w: int):
    bi = pl.program_id(0)
    ti = pl.program_id(1)
    d = 2 * radius + 1
    c = scratch.shape[0]
    dma = pltpu.make_async_copy(
        f2p_ref.at[bi, :, pl.ds(ti * th, th + 2 * radius), :], scratch, sem)
    dma.start()
    dma.wait()
    f1v = f1_ref[0].astype(jnp.float32)  # (C, TH, W)
    inv_c = 1.0 / c
    for dy in range(d):
        for dx in range(d):
            win = scratch[:, dy:dy + th, dx:dx + w].astype(jnp.float32)
            out_ref[0, dy * d + dx] = jnp.sum(f1v * win, axis=0) * inv_c


@functools.partial(jax.jit, static_argnames=("radius", "interpret", "tile_h"))
def cost_volume_pallas(f1: jnp.ndarray, f2: jnp.ndarray, radius: int = 4,
                       interpret: bool = False,
                       tile_h: int = 32) -> jnp.ndarray:
    b, h, w, c = f1.shape
    d = 2 * radius + 1
    # rows pad to an 8-SUBLANE multiple before tiling: PWC's coarse pyramid
    # levels have h in {2..14}, and a block sublane dim that is not a
    # multiple of 8 faults Mosaic on real hardware (hardware-validated
    # across every real pyramid shape; invisible in interpret mode)
    h8 = -(-h // 8) * 8
    th = min(tile_h, h8)
    hp = -(-h8 // th) * th  # then to a tile multiple; cropped after
    # the f1/out width ALSO must be lane-aligned: an un-128-multiple W in
    # the block shapes faults Mosaic on real hardware (observed as a TPU
    # worker crash at W=64 — invisible in interpret mode)
    wp = -(-w // 128) * 128
    f1t = jnp.moveaxis(f1, -1, 1)  # (B, C, H, W) channel-major
    f2t = jnp.moveaxis(f2, -1, 1)
    f1t = jnp.pad(f1t, ((0, 0), (0, 0), (0, hp - h), (0, wp - w)))
    # the halo DMA slices f2p along rows only, so its lane (width) dim must
    # stay whole-and-tile-aligned for Mosaic: pad W+2r up to a 128 multiple
    w2 = -(-(wp + 2 * radius) // 128) * 128
    f2p = jnp.pad(f2t, ((0, 0), (0, 0),
                        (radius, radius + hp - h),
                        (radius, w2 - w - radius)))
    out = pl.pallas_call(
        functools.partial(_kernel, th=th, radius=radius, w=wp),
        grid=(b, hp // th),
        in_specs=[
            pl.BlockSpec((1, c, th, wp), lambda bi, ti: (bi, 0, ti, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),  # f2p stays in HBM
        ],
        out_specs=pl.BlockSpec((1, d * d, th, wp),
                               lambda bi, ti: (bi, 0, ti, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, d * d, hp, wp), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((c, th + 2 * radius, w2), f2p.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(f1t, f2p)
    # accumulate in f32, return the input dtype like the XLA twin does
    return jnp.moveaxis(out[:, :, :h, :w], 1, -1).astype(f1.dtype)


def cost_volume(f1: jnp.ndarray, f2: jnp.ndarray, radius: int = 4,
                impl: Optional[str] = None) -> jnp.ndarray:
    """Dispatching wrapper; see package docstring for ``impl`` semantics."""
    from . import interpret_mode, pallas_enabled
    if impl is None:
        impl = "pallas" if pallas_enabled() else "xla"
    if impl == "pallas":
        return cost_volume_pallas(f1, f2, radius, interpret=interpret_mode())
    if impl != "xla":
        raise ValueError(f"cost_volume impl={impl!r}: expected "
                         "'pallas' or 'xla'")
    return cost_volume_xla(f1, f2, radius)
