"""PWC-Net 81-channel cost volume — XLA shifted-window formulation.

Replaces the reference's raw-CUDA correlation kernel (reference
models/pwc/pwc_src/correlation.py:47-115: output channel ``(dy+4)*9+(dx+4)``
is the channel-mean of ``f1 * shift(f2, dy, dx)`` with 4 px zero padding).
The 81 displacement windows are expressed as static slices of a padded
``f2``; XLA fuses the multiply-reduce chain into a handful of kernels.

MEASURED NEGATIVE RESULT — why there is no Pallas kernel here (round-5
keep-or-delete decision, VERDICT r4 #8). Rounds 2-4 carried a Pallas twin
(halo-DMA'd second feature map, channel-major VMEM tiles, f32
accumulation) that was hardware-validated clean on all 15 real PWC pyramid
shapes after lane/sublane padding fixes. Timed on v5e with D2H-fenced
best-of-3 over every (3 geometries x 5 decoder levels) shape in BOTH f32
and bf16 (scripts history; round-5 run, 30 combos): the two
implementations are within noise of each other everywhere — e.g. f32
L2 48x112xC32: pallas 22.9 vs xla 24.3 ms; f32 L6 4x5xC196: 3.6 vs 3.5;
bf16 L4 16x20xC96: 3.4 vs 4.6; bf16 L6 2x2xC196: 4.1 vs 2.9 — with no
shape class where Pallas wins consistently. The op is bandwidth-bound and
XLA's fusion already reaches the same HBM traffic; the per-call floor is
dispatch latency, which a custom kernel cannot remove. Per the pattern
established for the lane-dense corr lookup (kernels/corr_lookup.py
docstring), the tied kernel is DELETED rather than shipped disabled; this
note and the numbers are the record. If the cost volume ever needs to
fuse with the warp that feeds it (the one case XLA cannot express), start
from git history: the kernel lived here until round 5.
"""
from __future__ import annotations

import jax.numpy as jnp


def cost_volume_xla(f1: jnp.ndarray, f2: jnp.ndarray,
                    radius: int = 4) -> jnp.ndarray:
    """(B, H, W, C) x2 -> (B, H, W, (2r+1)^2), channel (dy+r)*(2r+1)+(dx+r)."""
    b, h, w, c = f1.shape
    f2p = jnp.pad(f2, ((0, 0), (radius, radius), (radius, radius), (0, 0)))
    out = []
    for dy in range(-radius, radius + 1):
        for dx in range(-radius, radius + 1):
            win = f2p[:, radius + dy:radius + dy + h,
                      radius + dx:radius + dx + w, :]
            # f32 accumulation regardless of input dtype (bf16 mode: a
            # 196-term bf16 channel sum costs ~1% relative error)
            out.append(jnp.mean(f1 * win, axis=-1, dtype=jnp.float32))
    return jnp.stack(out, axis=-1).astype(f1.dtype)


#: single implementation since round 5 (see module docstring)
cost_volume = cost_volume_xla
