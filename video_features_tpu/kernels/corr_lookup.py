"""RAFT correlation-pyramid lookup, gather-free (one-hot matmul), for TPU.

The reference implements the per-iteration windowed lookup as a
``grid_sample`` bilinear gather over each pyramid level (reference
models/raft/raft_src/corr.py:29-50): 81 taps x 4 bilinear corners per query
pixel — random scalar loads, the classic GPU formulation.

TPU redesign: random gathers are the one access pattern the TPU dislikes, so
the lookup is recast as two dense contractions per level that ride the MXU.
For each query p the 10x10 corner window of ``corr_l[p]`` (10 = 2r+2 corner
rows/cols covering all 81 bilinearly-interpolated taps) equals

    window[p] = Y[p] @ corr_l[p] @ X[p]^T

where ``Y[p]`` (10, Hl) and ``X[p]`` (10, Wl) are one-hot row selectors built
from ``floor``-ed window base coordinates by an iota comparison. Out-of-range
rows have all-zero one-hots, which reproduces the reference's zeros-padding
semantics with no clamping or masking. The four bilinear corner blends then
reduce the (10, 10) corner window to the (9, 9) tap window with scalar
weights per query. Channel order matches the reference quirk (x-offset
slowest; corr.py:37-43 adds its meshgrid "dy" to x).

Two implementations with identical numerics:

  - :func:`corr_lookup_onehot` — pure jnp/XLA (runs anywhere);
  - :func:`corr_lookup_level_pallas` — fused Pallas kernel per level: the
    one-hots are built in VMEM and contracted in-kernel, so the (P, 10, Hl)
    selector tensors never touch HBM.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _blend(window: jnp.ndarray, fx: jnp.ndarray, fy: jnp.ndarray,
           n: int) -> jnp.ndarray:
    """(..., 2r+2, 2r+2) corner windows -> (..., n*n) taps, x-offset slowest.

    window[..., yy, xx] = corr at (iy+yy, ix+xx); fx, fy broadcast over the
    window dims."""
    fx = fx[..., None, None]
    fy = fy[..., None, None]
    v = ((1 - fy) * (1 - fx) * window[..., :n, :n]
         + (1 - fy) * fx * window[..., :n, 1:]
         + fy * (1 - fx) * window[..., 1:, :n]
         + fy * fx * window[..., 1:, 1:])
    # tap channel k = xx*n + yy  (the reference's x-slowest order)
    v = jnp.swapaxes(v, -1, -2)
    return v.reshape(*v.shape[:-2], n * n)


def corr_lookup_onehot(pyramid: Sequence[jnp.ndarray], coords: jnp.ndarray,
                       radius: int = 4) -> jnp.ndarray:
    """Pure-XLA twin of the fused kernel. pyramid: per level (B, P, Hl, Wl);
    coords: (B, H, W, 2) level-0 (x, y). Returns (B, H, W, L*(2r+1)^2)."""
    b, h, w, _ = coords.shape
    p = h * w
    n = 2 * radius + 1
    d10 = jnp.arange(n + 1, dtype=jnp.float32)
    cx = coords[..., 0].reshape(b, p)
    cy = coords[..., 1].reshape(b, p)
    out = []
    for lvl, corr in enumerate(pyramid):
        hl, wl = corr.shape[2], corr.shape[3]
        px0 = cx / (2 ** lvl) - radius
        py0 = cy / (2 ** lvl) - radius
        ix = jnp.floor(px0)
        iy = jnp.floor(py0)
        ycorn = iy[..., None] + d10  # (B, P, 10)
        xcorn = ix[..., None] + d10
        ysel = (ycorn[..., None] ==
                jnp.arange(hl, dtype=jnp.float32)).astype(corr.dtype)
        xsel = (xcorn[..., None] ==
                jnp.arange(wl, dtype=jnp.float32)).astype(corr.dtype)
        t = jnp.einsum("bpyh,bphw->bpyw", ysel, corr)
        window = jnp.einsum("bpyw,bpxw->bpyx", t, xsel)
        out.append(_blend(window, px0 - ix, py0 - iy, n))
    return jnp.concatenate(out, axis=-1).reshape(b, h, w, -1)


def _level_kernel(px0_ref, py0_ref, corr_ref, out_ref, *, radius: int):
    """Block shapes: px0/py0 (1, TP, 1, 1) — pre-expanded on the host so no
    rank-changing relayout happens in-kernel (Mosaic rejects 1D->3D
    reshapes); corr (1, TP, Hl, Wl); out (1, TP, n*n) with tap channel
    k = xx*n + yy (x-offset slowest — the reference's order). The flatten
    happens IN-kernel as a lane concat of the n sublane rows: emitting
    (TP, n, n) and reshaping on the host instead costs a full extra HBM
    pass per level per GRU iteration (measured ~43 ms per 64-pair RAFT
    forward, re-laying (9,9)-minor tiles into dense lanes)."""
    n = 2 * radius + 1
    tp, hl, wl = corr_ref.shape[1:]
    px0 = px0_ref[0]  # (TP, 1, 1)
    py0 = py0_ref[0]
    ix = jnp.floor(px0)
    iy = jnp.floor(py0)
    # Mosaic iota is integer-only; compare in f32 (floor() values are exact)
    d10 = jax.lax.broadcasted_iota(
        jnp.int32, (1, n + 1, 1), 1).astype(jnp.float32)
    ysel = (iy + d10 ==
            jax.lax.broadcasted_iota(
                jnp.int32, (tp, n + 1, hl), 2).astype(jnp.float32)
            ).astype(jnp.float32)
    xsel = (ix + d10 ==
            jax.lax.broadcasted_iota(
                jnp.int32, (tp, n + 1, wl), 2).astype(jnp.float32)
            ).astype(jnp.float32)
    corrv = corr_ref[0].astype(jnp.float32)  # (TP, Hl, Wl)
    # contract x first, then y, so the window lands as [p, xx, yy]
    u = jax.lax.dot_general(                 # (TP, 10x, Hl)
        xsel, corrv, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    window = jax.lax.dot_general(            # (TP, 10x, 10y)
        u, ysel, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    fx = px0 - ix  # (TP, 1, 1), broadcasts over the window dims
    fy = py0 - iy
    blended = ((1 - fx) * (1 - fy) * window[:, :n, :n]
               + fx * (1 - fy) * window[:, 1:, :n]
               + (1 - fx) * fy * window[:, :n, 1:]
               + fx * fy * window[:, 1:, 1:])  # (TP, n_x, n_y)
    for i in range(n):  # static lane-sliced stores: row i -> taps [i*n, i*n+n)
        out_ref[0, :, i * n:(i + 1) * n] = blended[:, i, :]


def align_level(corr: jnp.ndarray) -> jnp.ndarray:
    """Zero-pad a (B, P, Hl, Wl) level so Hl is an 8-sublane and Wl a
    128-lane multiple — the physical tiling Mosaic wants for the kernel's
    VMEM blocks. Zero padding is semantically free for the lookup: a window
    corner landing in the pad region one-hot-selects a zero, which IS the
    reference's zeros-padding rule (corr.py bilinear_sampler zeros mode).

    Callers running the lookup inside a scan (RAFT's 20-iteration GRU)
    should align the loop-invariant pyramid ONCE before the scan — XLA does
    not hoist the pads out of the while body, and paying them per iteration
    measured ~30% of the whole RAFT forward."""
    _, _, hl, wl = corr.shape
    hlp = -(-hl // 8) * 8
    wlp = -(-wl // 128) * 128
    if (hlp, wlp) == (hl, wl):
        return corr
    return jnp.pad(corr, ((0, 0), (0, 0), (0, hlp - hl), (0, wlp - wl)))


def _best_tile(p: int, cap: int) -> int:
    """Largest divisor of p that is <= cap and usable as a block's
    second-minor dim (multiple of 8, or the whole array, per the Pallas TPU
    block rule); a dividing tile means no P padding of the coords and no
    output slice — both of which would otherwise run EVERY scan iteration
    (for RAFT's 224px geometry, P=784 with tile 128 re-padded to 896 and
    re-sliced 20 times per forward). Falls back to an 8-aligned cap (pad
    path) when p has no usable divisor >= 32."""
    for t in range(min(cap, p), 0, -1):
        if p % t == 0 and (t % 8 == 0 or t == p) and t >= 32:
            return t
    return max(8, (min(cap, p) // 8) * 8)


#: VMEM budget for one corr block (leaves room for Mosaic's double
#: buffering + the selector/accumulator tensors). Sizing the tile to fill
#: this matters: with tiles capped at 128 queries the grid ran 448 programs
#: per level and ALL levels cost the same ~25 ms/forward — pure
#: per-program overhead, not compute or DMA.
_VMEM_BLOCK_BYTES = 2 * 1024 * 1024  # corr-block bytes; hardware-probed on
#                                      v5e: 4 MiB blocks compile standalone
#                                      but overflow INSIDE the jitted RAFT
#                                      scan (VMEM is shared with the
#                                      surrounding program), 2 MiB fits
_MAX_TILE_P = 256


@functools.partial(jax.jit,
                   static_argnames=("radius", "interpret", "tile_p"))
def corr_lookup_level_pallas(corr: jnp.ndarray, px0: jnp.ndarray,
                             py0: jnp.ndarray, radius: int = 4,
                             interpret: bool = False,
                             tile_p: Optional[int] = None) -> jnp.ndarray:
    """One pyramid level: corr (B, P, Hl, Wl), window base coords px0/py0
    (B, P) (level coords minus radius). Returns (B, P, (2r+1)^2)."""
    corr = align_level(corr)  # no-op when the caller pre-aligned
    b, p, hl, wl = corr.shape
    n = 2 * radius + 1
    if tile_p is None:
        # as many queries per program as the VMEM budget allows: fewer,
        # bigger programs matter because the coarse levels are
        # per-program-latency-bound, not compute-bound
        # the budget is the hard bound (it is the hardware-probed VMEM
        # envelope); the floor of 8 only keeps the tile a legal sublane
        # multiple for very large level planes (wide inputs)
        tile_p = min(_MAX_TILE_P,
                     max(8, _VMEM_BLOCK_BYTES // (hl * wl * 4)))
    tp = _best_tile(p, tile_p)
    pp = -(-p // tp) * tp
    if pp != p:
        corr = jnp.pad(corr, ((0, 0), (0, pp - p), (0, 0), (0, 0)))
        px0 = jnp.pad(px0, ((0, 0), (0, pp - p)))
        py0 = jnp.pad(py0, ((0, 0), (0, pp - p)))
    coord_spec = pl.BlockSpec((1, tp, 1, 1), lambda bi, pi: (bi, pi, 0, 0),
                              memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_level_kernel, radius=radius),
        grid=(b, pp // tp),
        in_specs=[
            coord_spec,
            coord_spec,
            pl.BlockSpec((1, tp, hl, wl), lambda bi, pi: (bi, pi, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tp, n * n), lambda bi, pi: (bi, pi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, pp, n * n), jnp.float32),
        # grid iterations are independent (each owns its query tile):
        # declaring them parallel lets Mosaic pipeline the block DMAs more
        # aggressively (the coarse levels are DMA-latency-bound)
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(px0.astype(jnp.float32)[..., None, None],
      py0.astype(jnp.float32)[..., None, None], corr)
    return out[:, :p]


def corr_lookup_pallas(pyramid: Sequence[jnp.ndarray], coords: jnp.ndarray,
                       radius: int = 4,
                       interpret: bool = False) -> jnp.ndarray:
    """Full 4-level lookup via the fused per-level kernel; same signature
    and channel layout as :func:`corr_lookup_onehot`.

    The pair-batch dim folds into the query dim before the kernel: the
    lookup is purely per-query, so (B, P) queries are just B*P queries —
    one flat grid instead of a (B, P/tile) one. The coarse levels are
    per-program-latency-bound (tiny DMAs), so halving the program count
    measurably shortens the RAFT scan."""
    b, h, w, _ = coords.shape
    p = h * w
    cx = coords[..., 0].reshape(1, b * p)
    cy = coords[..., 1].reshape(1, b * p)
    out: List[jnp.ndarray] = []
    for lvl, corr in enumerate(pyramid):
        px0 = cx / (2 ** lvl) - radius
        py0 = cy / (2 ** lvl) - radius
        flat = corr.reshape(1, b * p, *corr.shape[2:])
        out.append(corr_lookup_level_pallas(flat, px0, py0, radius,
                                            interpret=interpret))
    return jnp.concatenate(out, axis=-1).reshape(b, h, w, -1)
