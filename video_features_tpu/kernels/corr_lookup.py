"""RAFT correlation-pyramid lookup, gather-free (one-hot matmul), for TPU.

The reference implements the per-iteration windowed lookup as a
``grid_sample`` bilinear gather over each pyramid level (reference
models/raft/raft_src/corr.py:29-50): 81 taps x 4 bilinear corners per query
pixel — random scalar loads, the classic GPU formulation.

TPU redesign: random gathers are the one access pattern the TPU dislikes, so
the lookup is recast as two dense contractions per level that ride the MXU.
For each query p the 10x10 corner window of ``corr_l[p]`` (10 = 2r+2 corner
rows/cols covering all 81 bilinearly-interpolated taps) equals

    window[p] = Y[p] @ corr_l[p] @ X[p]^T

where ``Y[p]`` (10, Hl) and ``X[p]`` (10, Wl) are one-hot row selectors built
from ``floor``-ed window base coordinates by an iota comparison. Out-of-range
rows have all-zero one-hots, which reproduces the reference's zeros-padding
semantics with no clamping or masking. The four bilinear corner blends then
reduce the (10, 10) corner window to the (9, 9) tap window with scalar
weights per query. Channel order matches the reference quirk (x-offset
slowest; corr.py:37-43 adds its meshgrid "dy" to x).

Implementations with identical numerics:

  - :func:`corr_lookup_onehot` — pure jnp/XLA (runs anywhere);
  - :func:`corr_lookup_level_pallas` / :func:`corr_lookup_pallas` — fused
    Pallas kernel per level over lane-PADDED planes: the one-hots are built
    in VMEM and contracted in-kernel, so the (P, 10, Hl) selector tensors
    never touch HBM. **TPU default** (fastest measured).
  - :func:`corr_lookup_packed` — ONE fused kernel for ALL levels over a
    lane-DENSE repacked pyramid (``VFT_CORR_LOOKUP=packed``). Kept as a
    measured negative result — see below.

Round-3 negative result (recorded so nobody re-litigates it from theory):
the per-level default lane-pads narrow planes (28 -> 128 at RAFT-224's
finest level), so round 2 hypothesized a ~4.6x useless-DMA tax as the
throughput floor. Round 3 built the lane-dense alternative — J=4 image
rows per 128-lane line, all levels' row-groups fused into one (Q, 1408)
plane, 5.8x fewer bytes per GRU iteration (282 MB vs 1.64 GB), one kernel
launch instead of four — and measured the flagship I3D RGB+Flow bench on
v5e across six structural variants (fused 1-call Pallas, per-level 4-call
Pallas, pure-XLA einsum form, tile sweeps 32..512, empty-body DMA floor,
select-vs-dot row routing): EVERY dense variant landed at 3.47-3.60
stacks/s vs 3.95 for the padded default, same-day A/B. An empty kernel
body over the same blocks cost the same as the full kernel. Conclusion:
the lookup is bound by per-query selection work (mask/select VPU ops +
grid machinery), NOT by HBM bytes — the padded layout wins because its
selectors are plain 2-compare iota one-hots, while any dense packing must
additionally route J-packed rows (G-way selects or an extra mask pass),
which costs more than the bytes it saves.
"""
from __future__ import annotations

import functools
import os
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams; accept both so
# the kernels (and their CPU interpret-mode tests) run across jax versions
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def _blend(window: jnp.ndarray, fx: jnp.ndarray, fy: jnp.ndarray,
           n: int) -> jnp.ndarray:
    """(..., 2r+2, 2r+2) corner windows -> (..., n*n) taps, x-offset slowest.

    window[..., yy, xx] = corr at (iy+yy, ix+xx); fx, fy broadcast over the
    window dims."""
    fx = fx[..., None, None]
    fy = fy[..., None, None]
    v = ((1 - fy) * (1 - fx) * window[..., :n, :n]
         + (1 - fy) * fx * window[..., :n, 1:]
         + fy * (1 - fx) * window[..., 1:, :n]
         + fy * fx * window[..., 1:, 1:])
    # tap channel k = xx*n + yy  (the reference's x-slowest order)
    v = jnp.swapaxes(v, -1, -2)
    return v.reshape(*v.shape[:-2], n * n)


def corr_lookup_onehot(pyramid: Sequence[jnp.ndarray], coords: jnp.ndarray,
                       radius: int = 4) -> jnp.ndarray:
    """Pure-XLA twin of the fused kernels. pyramid: per level (B, P, Hl, Wl);
    coords: (B, H, W, 2) level-0 (x, y). Returns (B, H, W, L*(2r+1)^2)."""
    b, h, w, _ = coords.shape
    p = h * w
    n = 2 * radius + 1
    d10 = jnp.arange(n + 1, dtype=jnp.float32)
    cx = coords[..., 0].reshape(b, p)
    cy = coords[..., 1].reshape(b, p)
    out = []
    for lvl, corr in enumerate(pyramid):
        hl, wl = corr.shape[2], corr.shape[3]
        px0 = cx / (2 ** lvl) - radius
        py0 = cy / (2 ** lvl) - radius
        ix = jnp.floor(px0)
        iy = jnp.floor(py0)
        ycorn = iy[..., None] + d10  # (B, P, 10)
        xcorn = ix[..., None] + d10
        ysel = (ycorn[..., None] ==
                jnp.arange(hl, dtype=jnp.float32)).astype(corr.dtype)
        xsel = (xcorn[..., None] ==
                jnp.arange(wl, dtype=jnp.float32)).astype(corr.dtype)
        t = jnp.einsum("bpyh,bphw->bpyw", ysel, corr)
        window = jnp.einsum("bpyw,bpxw->bpyx", t, xsel)
        out.append(_blend(window, px0 - ix, py0 - iy, n))
    return jnp.concatenate(out, axis=-1).reshape(b, h, w, -1)


# ---- per-level fused kernel over lane-padded planes (TPU default) --------

def _level_kernel(px0_ref, py0_ref, corr_ref, out_ref, *, radius: int):
    """Block shapes: px0/py0 (1, TP, 1, 1) — pre-expanded on the host so no
    rank-changing relayout happens in-kernel (Mosaic rejects 1D->3D
    reshapes); corr (1, TP, Hl, Wl); out (1, TP, n*n) with tap channel
    k = xx*n + yy (x-offset slowest — the reference's order). The flatten
    happens IN-kernel as a lane concat of the n sublane rows: emitting
    (TP, n, n) and reshaping on the host instead costs a full extra HBM
    pass per level per GRU iteration (measured ~43 ms per 64-pair RAFT
    forward, re-laying (9,9)-minor tiles into dense lanes)."""
    n = 2 * radius + 1
    tp, hl, wl = corr_ref.shape[1:]
    px0 = px0_ref[0]  # (TP, 1, 1)
    py0 = py0_ref[0]
    ix = jnp.floor(px0)
    iy = jnp.floor(py0)
    # Mosaic iota is integer-only; compare in f32 (floor() values are exact)
    d10 = jax.lax.broadcasted_iota(
        jnp.int32, (1, n + 1, 1), 1).astype(jnp.float32)
    ysel = (iy + d10 ==
            jax.lax.broadcasted_iota(
                jnp.int32, (tp, n + 1, hl), 2).astype(jnp.float32)
            ).astype(jnp.float32)
    xsel = (ix + d10 ==
            jax.lax.broadcasted_iota(
                jnp.int32, (tp, n + 1, wl), 2).astype(jnp.float32)
            ).astype(jnp.float32)
    corrv = corr_ref[0].astype(jnp.float32)  # (TP, Hl, Wl)
    # contract x first, then y, so the window lands as [p, xx, yy]
    u = jax.lax.dot_general(                 # (TP, 10x, Hl)
        xsel, corrv, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    window = jax.lax.dot_general(            # (TP, 10x, 10y)
        u, ysel, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    fx = px0 - ix  # (TP, 1, 1), broadcasts over the window dims
    fy = py0 - iy
    blended = ((1 - fx) * (1 - fy) * window[:, :n, :n]
               + fx * (1 - fy) * window[:, 1:, :n]
               + (1 - fx) * fy * window[:, :n, 1:]
               + fx * fy * window[:, 1:, 1:])  # (TP, n_x, n_y)
    for i in range(n):  # static lane-sliced stores: row i -> taps [i*n, i*n+n)
        out_ref[0, :, i * n:(i + 1) * n] = blended[:, i, :]


def align_level(corr: jnp.ndarray) -> jnp.ndarray:
    """Zero-pad a (B, P, Hl, Wl) level so Hl is an 8-sublane and Wl a
    128-lane multiple — the physical tiling Mosaic wants for the kernel's
    VMEM blocks. Zero padding is semantically free for the lookup: a window
    corner landing in the pad region one-hot-selects a zero, which IS the
    reference's zeros-padding rule (corr.py bilinear_sampler zeros mode).

    Callers running the lookup inside a scan (RAFT's 20-iteration GRU)
    should align the loop-invariant pyramid ONCE before the scan — XLA does
    not hoist the pads out of the while body, and paying them per iteration
    measured ~30% of the whole RAFT forward."""
    _, _, hl, wl = corr.shape
    hlp = -(-hl // 8) * 8
    wlp = -(-wl // 128) * 128
    if (hlp, wlp) == (hl, wl):
        return corr
    return jnp.pad(corr, ((0, 0), (0, 0), (0, hlp - hl), (0, wlp - wl)))


def _best_tile(p: int, cap: int) -> int:
    """Largest divisor of p that is <= cap and usable as a block's
    second-minor dim (multiple of 8, or the whole array, per the Pallas TPU
    block rule); a dividing tile means no P padding of the coords and no
    output slice — both of which would otherwise run EVERY scan iteration
    (for RAFT's 224px geometry, P=784 with tile 128 re-padded to 896 and
    re-sliced 20 times per forward). Falls back to an 8-aligned cap (pad
    path) when p has no usable divisor >= 32."""
    for t in range(min(cap, p), 0, -1):
        if p % t == 0 and (t % 8 == 0 or t == p) and t >= 32:
            return t
    return max(8, (min(cap, p) // 8) * 8)


#: VMEM budget for one corr block (leaves room for Mosaic's double
#: buffering + the selector/accumulator tensors). Sizing the tile to fill
#: this matters: with tiles capped at 128 queries the grid ran 448 programs
#: per level and ALL levels cost the same ~25 ms/forward — pure
#: per-program overhead, not compute or DMA.
_VMEM_BLOCK_BYTES = 2 * 1024 * 1024  # corr-block bytes; hardware-probed on
#                                      v5e: 4 MiB blocks compile standalone
#                                      but overflow INSIDE the jitted RAFT
#                                      scan (VMEM is shared with the
#                                      surrounding program), 2 MiB fits
_MAX_TILE_P = 256


def pallas_lookup_supported(pyramid: Sequence[jnp.ndarray]) -> bool:
    """Whether the per-level kernel can tile these planes within the probed
    VMEM envelope: even an 8-query tile must fit the budget. False only for
    extreme inputs (~>5800 px on a side at RAFT's /8 feature stride) where
    ``_VMEM_BLOCK_BYTES // plane_bytes`` underflows and the 8-query floor
    would demand a >16 MiB block. Callers fall back to
    :func:`corr_lookup_onehot`, the tiling-free twin."""
    for c in pyramid:
        hl, wl = c.shape[2], c.shape[3]
        plane = (-(-hl // 8) * 8) * (-(-wl // 128) * 128) * 4
        if 8 * plane > _VMEM_BLOCK_BYTES:
            return False
    return True


@functools.partial(jax.jit,
                   static_argnames=("radius", "interpret", "tile_p"))
def corr_lookup_level_pallas(corr: jnp.ndarray, px0: jnp.ndarray,
                             py0: jnp.ndarray, radius: int = 4,
                             interpret: bool = False,
                             tile_p: Optional[int] = None) -> jnp.ndarray:
    """One pyramid level: corr (B, P, Hl, Wl), window base coords px0/py0
    (B, P) (level coords minus radius). Returns (B, P, (2r+1)^2)."""
    corr = align_level(corr)  # no-op when the caller pre-aligned
    b, p, hl, wl = corr.shape
    n = 2 * radius + 1
    if tile_p is None:
        # as many queries per program as the VMEM budget allows: fewer,
        # bigger programs matter because the coarse levels are
        # per-program-latency-bound, not compute-bound. The floor of 8
        # keeps the tile a legal sublane multiple; oversized planes where
        # even that floor would bust the budget are refused loudly
        # (pallas_lookup_supported is the caller-facing check).
        if 8 * hl * wl * 4 > _VMEM_BLOCK_BYTES:
            raise ValueError(
                f"corr plane ({hl}x{wl}) too large for any legal VMEM "
                "tile; use corr_lookup_onehot (pallas_lookup_supported "
                "gates this dispatch)")
        tile_p = min(_MAX_TILE_P,
                     max(8, _VMEM_BLOCK_BYTES // (hl * wl * 4)))
    tp = _best_tile(p, tile_p)
    pp = -(-p // tp) * tp
    if pp != p:
        corr = jnp.pad(corr, ((0, 0), (0, pp - p), (0, 0), (0, 0)))
        px0 = jnp.pad(px0, ((0, 0), (0, pp - p)))
        py0 = jnp.pad(py0, ((0, 0), (0, pp - p)))
    coord_spec = pl.BlockSpec((1, tp, 1, 1), lambda bi, pi: (bi, pi, 0, 0),
                              memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_level_kernel, radius=radius),
        grid=(b, pp // tp),
        in_specs=[
            coord_spec,
            coord_spec,
            pl.BlockSpec((1, tp, hl, wl), lambda bi, pi: (bi, pi, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tp, n * n), lambda bi, pi: (bi, pi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, pp, n * n), jnp.float32),
        # grid iterations are independent (each owns its query tile):
        # declaring them parallel lets Mosaic pipeline the block DMAs more
        # aggressively (the coarse levels are DMA-latency-bound)
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(px0.astype(jnp.float32)[..., None, None],
      py0.astype(jnp.float32)[..., None, None], corr)
    return out[:, :p]


def corr_lookup_pallas(pyramid: Sequence[jnp.ndarray], coords: jnp.ndarray,
                       radius: int = 4,
                       interpret: bool = False) -> jnp.ndarray:
    """Full 4-level lookup via the fused per-level kernel; same signature
    and channel layout as :func:`corr_lookup_onehot`.

    The pair-batch dim folds into the query dim before the kernel: the
    lookup is purely per-query, so (B, P) queries are just B*P queries —
    one flat grid instead of a (B, P/tile) one. The coarse levels are
    per-program-latency-bound (tiny DMAs), so halving the program count
    measurably shortens the RAFT scan."""
    b, h, w, _ = coords.shape
    p = h * w
    cx = coords[..., 0].reshape(1, b * p)
    cy = coords[..., 1].reshape(1, b * p)
    out: List[jnp.ndarray] = []
    for lvl, corr in enumerate(pyramid):
        px0 = cx / (2 ** lvl) - radius
        py0 = cy / (2 ** lvl) - radius
        flat = corr.reshape(1, b * p, *corr.shape[2:])
        out.append(corr_lookup_level_pallas(flat, px0, py0, radius,
                                            interpret=interpret))
    return jnp.concatenate(out, axis=-1).reshape(b, h, w, -1)


# ---- fused lookup + convc1 projection (round-4 TPU default) --------------
#
# Round-4 profiling (scripts/bench_i3d_variants.py --trace): the four
# per-level lookup kernels cost ~100 ms of a 215 ms I3D RGB+Flow step and
# ALL levels cost the same ~25 ms despite 4-64x different plane sizes —
# the binding cost is per-query work on the 128-lane-padded width
# (selector build + blend + 9-lane-wide stores), which is level-size
# independent. Downstream, the (B, H, W, 324) lookup output is a relayout
# boundary XLA cannot see through (~17 ms/step of reshape passes feeding
# the motion encoder's convc1, models/raft.py:177-180).
#
# This kernel removes both ends at once:
#   - the bilinear blend folds INTO the selectors (9 weighted rows instead
#     of 10 one-hot rows + a 4-corner blend), and
#   - the motion encoder's convc1 (a 1x1 conv, i.e. a (324, 256) matmul)
#     folds INTO the kernel as per-level (81, 256) projections of the tap
#     window, accumulated across levels in VMEM — so the kernel emits the
#     post-conv (TP, 256) activation (dense, tile-aligned stores) and the
#     324-channel intermediate never exists.
#
# All four levels ride ONE kernel over a sublane-stacked pyramid plane
# (one contiguous block DMA per grid step; level planes are static sublane
# slices). The projection weight is a constant-index block, so Mosaic
# keeps it resident across grid steps.


class ProjMeta(NamedTuple):
    """Static geometry of one level inside the sublane-stacked plane."""
    hlp: int  # lane-padded sublane rows of this level
    off: int  # sublane offset of this level in the stacked plane


def stack_aligned_pyramid(pyramid: Sequence[jnp.ndarray]
                          ) -> Tuple[jnp.ndarray, Tuple[ProjMeta, ...]]:
    """Align every (B, P, Hl, Wl) level (zero pad: Hl -> 8-multiple, Wl ->
    128-multiple — the zeros ARE the reference's out-of-range rule, see
    :func:`align_level`), pad all levels to the widest lane width, and
    concatenate along sublanes into ONE (B, P, Hsum, Wp) plane. Hoist this
    OUT of the GRU scan (loop-invariant)."""
    aligned = [align_level(c) for c in pyramid]
    wp = max(c.shape[3] for c in aligned)
    aligned = [c if c.shape[3] == wp else
               jnp.pad(c, ((0, 0), (0, 0), (0, 0), (0, wp - c.shape[3])))
               for c in aligned]
    metas = []
    off = 0
    for c in aligned:
        metas.append(ProjMeta(c.shape[2], off))
        off += c.shape[2]
    return jnp.concatenate(aligned, axis=2), tuple(metas)


def stacked_plane_cells(h8: int, w8: int, levels: int = 4) -> int:
    """Per-query cell count (Hsum * Wp) of the plane
    :func:`stack_aligned_pyramid` builds for a /8 feature grid of
    (h8, w8) — each level 8-sublane/128-lane aligned, floor-halved with
    the odd-drop rule (build_corr_pyramid's torch avg_pool semantics).
    Shared by the VMEM support gate here and the flow-stream HBM budget
    (extractors/i3d_flow.py _stacks_per_forward) so the geometry math has
    exactly one owner."""
    hsum, wp = 0, 128
    for _ in range(levels):
        hsum += -(-h8 // 8) * 8
        wp = max(wp, -(-w8 // 128) * 128)
        h8, w8 = h8 // 2, w8 // 2
    return hsum * wp


def proj_lookup_supported(pyramid: Sequence[jnp.ndarray]) -> bool:
    """Whether the fused projection kernel can tile these planes: one
    stacked-plane block at the 8-query tile floor must fit the probed VMEM
    budget (same envelope as the per-level kernel)."""
    h0, w0 = pyramid[0].shape[2], pyramid[0].shape[3]
    cells = stacked_plane_cells(h0, w0, levels=len(pyramid))
    return 8 * cells * 4 <= _VMEM_BLOCK_BYTES


def _proj_kernel(cx_ref, cy_ref, corr_ref, w_ref, b_ref, out_ref, taps_ref,
                 *, radius: int, metas: Tuple[ProjMeta, ...]):
    """One grid step: TP queries x ALL levels -> relu(lookup @ W + b).

    Block shapes: cx/cy (1, TP, 1, 1) pre-expanded on the host; corr
    (1, TP, Hsum, Wp) — the stacked plane; w (L*n*n, C) with row order
    matching the lookup channel order (per level, tap k = xx*n + yy,
    x-offset slowest — the reference's quirk); b (1, C); out (1, TP, C);
    taps_ref a (TP, L*n*n) VMEM scratch. The blended windows land in
    scratch via lane-sliced stores (never HBM), then ONE rank-2
    (TP, L*n*n) @ (L*n*n, C) matmul projects them — Mosaic's tpu.matmul
    takes exactly one contracting dim and position-matched batch dims
    only, so the multi-dim-contraction and batched forms of this
    projection are unavailable (both probed on hardware)."""
    n = 2 * radius + 1
    tp, hsum, wp = corr_ref.shape[1:]
    cx = cx_ref[0]  # (TP, 1, 1)
    cy = cy_ref[0]
    corr_all = corr_ref[0].astype(jnp.float32)  # (TP, Hsum, Wp)
    d9 = jax.lax.broadcasted_iota(
        jnp.int32, (1, n, 1), 1).astype(jnp.float32)
    for lvl, m in enumerate(metas):
        if m.hlp == 0:
            # degenerate level (tiny inputs pool to 0x0): every tap reads
            # the zeros-padding region and contributes nothing to the
            # projection; zero the scratch lanes it owns
            base = lvl * n * n
            taps_ref[:, base:base + n * n] = jnp.zeros((tp, n * n),
                                                       jnp.float32)
            continue
        px0 = cx * (1.0 / (1 << lvl)) - radius
        py0 = cy * (1.0 / (1 << lvl)) - radius
        # bilinear selectors DIRECTLY as triangular hats: the weight of
        # plane column w for tap xx is relu(1 - |w - (px0 + xx)|) — exactly
        # (1-fx) at the left corner, fx at the right, 0 elsewhere, and 0
        # for every out-of-plane tap (no lane in range), which is the
        # reference's zeros-padding rule. Half the VPU work of building
        # (n+1)-row corner one-hots and blending 4 corners.
        yl = jax.lax.broadcasted_iota(
            jnp.int32, (tp, n, m.hlp), 2).astype(jnp.float32)
        xl = jax.lax.broadcasted_iota(
            jnp.int32, (tp, n, wp), 2).astype(jnp.float32)
        yw = jnp.maximum(1.0 - jnp.abs(yl - py0 - d9), 0.0)  # (TP, 9, Hlp)
        xw = jnp.maximum(1.0 - jnp.abs(xl - px0 - d9), 0.0)  # (TP, 9, Wp)
        level = jax.lax.slice_in_dim(corr_all, m.off, m.off + m.hlp, axis=1)
        u = jax.lax.dot_general(       # (TP, 9x, Hlp)
            xw, level, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        taps = jax.lax.dot_general(    # (TP, 9x, 9y) — blended tap window
            u, yw, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        base = lvl * n * n
        for i in range(n):  # lane-sliced stores into VMEM scratch
            taps_ref[:, base + i * n:base + (i + 1) * n] = taps[:, i, :]
    acc = jax.lax.dot_general(  # ONE rank-2 projection matmul off scratch
        taps_ref[...], w_ref[...].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    out_ref[0] = jnp.maximum(acc + b_ref[...], 0.0)


@functools.partial(jax.jit, static_argnames=("metas", "radius", "interpret",
                                             "tile_p"))
def _corr_lookup_proj_flat(stacked: jnp.ndarray,
                           metas: Tuple[ProjMeta, ...],
                           cx: jnp.ndarray, cy: jnp.ndarray,
                           weight: jnp.ndarray, bias: jnp.ndarray,
                           radius: int = 4, interpret: bool = False,
                           tile_p: Optional[int] = None) -> jnp.ndarray:
    """Flat-query fused lookup+projection: stacked (1, Q, Hsum, Wp) plane,
    cx/cy (1, Q) level-0 centers, weight (L*(2r+1)^2, C), bias (C,).
    Returns (1, Q, C) = relu(lookup @ weight + bias)."""
    _, q, hsum, wp = stacked.shape
    n = 2 * radius + 1
    c_out = weight.shape[1]
    plane = hsum * wp * 4
    if 8 * plane > _VMEM_BLOCK_BYTES:
        raise ValueError(
            f"stacked corr plane ({hsum}x{wp}) too large for any legal "
            "VMEM tile; use the unfused path (proj_lookup_supported "
            "gates this dispatch)")
    if tile_p is None:
        tile_p = min(_MAX_TILE_P, max(8, _VMEM_BLOCK_BYTES // plane))
    tp = _best_tile(q, tile_p)
    qq = -(-q // tp) * tp
    if qq != q:
        stacked = jnp.pad(stacked, ((0, 0), (0, qq - q), (0, 0), (0, 0)))
        cx = jnp.pad(cx, ((0, 0), (0, qq - q)))
        cy = jnp.pad(cy, ((0, 0), (0, qq - q)))
    coord_spec = pl.BlockSpec((1, tp, 1, 1), lambda qi: (0, qi, 0, 0),
                              memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_proj_kernel, radius=radius, metas=metas),
        grid=(qq // tp,),
        in_specs=[
            coord_spec, coord_spec,
            pl.BlockSpec((1, tp, hsum, wp), lambda qi: (0, qi, 0, 0),
                         memory_space=pltpu.VMEM),
            # constant index maps: Mosaic keeps these blocks resident
            # across grid steps (no per-program re-DMA)
            pl.BlockSpec((len(metas) * n * n, c_out), lambda qi: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c_out), lambda qi: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tp, c_out), lambda qi: (0, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, qq, c_out), jnp.float32),
        scratch_shapes=[pltpu.VMEM((tp, len(metas) * n * n), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(cx.astype(jnp.float32)[..., None, None],
      cy.astype(jnp.float32)[..., None, None], stacked,
      weight, bias.reshape(1, c_out))
    return out[:, :q]


def corr_lookup_proj(stacked: jnp.ndarray, metas: Tuple[ProjMeta, ...],
                     coords: jnp.ndarray, weight: jnp.ndarray,
                     bias: jnp.ndarray, radius: int = 4,
                     interpret: bool = False) -> jnp.ndarray:
    """Fused windowed lookup + convc1 projection + bias + relu over a
    pre-stacked pyramid (see :func:`stack_aligned_pyramid`).

    coords: (B, H, W, 2) level-0 (x, y); weight (L*(2r+1)^2, C) rows in
    the lookup's channel order; bias (C,). Returns (B, H, W, C) float32 =
    ``relu(corr_lookup(pyramid, coords) @ weight + bias)`` with the pair
    batch folded into the query dim (the lookup is purely per-query).

    ``VFT_PROJ_TILE_P`` (perf probes) overrides the VMEM-derived query
    tile. Read HERE, outside the jit, and passed as a static argument —
    an env read inside the jitted body would be frozen into the first
    trace and silently ignored for every later value."""
    b, h, w, _ = coords.shape
    cx = coords[..., 0].reshape(1, b * h * w)
    cy = coords[..., 1].reshape(1, b * h * w)
    flat = stacked.reshape(1, b * h * w, *stacked.shape[2:])
    env = os.environ.get("VFT_PROJ_TILE_P", "").strip()
    out = _corr_lookup_proj_flat(flat, metas, cx, cy, weight, bias,
                                 radius, interpret,
                                 tile_p=int(env) if env else None)
    return out.reshape(b, h, w, -1)


def corr_lookup_proj_ref(pyramid: Sequence[jnp.ndarray], coords: jnp.ndarray,
                         weight: jnp.ndarray, bias: jnp.ndarray,
                         radius: int = 4) -> jnp.ndarray:
    """Pure-XLA reference of the fused projection (tests): the unfused
    composition relu(onehot_lookup @ W + b)."""
    corr = corr_lookup_onehot(pyramid, coords, radius)
    return jax.nn.relu(jnp.einsum("bhwk,kc->bhwc", corr, weight) + bias)


# ---- lane-dense packed pyramid (opt-in: VFT_CORR_LOOKUP=packed) ----------
#
# Measured ~10% SLOWER end-to-end than the per-level default on v5e (see
# the module docstring's negative-result record) — retained because the
# layout is the textbook fix for the padding tax and the measurement that
# refutes it should stay reproducible.

class LevelMeta(NamedTuple):
    """Static packing geometry of one pyramid level."""
    hl: int   # image rows
    wl: int   # image cols
    j: int    # rows packed per 128-lane line
    g: int    # row-groups (ceil(hl / j))
    k: int    # packed lane width (j*wl rounded up to 128)
    off: int = 0  # lane offset of this level in the fused (Q, K_total) plane


def _plan_level(hl: int, wl: int) -> LevelMeta:
    if hl == 0 or wl == 0:
        # degenerate level (tiny inputs pool to nothing): every tap reads
        # the zeros-padding region, so a placeholder one-lane-line plane of
        # zeros reproduces the gather semantics exactly
        return LevelMeta(0, 0, 1, 1, 128)
    j = min(hl, max(1, 128 // wl))
    g = -(-hl // j)
    k = -(-(j * wl) // 128) * 128
    return LevelMeta(hl, wl, j, g, k)


def pack_level(corr: jnp.ndarray) -> Tuple[jnp.ndarray, LevelMeta]:
    """(B, P, Hl, Wl) level -> ((B*P, G*K) lane-dense row-group planes,
    meta). Row-group g of query q lives in lanes [g*K, g*K + K).

    Zero fill everywhere the packed layout exceeds the image plane (phantom
    rows of the last group, lane tail beyond J*Wl): a window corner landing
    there selects a zero, which IS the reference's zeros-padding rule
    (corr.py bilinear_sampler zeros mode)."""
    b, p, hl, wl = corr.shape
    m = _plan_level(hl, wl)
    if m.hl == 0:
        return jnp.zeros((b * p, m.g * m.k), corr.dtype), m
    x = corr.reshape(b * p, hl, wl)
    if m.g * m.j != hl:
        x = jnp.pad(x, ((0, 0), (0, m.g * m.j - hl), (0, 0)))
    x = x.reshape(b * p, m.g, m.j * wl)
    if m.k != m.j * wl:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, m.k - m.j * wl)))
    return x.reshape(b * p, m.g * m.k), m


def fused_lookup_supported(pyramid: Sequence[jnp.ndarray]) -> bool:
    """Whether the packed fused kernel can tile these levels within the
    probed VMEM envelope (one query's packed planes must fit
    _VMEM_BLOCK_BYTES) with a sane unroll (the G-way select-accumulate is
    statically unrolled; G grows with input size — 28 groups at 448 px —
    and past ~16 the routing chain is hopeless anyway, see the module
    docstring's negative result). Callers fall back to
    corr_lookup_onehot."""
    metas = [_plan_level(c.shape[2], c.shape[3]) for c in pyramid]
    per_q = sum(m.g * m.k for m in metas) * 4
    return per_q <= _VMEM_BLOCK_BYTES and max(m.g for m in metas) <= 16


def pack_pyramid(pyramid: Sequence[jnp.ndarray]
                 ) -> Tuple[jnp.ndarray, Tuple[LevelMeta, ...]]:
    """All levels -> ONE (B*P, K_total) lane-dense plane + per-level metas
    carrying each level's lane offset (one contiguous block DMA per grid
    step). Hoist this OUT of the GRU scan — XLA does not hoist relayouts
    out of while bodies."""
    packed, metas = zip(*(pack_level(c) for c in pyramid))
    offs = []
    off = 0
    for m in metas:
        offs.append(m._replace(off=off))
        off += m.g * m.k
    return jnp.concatenate(packed, axis=1), tuple(offs)


def _packed_kernel(cx_ref, cy_ref, corr_ref, out_ref, *, radius: int,
                   metas: Tuple[LevelMeta, ...]):
    """One grid step: TILE_Q queries x ALL pyramid levels.

    Block shapes: cx/cy (TQ, 1, 1); corr (TQ, K_total) — ONE contiguous
    lane-dense plane carrying every level's row-groups (level l group g at
    lanes [off_l + g*K_l, ...), selected in-kernel by static lane slices,
    free at the 128-lane tile granularity); out (TQ, L*n*n) with per-level
    tap channel k = xx*n + yy (x-offset slowest — the reference's order),
    levels concatenated in pyramid order."""
    n = 2 * radius + 1
    cx = cx_ref[...]  # (TQ, 1, 1)
    cy = cy_ref[...]
    corr_all = corr_ref[...].astype(jnp.float32)  # (TQ, K_total)
    tq = corr_all.shape[0]
    d10 = jax.lax.broadcasted_iota(
        jnp.int32, (1, n + 1, 1), 1).astype(jnp.float32)
    for lvl, m in enumerate(metas):
        if m.hl == 0:  # degenerate level: all taps hit the zeros padding
            zeros = jnp.zeros((tq, n), jnp.float32)
            for i in range(n):
                out_ref[:, (lvl * n + i) * n:(lvl * n + i + 1) * n] = zeros
            continue
        px0 = cx * (1.0 / (1 << lvl)) - radius
        py0 = cy * (1.0 / (1 << lvl)) - radius
        ix = jnp.floor(px0)
        iy = jnp.floor(py0)
        r = iy + d10   # (TQ, 10, 1) window-corner row indices
        # lane coordinate -> (sub-row j, column w); Mosaic iota is
        # integer-only, so the decomposition runs in f32 (exact: all values
        # are small integers, and IEEE division of exact quotients is exact)
        kf = jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, m.k), 2).astype(jnp.float32)
        j_of_k = jnp.floor(kf / m.wl)
        w_of_k = kf - m.wl * j_of_k

        def plane(g):
            # static lane slice (free at 128-lane tile granularity), then
            # a rank-expand so the group plane broadcasts over the 10 rows.
            # Explicit lax ops: jnp's mixed None/slice indexing can lower
            # through gather, which Mosaic rejects.
            sl = jax.lax.slice_in_dim(corr_all, m.off + g * m.k,
                                      m.off + (g + 1) * m.k, axis=1)
            return jax.lax.expand_dims(sl, (1,))  # (TQ, 1, K)

        if m.g == 1:
            # whole plane in one lane line set: row index IS the sub-row.
            # No modulo here — a negative r must match nothing, not wrap.
            jr = r
            u = plane(0)  # broadcasts over the 10 rows
        else:
            g_of_r = jnp.floor(r / m.j)
            jr = r - m.j * g_of_r
            # G-way select-accumulate picks each corner row's group plane
            # (G <= 8; out-of-range groups match nothing -> zero row, the
            # zeros-padding rule again). This routing is the measured cost
            # that eats the DMA savings — see the module docstring.
            u = jnp.zeros((tq, n + 1, m.k), jnp.float32)
            for g in range(m.g):
                u = u + jnp.where(g_of_r == g, plane(g), 0.0)
        v = jnp.where(j_of_k == jr, u, 0.0)          # (TQ, 10, K)
        xb = (w_of_k == ix + d10).astype(jnp.float32)  # (TQ, 10, K)
        window = jax.lax.dot_general(                 # (TQ, 10x, 10y)
            xb, v, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        fx = px0 - ix  # (TQ, 1, 1), broadcasts over the window dims
        fy = py0 - iy
        blended = ((1 - fx) * (1 - fy) * window[:, :n, :n]
                   + fx * (1 - fy) * window[:, 1:, :n]
                   + (1 - fx) * fy * window[:, :n, 1:]
                   + fx * fy * window[:, 1:, 1:])  # (TQ, n_x, n_y)
        base = lvl * n * n
        for i in range(n):  # static lane-sliced stores (Mosaic rejects
            # 9-wide lane concats but accepts sliced stores)
            out_ref[:, base + i * n:base + (i + 1) * n] = blended[:, i, :]


#: Scoped-VMEM target for one packed grid step. v5e's scoped limit is
#: 16 MiB (hardware-observed OOM reports say so exactly); 12 MiB leaves
#: margin for the surrounding program, which matters INSIDE the RAFT GRU
#: scan — the same kernel allocates more scoped VMEM in a while body than
#: standalone (measured 20.16 MiB in-scan at TQ=256 vs compiling clean
#: standalone).
_VMEM_TARGET = 12 * 1024 * 1024
_MAX_TILE_Q = 512


@functools.partial(jax.jit, static_argnames=("metas", "radius", "interpret",
                                             "tile_q", "out_dtype"))
def _corr_lookup_packed_flat(packed: jnp.ndarray,
                             metas: Tuple[LevelMeta, ...],
                             cx: jnp.ndarray, cy: jnp.ndarray,
                             radius: int = 4, interpret: bool = False,
                             tile_q: Optional[int] = None,
                             out_dtype=jnp.float32) -> jnp.ndarray:
    """Flat-query fused lookup: packed (Q, K_total) fused plane; cx/cy (Q,)
    level-0 centers. Returns (Q, L*(2r+1)^2)."""
    q = cx.shape[0]
    n = 2 * radius + 1
    per_q = sum(m.g * m.k for m in metas) * 4
    if tile_q is None:
        env = os.environ.get("VFT_CORR_TILE_Q", "").strip()
        if env:  # perf-probe override (trace-time, like VFT_CORR_LOOKUP)
            tile_q = int(env)
    if tile_q is None:
        # scoped-VMEM model per query, calibrated against measured Mosaic
        # OOM reports (in-scan, the worst case): double-buffered corr blocks
        # (2x per_q) plus ~(7 + G_max) live (TQ, n+1, K) f32 selector/
        # accumulator tensors at the widest level — the G-way routing keeps
        # its operands live, so the model scales with the unroll (in-scan
        # OOM arithmetic: 20.16 MiB at TQ=256 for the RAFT-224 pyramid
        # with G_max=7 = 78.8 KiB/query)
        k_max = max(m.k for m in metas)
        g_max = max(m.g for m in metas)
        inter = (7 + g_max) * (n + 1) * 4 * k_max
        tile_q = min(_MAX_TILE_Q,
                     max(8, _VMEM_TARGET // (2 * per_q + inter)))
    if per_q > _VMEM_BLOCK_BYTES:
        # a single query's packed planes exceed the probed VMEM envelope
        # (inputs ~>5800 px on a side): no legal tile exists, so refuse
        # loudly rather than fault in Mosaic — callers can use the XLA
        # one-hot twin at such sizes
        raise ValueError(
            f"corr planes too large for the fused kernel ({per_q} B/query "
            f"> {_VMEM_BLOCK_BYTES} B VMEM budget); use corr_lookup_onehot")
    tq = _best_tile(q, tile_q)
    qq = -(-q // tq) * tq
    if qq != q:
        packed = jnp.pad(packed, ((0, qq - q), (0, 0)))
        cx = jnp.pad(cx, (0, qq - q))
        cy = jnp.pad(cy, (0, qq - q))
    k_total = packed.shape[1]
    coord_spec = pl.BlockSpec((tq, 1, 1), lambda qi: (qi, 0, 0),
                              memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_packed_kernel, radius=radius, metas=metas),
        grid=(qq // tq,),
        in_specs=[coord_spec, coord_spec,
                  pl.BlockSpec((tq, k_total), lambda qi: (qi, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((tq, len(metas) * n * n),
                               lambda qi: (qi, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((qq, len(metas) * n * n), out_dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(cx[:, None, None].astype(jnp.float32),
      cy[:, None, None].astype(jnp.float32), packed)
    return out[:q]


def corr_lookup_packed(packed: jnp.ndarray,
                       metas: Tuple[LevelMeta, ...], coords: jnp.ndarray,
                       radius: int = 4, interpret: bool = False,
                       tile_q: Optional[int] = None) -> jnp.ndarray:
    """Fused lookup over a pre-packed pyramid (see :func:`pack_pyramid`).

    coords: (B, H, W, 2) level-0 (x, y) with B folded into Q = B*H*W at
    pack time (the lookup is purely per-query). Returns
    (B, H, W, L*(2r+1)^2) in the reference's level/tap channel order."""
    b, h, w, _ = coords.shape
    cx = coords[..., 0].reshape(b * h * w)
    cy = coords[..., 1].reshape(b * h * w)
    out = _corr_lookup_packed_flat(packed, metas, cx, cy, radius,
                                   interpret, tile_q)
    return out.reshape(b, h, w, -1)
