"""Pallas TPU kernels for the framework's hot ops.

The reference ships exactly one native compute kernel — the PWC-Net
correlation (cost volume) written in raw CUDA C and JIT-compiled through CuPy
(reference models/pwc/pwc_src/correlation.py:47-115) — and does its other
memory-bound hot loop, the RAFT correlation-pyramid lookup, as a
grid_sample gather (reference models/raft/raft_src/corr.py:29-50). Here both
are first-class TPU kernels:

  - :mod:`cost_volume` — the 81-channel windowed cost volume as a Pallas
    kernel (halo-DMA'd second feature map, channel-major VMEM tiles);
  - :mod:`corr_lookup` — the windowed bilinear pyramid lookup recast as
    one-hot matmul contractions (gather-free, rides the MXU), as a fused
    Pallas kernel and a pure-XLA twin.

Dispatch: the cost-volume wrapper takes ``impl`` = ``'pallas' | 'xla' |
None``; ``None`` follows ``VFT_PALLAS`` (default: XLA everywhere — see
:func:`pallas_enabled` for the hardware-fault rationale; interpret mode
keeps the kernel testable on CPU). The corr lookup is selected separately
by ``VFT_CORR_LOOKUP`` in models/raft.py — ``pallas`` (TPU default, the
20x one) | ``onehot`` | ``gather`` (CPU default); both env vars are read
at trace time, so set them before the first forward.

Measured on TPU v5e with a D2H-fenced timer (parallel/mesh.py settle;
earlier microbenchmarks fenced with block_until_ready, which acks early
through dev-chip tunnels and reported pure dispatch latency — those
"everything is tens of microseconds" numbers were artifacts):

  corr lookup, end-to-end 20-iteration RAFT forward (16 pairs @224px):
    gather 4,097 ms / one-hot 331 ms / fused Pallas 200 ms. The 81-tap
    4-corner scalar gathers are the worst access pattern the TPU has; the
    MXU contraction forms win by 12-20x, so Pallas is the TPU default.
  cost volume (per call, fine levels): XLA 51 ms vs Pallas 45 ms at
    (1,112,256,32); 15 vs 8 ms at (1,56,128,64) — Pallas modestly ahead
    where it runs. But at un-128-aligned widths — PWC's coarse levels —
    the Pallas kernel faults on real hardware (worker crash / Mosaic
    compile error; interpret mode cannot catch it), so XLA is the default
    and ``VFT_PALLAS=1`` is an explicit opt-in for aligned shapes.
"""
from __future__ import annotations

import os

import jax


def pallas_enabled() -> bool:
    """Static (trace-time) switch for the COST-VOLUME pallas-vs-XLA dispatch
    (the corr lookup has its own dispatcher in models/raft.py).

    Defaults to False everywhere: on real hardware the Pallas cost-volume
    kernel faults (TPU worker crash, later a Mosaic compile error) at
    un-128-aligned widths — exactly PWC's coarse pyramid levels — which
    interpret-mode tests cannot catch. The XLA formulation is sub-ms at
    every PWC shape, so it is the safe default; ``VFT_PALLAS=1`` opts in
    explicitly (128-aligned shapes verified working on v5e).
    """
    flag = os.environ.get("VFT_PALLAS", "").strip().lower()
    if flag in ("1", "true", "yes"):
        return True
    return False


def interpret_mode() -> bool:
    """Pallas TPU kernels run in interpreter mode off-TPU (tests on CPU)."""
    return jax.default_backend() != "tpu"


from .cost_volume import cost_volume  # noqa: E402
from .corr_lookup import corr_lookup_onehot, corr_lookup_pallas  # noqa: E402

__all__ = [
    "pallas_enabled", "interpret_mode",
    "cost_volume", "corr_lookup_onehot", "corr_lookup_pallas",
]
