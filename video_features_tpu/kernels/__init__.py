"""TPU kernels for the framework's hot ops.

The reference ships exactly one native compute kernel — the PWC-Net
correlation (cost volume) written in raw CUDA C and JIT-compiled through CuPy
(reference models/pwc/pwc_src/correlation.py:47-115) — and does its other
memory-bound hot loop, the RAFT correlation-pyramid lookup, as a
grid_sample gather (reference models/raft/raft_src/corr.py:29-50). Here:

  - :mod:`corr_lookup` — the windowed bilinear pyramid lookup recast as
    one-hot matmul contractions (gather-free, rides the MXU), as a fused
    Pallas kernel and a pure-XLA twin. Selected by the
    ``corr_lookup_impl`` config key (models/raft.py
    configure_corr_lookup, applied at extractor init; the
    ``VFT_CORR_LOOKUP`` env var is the trace-time override) —
    ``pallas`` (TPU default, the 20x one) | ``onehot`` | ``gather``
    (CPU default).
  - :mod:`cost_volume` — the 81-channel windowed cost volume as the XLA
    shifted-window formulation. A Pallas twin was built, hardware-
    validated, measured TIED with XLA across every real PWC shape in f32
    and bf16, and deleted in round 5 (measured negative result recorded
    in that module's docstring).

Measured on TPU v5e with a D2H-fenced timer (parallel/mesh.py settle;
earlier microbenchmarks fenced with block_until_ready, which acks early
through dev-chip tunnels and reported pure dispatch latency — those
"everything is tens of microseconds" numbers were artifacts):

  corr lookup, end-to-end 20-iteration RAFT forward (16 pairs @224px):
    gather 4,097 ms / one-hot 331 ms / fused Pallas 200 ms. The 81-tap
    4-corner scalar gathers are the worst access pattern the TPU has; the
    MXU contraction forms win by 12-20x, so Pallas is the TPU default.
"""
from __future__ import annotations

import jax


def interpret_mode() -> bool:
    """Pallas TPU kernels run in interpreter mode off-TPU (tests on CPU)."""
    return jax.default_backend() != "tpu"


from .cost_volume import cost_volume  # noqa: E402
from .corr_lookup import corr_lookup_onehot, corr_lookup_pallas  # noqa: E402

__all__ = [
    "interpret_mode",
    "cost_volume", "corr_lookup_onehot", "corr_lookup_pallas",
]
