"""Pallas TPU kernels for the framework's hot ops.

The reference ships exactly one native compute kernel — the PWC-Net
correlation (cost volume) written in raw CUDA C and JIT-compiled through CuPy
(reference models/pwc/pwc_src/correlation.py:47-115) — and does its other
memory-bound hot loop, the RAFT correlation-pyramid lookup, as a
grid_sample gather (reference models/raft/raft_src/corr.py:29-50). Here both
are first-class TPU kernels:

  - :mod:`cost_volume` — the 81-channel windowed cost volume as a Pallas
    kernel (halo-DMA'd second feature map, channel-major VMEM tiles);
  - :mod:`corr_lookup` — the windowed bilinear pyramid lookup recast as
    one-hot matmul contractions (gather-free, rides the MXU), as a fused
    Pallas kernel and a pure-XLA twin.

Dispatch: the cost-volume wrapper takes ``impl`` = ``'pallas' | 'xla' |
None``; ``None`` follows ``VFT_PALLAS`` (default: XLA everywhere — see
:func:`pallas_enabled` for the hardware-fault rationale; interpret mode
keeps the kernel testable on CPU). The corr lookup is selected separately
by ``VFT_CORR_LOOKUP`` in models/raft.py — ``pallas`` (TPU default, the
20x one) | ``onehot`` | ``gather`` (CPU default); both env vars are read
at trace time, so set them before the first forward.

Measured on TPU v5e with a D2H-fenced timer (parallel/mesh.py settle;
earlier microbenchmarks fenced with block_until_ready, which acks early
through dev-chip tunnels and reported pure dispatch latency — those
"everything is tens of microseconds" numbers were artifacts):

  corr lookup, end-to-end 20-iteration RAFT forward (16 pairs @224px):
    gather 4,097 ms / one-hot 331 ms / fused Pallas 200 ms. The 81-tap
    4-corner scalar gathers are the worst access pattern the TPU has; the
    MXU contraction forms win by 12-20x, so Pallas is the TPU default.
  cost volume (per call, fine levels): XLA 51 ms vs Pallas 45 ms at
    (1,112,256,32); 15 vs 8 ms at (1,56,128,64) — Pallas modestly ahead
    where it runs. But at un-128-aligned widths — PWC's coarse levels —
    the Pallas kernel faults on real hardware (worker crash / Mosaic
    compile error; interpret mode cannot catch it), so XLA is the default
    and ``VFT_PALLAS=1`` is an explicit opt-in for aligned shapes.
"""
from __future__ import annotations

import os

import jax


def pallas_enabled() -> bool:
    """Static (trace-time) switch for the COST-VOLUME pallas-vs-XLA dispatch
    (the corr lookup has its own dispatcher in models/raft.py).

    Defaults to False ON MEASUREMENT, not fear: after the round-2 lane
    (W->128) and sublane (H->8) padding fixes, ``cost_volume_pallas`` is
    hardware-validated CLEAN on every real PWC pyramid shape (15 shapes, 3
    input geometries x 5 decoder levels, odd/tiny sizes included; parity
    <3e-7 vs the XLA twin). Timed best-of-3 on v5e it is within noise of the
    XLA formulation overall — ahead at the tiny coarse levels (1.7x at
    4x5xC196), behind at the large ones (0.7-0.9x at /4 and /8) where XLA's
    fusion wins. The XLA twin therefore stays the default; ``VFT_PALLAS=1``
    opts in (useful as the starting point if the cost volume ever needs to
    fuse with the warp that feeds it).
    """
    flag = os.environ.get("VFT_PALLAS", "").strip().lower()
    if flag in ("1", "true", "yes"):
        return True
    return False


def interpret_mode() -> bool:
    """Pallas TPU kernels run in interpreter mode off-TPU (tests on CPU)."""
    return jax.default_backend() != "tpu"


from .cost_volume import cost_volume  # noqa: E402
from .corr_lookup import corr_lookup_onehot, corr_lookup_pallas  # noqa: E402

__all__ = [
    "pallas_enabled", "interpret_mode",
    "cost_volume", "corr_lookup_onehot", "corr_lookup_pallas",
]
