"""Pallas TPU kernels for the framework's hot ops.

The reference ships exactly one native compute kernel — the PWC-Net
correlation (cost volume) written in raw CUDA C and JIT-compiled through CuPy
(reference models/pwc/pwc_src/correlation.py:47-115) — and does its other
memory-bound hot loop, the RAFT correlation-pyramid lookup, as a
grid_sample gather (reference models/raft/raft_src/corr.py:29-50). Here both
are first-class TPU kernels:

  - :mod:`cost_volume` — the 81-channel windowed cost volume as a Pallas
    kernel (halo-DMA'd second feature map, channel-major VMEM tiles);
  - :mod:`corr_lookup` — the windowed bilinear pyramid lookup recast as
    one-hot matmul contractions (gather-free, rides the MXU), as a fused
    Pallas kernel and a pure-XLA twin.

Dispatch: the cost-volume wrapper takes ``impl`` = ``'pallas' | 'xla' |
None``; ``None`` reads the ``VFT_PALLAS`` env var (``1``/``0``), defaulting
to pallas on TPU backends and XLA elsewhere (pallas interpret mode is used
automatically on CPU so the kernels stay testable everywhere). The corr
lookup is selected separately by ``VFT_CORR_LOOKUP`` in models/raft.py —
``gather`` (default) | ``onehot`` | ``pallas``; both env vars are read at
trace time, so set them before the first forward of the process.

Measured on TPU v5e (scripts/bench_kernels.py, f32, 200-iteration mean;
everything here is tens of microseconds, so +-30% run-to-run noise):

  cost volume: pallas 2.2x faster than XLA on the two finest (dominant)
    pyramid levels — (1,112,256,32): 0.012 vs 0.028 ms; (1,56,128,64):
    0.011 vs 0.023 ms — the halo-DMA tile reads f2 from HBM once instead
    of 81 shifted times; coarse levels are launch-bound and come out even.
  corr lookup (jitted end-to-end): gather / one-hot / fused pallas are all
    within noise of each other (14-37 us across B=1..8 shapes) — XLA's
    lane-dim dynamic gather is already near-optimal, so RAFT keeps gather
    as its default (models/raft.py) and the matmul forms stay alternates.
"""
from __future__ import annotations

import os

import jax


def pallas_enabled() -> bool:
    """Static (trace-time) switch for pallas-vs-XLA kernel dispatch."""
    flag = os.environ.get("VFT_PALLAS", "").strip().lower()
    if flag in ("1", "true", "yes"):
        return True
    if flag in ("0", "false", "no"):
        return False
    return jax.default_backend() == "tpu"


def interpret_mode() -> bool:
    """Pallas TPU kernels run in interpreter mode off-TPU (tests on CPU)."""
    return jax.default_backend() != "tpu"


from .cost_volume import cost_volume  # noqa: E402
from .corr_lookup import corr_lookup_onehot, corr_lookup_pallas  # noqa: E402

__all__ = [
    "pallas_enabled", "interpret_mode",
    "cost_volume", "corr_lookup_onehot", "corr_lookup_pallas",
]
