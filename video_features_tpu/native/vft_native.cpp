// Native runtime IO for video_features_tpu.
//
// The reference's resume contract (reference models/_base/base_extractor.py:
// 95-127) treats an output file that exists but fails to load as absent —
// corruption detection by fully loading every array on every resume scan.
// This library hardens and accelerates that contract:
//
//   vft_write_npy    — writes a NumPy .npy v1/v2 file to <path>.tmp.<pid>,
//                      fsyncs, then atomically rename()s into place, so a
//                      preempted worker can never leave a half-written
//                      feature file behind (POSIX rename atomicity).
//   vft_validate_npy — structural corruption check without reading the
//                      payload: parses the magic/version/header, computes the
//                      expected payload size from descr+shape, and compares
//                      with the on-disk size. O(header bytes) instead of the
//                      reference's O(array bytes) per resume scan.
//
// Built on demand by video_features_tpu/native/__init__.py with g++; all
// entry points return 0 on success / negative error codes, never throw.
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr unsigned char kMagic[] = {0x93, 'N', 'U', 'M', 'P', 'Y'};

// "{'descr': '<f4', 'fortran_order': False, 'shape': (3, 4), }" + padding
std::string build_header(const char* descr, const int64_t* shape, int ndim) {
  std::string dict = "{'descr': '";
  dict += descr;
  dict += "', 'fortran_order': False, 'shape': (";
  for (int i = 0; i < ndim; ++i) {
    dict += std::to_string(shape[i]);
    if (ndim == 1 || i + 1 < ndim) dict += ",";
    if (i + 1 < ndim) dict += " ";
  }
  dict += "), }";
  return dict;
}

}  // namespace

extern "C" {

// Error codes (negative): -errno for OS errors, -1000.. for format errors.
enum {
  VFT_EFORMAT = -1000,   // not a .npy file / bad header
  VFT_ETRUNCATED = -1001,  // payload size mismatch (partial write)
  VFT_EHEADER = -1002,   // header unparseable
};

int vft_write_npy(const char* path, const char* descr, const int64_t* shape,
                  int ndim, const void* data, int64_t nbytes) {
  std::string dict = build_header(descr, shape, ndim);
  // v1 header: 10-byte preamble + dict padded with spaces to a multiple of
  // 64, '\n'-terminated; v2 (4-byte length) when the dict exceeds 65535
  bool v2 = false;
  size_t preamble = 10;
  size_t unpadded = preamble + dict.size() + 1;
  size_t total = (unpadded + 63) / 64 * 64;
  if (total - preamble > 65535) {
    v2 = true;
    preamble = 12;
    unpadded = preamble + dict.size() + 1;
    total = (unpadded + 63) / 64 * 64;
  }
  std::string header;
  header.reserve(total);
  header.append(reinterpret_cast<const char*>(kMagic), 6);
  header.push_back(v2 ? 2 : 1);
  header.push_back(0);
  size_t hlen = total - preamble;
  if (v2) {
    uint32_t n = static_cast<uint32_t>(hlen);
    header.append(reinterpret_cast<const char*>(&n), 4);
  } else {
    uint16_t n = static_cast<uint16_t>(hlen);
    header.append(reinterpret_cast<const char*>(&n), 2);
  }
  header += dict;
  header.append(total - unpadded, ' ');
  header.push_back('\n');

  std::string tmp = std::string(path) + ".tmp." + std::to_string(getpid());
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -errno;
  auto write_all = [&](const char* p, int64_t n) -> int {
    while (n > 0) {
      ssize_t w = write(fd, p, static_cast<size_t>(n));
      if (w < 0) {
        if (errno == EINTR) continue;
        return -errno;
      }
      p += w;
      n -= w;
    }
    return 0;
  };
  int rc = write_all(header.data(), static_cast<int64_t>(header.size()));
  if (rc == 0) rc = write_all(static_cast<const char*>(data), nbytes);
  if (rc == 0 && fsync(fd) != 0) rc = -errno;
  if (close(fd) != 0 && rc == 0) rc = -errno;
  if (rc != 0) {
    unlink(tmp.c_str());
    return rc;
  }
  if (rename(tmp.c_str(), path) != 0) {
    rc = -errno;
    unlink(tmp.c_str());
    return rc;
  }
  return 0;
}

// Parses the header and verifies file size == header + itemsize*prod(shape).
// Returns 0 if structurally valid.
int vft_validate_npy(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -errno;
  unsigned char pre[12];
  ssize_t got = read(fd, pre, 12);
  if (got < 10 || memcmp(pre, kMagic, 6) != 0) {
    close(fd);
    return VFT_EFORMAT;
  }
  int major = pre[6];
  size_t hlen, preamble;
  if (major == 1) {
    hlen = static_cast<size_t>(pre[8]) | (static_cast<size_t>(pre[9]) << 8);
    preamble = 10;
  } else if (major == 2 || major == 3) {
    if (got < 12) {
      close(fd);
      return VFT_EFORMAT;
    }
    hlen = static_cast<size_t>(pre[8]) | (static_cast<size_t>(pre[9]) << 8) |
           (static_cast<size_t>(pre[10]) << 16) |
           (static_cast<size_t>(pre[11]) << 24);
    preamble = 12;
  } else {
    close(fd);
    return VFT_EFORMAT;
  }
  if (hlen > (1u << 20)) {  // pathological header
    close(fd);
    return VFT_EHEADER;
  }
  std::string dict(hlen, '\0');
  if (lseek(fd, static_cast<off_t>(preamble), SEEK_SET) < 0 ||
      read(fd, dict.data(), hlen) != static_cast<ssize_t>(hlen)) {
    close(fd);
    return VFT_EFORMAT;
  }
  struct stat st;
  if (fstat(fd, &st) != 0) {
    int rc = -errno;
    close(fd);
    return rc;
  }
  close(fd);

  // descr: '<f4' style simple strings only; compound dtypes (rare, not
  // produced by this framework) report VFT_EHEADER and the caller falls
  // back to a full np.load
  size_t dpos = dict.find("'descr'");
  if (dpos == std::string::npos) return VFT_EHEADER;
  size_t q1 = dict.find('\'', dpos + 7);
  if (q1 == std::string::npos) return VFT_EHEADER;
  size_t q2 = dict.find('\'', q1 + 1);
  if (q2 == std::string::npos) return VFT_EHEADER;
  std::string descr = dict.substr(q1 + 1, q2 - q1 - 1);
  if (descr.size() < 2) return VFT_EHEADER;
  size_t digits = descr.find_first_of("0123456789");
  if (digits == std::string::npos) return VFT_EHEADER;
  long itemsize = strtol(descr.c_str() + digits, nullptr, 10);
  if (itemsize <= 0) return VFT_EHEADER;
  if (descr.find('U') != std::string::npos) itemsize *= 4;  // unicode chars

  size_t spos = dict.find("'shape'");
  if (spos == std::string::npos) return VFT_EHEADER;
  size_t p1 = dict.find('(', spos);
  size_t p2 = dict.find(')', spos);
  if (p1 == std::string::npos || p2 == std::string::npos || p2 < p1)
    return VFT_EHEADER;
  int64_t count = 1;
  std::string nums = dict.substr(p1 + 1, p2 - p1 - 1);
  const char* p = nums.c_str();
  while (*p) {
    while (*p == ' ' || *p == ',') ++p;
    if (!*p) break;
    char* end;
    long long dim = strtoll(p, &end, 10);
    if (end == p) return VFT_EHEADER;
    if (dim < 0) return VFT_EHEADER;
    count *= dim;
    p = end;
  }
  int64_t expected =
      static_cast<int64_t>(preamble + hlen) + count * itemsize;
  if (st.st_size != expected) return VFT_ETRUNCATED;
  return 0;
}

}  // extern "C"
