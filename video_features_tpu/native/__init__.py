"""Native (C++) runtime IO: build-on-demand, ctypes-bound, always optional.

``vft_native.cpp`` is compiled with g++ into a cached shared library on first
import (no pybind11 in this environment — plain ``extern "C"`` + ctypes).
Every entry point has a pure-Python fallback at its call site, so the
framework runs unchanged where a toolchain is unavailable; set
``VFT_NATIVE=0`` to force the fallbacks.

Exports:
  available()               -> bool
  write_npy_atomic(path, a) -> write a .npy via temp-file + fsync + rename
  validate_npy(path)        -> structural corruption check, O(header)
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Optional

import numpy as np

_SRC = Path(__file__).with_name("vft_native.cpp")
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _cache_dir() -> Path:
    root = os.environ.get("VFT_CACHE_DIR",
                          os.path.join(os.path.expanduser("~"), ".cache",
                                       "video_features_tpu"))
    d = Path(root) / "native"
    d.mkdir(parents=True, exist_ok=True)
    return d


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    if os.environ.get("VFT_NATIVE", "").strip() == "0":
        _build_failed = True
        return None
    try:
        src = _SRC.read_bytes()
        tag = hashlib.sha1(src).hexdigest()[:16]
        so = _cache_dir() / f"vft_native-{tag}.so"
        if not so.exists():
            # build into a temp name then rename: parallel workers racing to
            # build get a whole file or none
            with tempfile.NamedTemporaryFile(
                    dir=so.parent, suffix=".so", delete=False) as tmp:
                tmp_path = tmp.name
            try:
                cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                       str(_SRC), "-o", tmp_path]
                subprocess.run(cmd, check=True, capture_output=True)
                os.replace(tmp_path, so)
            except BaseException:
                try:
                    os.unlink(tmp_path)
                except OSError:
                    pass
                raise
        lib = ctypes.CDLL(str(so))
        lib.vft_write_npy.restype = ctypes.c_int
        lib.vft_write_npy.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.c_void_p, ctypes.c_int64]
        lib.vft_validate_npy.restype = ctypes.c_int
        lib.vft_validate_npy.argtypes = [ctypes.c_char_p]
        _lib = lib
    except Exception:
        _build_failed = True
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def write_npy_atomic(fpath: str, value) -> bool:
    """Write ``value`` as .npy with atomic replace. Returns False when the
    native path cannot handle it (object/structured dtype, or the library
    is unavailable) — callers fall back to np.save. Non-contiguous inputs
    are copied to C order first."""
    lib = _load()
    if lib is None:
        return False
    arr = np.asanyarray(value)
    if arr.dtype.hasobject or arr.dtype.fields is not None:
        return False
    # np.save appends '.npy' when missing — preserve that contract
    if not str(fpath).endswith(".npy"):
        fpath = str(fpath) + ".npy"
    if not arr.flags.c_contiguous:
        # NOT ascontiguousarray unconditionally: it promotes 0-d to (1,)
        arr = np.ascontiguousarray(arr)
    shape = (ctypes.c_int64 * max(arr.ndim, 1))(*arr.shape)
    rc = lib.vft_write_npy(
        str(fpath).encode(), arr.dtype.str.encode(), shape, arr.ndim,
        arr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(arr.nbytes))
    if rc != 0:
        raise OSError(f"vft_write_npy({fpath}) failed: {rc} "
                      f"({os.strerror(-rc) if -rc < 1000 else 'format'})")
    return True


def validate_npy(fpath: str) -> Optional[bool]:
    """True = structurally valid, False = corrupt/truncated, None = cannot
    judge natively (no lib, exotic header) — caller should np.load."""
    lib = _load()
    if lib is None:
        return None
    rc = lib.vft_validate_npy(str(fpath).encode())
    if rc == 0:
        return True
    if rc in (-1000, -1001):  # VFT_EFORMAT, VFT_ETRUNCATED
        return False
    return None  # header we don't parse, or OS error: let np.load decide
