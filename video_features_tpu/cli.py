"""CLI driver: ``python main.py feature_type=X key=val ...``.

Same surface as reference main.py:7-51: per-feature YAML defaults merged under
CLI dotlist overrides, validated, then a progress-bar loop over the (shuffled)
video list with per-video error isolation. Multi-host runs additionally filter
the list to this host's deterministic shard (parallel/mesh.py).
"""
from __future__ import annotations

import sys
import time
from typing import List, Optional

from tqdm import tqdm

from .config import load_config, parse_dotlist, sanity_check
from .registry import get_extractor_cls
from .utils.lists import form_list_from_user_input
from .utils.sinks import safe_extract


def _enable_compilation_cache(args) -> None:
    """Persistent XLA compilation cache, on by default.

    The serial-reference analog of this cost doesn't exist (torch eager has
    no compile step), but here every (family, resolution, batch) executable
    costs tens of seconds of XLA compile on first use — paying it once per
    *machine* instead of once per *run* matters for the CLI's
    one-process-per-invocation lifecycle. ``compilation_cache_dir=null``
    disables; the default honors JAX's own env var when set."""
    import os
    cache_dir = args.get("compilation_cache_dir", "auto")
    # CLI values go through yaml.safe_load: `false`/`off`/`no` arrive as
    # bool False, `true` as bool True
    if cache_dir in (None, "null", "false", "") or cache_dir is False:
        return
    if args.get("device") == "cpu" and cache_dir in ("auto", True):
        # XLA:CPU executables bake in the compiling host's CPU features; on a
        # heterogeneous fleet a cache hit from a different machine risks
        # SIGILL (XLA warns loudly and may crash). TPU executables have no
        # such hazard and are where compiles are expensive — so 'auto' only
        # persists for TPU runs; an explicit dir still opts CPU runs in.
        return
    if cache_dir == "auto" or cache_dir is True:
        cache_dir = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "video_features_tpu", "xla_cache"))
    import jax
    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    # small executables are worth caching too: the CLI compiles few, reuses
    # them across runs, and the default 1s min-compile-time would skip them
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def _maybe_init_distributed(args) -> None:
    if bool(args.get("distributed", False)):
        # multi-host pod slice: one process per host, launched by the TPU VM
        # runtime (GKE/gcloud); coordinator/process env comes from the
        # platform, so the no-arg initialize() is correct. Must run BEFORE
        # sanity_check: resolve_device calls jax.devices(), which initializes
        # the backend and would lock process_count() at 1. After this,
        # jax.process_index()/process_count() drive local_shard_of_list.
        import jax
        if str(args.get("device", "")) == "cpu":
            # explicit device=cpu must hold through distributed init: some
            # hosts' sitecustomize re-points jax at an accelerator plugin
            # after env vars are read (same hard-pin as extractors/base.py),
            # and a CPU cluster needs the gloo cross-process collectives
            # client for process_count()/process_index() to reflect the job
            jax.config.update("jax_platforms", "cpu")
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except (AttributeError, ValueError):
                pass  # older/newer jax without the knob: fine for TPU pods
        # tolerate in-process re-runs AND launcher-preinitialized workers;
        # is_initialized is absent on older jax, where the coordinator
        # client on distributed.global_state is the ground truth (an older
        # jax also raises a DIFFERENT message for a double init — "must be
        # called before any JAX computations" — so the string probe on the
        # RuntimeError alone is not a reliable detector)
        def _already() -> bool:
            fn = getattr(jax.distributed, "is_initialized", None)
            if fn is not None:
                return bool(fn())
            try:
                from jax._src.distributed import global_state
                return global_state.client is not None \
                    or global_state.coordinator_address is not None
            except Exception:
                return False
        try:
            if not _already():
                jax.distributed.initialize()
        except RuntimeError as e:
            if "already" not in str(e).lower():
                raise


def main(argv: Optional[List[str]] = None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "serve":
        # warm serving mode: `python main.py serve feature_type=...
        # spool_dir=...` routes to the long-lived spool drainer
        # (serve.py; also installed as the `vft-serve` console script)
        from .serve import serve_main
        return serve_main(argv[1:])
    if argv and argv[0] == "gateway":
        # network front door: `python main.py gateway spool_dir=...`
        # routes to the HTTP ingress (gateway.py; also installed as the
        # `vft-gateway` console script)
        from .gateway import gateway_main
        return gateway_main(argv[1:])
    if argv and argv[0] == "loadgen":
        # traffic drills: `python main.py loadgen scenarios/steady.yml
        # --spool ... --base-url ...` replays a seeded scenario against
        # the gateway and publishes the _scenario.json verdict
        # (loadgen.py; also installed as the `vft-loadgen` console
        # script). Exits with the drill verdict.
        from .loadgen import loadgen_main
        raise SystemExit(loadgen_main(argv[1:]))
    if argv and argv[0] == "lint":
        # contract-aware static analysis: `python main.py lint [--json
        # --baseline ...]` proves the repo's cross-file invariants in
        # seconds (lint/; also installed as the `vft-lint` console
        # script). Exits with the lint verdict.
        from .lint.engine import main as lint_main
        raise SystemExit(lint_main(argv[1:]))
    if argv and argv[0] == "parity":
        # numerics observatory: `python main.py parity <run_dir>` renders
        # a run's _parity.jsonl; `python main.py parity certify --config
        # raft.yml --flip dtype=bf16` A/B-certifies a precision flip
        # with per-seam error attribution (telemetry/parity.py; also
        # installed as the `vft-parity` console script, docs/numerics.md)
        from .telemetry.parity import main as parity_main
        raise SystemExit(parity_main(argv[1:]))
    if argv and argv[0] == "warmup":
        # ahead-of-time compile warmup: `python main.py warmup resnet ...`
        # routes to the store populator (compile_cache.py; also installed
        # as the `vft-warmup` console script)
        from .compile_cache import warmup_main
        return warmup_main(argv[1:])
    cli_args = parse_dotlist(argv)
    if "feature_type" not in cli_args:
        raise SystemExit("Usage: main.py feature_type=<family>[,<family>...]"
                         " [key=value ...] | main.py serve feature_type=... "
                         "spool_dir=<dir> (docs/serving.md)")
    from .registry import parse_feature_types
    families = parse_feature_types(cli_args.feature_type)
    multi_mode = len(families) > 1
    if multi_mode:
        # multi-family run: per-family configs (top-level keys shared,
        # `family.key=` overrides private), ONE shared decode pass per
        # video (extractors/multi.py + parallel/fanout.py)
        from .config import load_multi_config, sanity_check_multi
        per_family = load_multi_config(families, cli_args)
        args = per_family[families[0]]
        # the user-level output root, captured BEFORE sanity_check
        # namespaces each family's own path under it: run-scoped
        # artifacts (telemetry) live here, per-family sinks/journals in
        # their subdirs
        out_root = str(args.output_path)
        _maybe_init_distributed(args)
        sanity_check_multi(per_family)
    else:
        per_family = None
        args = load_config(cli_args.feature_type, cli_args)
        _maybe_init_distributed(args)
        sanity_check(args)
        out_root = str(args.output_path)
    _enable_compilation_cache(args)
    verbose = (not multi_mode) and \
        args.get("on_extraction", "print") == "print"
    if verbose:
        print(args.to_yaml())

    # Deterministic fault injection (inject=, utils/inject.py): seeded,
    # replayable faults at named durability sites — chaos testing only.
    # VFT_INJECT overrides the config key (and armed subprocess workers
    # at import). Off (the default): every site is one global read.
    from .utils import inject
    inject_plan = inject.arm_for_run(args.get("inject"))
    if inject_plan is not None:
        print(f"inject: armed plan {inject_plan.spec!r} "
              f"(seed={inject_plan.seed}; docs/chaos.md — replay by "
              "re-running with this exact inject= string)")

    # Fleet-shared compile cache (compile_cache.py): attach this process
    # to its (family, resolved config, environment) entry BEFORE the
    # extractors are even constructed — the init-time compiles (flax
    # model.init of the scan-heavy families costs seconds) are part of
    # the warm set. Verify-before-trust on the way in, sealed in the
    # finally below. Supersedes the per-machine compilation_cache_dir
    # wiring above whenever it resolves enabled. A warm attach means a
    # joining host compiles nothing it has seen before.
    from . import compile_cache
    cc_entry = (compile_cache.attach_for_multi_args(per_family) if multi_mode
                else compile_cache.attach_for_args(args.feature_type, args))
    if cc_entry is not None:
        print(f"compile cache: entry {cc_entry.key[:12]} "
              f"({'warm' if cc_entry.warm_at_attach else 'cold'}, "
              f"{cc_entry.verified} verified"
              + (f", {cc_entry.dropped} dropped" if cc_entry.dropped else "")
              + f") at {cc_entry.dir}")

    if multi_mode:
        from .extractors.multi import MultiExtractor
        extractor = None
        multi = MultiExtractor(per_family)
    else:
        multi = None
        extractor = get_extractor_cls(args.feature_type)(args)
    run_label = ",".join(families)

    video_paths = form_list_from_user_input(
        args.get("video_paths"), args.get("file_with_video_paths"),
        to_shuffle=True)
    # multi-host partitioning, fleet= config key (sanity_check-validated):
    #   static (default) — keep only this host's deterministic hash shard
    #     of the work list, byte-identical to the pre-queue behavior
    #     (jax.process_count() is 1 when jax.distributed is not up);
    #   queue — every host sees the FULL list and seeds the shared
    #     work-stealing queue instead (parallel/queue.py, constructed
    #     below once the telemetry recorder exists to renew leases)
    fleet_mode = str(args.get("fleet", "static") or "static")
    if fleet_mode != "queue":
        from .parallel.mesh import local_shard_of_list
        video_paths = local_shard_of_list(video_paths)

    # profile=true: per-stage decode/forward/write breakdown at the end;
    # profile_trace_dir=/path: additionally capture a jax.profiler trace
    from .utils.profiling import TraceCapture, profiler
    profiler.enabled = bool(args.get("profile", False))
    profiler.reset()  # the profiler is process-global; in-process re-runs
    # (library use, tests) must not inherit the previous run's stats

    # Graceful preemption: preemptible TPU workers get SIGTERM with a grace
    # window. Finish the in-flight video(s) — atomic writes + the idempotent
    # skip make a restarted worker resume exactly where this one stopped —
    # drop the rest, and exit 143. (The reference's only preemption story
    # was re-running the whole shuffled list, README.md:75-77.)
    import signal
    import threading
    stop = threading.Event()
    in_main = threading.current_thread() is threading.main_thread()
    prev_handler = None
    if in_main:
        def _on_sigterm(signo, frame):
            print("SIGTERM: finishing in-flight video(s), dropping the rest")
            stop.set()
        prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)

    workers_arg = args.get("video_workers") or 1
    if workers_arg == "auto":  # sanity_check normalized/validated strings
        # decode threads beyond the core count just contend; beyond ~8 the
        # single device queue is the limiter anyway
        import os as _os
        workers_arg = max(1, min(8, (_os.cpu_count() or 1) // 2))
    workers = int(workers_arg)
    tally = {"done": 0, "skipped": 0, "error": 0, "quarantined": 0}
    # multi-family: the tally counts (video, family) units; this breaks
    # them out per family for the end-of-run summary
    fam_tally = {f: dict(tally) for f in families} if multi_mode else None
    videos_run = [0]  # videos that entered run_one (vs dropped by SIGTERM)
    tally_lock = threading.Lock()
    t_run = time.perf_counter()

    # Fault-tolerance runtime (utils/faults.py): categorized retries with
    # backoff + the decode degradation ladder per video, a per-video
    # deadline watchdog, and — for file sinks — the persistent failure
    # journal that quarantines known-poison inputs across restarts. The
    # print sink has no resume contract, so it keeps no journal.
    # (Multi-family runs carry one policy+journal PER FAMILY inside the
    # MultiExtractor instead — a quarantine is a per-family verdict.)
    from .utils.faults import FailureJournal, RetryPolicy
    policy = journal = None
    if not multi_mode:
        policy = RetryPolicy.from_config(args)
        journal = (FailureJournal(args.output_path)
                   if args.get("on_extraction", "print") != "print" else None)
    failures: List[dict] = []  # this run's terminal records (GIL-safe append)

    # Structured telemetry (telemetry=true): per-video span records in
    # {output_path}/_telemetry.jsonl, periodic _heartbeat_{host_id}.json,
    # and the _run.json manifest at exit. Off by default: every
    # instrumentation point below degrades to a no-op context manager /
    # one-global-read helper (docs/observability.md).
    from .telemetry import NOOP_SPAN
    recorder = None
    if bool(args.get("telemetry", False)):
        import socket
        from .config import _plain
        from .telemetry.recorder import TelemetryRecorder
        host_id = socket.gethostname()
        try:
            import jax
            host_id = f"p{jax.process_index()}-{host_id}"
        except Exception:
            pass
        if fleet_mode == "queue":
            # lease ownership + heartbeat files are keyed on host_id, and
            # queue workers may legitimately share one machine (tests,
            # smoke gates, over-subscribed hosts) — pid + a nonce keep
            # each worker's identity, claims dir and liveness file
            # distinct even for in-process sibling workers
            import os
            import uuid
            host_id = f"{host_id}-{os.getpid()}-{uuid.uuid4().hex[:4]}"
        run_config = (_plain(args) if not multi_mode else
                      {"feature_type": run_label,
                       "families": {f: _plain(a)
                                    for f, a in per_family.items()}})
        recorder = TelemetryRecorder(
            # multi: run-scoped artifacts live at the common output root
            # (per-family sinks are namespaced beneath it); spans carry
            # their own per-family feature_type
            out_root,
            run_config=run_config,
            feature_type=run_label,
            interval_s=float(args.get("metrics_interval_s") or 30.0),
            host_id=host_id,
        )

    # Alerting & flight recorder (alerts=true) + retained heartbeat
    # history (history=true): both ride the heartbeat tick as recorder
    # hooks, registered BEFORE start() so the t=0 heartbeat seeds the
    # windowed baselines. alerts=true implies history retention — the
    # burn-rate/spike rules diff retained samples. A firing rule appends
    # a transition to {out_root}/_alerts.jsonl and captures a black-box
    # bundle under _incidents/{alert_id}/ (telemetry/alerts.py;
    # docs/observability.md "Alerting & incident bundles").
    alert_engine = None
    if recorder is not None:
        if bool(args.get("history", False)) or bool(args.get("alerts",
                                                             False)):
            from .telemetry.history import HistoryWriter
            HistoryWriter(out_root, recorder.host_id).attach(recorder)
        if bool(args.get("alerts", False)):
            from .telemetry.alerts import AlertEngine
            alert_engine = AlertEngine(
                out_root, run_id=recorder.run_id).attach(recorder)
        # Storage lifecycle accounting (gc=true, gc.py): a heartbeat
        # "gc" section with per-plane/per-tenant byte usage (cached —
        # the tree walk refreshes at most every gc_interval_s) plus the
        # vft_gc_* gauges the disk_pressure alert rule projects from.
        # Accounting only: eviction is vft-gc's job (docs/storage.md).
        # gc=false (default) registers nothing — zero footprint.
        if bool(args.get("gc", False)):
            from .gc import GcConfig, GcMonitor
            GcMonitor(out_root, GcConfig.from_args(args)).attach(recorder)
        recorder.start()

    # Pipeline tracing (trace=true): a Chrome-trace timeline of the host
    # pipeline — every profiler.stage call, fan-out backpressure stall,
    # prefetch and retry wait — drained to {out_root}/_trace.json at exit
    # (telemetry/trace.py). Off by default: every trace helper is a
    # one-global-read no-op, the same discipline as telemetry=false.
    tracer = None
    if bool(args.get("trace", False)):
        from .telemetry.trace import TraceRecorder
        # fleet=queue workers co-own out_root: each writes its own
        # _trace_{host_id}.json (single-writer dirs keep _trace.json) —
        # otherwise the last worker to exit would overwrite every other
        # host's timeline, and vft-fleet --stitch needs them all
        tracer = TraceRecorder(
            out_root,
            host_id=(host_id if fleet_mode == "queue"
                     and recorder is not None else None)).start()

    # Roofline observatory (roofline=true, telemetry/roofline.py): XLA
    # cost cards per dispatched program + measured forward/h2d stage
    # seconds -> per-family effective TFLOPS, MFU vs the device peak
    # registry, and a compute/bandwidth/launch-overhead/host-bound
    # verdict, written to {out_root}/_roofline.json at exit (per-host in
    # fleet=queue dirs, like traces). Off by default: the dispatch hook
    # is one module-global read.
    rf_observer = None
    if bool(args.get("roofline", False)):
        from .telemetry.roofline import RooflineObserver
        rf_observer = RooflineObserver(
            out_root, default_family=run_label,
            run_id=(recorder.run_id if recorder is not None else None),
            host_id=(recorder.host_id if fleet_mode == "queue"
                     and recorder is not None else None)).start()

    # Parity observatory (parity=true, telemetry/parity.py): per-seam
    # numerics digests (decode -> transform -> backbone -> head) appended
    # to {out_root}/_parity.jsonl (per-host in fleet=queue dirs, like
    # traces). Off by default: every tap is one module-global read, and
    # the transform-seam wrapper is never even installed.
    parity_observer = None
    if bool(args.get("parity", False)):
        from .telemetry import parity as parity_mod
        parity_observer = parity_mod.ParityObserver(
            out_root,
            host_id=(recorder.host_id if fleet_mode == "queue"
                     and recorder is not None else None))
        parity_mod._set_active(parity_observer)

    # Work-stealing fleet queue (fleet=queue, parallel/queue.py): instead
    # of owning a fixed hash shard, this host claims videos one at a time
    # from the shared {out_root}/_queue/ by atomic rename, renews its
    # lease stamps from the heartbeat flusher thread (extra_sections
    # hook), and steals expired leases when idle — fleet makespan
    # approaches total_work/n_hosts instead of max(shard). sanity_check
    # guarantees recorder is live here (fleet=queue needs telemetry=true).
    work_queue = None
    if fleet_mode == "queue":
        if recorder is None:  # library callers can bypass sanity_check
            raise ValueError("fleet=queue needs telemetry=true: the "
                             "heartbeat thread renews the work-item leases")
        from .parallel.queue import WorkQueue
        work_queue = WorkQueue(
            out_root, host_id=host_id, run_id=recorder.run_id,
            lease_s=float(args.get("fleet_lease_s") or 60.0),
            max_reclaims=int(args.get("fleet_max_reclaims") or 3),
            journal=(journal if not multi_mode else None),
            staging_retention_s=(
                float(args["gc_staging_retention_s"])
                if args.get("gc_staging_retention_s") is not None
                else None))
        recorder.extra_sections["fleet"] = work_queue.heartbeat_section
        # canary warm fast path (compile_cache.py): a joining host whose
        # compile-cache fingerprint fully hit has no cold-compile jitter
        # for the canary timing bands to absorb — the gate tightens, and
        # the heartbeat fleet section records canary_warm=true
        work_queue.canary_warm = bool(cc_entry is not None
                                      and cc_entry.warm_at_attach)
        seeded = work_queue.seed(video_paths)
        print(f"fleet: queue mode — seeded {seeded} new item(s) into "
              f"{work_queue.root} as {host_id}")

    # Output health (health=true): per-(video, family) feature digests at
    # the sink boundary, appended to each family's {output_path}/
    # _health.jsonl, with NaN/Inf outputs quarantined via the faults
    # taxonomy instead of written (telemetry/health.py). The gate itself
    # lives in BaseExtractor.action_on_extraction — this flag only drives
    # the end-of-run pointer below.
    health_on = (any(bool(a.get("health", False))
                     for a in per_family.values())
                 if multi_mode else bool(args.get("health", False)))

    def run_one(video_path: str) -> str:
        """Extract one video; the returned status feeds the fleet queue's
        done marker ('dropped' = preempted before starting, the queue
        releases the claim instead of completing it)."""
        if stop.is_set():
            return "dropped"
        with tally_lock:
            videos_run[0] += 1
        if multi is not None:
            statuses = multi.run_video(video_path, recorder=recorder,
                                       failures=failures)
            with tally_lock:
                for fam, status in statuses.items():
                    tally[status] += 1
                    fam_tally[fam][status] += 1
            # one done marker per video: the worst per-family verdict
            for agg in ("error", "quarantined", "done"):
                if agg in statuses.values():
                    return agg
            return "skipped"
        span_cm = (recorder.video_span(video_path)
                   if recorder is not None else NOOP_SPAN)
        with span_cm as span:
            status = safe_extract(extractor._extract, video_path,
                                  policy=policy, journal=journal,
                                  decode_mode=extractor.video_decode,
                                  on_terminal_failure=failures.append)
            span.annotate(status=status)
        with tally_lock:
            tally[status] += 1
        return status

    def canary_extract(video_path: str, canary_dir: str):
        """Joining-host canary (fleet_canary=true): re-extract one
        already-completed video into a throwaway dir with a FRESH
        extractor — cache off (the gate must recompute, not re-serve)
        and health on (compare_runs digest bands need digests)."""
        from .config import Config, _plain
        c_args = Config(_plain(args))
        c_args.output_path = canary_dir
        c_args.cache = False
        c_args.health = True
        c_ext = get_extractor_cls(args.feature_type)(c_args)
        t0 = time.perf_counter()
        status = safe_extract(c_ext._extract, video_path, policy=policy,
                              journal=None, decode_mode=c_ext.video_decode)
        return status, time.perf_counter() - t0

    try:
        with TraceCapture(args.get("profile_trace_dir")):
            if work_queue is not None:
                if bool(args.get("fleet_canary", False)):
                    if multi_mode:
                        print("fleet canary: multi-family runs are not "
                              "canary-gated yet — claims open (per-family "
                              "health gates still apply)")
                    else:
                        ok, lines = work_queue.canary_gate(canary_extract)
                        print("\n".join(lines))
                        if not ok:
                            raise SystemExit(
                                "fleet canary: FAILED — this host is gated "
                                "out of the queue (digest or timing drift; "
                                "verdict in "
                                f"{work_queue.root}/canary/, docs/fleet.md)")
                # claim -> extract -> complete until the queue is drained
                # FLEET-wide; the bar tracks this host's completions
                # against the full corpus (other hosts take the rest)
                pbar = tqdm(total=len(video_paths), desc="fleet")
                try:
                    work_queue.drain(
                        run_one, workers=workers, stop=stop,
                        on_complete=lambda rec, status: pbar.update(1))
                finally:
                    pbar.close()
                    # escaped-exception / preemption safety net: hand any
                    # still-held claims back unbumped so another host
                    # re-dispatches them immediately
                    work_queue.release_all()
            elif workers <= 1:
                for video_path in tqdm(video_paths):
                    if stop.is_set():
                        break
                    run_one(video_path)
            else:
                # Cross-video pipelining: the host side (cv2 decode + PIL
                # transforms) of up to `video_workers` videos runs on
                # concurrent threads feeding the single device queue — while
                # one video's batch computes, another video decodes. cv2/PIL
                # release the GIL; each video's FeatureStream keeps its own
                # submit order, and per-video error isolation (safe_extract)
                # is unchanged. The reference's only cross-video parallelism
                # was whole extra processes per GPU (reference README.md:
                # 70-84).
                from concurrent.futures import (ThreadPoolExecutor,
                                                as_completed)
                with ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="vft-video") as pool:
                    futures = [pool.submit(run_one, vp)
                               for vp in video_paths]
                    try:
                        # completion order, not submission order: with
                        # pool.map the bar (the operator's liveness read)
                        # stalls on the slowest head-of-line video while
                        # finished ones pile up uncounted behind it.
                        # result() re-raises a worker's escaped exception,
                        # as iterating pool.map's results did. SIGTERM
                        # semantics are unchanged: queued videos still run
                        # run_one, which drops them via the stop flag.
                        for fut in tqdm(as_completed(futures),
                                        total=len(futures)):
                            fut.result()
                    except BaseException:
                        # drop the not-yet-started videos; in-flight ones
                        # finish (their outputs stay valid thanks to atomic
                        # writes + resume-on-restart)
                        pool.shutdown(cancel_futures=True)
                        raise
    finally:
        # prev_handler is None when a C-level handler was installed before
        # us; signal.signal() can't restore those (TypeError)
        if in_main and prev_handler is not None:
            signal.signal(signal.SIGTERM, prev_handler)
        if recorder is not None:
            by_cat: dict = {}
            for rec in failures:
                cat = rec.get("category") or "?"
                by_cat[cat] = by_cat.get(cat, 0) + 1
            # close() in the finally: a SIGTERM/KeyboardInterrupt exit must
            # still leave a manifest + final heartbeat behind — that partial
            # record is exactly what an operator debugs the abort with
            rf_summary = None
            if rf_observer is not None:
                try:
                    # summarized BEFORE the recorder closes so the manifest
                    # (and the final heartbeat's live snapshot) carry the
                    # end-of-run MFU/verdicts
                    rf_summary = rf_observer.summary(resolve_peak=True)
                except Exception:
                    rf_summary = None
            recorder.close(tally=dict(tally),
                           wall_s=time.perf_counter() - t_run,
                           failure_tallies=by_cat,
                           roofline=rf_summary)
        if rf_observer is not None:
            # after the recorder: observer.close restores the stage hook
            # only if still its own, and writes _roofline.json atomically
            rf_observer.close()
        if tracer is not None:
            # likewise in the finally: an aborted run's partial timeline is
            # still a complete, loadable trace file (atomic temp+rename)
            tracer.close()
        if parity_observer is not None:
            # appends are already durable (O_APPEND); close just detaches
            # the module global so in-process callers don't inherit taps
            from .telemetry import parity as parity_mod
            if parity_mod.active() is parity_observer:
                parity_mod._set_active(None)
            parity_observer.close()
        if inject_plan is not None:
            # the chaos run's record of exactly what it injected (the
            # counters land in the manifest metrics dump too)
            print(inject_plan.summary())
        inject.disarm()  # in-process callers must not inherit the plan
        # seal the compile-cache entry even on an aborted run: every
        # executable XLA finished writing is complete (its own write is
        # atomic), and sealing it saves the next host that compile
        compile_cache.seal_active()

    elapsed = time.perf_counter() - t_run
    n_run = sum(tally.values())
    if multi_mode:
        summary = (f"{videos_run[0]}/{len(video_paths)} videos x "
                   f"{len(families)} families in {elapsed:.1f}s: "
                   f"{tally['done']} extracted, {tally['skipped']} already "
                   f"done, {tally['error']} failed")
    else:
        summary = (f"{n_run}/{len(video_paths)} videos in {elapsed:.1f}s: "
                   f"{tally['done']} extracted, {tally['skipped']} already "
                   f"done, {tally['error']} failed")
    if tally["quarantined"]:
        summary += f", {tally['quarantined']} quarantined"
    if failures:
        by_cat: dict = {}
        for rec in failures:
            cat = rec.get("category") or "?"
            by_cat[cat] = by_cat.get(cat, 0) + 1
        summary += (" [" + ", ".join(f"{k}={v}"
                                     for k, v in sorted(by_cat.items()))
                    + "]")
    if tally["done"]:
        unit = "extractions/s" if multi_mode else "videos/s"
        summary += f" ({tally['done'] / elapsed:.2f} {unit})"
    print(summary)
    if multi_mode:
        for fam in families:
            ft = fam_tally[fam]
            line = (f"  {fam}: {ft['done']} extracted, {ft['skipped']} "
                    f"already done, {ft['error']} failed")
            if ft["quarantined"]:
                line += f", {ft['quarantined']} quarantined"
            print(line)
    if failures and multi_mode:
        for fam in sorted({rec.get("family") for rec in failures
                           if rec.get("family")}):
            j = multi.journals.get(fam)
            if j is not None:
                print(f"failure journal ({fam}): {j.path} "
                      "(retry_failed=true re-runs quarantined videos)")
    if failures and journal is not None:
        print(f"failure journal: {journal.path} (retry_failed=true re-runs "
              "quarantined videos)")
    if recorder is not None:
        print(f"telemetry: {recorder.manifest_path} + {recorder.spans_path} "
              f"(render with scripts/telemetry_report.py "
              f"{out_root})")
    if alert_engine is not None:
        s = alert_engine.heartbeat_section()
        print(f"alerts: {s.get('firing', 0)} firing / "
              f"{s.get('pending', 0)} pending at exit — journal in "
              f"{out_root}/_alerts.jsonl, incident bundles in "
              f"{out_root}/_incidents/ (render with vft-alert {out_root})")
    if tracer is not None:
        print(f"trace: {tracer.trace_path} (render with "
              f"scripts/trace_report.py {out_root}, or open in "
              "https://ui.perfetto.dev)")
    if rf_observer is not None:
        print(f"roofline: {rf_observer.path} (render with vft-roofline "
              f"{out_root})")
    if parity_observer is not None:
        print(f"parity: per-seam numerics digests in {parity_observer.path} "
              f"(render with vft-parity {out_root}; certify flips with "
              "vft-parity certify)")
    if health_on:
        from .telemetry.health import HEALTH_FILENAME
        print(f"health: per-(video, family) feature digests in "
              f"{{output_path}}/{HEALTH_FILENAME} under {out_root} "
              f"(diff two runs with scripts/compare_runs.py)")
    if profiler.enabled:
        print(profiler.summary(f"profile: {run_label} x "
                               f"{len(video_paths)} videos"))
    if stop.is_set():
        raise SystemExit(143)  # conventional SIGTERM exit; resume = re-run
    if verbose:
        print(f"Yay! Done! The results are in {args.output_path}")


if __name__ == "__main__":
    main()
